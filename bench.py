"""Benchmark: IWAE k=50, 2-stochastic-layer flagship train throughput.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "steps/sec", "vs_baseline": N}``

`value` is the jitted JAX train-step throughput on the available accelerator
(one TPU chip under the driver). `vs_baseline` is the speedup over a freshly
measured eager-CPU baseline (the torch oracle backend, standing in for the
reference's eager TF2-CPU execution — BASELINE.md records no published
throughput, so the baseline is measured, not assumed; north-star target is
>=10x).

Set BENCH_SKIP_BASELINE=1 to reuse the last cached baseline measurement.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BATCH = 100
K = 50
WARMUP = 5
ITERS = 30
BASELINE_ITERS = 3
BASELINE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              ".bench_baseline.json")


def make_data(n=BATCH):
    return (np.random.RandomState(0).rand(n, 784) > 0.5).astype(np.float32)


def bench_jax() -> float:
    import jax

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.training import create_train_state, make_train_step

    cfg = ModelConfig.two_layer()
    spec = ObjectiveSpec("IWAE", k=K)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    step = make_train_step(spec, cfg, donate=False)
    x = jax.numpy.asarray(make_data())

    for _ in range(WARMUP):
        state, m = step(state, x)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, m = step(state, x)
    jax.block_until_ready(m["loss"])
    return ITERS / (time.perf_counter() - t0)


def bench_baseline() -> float:
    """Eager-CPU steps/sec (torch oracle), cached across runs."""
    if os.environ.get("BENCH_SKIP_BASELINE") and os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            return json.load(f)["steps_per_sec"]
    import torch

    torch.set_num_threads(max(1, os.cpu_count() or 1))
    from iwae_replication_project_tpu.api import FlexibleModel

    mdl = FlexibleModel([200, 100], [100, 200], [100, 50], [100, 784],
                        dataset_bias=None, loss_function="IWAE", k=K,
                        backend="torch").compile()
    x = torch.from_numpy(make_data())
    mdl.train_step(x)  # warmup
    t0 = time.perf_counter()
    for _ in range(BASELINE_ITERS):
        mdl.train_step(x)
    sps = BASELINE_ITERS / (time.perf_counter() - t0)
    try:
        with open(BASELINE_CACHE, "w") as f:
            json.dump({"steps_per_sec": sps, "time": time.time()}, f)
    except OSError:
        pass
    return sps


def main():
    jax_sps = bench_jax()
    base_sps = bench_baseline()
    print(json.dumps({
        "metric": "IWAE-k50-2L train throughput (batch 100)",
        "value": round(jax_sps, 2),
        "unit": "steps/sec",
        "vs_baseline": round(jax_sps / base_sps, 2),
    }))


if __name__ == "__main__":
    main()
