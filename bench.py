"""Benchmark: IWAE k=50, 2-stochastic-layer flagship train throughput.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "steps/sec", "vs_baseline": N}``

`value` measures the framework's production training path — the whole-epoch
`lax.scan` (training/epoch.py) with the Pallas fused-likelihood decoder head —
on the available accelerator, with an honest host-side fetch of the losses at
the end (async dispatch through the device tunnel makes `block_until_ready`
report enqueue rate, not completion rate).

`vs_baseline` is the speedup over a freshly measured eager-CPU baseline (the
torch oracle backend, standing in for the reference's eager TF2-CPU execution
— BASELINE.md records no published throughput, so the baseline is measured,
not assumed; north-star target is >=10x).

Set BENCH_SKIP_BASELINE=1 to reuse the last cached baseline measurement.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

N_TRAIN = 50000   # rows resident in HBM for the scanned epoch (MNIST train-set scale)
BATCH = 100
K = 50
EPOCHS = 5        # measured epochs (2500 steps) after 1 warmup/compile epoch
BASELINE_ITERS = 3
BASELINE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              ".bench_baseline.json")


def make_data(n):
    return (np.random.RandomState(0).rand(n, 784) > 0.5).astype(np.float32)


def bench_jax() -> float:
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.training.epoch import make_epoch_fn

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    cfg = ModelConfig.two_layer(likelihood="logits", fused_likelihood=on_tpu)
    spec = ObjectiveSpec("IWAE", k=K)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    epoch = make_epoch_fn(spec, cfg, N_TRAIN, BATCH, donate=False)
    x = jnp.asarray(make_data(N_TRAIN))

    state, losses = epoch(state, x)   # compile + warmup
    np.asarray(losses)                # sync
    steps = EPOCHS * (N_TRAIN // BATCH)
    best = 0.0
    for _ in range(3):                # best-of-3: device tunnel can be bursty
        t0 = time.perf_counter()
        for _ in range(EPOCHS):
            state, losses = epoch(state, x)
        np.asarray(losses)            # honest completion sync
        best = max(best, steps / (time.perf_counter() - t0))
    return best


def bench_baseline() -> float:
    """Eager-CPU steps/sec (torch oracle), cached across runs."""
    if os.environ.get("BENCH_SKIP_BASELINE") and os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            return json.load(f)["steps_per_sec"]
    import torch

    torch.set_num_threads(max(1, os.cpu_count() or 1))
    from iwae_replication_project_tpu.api import FlexibleModel

    mdl = FlexibleModel([200, 100], [100, 200], [100, 50], [100, 784],
                        dataset_bias=None, loss_function="IWAE", k=K,
                        backend="torch").compile()
    x = torch.from_numpy(make_data(BATCH))
    mdl.train_step(x)  # warmup
    t0 = time.perf_counter()
    for _ in range(BASELINE_ITERS):
        mdl.train_step(x)
    sps = BASELINE_ITERS / (time.perf_counter() - t0)
    try:
        with open(BASELINE_CACHE, "w") as f:
            json.dump({"steps_per_sec": sps, "time": time.time()}, f)
    except OSError:
        pass
    return sps


def main():
    jax_sps = bench_jax()
    base_sps = bench_baseline()
    print(json.dumps({
        "metric": "IWAE-k50-2L train throughput (batch 100, whole-epoch scan)",
        "value": round(jax_sps, 2),
        "unit": "steps/sec",
        "vs_baseline": round(jax_sps / base_sps, 2),
    }))


if __name__ == "__main__":
    main()
