"""Benchmark: IWAE k=50, 2-stochastic-layer flagship train + eval throughput.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "steps/sec", "vs_baseline": N, ...}``

`value` measures the framework's production training path — the whole-epoch
`lax.scan` (training/epoch.py) with the Pallas fused-likelihood decoder head —
on the available accelerator, with an honest host-side fetch of the losses at
the end (async dispatch through the device tunnel makes `block_until_ready`
report enqueue rate, not completion rate). Extra keys (VERDICT r1 item 8):

* ``spread`` — min/mean/max steps/sec over the repetitions (run-to-run
  variance is visible, not hidden behind a best-of);
* ``eval_images_per_sec`` — the k=5000 streaming-NLL evaluation path
  (the reference's memory hot spot, flexible_IWAE.py:463);
* ``mfu`` — achieved fraction of peak chip FLOP/s from analytic matmul
  FLOPs (fwd + ~2x bwd), honesty metric for how much of the MXU this
  small model can occupy. MFU is per-phase since ISSUE 6: ``mfu`` (train),
  ``eval_mfu``, and serving's ``mfu`` (bench --serving), all over the
  peak-FLOPs table in utils/flops.py (detected from device_kind;
  ``--peak-flops N`` / ``BENCH_PEAK_FLOPS`` override) with the numerator
  and denominator stamped. ``--hot-loop`` runs the full before/after sweep
  of the blocked hot-loop dispatcher at the paper config and commits it to
  results/hot_loop_bench.json (the default run refreshes the train legs);
* ``baseline_steps`` — the eager-CPU baseline is now measured over >= 50
  steps (was 3 in round 1).

`vs_baseline` is the speedup over a freshly measured eager-CPU baseline (the
torch oracle backend, standing in for the reference's eager TF2-CPU execution
— BASELINE.md records no published throughput, so the baseline is measured,
not assumed; north-star target is >=10x).

Set BENCH_SKIP_BASELINE=1 to reuse the last cached baseline measurement.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

N_TRAIN = 50000   # rows resident in HBM for the scanned epoch (MNIST train-set scale)
BATCH = 100
K = 50
EPOCHS = 5        # measured epochs (2500 steps) after 1 warmup/compile epoch
REPS = 3
BASELINE_ITERS = 50
EVAL_BATCH = 500  # the round-5 production default (+9% over 200; utils/config.py)
EVAL_K = 5000
EVAL_CHUNK = 250  # the round-4 production default (utils/config.py)
EVAL_REPS = 3
EVAL_N = 10000    # full-test-set-sized fused eval (one dispatch)
BASELINE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              ".bench_baseline.json")

def make_data(n):
    return (np.random.RandomState(0).rand(n, 784) > 0.5).astype(np.float32)


def train_step_flops(batch: int, k: int) -> float:
    """Analytic matmul FLOPs per flagship optimizer step (fwd + ~2x bwd).

    Derived from the architecture by utils/flops.py (one accounting shared
    by every phase and shape; through round 5 this was a hard-coded dims
    table here).
    """
    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.utils import flops
    return flops.train_step_flops(ModelConfig.two_layer(likelihood="logits"),
                                  batch, k)


def peak_flops():
    """``(peak chip FLOP/s | None, source)`` for the MFU denominator.

    Detection order (ISSUE 6 satellite — through round 5 this was one
    hard-coded "platform is TPU -> v5e" entry):

    1. explicit override: ``--peak-flops N`` / ``BENCH_PEAK_FLOPS=N``;
    2. the per-generation bf16 peak table (utils/flops.PEAK_BF16_FLOPS)
       matched against ``jax.devices()[0].device_kind``;
    3. unrecognized TPU kind: assume the v5e entry (197e12) with the
       assumption stamped in `source` and a loud stderr pointer to the
       override — r05's behavior, made explicit instead of silent;
    4. non-TPU platforms: ``(None, reason)`` — `mfu` is reported as null
       with the documented override rather than a fabricated denominator
       (ADVICE r2).
    """
    import sys

    from iwae_replication_project_tpu.utils.flops import peak_flops_for_kind

    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env), "explicit override (--peak-flops/BENCH_PEAK_FLOPS)"
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    if dev.platform == "tpu":
        peak, source = peak_flops_for_kind(kind)
        if peak is not None:
            return peak, source
        source = (f"unrecognized TPU device_kind {kind!r}: assuming v5e "
                  f"197e12 — set --peak-flops/BENCH_PEAK_FLOPS to correct")
        print(f"bench: {source}", file=sys.stderr)
        return 197e12, source
    reason = (f"no peak-FLOPs entry for platform {dev.platform!r} (kind "
              f"{kind!r}); mfu reported as null — set --peak-flops or "
              f"BENCH_PEAK_FLOPS (bytes are FLOP/s, e.g. 197e12)")
    print(f"bench: {reason}", file=sys.stderr)
    return None, reason


def step_flops_for(hidden: int, batch: int, k: int) -> float:
    """`train_step_flops` for a width-scaled architecture (bench --scaling):
    derived from the scaled ModelConfig by the same utils/flops accounting."""
    from iwae_replication_project_tpu.utils import flops
    return flops.train_step_flops(scaled_config(hidden, False), batch, k)


def scaled_config(hidden: int, on_tpu: bool, compute_dtype=None):
    from iwae_replication_project_tpu.models import ModelConfig
    h, h2, l1, l2 = hidden, hidden // 2, hidden // 2, hidden // 4
    return ModelConfig(n_hidden_enc=(h, h2), n_latent_enc=(l1, l2),
                       n_hidden_dec=(h2, h), n_latent_dec=(l1, 784),
                       likelihood="logits", fused_likelihood=on_tpu,
                       compute_dtype=compute_dtype)


def bench_scaling():
    """Width-scaling MFU sweep (VERDICT r4 #1): the same whole-epoch scanned
    IWAE step at hidden widths 200..2048 (all dims scaled except the 784
    pixels), k=50, batch {100, 256}, f32 and bf16-matmul variants. Prints one
    JSON line with a row per shape: steps/s, analytic TFLOP/s, MFU.

    Purpose: the flagship widths (50-200) leave the 128x128 MXU tiles
    quarter-filled — this sweep measures whether MFU climbs when the tiles
    fill (architecture was the bottleneck) or stalls (framework bottleneck
    hidden behind the parity shapes)."""
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.training.epoch import make_epoch_fn

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    peak, peak_source = peak_flops()
    n_train = 25600  # divisible by both batch sizes; 256/100 steps per epoch
    x = jnp.asarray(make_data(n_train))
    spec = ObjectiveSpec("IWAE", k=K)
    rows = []
    shapes = [(h, b, dt) for h in (200, 512, 1024, 2048)
              for b, dt in ((100, None), (256, None), (256, "bfloat16"))]
    for hidden, batch, dtype in shapes:
        cfg = scaled_config(hidden, on_tpu, compute_dtype=dtype)
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        epoch = make_epoch_fn(spec, cfg, n_train, batch, donate=False)
        state, losses = epoch(state, x)     # compile + warmup
        np.asarray(losses)
        steps = n_train // batch
        rates = []
        for _ in range(2):
            t0 = time.perf_counter()
            state, losses = epoch(state, x)
            np.asarray(losses)              # honest completion sync
            rates.append(steps / (time.perf_counter() - t0))
        sps = float(np.mean(rates))
        flops = step_flops_for(hidden, batch, K)
        rows.append({
            "hidden": hidden, "batch": batch,
            "dtype": dtype or "float32",
            "steps_per_sec": round(sps, 2),
            "tflops_per_sec": round(sps * flops / 1e12, 2),
            "mfu": round(sps * flops / peak, 4) if peak else None,
        })
        print(json.dumps(rows[-1]), flush=True)
    print(json.dumps({
        "metric": "IWAE-k50-2L width-scaling sweep (whole-epoch scan)",
        "unit": "per-shape steps/sec + analytic TFLOP/s + MFU",
        "peak_flops": peak,
        "peak_flops_source": peak_source,
        "rows": rows,
    }))


def _train_rates(cfg, reps=REPS):
    """Steps/sec of the production training path: the whole-epoch lax.scan
    with EPOCHS epochs fused into one dispatch (`epochs_per_call`), the same
    multi-pass batching the experiment driver uses for the long Burda stages
    (experiment.py PASS_BLOCK=27; 5 here is conservative). Through round 4
    the bench dispatched per-epoch, paying 4 extra ~10-15 ms tunnel
    round-trips per rep that the production driver does not pay.

    The program goes through the warm-path AOT registry exactly like the
    driver's, so the returned `compile_info` cleanly separates compile from
    execute time: `aot_compile_seconds` is the lower+compile wall (collapsing
    to cache-deserialization on a warm start) and `persistent_cache_misses`
    counts true XLA recompiles (0 when the persistent cache is warm).
    """
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.training.epoch import make_epoch_fn
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta, warm_callable)

    spec = ObjectiveSpec("IWAE", k=K)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    epoch = make_epoch_fn(spec, cfg, N_TRAIN, BATCH, donate=False,
                          epochs_per_call=EPOCHS)
    epoch = warm_callable("bench_epoch", epoch,
                          build_key=(spec, cfg, N_TRAIN, BATCH, EPOCHS))
    x = jnp.asarray(make_data(N_TRAIN))

    s0 = cache_stats()
    state, losses = epoch(state, x)   # compile + warmup
    np.asarray(losses)                # sync
    compile_info = stats_delta(s0)
    steps = EPOCHS * (N_TRAIN // BATCH)
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        state, losses = epoch(state, x)
        np.asarray(losses)            # honest completion sync
        rates.append(steps / (time.perf_counter() - t0))
    return rates, state, compile_info


def bench_jax():
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.models import ModelConfig

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    # headline = the production path: compute_dtype defaults to bfloat16
    # since round 5 (utils/config.py, RESULTS.md §2b)
    cfg = ModelConfig.two_layer(likelihood="logits", fused_likelihood=on_tpu,
                                compute_dtype="bfloat16")
    rates, state, compile_info = _train_rates(cfg)
    # secondary datapoint: full-f32 matmuls (the pre-r5 default)
    cfg_f32 = ModelConfig.two_layer(likelihood="logits",
                                    fused_likelihood=on_tpu)
    rates_f32, _, _ = _train_rates(cfg_f32, reps=1)
    # hot-loop "before" leg: the same production dtype with the blocked
    # dispatcher off (pure XLA composition) — the denominator of the
    # before/after MFU comparison committed to results/hot_loop_bench.json
    cfg_before = ModelConfig.two_layer(likelihood="logits",
                                       fused_likelihood=False,
                                       compute_dtype="bfloat16")
    rates_before, _, _ = _train_rates(cfg_before, reps=2)

    # eval path: the full per-batch scalar suite (VAE/IWAE bounds at k=50,
    # streaming k=5000 NLL, recon BCE) over EVAL_N images as ONE fused
    # dispatch — evaluation.metrics.dataset_scalars, the same program
    # run_experiment's per-stage eval uses.
    from iwae_replication_project_tpu.evaluation.metrics import dataset_scalars
    xe = jnp.asarray(make_data(EVAL_N)).reshape(EVAL_N // EVAL_BATCH,
                                                EVAL_BATCH, 784)
    key = jax.random.PRNGKey(1)
    np.asarray(dataset_scalars(state.params, cfg, key, xe, K,
                               EVAL_K, EVAL_CHUNK))  # compile
    eval_rates = []
    for _ in range(EVAL_REPS):
        t0 = time.perf_counter()
        np.asarray(dataset_scalars(state.params, cfg, key, xe, K,  # iwaelint: disable=key-reuse -- timing reps deliberately re-run the IDENTICAL program (same key) so only dispatch variance is measured
                                   EVAL_K, EVAL_CHUNK))
        eval_rates.append(EVAL_N / (time.perf_counter() - t0))
    return rates, rates_f32, rates_before, eval_rates, compile_info


def bench_baseline() -> tuple:
    """Eager-CPU steps/sec (torch oracle) over >= 50 steps, cached across runs."""
    if os.environ.get("BENCH_SKIP_BASELINE") and os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            d = json.load(f)
            return d["steps_per_sec"], d.get("n_steps", 0)
    import torch

    torch.set_num_threads(max(1, os.cpu_count() or 1))
    from iwae_replication_project_tpu.api import FlexibleModel

    mdl = FlexibleModel([200, 100], [100, 200], [100, 50], [100, 784],
                        dataset_bias=None, loss_function="IWAE", k=K,
                        backend="torch").compile()
    x = torch.from_numpy(make_data(BATCH))
    for _ in range(3):
        mdl.train_step(x)  # warmup
    t0 = time.perf_counter()
    for _ in range(BASELINE_ITERS):
        mdl.train_step(x)
    sps = BASELINE_ITERS / (time.perf_counter() - t0)
    try:
        with open(BASELINE_CACHE, "w") as f:
            json.dump({"steps_per_sec": sps, "n_steps": BASELINE_ITERS,
                       "time": time.time()}, f)
    except OSError:
        pass
    return sps, BASELINE_ITERS


#: ragged request-batch size cycle for the serving profile — deliberately
#: non-bucket-aligned so every ladder rung gets traffic
SERVING_SIZES = (1, 3, 7, 17, 5, 2, 9, 30)
SERVING_MAX_BATCH = 32
SERVING_BATCHES = 60           # request batches per offered-load level
SERVING_RATES = (0.0, 50.0)    # batches/sec offered; 0 = closed loop
SERVING_WARM_REPS = 25         # single-request warm-latency reps
#: serial-vs-pipelined comparison: the latency-tier op point (small bucket,
#: modest k — the pre-filled queue coalesces everything into full
#: max_batch-sized dispatches, so the measured regime is a uniform stream
#: of bucket-4 programs) where per-dispatch host work — coalesce, pad,
#: device_put, enqueue, fetch, futures — is commensurate with device
#: compute. That is the regime the two-stage pipeline exists for: big
#: bucket-saturating k=50 batches are ~97% device-bound on this box and
#: overlap can't show there (the load sweep above covers ragged traffic).
SERVING_PIPE_ROWS = 420        # rows per serial/pipelined rep
SERVING_PIPE_K = 10
SERVING_PIPE_MAX_BATCH = 4
SERVING_PIPE_REPS = 12         # paired closed-loop reps per dispatch mode
REPLICA_COUNTS = (1, 2)        # fleet sizes for the replica_scaling sweep
REPLICA_ROWS = 480             # rows per closed-loop rep
REPLICA_REPS = 10              # timed reps per fleet size (paired medians:
                               # this shared box's hypervisor steals whole
                               # cores for stretches and per-pair ratios
                               # spread ~0.9-1.5x, so the median needs a
                               # deep sample; all walls are committed)
REPLICA_BUCKET = 32            # one pinned bucket: every dispatch is the
                               # same padded shape on every replica
REPLICA_K = 150                # the scaling op point: an eval-grade score
                               # budget (3x the training k; the repo's NLL
                               # evals go to k=5000) so per-row device time
                               # dominates the parent's JSON/TCP work — at
                               # k=50 the sweep measures the wire, not the
                               # fleet
REPLICA_MAX_WAIT_US = 20000    # child coalescing window: splitting one
                               # arrival stream N ways halves each child's
                               # fill rate, and the engine default (2 ms)
                               # then flushes half-empty buckets whose
                               # padding burns the second core's win — 20 ms
                               # lets every steady-state dispatch fill
SERVING_PIPE_INFLIGHT = 10     # deeper than the serving default (2): small
#                                CPU executions overlap, so a deeper window
#                                keeps every core fed during fetch stalls


def _bench_replica_scaling(cfg, state):
    """The ``replica_scaling`` block: closed-loop throughput of the network
    tier (serving/frontend/) at 1 and 2 replicas.

    Each replica is ONE single-replica child tier in its OWN process with
    single-threaded XLA compute and its own core pin (``iwae-serve
    --replicas 1 --pin-core i`` under ``--xla_cpu_multi_thread_eigen=
    false``) — the CPU bench box's stand-in for one accelerator per
    replica: one core's worth of disjoint compute, a private XLA runtime,
    and a private AOT cache, talking JSON-lines over TCP. The parent composes them with a :class:`ReplicaRouter` over
    :class:`RemoteEngine` proxies — exactly the fleet shape the frontend
    ships — and measures:

    * **throughput per fleet size** — rows/sec over REPLICA_REPS closed
      loops of REPLICA_ROWS single-row score requests (best-of, like the
      pipeline comparison; all walls committed);
    * **the box's own parallel ceiling** — the same workload through two
      DIRECT pinned engines (no tier, no router, no sockets) run solo and
      then concurrently, probe rounds interleaved with the fleet reps so
      both see the same machine windows. A CPU "core" is not a device:
      this box's two schedulable cores share FPU ports and memory
      bandwidth, so two truly-single-threaded f32 engine processes reach
      only ~1.2-1.3x aggregate (measured by this probe, committed as
      ``box_ceiling_2proc``) — that ceiling, not the tier, bounds what ANY
      2-process fleet can show here. The honest fleet metric on such a box
      is ``scaling_efficiency_vs_box_ceiling`` = fleet speedup / ceiling;
      the ``>= 1.5x at 2 replicas`` target is asserted against hardware
      whose replicas have disjoint compute (one device — or one real core
      — each), which the probe verifies rather than assumes;
    * **front-end cost at 1 replica** — the 1-replica tier against the
      bare engine in the same windows (``tier_1replica_over_direct_
      engine``): how much of the parent's routing + JSON/TCP work hides
      behind the replica's compute vs lands as a throughput tax. The
      parent is a third process on this 2-core box, competing for the
      same shared capacity — another reason the 2-replica gain here is
      bounded by the measured leftover, not by the tier;
    * **bitwise parity** — the untimed first round's results (parent seeds
      0..N-1 in admission order) against the direct probe engine's first
      pass over the same rows in the same order (identically configured
      process: XLA:CPU partitions reductions by pool size, so the
      reference must share the replicas' single-threaded compute config):
      routing, processes, and the wire must be bitwise invisible;
    * **zero recompiles** — every child's over-the-wire ``stats`` must show
      0 ``aot_misses`` / 0 ``recompiles`` across the whole post-warmup
      stream.

    Children run on JAX_PLATFORMS=cpu by design: the sweep measures the
    TIER (routing + wire + admission overhead and how it scales), with
    pinned cores modeling per-replica devices; a per-chip TPU fleet round
    reuses this harness with one process per accelerator.
    """
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    from iwae_replication_project_tpu.serving.frontend import (
        RemoteEngine, ReplicaRouter, TierClient)
    from iwae_replication_project_tpu.utils.checkpoint import save_checkpoint
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    cores = sorted(os.sched_getaffinity(0))
    counts = [n for n in REPLICA_COUNTS if n <= len(cores)]
    if len(counts) < len(REPLICA_COUNTS):
        return {"skipped": f"needs >= {max(REPLICA_COUNTS)} cores to pin "
                           f"one replica process per core; box has "
                           f"{len(cores)}"}

    # children serve THIS bench's weights from a throwaway checkpoint (the
    # default ExperimentConfig IS the flagship 2L the bench builds;
    # compute_dtype pinned to f32 to match the parent's direct engine —
    # the stored default is the TPU bf16 knob, and a dtype mismatch would
    # break the bitwise-parity contract, not just weaken it)
    tmp = tempfile.mkdtemp(prefix="iwae_replica_bench_")
    run_dir = os.path.join(tmp, "run")
    save_checkpoint(run_dir, 0, state, stage=1,
                    config_json=ExperimentConfig(
                        compute_dtype=None).to_json())

    rng = np.random.RandomState(11)
    stream = (rng.rand(REPLICA_ROWS, 784) > 0.5).astype(np.float32)

    # every replica-model process (children AND the parity reference) runs
    # single-threaded XLA compute + its own core pin: one replica = one
    # core's worth of compute, enforced two ways because each covers the
    # other's blind spot — the eigen flag stops the intra-op pool from
    # spanning cores (and from SPINNING: N replicas x multi-thread pools
    # oversubscribe the box into anti-scaling, measured 0.85x; sandboxed
    # kernels like this CI box's also simply ignore sched_setaffinity),
    # the pin gives placement isolation where the kernel honors it. The
    # reference shares the config because XLA partitions reductions by
    # pool size — a differently-threaded engine is bitwise-different
    # float32, and the parity contract is against the engine the fleet
    # actually models.
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_cpu_multi_thread_eigen=false").strip()

    # direct probe engines: the parity reference AND the box-ceiling
    # calibration in one process per core — warm up, score the stream once
    # (the parity payload: engine-minted seeds 0..N-1 in submit order, the
    # exact semantics the tier must reproduce), then serve timed scoring
    # rounds on demand (one line in = one timed pass, wall out), so solo
    # and duo rounds can be interleaved with the fleet reps against LIVE
    # processes without re-paying startup
    probe_code = (
        "import json, os, sys, time\n"
        "os.sched_setaffinity(0, {int(sys.argv[1])})\n"
        "import numpy as np\n"
        "from iwae_replication_project_tpu.serving import ServingEngine\n"
        "from iwae_replication_project_tpu.serving.buckets import "
        "BucketLadder\n"
        "req = json.loads(sys.stdin.readline())\n"
        "eng = ServingEngine(req['run_dir'], k=req['k'],\n"
        "                    ladder=BucketLadder((req['bucket'],)),\n"
        "                    max_batch=req['bucket'], max_inflight=0,\n"
        "                    timeout_s=None)\n"
        "eng.warmup(ops=('score',))\n"
        "x = np.asarray(req['x'], np.float32)\n"
        "out = eng.score(x)\n"
        "print(json.dumps([float(v) for v in out]), flush=True)\n"
        "for line in sys.stdin:\n"
        "    if not line.strip():\n"
        "        continue\n"
        "    t0 = time.perf_counter()\n"
        "    eng.score(x)\n"
        "    print(json.dumps({'wall': time.perf_counter() - t0}),\n"
        "          flush=True)\n")

    spawned = []       # every live subprocess, for the failure-path sweep

    def spawn_probe(core):
        p = subprocess.Popen(
            [_sys.executable, "-c", probe_code, str(core)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        spawned.append(p)
        p.stdin.write(json.dumps({
            "run_dir": run_dir, "k": REPLICA_K, "bucket": REPLICA_BUCKET,
            "x": stream.tolist()}) + "\n")
        p.stdin.flush()
        first = np.asarray(json.loads(p.stdout.readline()),
                           dtype=np.float32)
        return p, first

    def probe_round(probes):
        """One timed scoring pass on each probe, started together."""
        for p, _ in probes:
            p.stdin.write("go\n")
            p.stdin.flush()
        return [json.loads(p.stdout.readline())["wall"] for p, _ in probes]

    def spawn(core):
        p = subprocess.Popen(
            [_sys.executable, "-m", "iwae_replication_project_tpu.serving",
             "--replicas", "1", "--port", "0", "--checkpoint", run_dir,
             "--k", str(REPLICA_K), "--buckets", str(REPLICA_BUCKET),
             "--max-batch", str(REPLICA_BUCKET),
             "--max-wait-us", str(REPLICA_MAX_WAIT_US), "--timeout-s", "0",
             # one execution at a time per replica: the in-flight pipeline
             # would run 2 concurrent single-threaded executions on the
             # PJRT pool — a 2-core replica in disguise, breaking the
             # one-core-per-device model this sweep scales over
             "--max-inflight", "0",
             "--ops", "score", "--pin-core", str(core)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        spawned.append(p)
        ready = json.loads(p.stdout.readline())
        return p, ready["tier"]["port"]

    # both fleet sizes run over the SAME live children, reps interleaved
    # back-to-back in alternating order — this shared box's effective CPU
    # speed swings by tens of percent between windows, so an unpaired
    # best-of-N ratio mostly measures which fleet drew the quieter windows;
    # pairing cancels the common mode (the pipeline_comparison treatment).
    # Fleet "1" routes to child A only (child B idles, blocked on its
    # socket — zero CPU); fleet "2" routes over A and B via its own
    # connections.
    try:
        probe_a = spawn_probe(cores[0])
        probe_b = spawn_probe(cores[1])
        ref = probe_a[1]             # the parity reference results
        procs = [spawn(cores[i]) for i in range(max(counts))]
        fleets = {n: ReplicaRouter([RemoteEngine("127.0.0.1", port)
                                    for _, port in procs[:n]])
                  for n in counts}

        def closed_loop(router):
            futures = [router.submit("score", row) for row in stream]
            for f in futures:
                f.result()
            return futures

        # untimed warm round per fleet: parent seeds 0..N-1 — the parity
        # round (and it pre-touches the JSON/TCP path on every replica)
        parity = {}
        for n, router in fleets.items():
            got = np.asarray([f.result() for f in closed_loop(router)],
                             dtype=ref.dtype)
            parity[n] = bool(np.array_equal(got, ref))

        walls = {n: [] for n in counts}
        solo_walls, duo_walls = [], []
        for rep in range(REPLICA_REPS):
            order = list(counts) if rep % 2 else list(counts)[::-1]
            for n in order:
                t0 = time.perf_counter()
                closed_loop(fleets[n])
                walls[n].append(time.perf_counter() - t0)
            # the box-ceiling probe rides the same machine window as this
            # rep's fleet pair: one solo pass (probe A alone = the direct
            # single-replica workload) then one duo pass (A and B started
            # together = two disjoint "devices", if the box can express it)
            solo_walls.append(probe_round([probe_a])[0])
            duo_walls.append(max(probe_round([probe_a, probe_b])))

        # the zero-recompile proof, read over the wire from each child
        child_stats = []
        for _, port in procs:
            with TierClient("127.0.0.1", port) as cli:
                eng_c = cli.stats()["engines"][0]
            child_stats.append({k: int(eng_c.get(k, 0)) for k in
                                ("dispatches", "completed", "aot_hits",
                                 "aot_misses", "recompiles")})
        for router in fleets.values():
            router.drain(timeout_s=60)
        for p, _ in procs:
            p.stdin.close()          # lifetime control: stdin EOF = stop
            p.wait(timeout=60)
        for p, _ in (probe_a, probe_b):
            p.stdin.close()
            p.wait(timeout=60)
    finally:
        # failure sweep (no-op on success: everything above already
        # exited): a crashed sweep must not leave pinned child/probe
        # processes alive to skew every later bench stage, nor the
        # throwaway checkpoint dir behind
        for p in spawned:
            try:
                if p.stdin and not p.stdin.closed:
                    p.stdin.close()
            except OSError:
                pass
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass
        shutil.rmtree(tmp, ignore_errors=True)

    levels = [{
        "replicas": n,
        "rows_per_rep": REPLICA_ROWS,
        "rows_per_sec": round(REPLICA_ROWS / min(walls[n]), 2),
        "wall_seconds": [round(w, 4) for w in walls[n]],
        "bitwise_identical_to_direct_engine": parity[n],
    } for n in counts]
    def median(xs):
        xs = sorted(xs)
        mid = len(xs) // 2
        return xs[mid] if len(xs) % 2 else (xs[mid - 1] + xs[mid]) / 2

    # per-pair speedups (adjacent in time; the robust ratio estimator) —
    # the headline is their median, best-of throughputs sit alongside
    pairs = sorted(w1 / w2 for w1, w2 in zip(walls[counts[0]],
                                             walls[counts[-1]]))
    median_pair = median(pairs)
    # the box ceiling: aggregate throughput of two DIRECT pinned engines
    # over one, same paired treatment (2 * solo wall / duo wall per rep)
    ceiling_pairs = [2 * s / d for s, d in zip(solo_walls, duo_walls)]
    ceiling = median(ceiling_pairs)
    # the 1-replica tier vs the bare engine, same windows: how much of
    # the parent's routing + JSON/TCP work hides behind replica compute
    # (>= 1: fully overlapped) vs lands as a throughput tax (< 1)
    overlap_pairs = [s / w for s, w in zip(solo_walls, walls[counts[0]])]
    target = 1.5
    misses = sum(c["aot_misses"] for c in child_stats)
    recompiles = sum(c["recompiles"] for c in child_stats)
    return {
        "method": "one single-core child tier process per replica "
                  "(iwae-serve --replicas 1 --pin-core i, single-threaded "
                  "XLA compute via --xla_cpu_multi_thread_eigen=false), "
                  "parent ReplicaRouter over RemoteEngine proxies, "
                  "JSON-lines/TCP; parity reference + box-ceiling probe "
                  "are direct engines in identically configured processes "
                  "(XLA:CPU partitions reductions by pool size), probe "
                  "rounds interleaved with the fleet reps",
        "k": REPLICA_K, "bucket": REPLICA_BUCKET,
        "levels": levels,
        "per_child": child_stats,
        # median of per-pair (1-replica wall / 2-replica wall) ratios over
        # back-to-back alternating reps: machine-speed swings hit both
        # fleet sizes of a pair equally, so the pair ratio is the honest
        # scaling estimator on this box (all walls committed above)
        "speedup_2_over_1": round(median_pair, 3),
        "speedup_2_over_1_pairs": [round(r, 3) for r in pairs],
        # what 2 disjoint single-threaded engine processes — no tier at
        # all — deliver over 1 on THIS box: the physical bound on any
        # 2-replica result here (two schedulable cores sharing FPU ports
        # and memory bandwidth are not two devices)
        "box_ceiling_2proc": round(ceiling, 3),
        "box_ceiling_2proc_pairs": [round(r, 3) for r in ceiling_pairs],
        "box_probe_solo_walls": [round(w, 4) for w in solo_walls],
        "box_probe_duo_walls": [round(w, 4) for w in duo_walls],
        # 1-replica tier / bare direct engine, paired per rep: the front
        # end's net cost — 1.0 means routing + wire + admission fully
        # hide behind the replica's compute
        "tier_1replica_over_direct_engine": round(median(overlap_pairs), 3),
        "tier_1replica_over_direct_engine_pairs": [
            round(r, 3) for r in overlap_pairs],
        "target_speedup_2_replicas": target,
        "target_met": bool(median_pair >= target),
        "target_expressible_on_this_box": bool(ceiling >= target),
        # how much of the box's measured parallel capacity the tier
        # actually delivers — the number that transfers to real fleets
        # (one device per replica), where the ceiling is ~N
        "scaling_efficiency_vs_box_ceiling": round(median_pair / ceiling, 3),
        "bitwise_identical_to_direct_engine": all(parity.values()),
        "post_warmup_aot_misses": misses,
        "post_warmup_recompiles": recompiles,
    }


def bench_serving():
    """``--serving``: the online-inference engine profile (serving/).

    Measures, on the flagship 2L architecture at k=50:

    * **cold dispatch** — first single-request ``score`` on a fresh engine
      with an empty AOT registry (lower+compile+execute), the latency the
      warm path must beat;
    * **warm single-request latency** — p50/p95 over SERVING_WARM_REPS warm
      ``score`` calls (the acceptance bar: <= cold/10);
    * **offered-load sweep** — SERVING_BATCHES ragged request batches
      (SERVING_SIZES cycle) per rate level through the background
      dispatcher: completed rows/sec + per-bucket p50/p95/p99 from the
      engine's histograms;
    * **zero-recompile proof** — ``cache_stats`` delta across the whole
      post-warmup stream (aot_misses and persistent-cache misses must be 0);
    * **serial vs pipelined closed loop** — the same warmed program set, the
      same request stream, dispatched serially (``max_inflight=0``: the
      dispatcher blocks on every fetch) vs through the two-stage pipeline
      (async enqueue + completion thread, bounded in-flight window): the
      throughput ratio is the dispatch-overlap payoff, and the per-request
      results must be bitwise identical across modes;
    * **replica scaling** — the network tier (serving/frontend/) at 1 and 2
      replica processes (one pinned core each): closed-loop throughput per
      fleet size, bitwise parity against a direct single engine, and the
      over-the-wire zero-recompile proof (see
      :func:`_bench_replica_scaling`).

    Prints one JSON line and writes results/serving_bench.json.
    """
    import jax

    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, isolated_aot_registry, setup_persistent_cache,
        stats_delta)

    # the engine resolves its hot-loop path per (op, bucket, k) through the
    # lifted probe gate (serving/engine._kernel_for — ISSUE 12); its
    # metrics stamp the selection per dispatch config, and bench.py
    # --autotune carries the dedicated pinned-vs-unpinned comparison
    cfg = ModelConfig.two_layer(likelihood="logits")
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    params = state.params
    x = make_data(max(SERVING_SIZES))

    # cold: empty AOT registry AND persistent cache suspended — on a repeat
    # bench run the repo-local cache main() enabled would deserialize the
    # program and report a bogus (warm) "cold" number; the probe must pay
    # the true lower+XLA-compile price every run
    setup_persistent_cache("off")
    with isolated_aot_registry():
        cold_eng = ServingEngine(params=params, model_config=cfg, k=K,
                                 max_batch=SERVING_MAX_BATCH, timeout_s=None)
        t0 = time.perf_counter()
        cold_eng.score(x[0])
        cold_s = time.perf_counter() - t0
    # restore the repo-local cache for the warm path (same dir main() set up)
    setup_persistent_cache(
        base_dir=os.path.dirname(os.path.abspath(__file__)))

    eng = ServingEngine(params=params, model_config=cfg, k=K,
                        max_batch=SERVING_MAX_BATCH, timeout_s=None)
    warm_info = eng.warmup(ops=("score",))
    s0 = cache_stats()

    lat = []
    for _ in range(SERVING_WARM_REPS):
        t0 = time.perf_counter()
        eng.score(x[0])
        lat.append(time.perf_counter() - t0)
    lat.sort()
    warm_p50 = lat[len(lat) // 2]

    levels = []
    rng = np.random.RandomState(0)
    for rate in SERVING_RATES:
        eng.start()
        futures = []
        t0 = time.perf_counter()
        for i in range(SERVING_BATCHES):
            n = SERVING_SIZES[i % len(SERVING_SIZES)]
            for row in (rng.rand(n, 784) > 0.5).astype(np.float32):
                futures.append(eng.submit("score", row))
            if rate > 0:
                time.sleep(rng.exponential(1.0 / rate))
        for f in futures:
            f.result()
        wall = time.perf_counter() - t0
        eng.stop()
        levels.append({
            "offered_batches_per_sec": rate or "closed_loop",
            "rows": len(futures),
            "wall_seconds": round(wall, 3),
            "rows_per_sec": round(len(futures) / wall, 2),
        })
    d = stats_delta(s0)
    snap = eng.metrics.snapshot()
    p99 = {name: round(s["p99_s"], 6)
           for name, s in snap["latency"].items() if s["p99_s"] is not None}

    # serving-phase MFU: closed-loop score rows/sec x analytic per-row FLOPs
    # over the chip peak (same roofline accounting as the train/eval phases)
    from iwae_replication_project_tpu.ops.hot_loop import path_counters
    from iwae_replication_project_tpu.utils.flops import (
        serving_score_flops_per_row)
    peak, peak_source = peak_flops()
    closed = next(lv["rows_per_sec"] for lv in levels
                  if lv["offered_batches_per_sec"] == "closed_loop")
    row_flops = serving_score_flops_per_row(cfg, K)
    serving_mfu = (round(closed * row_flops / peak, 6) if peak else None)

    # -- serial vs pipelined closed loop: the dispatch-overlap payoff -------
    # Two fresh engines over the SAME weights, warmed onto the same AOT
    # registry entries (second warmup = zero compiles), fed the IDENTICAL
    # request stream in identical order: per-request seeds line up, so the
    # two modes must return bitwise-identical per-request results — the
    # pipeline only changes WHEN stages run, never what they compute.
    # The queue is pre-filled before each timed drain so batch formation is
    # deterministic and identical across modes (a live submitter thread
    # makes coalescing — and therefore the program mix — depend on dispatch
    # timing, which would compare different work, not different dispatch):
    # every dispatch is a full max_batch bucket, zero padding.
    rng = np.random.RandomState(7)
    stream = (rng.rand(SERVING_PIPE_ROWS, 784) > 0.5).astype(np.float32)
    n_rows = len(stream)

    def closed_loop(e):
        futures = [e.submit("score", row) for row in stream]
        t0 = time.perf_counter()
        e.start()
        # wait on the tail future first (FIFO completion: once it lands the
        # rest are done), so the measuring thread sleeps through the drain
        # instead of waking per future and stealing GIL time from the
        # engine threads — same treatment for both modes
        futures[-1].result()
        results = [f.result() for f in futures]
        wall = time.perf_counter() - t0
        e.stop()
        return wall, results

    mk = lambda mi: ServingEngine(params=params, model_config=cfg,
                                  k=SERVING_PIPE_K,
                                  max_batch=SERVING_PIPE_MAX_BATCH,
                                  max_inflight=mi, queue_limit=4 * n_rows,
                                  timeout_s=None)
    modes = {"serial": mk(0), "pipelined": mk(SERVING_PIPE_INFLIGHT)}
    for e in modes.values():
        e.warmup(ops=("score",))
    sp0 = cache_stats()
    walls = {name: [] for name in modes}
    outs = {}
    # one untimed round per mode first (thread spawn, allocator, frequency
    # ramp), then paired reps — the two modes run back to back within a
    # pair, alternating which goes first, so machine noise hits both evenly;
    # seeds advance identically (same submit count per round), keeping
    # round j bitwise-comparable across modes
    for rep in range(-1, SERVING_PIPE_REPS):
        order = list(modes) if rep % 2 else list(modes)[::-1]
        for name in order:
            wall, results = closed_loop(modes[name])
            if rep < 0:
                outs[name] = results   # warm round: parity data only
            else:
                walls[name].append(wall)
    spd = stats_delta(sp0)
    bitwise = all(np.array_equal(a, b)
                  for a, b in zip(outs["serial"], outs["pipelined"]))
    ratios = sorted(s / p for s, p in zip(walls["serial"],
                                          walls["pipelined"]))
    median_ratio = (ratios[len(ratios) // 2] if len(ratios) % 2 else
                    (ratios[len(ratios) // 2 - 1] +
                     ratios[len(ratios) // 2]) / 2)
    best = {name: min(w) for name, w in walls.items()}
    pipe_cmp = {
        # the measured regime: a uniform stream of full bucket-sized
        # dispatches (pre-filled queue -> max coalescing, zero padding)
        "op_point": {"k": SERVING_PIPE_K,
                     "bucket": SERVING_PIPE_MAX_BATCH},
        "dispatches_per_rep": n_rows // SERVING_PIPE_MAX_BATCH,
        "rows_per_rep": n_rows,
        "reps": SERVING_PIPE_REPS,
        "max_inflight": SERVING_PIPE_INFLIGHT,
        "serial_rows_per_sec": round(n_rows / best["serial"], 2),
        "pipelined_rows_per_sec": round(n_rows / best["pipelined"], 2),
        # the headline: ratio of each mode's best wall (standard best-of-N —
        # the pipeline's overlap needs the second core, so a neighbor on
        # this shared box collapses individual reps; each mode's best rep is
        # its least-contended measurement). Per-pair ratios + the median are
        # committed alongside so the spread stays visible.
        "pipelined_over_serial": round(best["serial"] / best["pipelined"], 3),
        "pipelined_over_serial_median_pair": round(median_ratio, 3),
        "pipelined_over_serial_pairs": [round(r, 3) for r in ratios],
        "bitwise_identical": bool(bitwise),
        "post_warmup_aot_misses": int(spd["aot_misses"]),
        "post_warmup_recompiles": int(spd["persistent_cache_misses"]),
    }

    # -- the fleet step: replica scaling through the network tier -----------
    replica_scaling = _bench_replica_scaling(cfg, state)

    out = {
        "metric": "online serving: dynamic micro-batching over AOT warm "
                  "paths (IWAE-k50-2L score)",
        "unit": "rows/sec + per-bucket tail latency",
        "buckets": list(eng.ladder.buckets),
        "k": K,
        "cold_dispatch_seconds": round(cold_s, 4),
        "warm_single_request_p50_seconds": round(warm_p50, 6),
        "warm_single_request_p95_seconds": round(lat[int(len(lat) * 0.95)], 6),
        # the acceptance bar: warm single-request score <= cold/10
        "warm_over_cold": round(warm_p50 / cold_s, 6),
        "warmup": warm_info,
        "load_sweep": levels,
        "pipeline_comparison": pipe_cmp,
        "replica_scaling": replica_scaling,
        # serving-phase roofline: closed-loop MFU + which hot-loop path the
        # warmed score programs traced with (ops/hot_loop.PATH_CODES)
        "mfu": serving_mfu,
        "mfu_config": {"peak_flops": peak, "peak_flops_source": peak_source,
                       "flops_per_row": row_flops,
                       "numerator": "analytic matmul FLOPs, forward only"},
        "kernel_path": snap["kernel_path"],
        "kernel_path_counters": path_counters(),
        "p99_per_bucket_seconds": p99,
        "padding_waste": round(snap["padding_waste"], 4),
        # zero-recompile proof across the whole post-warmup stream
        "post_warmup_aot_misses": int(d["aot_misses"]),
        "post_warmup_recompiles": int(d["persistent_cache_misses"]),
        "counters": snap["counters"],
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "serving_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


def bench_multi_model():
    """``--multi-model``: the capacity-bounded executable store under a
    round-robin multi-model ragged stream (ISSUE 13).

    Three distinct tiny models (different architectures = genuinely
    distinct programs) are served by model-labeled engines sharing the ONE
    process executable store, under three budgets:

    * ``unbounded`` — the historical behavior: every dispatch a store hit;
    * ``fits_all``  — an explicit budget sized to the full working set:
      must behave identically (0 evictions, 0 compiles, bitwise parity);
    * ``fits_half`` — half the working set: every model switch churns
      (LRU evictions, demotions to the persistent XLA cache, readmits),
      yet the stream performs ZERO fresh XLA compiles and stays bitwise
      identical to dedicated single-model engines.

    Reported per leg: hit rate, eviction churn, the warm-hit vs
    warm-readmit latency split against the cold-compile cost, counter
    reconciliation (hits + misses == dispatches), and the parity bit.
    Committed to results/multi_model_bench.json.
    """
    import jax

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.utils import compile_cache as cc

    D = 24
    cfgs = {
        "zoo-a": model.ModelConfig(x_dim=D, n_hidden_enc=(16,),
                                   n_latent_enc=(6,), n_hidden_dec=(16,),
                                   n_latent_dec=(D,)),
        "zoo-b": model.ModelConfig(x_dim=D, n_hidden_enc=(12, 8),
                                   n_latent_enc=(8, 4),
                                   n_hidden_dec=(8, 12),
                                   n_latent_dec=(8, D)),
        "zoo-c": model.ModelConfig(x_dim=D, n_hidden_enc=(20,),
                                   n_latent_enc=(10,), n_hidden_dec=(20,),
                                   n_latent_dec=(D,)),
    }
    names = list(cfgs)
    params = {n: model.init_params(jax.random.PRNGKey(i), cfgs[n])
              for i, n in enumerate(names)}

    def make_engine(name, label):
        # serial engines (max_inflight=0): each request's wall time is the
        # full dispatch+fetch, so per-request latency classifies cleanly
        # by what the store did for it
        return ServingEngine(params=params[name], model_config=cfgs[name],
                             k=4, max_batch=4, max_inflight=0,
                             timeout_s=None, model=label)

    # the round-robin ragged stream: model switches EVERY request (the
    # worst case for a bounded store), sizes cycle 1/3/2/4, seeds explicit
    # so every leg is bitwise comparable
    rng = np.random.RandomState(0)
    sizes = [1, 3, 2, 4]
    n_requests = 48
    stream, seed = [], 0
    for i in range(n_requests):
        n = sizes[i % len(sizes)]
        rows = (rng.rand(n, D) > 0.5).astype(np.float32)
        stream.append((names[i % len(names)], rows,
                       list(range(seed, seed + n))))
        seed += n

    def run_stream(engines):
        """Blocking round-robin over the stream; returns (per-request
        walls+classification, results, stream-phase stats delta)."""
        s0 = cc.cache_stats()
        walls, results = [], []
        for name, rows, seeds in stream:
            e = engines[name]
            r0 = cc.cache_stats()
            t0 = time.perf_counter()
            futs = [e.submit("score", row, seed=s)
                    for row, s in zip(rows, seeds)]
            e.flush()
            vals = [float(f.result()) for f in futs]
            wall = time.perf_counter() - t0
            rd = cc.stats_delta(r0)
            kind = "warm_hit" if rd["store_misses"] == 0 else \
                ("readmit" if rd["store_readmits"] > 0 else "fresh_compile")
            walls.append((kind, wall))
            results.extend(vals)
        return walls, results, cc.stats_delta(s0)

    def lat_split(walls):
        out = {}
        for kind in ("warm_hit", "readmit", "fresh_compile"):
            ws = sorted(w for k_, w in walls if k_ == kind)
            out[kind] = {
                "requests": len(ws),
                "p50_ms": round(1e3 * ws[len(ws) // 2], 3) if ws else None,
                "mean_ms": round(1e3 * sum(ws) / len(ws), 3) if ws else None,
            }
        return out

    # ---- reference leg: dedicated single-model engines, unbounded
    with cc.isolated_aot_registry(budget_bytes=None):
        engines = {n: make_engine(n, label=None) for n in names}
        for e in engines.values():
            e.warmup(ops=("score",))
        _, ref_results, _ = run_stream(engines)

    # ---- the TRUE cold-compile denominator: a FOURTH model (an arch this
    # process has never compiled, so neither JAX's in-memory HLO cache nor
    # the suspended persistent cache can serve it) — what a store miss
    # would cost WITHOUT the cold tier, i.e. the figure warm readmits must
    # sit well under
    fresh_cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(14, 10),
                                  n_latent_enc=(7, 5),
                                  n_hidden_dec=(10, 14),
                                  n_latent_dec=(7, D))
    fresh_params = model.init_params(jax.random.PRNGKey(99), fresh_cfg)
    with cc.suspended_persistent_cache():
        with cc.isolated_aot_registry(budget_bytes=None):
            f0 = cc.cache_stats()
            ServingEngine(params=fresh_params, model_config=fresh_cfg,
                          k=4, max_batch=4, max_inflight=0, timeout_s=None,
                          model="zoo-fresh").warmup(ops=("score",))
            fd = cc.stats_delta(f0)
            fresh_compile_s = fd["aot_compile_seconds"] / \
                max(fd["aot_misses"], 1)

    legs = {}
    working_set = None
    cold_compile_s = None
    for leg in ("unbounded", "fits_all", "fits_half"):
        if leg == "unbounded":
            budget = None
        elif leg == "fits_all":
            budget = working_set + 1
        else:
            budget = working_set // 2
        with cc.isolated_aot_registry(budget_bytes=budget):
            engines = {n: make_engine(n, n) for n in names}
            w0 = cc.cache_stats()
            for e in engines.values():
                e.warmup(ops=("score",))
            wd = cc.stats_delta(w0)
            if leg == "unbounded":
                working_set = cc.store_stats()["resident_bytes"]
                # the cold-compile denominator: measured wall per program
                # on this leg's (possibly disk-warm) first compile
                cold_compile_s = wd["aot_compile_seconds"] / \
                    max(wd["aot_misses"], 1)
            walls, results, d = run_stream(engines)
            # the INDEPENDENT dispatch denominator: the engines' own
            # per-batch metric counters (fresh engines, so the absolute
            # count is this leg's stream) — a store that dropped resolves
            # on the floor would fail this, unlike hits+misses vs itself
            engine_dispatches = int(sum(
                e.metrics.counters()["dispatches"]
                for e in engines.values()))
        dispatches = d["store_hits"] + d["store_misses"]
        parity = all(a == b for a, b in zip(results, ref_results)) and \
            len(results) == len(ref_results)
        legs[leg] = {
            "budget_bytes": budget,
            "working_set_bytes": working_set,
            "stream": {
                "dispatches": engine_dispatches,
                "hits": d["store_hits"], "misses": d["store_misses"],
                "evictions": d["store_evictions"],
                "demotions": d["store_demotions"],
                "readmits": d["store_readmits"],
                "hit_rate": round(d["store_hits"] / dispatches, 4)
                if dispatches else None,
                "fresh_xla_compiles": d["persistent_cache_misses"],
                # every engine dispatch is accounted by the store: one
                # resolve (hit or miss) per dispatched batch, checked
                # against the engines' OWN dispatch counters
                "counters_account_every_dispatch":
                    engine_dispatches == dispatches,
            },
            "latency_split": lat_split(walls),
            "bitwise_parity_vs_dedicated_engines": parity,
        }

    # the acceptance asserts, in-process so a regression fails the bench
    assert legs["fits_all"]["stream"]["evictions"] == 0, legs["fits_all"]
    assert legs["fits_all"]["stream"]["misses"] == 0, legs["fits_all"]
    assert legs["fits_half"]["stream"]["evictions"] > 0, legs["fits_half"]
    assert legs["fits_half"]["stream"]["readmits"] > 0, legs["fits_half"]
    for leg in legs.values():
        assert leg["bitwise_parity_vs_dedicated_engines"], leg
        assert leg["stream"]["fresh_xla_compiles"] == 0, leg
        assert leg["stream"]["counters_account_every_dispatch"], leg
    readmit_ms = legs["fits_half"]["latency_split"]["readmit"]["p50_ms"]
    assert readmit_ms is not None and \
        readmit_ms < 1e3 * fresh_compile_s, \
        (readmit_ms, fresh_compile_s)   # warm readmit << fresh compile

    out = {
        "metric": "multi-tenant executable store: round-robin 3-model "
                  "ragged stream under {unbounded, fits-all, fits-half} "
                  "budgets",
        "models": names,
        "requests_per_leg": n_requests,
        "cold_start_compile_seconds_per_program": round(cold_compile_s, 4),
        "fresh_compile_seconds_per_program_no_cache": round(
            fresh_compile_s, 4),
        "readmit_speedup_over_fresh_compile": round(
            1e3 * fresh_compile_s / readmit_ms, 1),
        "budgets": legs,
        "note": "warm-readmit latency is in-process: JAX's in-memory "
                "HLO-keyed compilation layer serves re-lowered programs "
                "without touching disk; across processes the persistent "
                "XLA cache is the cold tier (fresh_xla_compiles==0 is the "
                "pinned contract either way). Latencies are CPU-CI "
                "figures; the TPU bench round regenerates.",
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "multi_model_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


LARGE_K_SWEEP = (50, 500, 5000)   # the paper-grade k ladder (5000 = the
                                  # flagship NLL, arXiv:1509.00519)
LARGE_K_CHUNK = 250               # the production eval chunk (EVAL_CHUNK)
LARGE_K_REPS = {50: 6, 500: 4, 5000: 3}
LARGE_K_SCALING_DEVICES = (1, 2)  # child-process sp sweep (forced host
                                  # devices on CPU; real chips on TPU)


def _large_k_engine(params, cfg, mesh, **kw):
    from iwae_replication_project_tpu.serving import ShardedScoreEngine

    kw.setdefault("k_chunk", LARGE_K_CHUNK)
    kw.setdefault("k_max", max(LARGE_K_SWEEP))
    kw.setdefault("k", K)
    kw.setdefault("max_batch", 4)
    kw.setdefault("timeout_s", None)
    return ShardedScoreEngine(params=params, model_config=cfg, mesh=mesh,
                              **kw)


def _large_k_child(n_devices: int) -> None:
    """``--large-k-child N``: one device-scaling leg in its own process
    (the parent respawns with ``xla_force_host_platform_device_count=N`` on
    CPU; on a TPU host the same harness sees real chips). Warms a
    ``(dp=1, sp=N)`` sharded engine and times warm k=5000 single-row
    requests; prints one JSON line."""
    import jax

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.parallel import make_mesh
    from iwae_replication_project_tpu.training import create_train_state

    cfg = ModelConfig.two_layer(likelihood="logits")
    params = create_train_state(jax.random.PRNGKey(0), cfg).params
    mesh = make_mesh(dp=1, sp=n_devices)
    eng = _large_k_engine(params, cfg, mesh, max_batch=1)
    eng.warmup()
    x = make_data(1)[0]
    k = max(LARGE_K_SWEEP)
    eng.score(x, k=k)                      # one untimed warm pass
    walls = []
    for _ in range(LARGE_K_REPS[k]):
        t0 = time.perf_counter()
        eng.score(x, k=k)
        walls.append(time.perf_counter() - t0)
    walls.sort()
    print(json.dumps({"devices": n_devices, "k": k,
                      "mesh": {"dp": 1, "sp": n_devices},
                      "p50_seconds": round(walls[len(walls) // 2], 4),
                      "best_seconds": round(walls[0], 4),
                      "walls": [round(w, 4) for w in walls]}))


def bench_large_k():
    """``--large-k``: the distributed large-k scoring service profile
    (serving/sharded.py — ISSUE 9).

    Measures, on the flagship 2L architecture:

    * **warm per-request latency across the k ladder** — p50/p95 of warm
      single-row ``score`` requests at k in LARGE_K_SWEEP through the
      mesh-backed sharded engine, PLUS the single-device fast path at k=50
      (the class the router keeps below the threshold) — the
      tighter-vs-slower tradeoff (arXiv:1802.04537) as a measured curve;
    * **bitwise offline parity** — the engine's k=5000 answer vs the
      offline ``parallel/eval.sharded_score_offline`` scorer (which calls
      the same program: serving IS the paper's evaluation);
    * **zero-recompile proof over a ragged (batch, k) stream** — k is a
      dynamic scalar, so one executable per batch bucket covers the whole
      sweep; ``cache_stats`` delta must be zero after warmup;
    * **per-k serving MFU** — analytic per-row FLOPs (utils/flops) over
      the chip peak (null + reason on hosts without a peak entry);
    * **device-scaling curve** — child processes at
      LARGE_K_SCALING_DEVICES forced host devices, each timing warm k=5000
      requests on a ``(1, sp)`` mesh. On this CPU box the fake devices
      share the physical core(s), so the curve measures SHARDING OVERHEAD
      (recorded honestly as such); on hardware with one chip per sp slot
      the same harness reports the real speedup.

    Prints one JSON line and writes results/large_k_bench.json.
    """
    import subprocess
    import sys

    import jax

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.parallel import make_mesh
    from iwae_replication_project_tpu.parallel.eval import (
        sharded_score_offline)
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)
    from iwae_replication_project_tpu.utils.flops import (
        serving_score_flops_per_row)

    cfg = ModelConfig.two_layer(likelihood="logits")
    params = create_train_state(jax.random.PRNGKey(0), cfg).params
    mesh = make_mesh()                   # this host's devices (CPU CI: 1x1)
    eng = _large_k_engine(params, cfg, mesh)
    warm_info = eng.warmup()
    peak, peak_source = peak_flops()
    x = make_data(8)

    # -- the k ladder: warm per-request latency + per-k MFU -----------------
    s0 = cache_stats()
    per_k = {}
    for k in LARGE_K_SWEEP:
        eng.score(x[0], k=k)             # untimed: the first k touches
        walls = []                       # nothing cold but the jit cache
        for r in range(LARGE_K_REPS[k]):
            t0 = time.perf_counter()
            eng.score(x[r % 8], k=k)
            walls.append(time.perf_counter() - t0)
        walls.sort()
        p50 = walls[len(walls) // 2]
        row_flops = serving_score_flops_per_row(cfg, k)
        per_k[str(k)] = {
            "reps": len(walls),
            "p50_seconds": round(p50, 4),
            "p95_seconds": round(walls[min(len(walls) - 1,
                                           int(len(walls) * 0.95))], 4),
            "best_seconds": round(walls[0], 4),
            "flops_per_row": row_flops,
            "mfu": (round(row_flops / (p50 * peak), 6) if peak else None),
        }

    # -- ragged (batch, k) stream: the zero-recompile proof -----------------
    futures = []
    for n, k in ((1, 50), (3, 500), (2, 50), (4, 5000), (1, 4999),
                 (2, 500)):
        futures.extend(eng.submit("score", row, k=k) for row in x[:n])
    eng.flush()
    for f in futures:
        f.result()
    # delta taken HERE so it covers exactly the sharded engine's post-
    # warmup activity (the k ladder + the ragged stream), not the fast-
    # path reference engine's own warmup below
    d = stats_delta(s0)

    # -- bitwise offline parity at k=5000 -----------------------------------
    seed = eng._seed_counter
    got = eng.score(x[0], k=max(LARGE_K_SWEEP))
    off = np.asarray(sharded_score_offline(
        params, eng.cfg, mesh, eng._base_key,
        np.array([seed], np.int32), x[0][None], max(LARGE_K_SWEEP),
        k_chunk=LARGE_K_CHUNK))[0]
    parity = bool(np.array_equal(np.asarray(got), off))

    # the fast-path reference class (what the router serves below the
    # threshold): a plain single-device engine at the training k
    fast = ServingEngine(params=params, model_config=cfg, k=K, max_batch=4,
                         timeout_s=None)
    fast.warmup(ops=("score",))
    fast.score(x[0])
    walls = []
    for r in range(LARGE_K_REPS[50]):
        t0 = time.perf_counter()
        fast.score(x[r % 8])
        walls.append(time.perf_counter() - t0)
    walls.sort()
    fast_p50 = walls[len(walls) // 2]

    # -- device-scaling curve (child processes, forced device counts) -------
    scaling = []
    for n_dev in LARGE_K_SCALING_DEVICES:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu" if jax.devices()[0].platform == "cpu" \
            else env.get("JAX_PLATFORMS", "")
        if jax.devices()[0].platform == "cpu":
            flags = env.get("XLA_FLAGS", "")
            flags = " ".join(p for p in flags.split()
                             if "host_platform_device_count" not in p)
            env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_"
                                        f"device_count={n_dev}").strip()
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--large-k-child", str(n_dev)],
            capture_output=True, text=True, env=env, timeout=1800)
        if r.returncode != 0:
            scaling.append({"devices": n_dev,
                            "error": r.stderr[-500:] or "child failed"})
            continue
        scaling.append(json.loads(
            [ln for ln in r.stdout.splitlines() if ln.startswith("{")][-1]))
    on_cpu = jax.devices()[0].platform == "cpu"
    ok_legs = [s for s in scaling if "best_seconds" in s]
    curve_note = (
        "CPU host: forced host devices share the physical core(s), so this "
        "curve measures sharding OVERHEAD, not speedup — on hardware with "
        "one chip per sp slot the same harness reports the real curve"
        if on_cpu else
        "one device per sp slot: wall ratio vs 1 device is the sp-scaling "
        "speedup")

    snap = eng.metrics.snapshot()
    out = {
        "metric": "distributed large-k scoring service (sharded score over "
                  "the (dp, sp) mesh behind the serving API)",
        "unit": "warm per-request seconds across the k ladder",
        "mesh": {ax: int(n) for ax, n in mesh.shape.items()},
        "k_chunk": LARGE_K_CHUNK,
        "k_max": max(LARGE_K_SWEEP),
        "buckets": list(eng.ladder.buckets),
        "warmup": warm_info,
        "per_k": per_k,
        "fast_path_k50_p50_seconds": round(fast_p50, 4),
        # the engine-vs-offline acceptance pin: same program, same mesh,
        # same seed -> bit-identical log p-hat(x)
        "bitwise_parity_vs_offline_scorer": parity,
        # the tentpole warm-path proof: a ragged stream in BOTH batch and k
        # after warmup compiles nothing (k is a dynamic scalar)
        "ragged_batch_k_stream_rows": len(futures),
        "post_warmup_aot_misses": int(d["aot_misses"]),
        "post_warmup_recompiles": int(d["persistent_cache_misses"]),
        "device_scaling": {
            "legs": scaling,
            "note": curve_note,
            "speedup_vs_1dev": (
                round(ok_legs[0]["best_seconds"] / ok_legs[-1]
                      ["best_seconds"], 3)
                if len(ok_legs) >= 2 else None),
        },
        "mfu_config": {"peak_flops": peak,
                       "peak_flops_source": peak_source,
                       "numerator": "analytic matmul FLOPs, forward only"},
        "counters": snap["counters"],
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "large_k_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


ADAPTIVE_ROWS = int(os.environ.get("BENCH_ADAPTIVE_ROWS", "12"))
ADAPTIVE_REPS = int(os.environ.get("BENCH_ADAPTIVE_REPS", "2"))


def bench_adaptive_k():
    """``--adaptive-k``: accuracy-targeted scoring vs fixed k=5000 at equal
    achieved standard error (serving/engine ``score_adaptive`` — ISSUE 20).

    Over a mixed easy/hard row pool (binarized data-like rows next to
    degenerate near-constant rows, whose log-weight variance differs by
    construction), measures:

    * **fixed leg** — warm ``score`` at k=5000 for every row: wall-clock
      p50 over reps, total samples = rows x 5000, and the per-row SE the
      fixed budget actually ACHIEVED (read off one ``score_adaptive`` pass
      with an unreachable target, which runs to the cap and reports SE —
      its log p-hat is bitwise the fixed-k answer, the prefix contract);
    * **adaptive leg** — ``score_adaptive`` with ``target_se`` set to the
      fixed leg's WORST per-row achieved SE (so the comparison is at
      equal-or-better accuracy on every row): wall-clock p50, total
      samples = sum of measured k_used, per-row k_used histogram;
    * **the prefix-contract spot check** — an adaptive row's log p-hat ==
      the plain fixed-k score at k=k_used under the same seed, bitwise;
    * **zero recompiles** — both legs ride the warm executables
      (``cache_stats`` delta must be zero after warmup).

    Prints one JSON line and writes results/adaptive_k_bench.json. Sizes
    shrink via ``BENCH_ADAPTIVE_ROWS`` / ``BENCH_ADAPTIVE_REPS``.
    """
    import jax

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.parallel import make_mesh
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    cfg = ModelConfig.two_layer(likelihood="logits")
    params = create_train_state(jax.random.PRNGKey(0), cfg).params
    mesh = make_mesh()
    k_cap = max(LARGE_K_SWEEP)
    eng = _large_k_engine(params, cfg, mesh, max_batch=4)
    eng.warmup()

    # mixed difficulty by construction: ordinary binarized rows next to
    # near-constant rows (all-dark with a few hot pixels), whose posterior
    # is far from the prior and whose weights are heavy-tailed
    n = max(2, ADAPTIVE_ROWS)
    easy = make_data(n - n // 2)
    rng = np.random.RandomState(1)
    hard = np.zeros((n // 2, 784), np.float32)
    hard[np.arange(n // 2)[:, None],
         rng.randint(0, 784, size=(n // 2, 20))] = 1.0
    rows = np.concatenate([easy, hard], axis=0)
    seeds = list(range(n))

    def run_rows(op, k, **kw):
        futs = [eng.submit(op, r, k=k, seed=s, **kw)
                for s, r in zip(seeds, rows)]
        eng.flush()
        return np.stack([np.asarray(f.result()) for f in futs])

    s0 = cache_stats()
    # the fixed leg's achieved accuracy: run to the cap (unreachable
    # target), read the per-row SE off the augmented carry
    fixed_stats = run_rows("score_adaptive", k_cap, target_se=1e-9)
    fixed_se = fixed_stats[:, 1]
    assert int(fixed_stats[:, 2].max()) == k_cap
    target = float(fixed_se.max())    # equal-or-better SE on EVERY row

    fixed_walls, adaptive_walls = [], []
    for _ in range(max(1, ADAPTIVE_REPS)):
        t0 = time.perf_counter()
        fixed_out = run_rows("score", k_cap)
        fixed_walls.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        adaptive_out = run_rows("score_adaptive", k_cap, target_se=target)
        adaptive_walls.append(time.perf_counter() - t0)
    fixed_walls.sort()
    adaptive_walls.sort()
    k_used = adaptive_out[:, 2]
    # bitwise prefix spot check: the early-stopped row's bound IS the
    # fixed-k bound at k=k_used under the same seed
    i = int(np.argmin(k_used))
    pf = eng.submit("score", rows[i], k=int(k_used[i]), seed=seeds[i])
    eng.flush()
    prefix_ok = bool(
        np.float32(adaptive_out[i, 0]) == np.asarray(pf.result()))
    assert np.array_equal(fixed_out, fixed_stats[:, 0]), \
        "score_adaptive at an unreachable target must reproduce fixed-k bits"
    d = stats_delta(s0)

    total_fixed = n * k_cap
    total_adaptive = int(k_used.sum())
    hist = {str(int(v)): int(c)
            for v, c in zip(*np.unique(k_used, return_counts=True))}
    out = {
        "metric": "adaptive-k scoring vs fixed k=5000 at equal achieved SE",
        "unit": "total samples drawn (and warm wall-clock seconds)",
        "mesh": {ax: int(m) for ax, m in mesh.shape.items()},
        "rows": {"n": n, "easy": n - n // 2, "hard": n // 2},
        "k_cap": k_cap,
        "k_chunk": LARGE_K_CHUNK,
        "target_se": target,
        "fixed": {
            "total_samples": total_fixed,
            "wall_p50_seconds": round(
                fixed_walls[len(fixed_walls) // 2], 4),
            "achieved_se": {"max": round(float(fixed_se.max()), 6),
                            "mean": round(float(fixed_se.mean()), 6)},
        },
        "adaptive": {
            "total_samples": total_adaptive,
            "wall_p50_seconds": round(
                adaptive_walls[len(adaptive_walls) // 2], 4),
            "achieved_se": {
                "max": round(float(adaptive_out[:, 1].max()), 6),
                "mean": round(float(adaptive_out[:, 1].mean()), 6)},
            "k_used_histogram": hist,
            "k_used": {"min": int(k_used.min()), "max": int(k_used.max()),
                       "mean": round(float(k_used.mean()), 1)},
        },
        "sample_savings": round(1.0 - total_adaptive / total_fixed, 4),
        "wall_ratio_adaptive_over_fixed": round(
            adaptive_walls[len(adaptive_walls) // 2]
            / fixed_walls[len(fixed_walls) // 2], 3),
        "prefix_contract_bitwise": prefix_ok,
        "post_warmup_aot_misses": int(d["aot_misses"]),
        "post_warmup_recompiles": int(d["persistent_cache_misses"]),
        "caveats": [
            "CPU host: wall-clock tracks total samples only loosely — "
            "dispatch/merge overhead is a larger fraction of each request "
            "than on an accelerator, so the wall ratio understates the "
            "on-chip win the sample ratio predicts",
            "stopping is quantized to the sp*k_chunk block grid, so "
            "per-row savings round DOWN to the nearest grid multiple",
            "weights are random-init (no trained checkpoint in CI): the "
            "easy/hard split and the histogram SHAPE are the point, not "
            "absolute NLL values",
        ],
        "counters": eng.metrics.snapshot()["counters"],
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "adaptive_k_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


TELEMETRY_REPS = 5  # per mode; the off-vs-baseline delta must sit inside
                    # the rep-to-rep spread (noise), per the telemetry PR bar


def bench_telemetry():
    """``--telemetry``: train-step overhead of on-device diagnostics, off vs on.

    Three epoch programs on the flagship 2L IWAE-k50 shape, same data/key:

    * **baseline** — ``make_epoch_fn`` without a diagnostics argument (the
      pre-telemetry call shape);
    * **off** — ``DiagnosticsConfig(enabled=False)`` passed explicitly: must
      build the byte-identical program, so its throughput differs from
      baseline only by run noise;
    * **on** — ``DiagnosticsConfig(enabled=True)``: grad-moment accumulation
      over the trailing ``snr_window`` steps inside the scan, plus the
      per-eval estimator-diagnostics program measured separately.

    Prints one JSON line and writes results/telemetry_bench.json. Sizes
    shrink via ``BENCH_TELEMETRY_N_TRAIN`` for constrained hosts.
    """
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.telemetry.diagnostics import (
        DiagnosticsConfig, estimator_diagnostics)
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.training.epoch import make_epoch_fn

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    n_train = int(os.environ.get("BENCH_TELEMETRY_N_TRAIN", 25600))
    cfg = ModelConfig.two_layer(likelihood="logits", fused_likelihood=on_tpu,
                                compute_dtype="bfloat16")
    spec = ObjectiveSpec("IWAE", k=K)
    x = jnp.asarray(make_data(n_train))
    steps = n_train // BATCH

    def build(diagnostics):
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        epoch = make_epoch_fn(spec, cfg, n_train, BATCH, donate=False,
                              diagnostics=diagnostics)
        out = epoch(state, x)            # compile + warmup
        jax.block_until_ready(out)
        return [epoch, out[0]]

    # all three programs compile first, then the reps run ROUND-ROBIN across
    # modes: slow host-load drift (thermal, co-tenants) hits every mode
    # equally instead of biasing whichever mode was measured last
    modes = {"baseline": build(None),
             "off": build(DiagnosticsConfig(enabled=False)),
             "on": build(DiagnosticsConfig(enabled=True, snr_window=50))}
    rs = {name: [] for name in modes}
    for _ in range(TELEMETRY_REPS):
        for name, slot in modes.items():
            epoch, state = slot
            t0 = time.perf_counter()
            out = epoch(state, x)
            jax.block_until_ready(out)   # honest completion sync
            rs[name].append(steps / (time.perf_counter() - t0))
            slot[1] = out[0]
    r_base, r_off, r_on = rs["baseline"], rs["off"], rs["on"]

    # the per-eval weight-space diagnostics program, timed on its own: it
    # rides the eval cadence (once per stage), not the train hot path
    diag = DiagnosticsConfig(enabled=True, snr_window=50)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    eb = jnp.asarray(make_data(2000)).reshape(-1, EVAL_BATCH, 784)
    key = jax.random.PRNGKey(1)
    jax.block_until_ready(estimator_diagnostics(
        state.params, cfg, key, eb, K, diag))
    t0 = time.perf_counter()
    jax.block_until_ready(estimator_diagnostics(
        state.params, cfg, key, eb, K, diag))  # iwaelint: disable=key-reuse -- timing rep deliberately re-runs the IDENTICAL program (same key) so only dispatch variance is measured
    diag_eval_s = time.perf_counter() - t0

    base, off, on = (float(np.mean(r)) for r in (r_base, r_off, r_on))
    noise = (max(r_base) - min(r_base)) / base
    off_delta = abs(off - base) / base
    out = {
        "metric": "train-step overhead of on-device estimator diagnostics "
                  "(IWAE-k50-2L, whole-epoch scan)",
        "unit": "steps/sec",
        "n_train": n_train, "batch": BATCH, "k": K,
        "reps": TELEMETRY_REPS,
        "steps_per_sec_baseline": round(base, 2),
        "steps_per_sec_diag_off": round(off, 2),
        "steps_per_sec_diag_on": round(on, 2),
        "spread_baseline": {"min": round(min(r_base), 2),
                            "max": round(max(r_base), 2)},
        "spread_off": {"min": round(min(r_off), 2),
                       "max": round(max(r_off), 2)},
        "spread_on": {"min": round(min(r_on), 2),
                      "max": round(max(r_on), 2)},
        # the acceptance bar: off-mode == pre-PR program, so its delta vs
        # baseline must be indistinguishable from run noise
        "off_vs_baseline_rel_delta": round(off_delta, 4),
        "run_noise_rel": round(noise, 4),
        "off_within_noise": bool(off_delta <= max(noise, 0.02)),
        "on_overhead_pct": round((base - on) / base * 100.0, 2),
        "eval_diagnostics_seconds_per_eval": round(diag_eval_s, 4),
        "snr_window": 50,
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "telemetry_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


TRACING_ROWS = 240          # requests per closed-loop rep (ragged singles)
TRACING_REPS = 5            # paired, order-alternated reps per mode


def bench_tracing():
    """``--tracing``: measured overhead of end-to-end request tracing.

    Two serving tiers over the SAME tiny weights — tracing off vs tracing
    on (every request minting a client root span, riding the wire
    ``trace`` field, and fanning out tier/router/engine stage spans into a
    tail-sampled flight recorder) — fed the identical pipelined
    closed-loop request stream over a real socket.  A deliberately small
    architecture keeps each dispatch host-dominated, so the per-request
    tracing cost is measured at its WORST case, not hidden under device
    time.

    Committed claims (results/tracing_bench.json):

    * **bitwise parity** — per-request results identical across modes
      (tier admission-order seeds; tracing is host-side metadata only);
    * **overhead** — rows/sec per mode, the median paired wall ratio, and
      the per-request cost in microseconds;
    * **recorder accounting** — traces started/finalized/retained under
      the default tail-sampling policy (errors + slow tail + 1-in-N), and
      the SLO burn-rate gauges the traced tier published.
    """
    import jax

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)
    from iwae_replication_project_tpu.telemetry.tracing import FlightRecorder

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8),
                            n_latent_enc=(8, 4), n_hidden_dec=(8, 16),
                            n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    sizes = (1, 3, 2, 1)        # small ragged singles: request-path stress
    rows = [((rng.rand(sizes[i % len(sizes)], D) > 0.5)
             .astype(np.float32)).tolist() for i in range(TRACING_ROWS)]

    def build(tracing):
        rec = FlightRecorder() if tracing else None
        engines = [ServingEngine(params=params, model_config=cfg, k=4,
                                 max_batch=8, max_inflight=2,
                                 timeout_s=None) for _ in range(2)]
        tier = ServingTier(engines, port=0, tracing=tracing, recorder=rec)
        tier.warmup(ops=("score",))
        tier.start()
        cli = TierClient("127.0.0.1", tier.port, trace=tracing,
                         recorder=rec)
        return {"tier": tier, "cli": cli, "rec": rec, "walls": [],
                "out": None}

    def closed_loop(slot):
        cli = slot["cli"]
        t0 = time.perf_counter()
        ids = [cli.submit("score", x) for x in rows]
        resp = cli.drain(ids)
        wall = time.perf_counter() - t0
        assert all(resp[rid]["ok"] for rid in ids), "tracing bench errored"
        return wall, [resp[rid]["result"] for rid in ids]

    modes = {"off": build(False), "on": build(True)}
    # untimed warm round per mode (thread spawn, allocator), then paired
    # reps alternating order so machine noise hits both modes evenly;
    # seeds advance identically (same submit count per round), so round j
    # stays bitwise-comparable across modes
    for rep in range(-1, TRACING_REPS):
        order = list(modes) if rep % 2 else list(modes)[::-1]
        for name in order:
            wall, out = closed_loop(modes[name])
            if rep < 0:
                modes[name]["out"] = out
            else:
                modes[name]["walls"].append(wall)
                modes[name]["out_last"] = out
    import statistics
    bitwise = modes["off"]["out"] == modes["on"]["out"] and \
        modes["off"]["out_last"] == modes["on"]["out_last"]
    ratios = sorted(off / on for off, on in zip(modes["off"]["walls"],
                                                modes["on"]["walls"]))
    median_ratio = statistics.median(ratios)
    best = {name: min(slot["walls"]) for name, slot in modes.items()}
    rec = modes["on"]["rec"]
    slo_snap = modes["on"]["tier"].slo.snapshot()
    for slot in modes.values():
        slot["cli"].close()
        slot["tier"].stop(timeout_s=30)

    per_req_us = (best["on"] - best["off"]) / TRACING_ROWS * 1e6
    out = {
        "metric": "end-to-end request-tracing overhead "
                  "(tiny score model, pipelined closed loop over TCP)",
        "unit": "rows/sec + paired wall ratio (off/on; < 1 means tracing "
                "costs time)",
        "requests_per_rep": TRACING_ROWS,
        "reps": TRACING_REPS,
        "rows_per_sec_tracing_off": round(TRACING_ROWS / best["off"], 2),
        "rows_per_sec_tracing_on": round(TRACING_ROWS / best["on"], 2),
        # best-of walls (least-contended measurement on this shared box);
        # the per-pair ratios + median keep the spread visible
        "off_over_on_best": round(best["off"] / best["on"], 4),
        "off_over_on_median_pair": round(median_ratio, 4),
        "off_over_on_pairs": [round(r, 4) for r in ratios],
        "overhead_pct_best": round(
            (best["on"] - best["off"]) / best["off"] * 100.0, 2),
        "overhead_us_per_request_best": round(per_req_us, 1),
        "bitwise_identical": bool(bitwise),
        "recorder": rec.stats(),
        "slo": {key: doc["windows"]["5m"]
                for key, doc in slo_snap.items()},
        "note": "worst-case overhead by construction: host-dominated tiny "
                "model, single-row requests; production dispatches "
                "amortize the same per-request cost over real device time",
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "tracing_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


PROFILING_ROWS = 240        # requests per closed-loop rep (ragged singles)
PROFILING_REPS = 5          # paired, order-alternated reps per mode


def bench_profiling():
    """``--profiling``: measured overhead of the continuous profiling plane.

    Two pipelined serving engines over the SAME tiny weights — profiling
    off vs profiling on (the completion thread attributing every
    dispatch's device interval, computing measured MFU/bandwidth against
    explicit roofline peaks, and running the EWMA drift test) — fed the
    identical closed-loop single-row request stream.  The tiny
    host-dominated model measures the per-dispatch profiler cost at its
    WORST case, exactly like ``--tracing``.

    Committed claims (results/profiling_bench.json):

    * **bitwise parity** — results identical across modes (profiling is
      completion-thread metadata only: no extra sync, no program change);
    * **overhead** — rows/sec per mode, the median paired wall ratio, and
      the per-request cost in microseconds;
    * **attribution accounting** — dispatches/keys attributed and the
      drift detector's finding count (zero on a clean run).
    """
    import jax

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.telemetry.profiling import (
        ProfilingConfig)

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8),
                            n_latent_enc=(8, 4), n_hidden_dec=(8, 16),
                            n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    xs = (rng.rand(PROFILING_ROWS, D) > 0.5).astype(np.float32)

    def build(profiling):
        # explicit peaks: CPU has no chip-table entry, and the MFU gauge
        # math must run in the measured leg (it is part of the cost)
        prof = ProfilingConfig(peak_flops=1e12, peak_hbm_bytes=1e11) \
            if profiling else False
        eng = ServingEngine(params=params, model_config=cfg, k=4,
                            max_batch=8, max_inflight=2, timeout_s=None,
                            profiling=prof)
        eng.warmup(ops=("score",))
        eng.start()
        return {"eng": eng, "walls": [], "out": None}

    def closed_loop(slot):
        eng = slot["eng"]
        t0 = time.perf_counter()
        futs = [eng.submit("score", x) for x in xs]
        out = np.array([f.result() for f in futs])
        wall = time.perf_counter() - t0
        return wall, out

    modes = {"off": build(False), "on": build(True)}
    # untimed warm round per mode, then paired order-alternated reps so
    # machine noise hits both modes evenly; seeds advance identically
    # (same submit count per round), so round j stays bitwise-comparable
    for rep in range(-1, PROFILING_REPS):
        order = list(modes) if rep % 2 else list(modes)[::-1]
        for name in order:
            wall, out = closed_loop(modes[name])
            if rep < 0:
                modes[name]["out"] = out
            else:
                modes[name]["walls"].append(wall)
                modes[name]["out_last"] = out
    import statistics
    bitwise = (modes["off"]["out"].tobytes() == modes["on"]["out"].tobytes()
               and modes["off"]["out_last"].tobytes()
               == modes["on"]["out_last"].tobytes())
    ratios = sorted(off / on for off, on in zip(modes["off"]["walls"],
                                                modes["on"]["walls"]))
    median_ratio = statistics.median(ratios)
    best = {name: min(slot["walls"]) for name, slot in modes.items()}
    prof = modes["on"]["eng"].profiler
    snap = prof.snapshot()
    for slot in modes.values():
        slot["eng"].stop()

    per_req_us = (best["on"] - best["off"]) / PROFILING_ROWS * 1e6
    out = {
        "metric": "continuous-profiling overhead (tiny score model, "
                  "pipelined closed loop, per-dispatch attribution + "
                  "MFU + EWMA drift test on the completion thread)",
        "unit": "rows/sec + paired wall ratio (off/on; < 1 means "
                "profiling costs time)",
        "requests_per_rep": PROFILING_ROWS,
        "reps": PROFILING_REPS,
        "rows_per_sec_profiling_off": round(PROFILING_ROWS / best["off"], 2),
        "rows_per_sec_profiling_on": round(PROFILING_ROWS / best["on"], 2),
        # best-of walls (least-contended measurement on this shared box);
        # the per-pair ratios + median keep the spread visible
        "off_over_on_best": round(best["off"] / best["on"], 4),
        "off_over_on_median_pair": round(median_ratio, 4),
        "off_over_on_pairs": [round(r, 4) for r in ratios],
        "overhead_pct_best": round(
            (best["on"] - best["off"]) / best["off"] * 100.0, 2),
        "overhead_us_per_request_best": round(per_req_us, 1),
        "bitwise_identical": bool(bitwise),
        "attribution": {
            "keys": len(snap["keys"]),
            "dispatches": int(sum(st["count"]
                                  for st in snap["keys"].values())),
            "drift_findings": len(snap["findings"]),
            "mfu_live": any(st["last_mfu"] is not None
                            for st in snap["keys"].values()),
        },
        "note": "worst-case overhead by construction: host-dominated tiny "
                "model, single-row requests; production dispatches "
                "amortize the same per-dispatch cost over real device "
                "time",
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "profiling_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


MEMORY_CASES = ("flagship_train_dispatch", "eval_suite",
                "widest_scaling_shape")


def _memory_case(case: str) -> dict:
    """Run one ``--memory`` case in THIS process and return its row.

    ``peak_bytes_in_use`` is a process-lifetime high-water mark with no reset
    API, so each case must run in a fresh process (bench_memory spawns one
    per case) — otherwise every later row would just repeat the max over all
    earlier cases.
    """
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.evaluation.metrics import dataset_scalars
    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.training.epoch import make_epoch_fn

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    dev = jax.local_devices()[0]

    def stats():
        try:
            return dev.memory_stats() or {}
        except Exception:
            return {}

    n_train = int(os.environ.get("BENCH_MEMORY_N_TRAIN", N_TRAIN))
    eval_n = int(os.environ.get("BENCH_MEMORY_EVAL_N", EVAL_N))
    spec = ObjectiveSpec("IWAE", k=K)

    if case == "flagship_train_dispatch":
        # the whole-epoch scan with x_train resident in device memory
        cfg = scaled_config(200, on_tpu, compute_dtype="bfloat16")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        epoch = make_epoch_fn(spec, cfg, n_train, BATCH, donate=False)
        state, losses = epoch(state, jnp.asarray(make_data(n_train)))
        np.asarray(losses)
        row = {"case": case, "n_train": n_train, "batch": BATCH, "k": K}
    elif case == "eval_suite":
        # the production eval suite (batch 500 / chunk 250 / k=5000)
        cfg = scaled_config(200, on_tpu, compute_dtype="bfloat16")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        xe = jnp.asarray(make_data(eval_n)).reshape(-1, EVAL_BATCH, 784)
        np.asarray(dataset_scalars(state.params, cfg, jax.random.PRNGKey(1),
                                   xe, K, EVAL_K, EVAL_CHUNK))
        row = {"case": case, "n_images": eval_n, "batch": EVAL_BATCH,
               "nll_k": EVAL_K, "chunk": EVAL_CHUNK}
    elif case == "widest_scaling_shape":
        # the widest scaling-sweep shape (hidden 2048, batch 256, bf16)
        wide = scaled_config(2048, on_tpu, compute_dtype="bfloat16")
        state = create_train_state(jax.random.PRNGKey(0), wide)
        n_wide = min(n_train, 25600)
        epoch = make_epoch_fn(spec, wide, n_wide, 256, donate=False)
        state, losses = epoch(state, jnp.asarray(make_data(n_wide)))
        np.asarray(losses)
        row = {"case": case, "hidden": 2048, "n_train": n_wide, "batch": 256,
               "k": K}
    else:
        raise ValueError(f"unknown memory case {case!r}")

    s = stats()
    row["peak_bytes"] = s.get("peak_bytes_in_use")
    row["bytes_limit"] = s.get("bytes_limit")
    row["memory_stats_available"] = bool(s)
    row["device"] = getattr(dev, "device_kind", dev.platform)
    return row


def bench_memory():
    """``--memory``: peak device-memory accounting for the three production
    shapes (VERDICT r5 weak #4) — the flagship train dispatch, the
    batch-500/chunk-250 eval suite, and the widest scaling-sweep shape —
    plus the replicated-``x_train`` max-dataset bound those peaks imply.

    Each case runs in its own subprocess (true per-case peaks — see
    :func:`_memory_case`); prints one JSON line. ``memory_stats()`` is a
    TPU/GPU allocator API; hosts without it (CPU) stamp null peaks but still
    report the analytic bound (``x_train`` is replicated per device at
    4 bytes/pixel, so max rows = headroom / (784*4)).
    """
    import subprocess
    import sys

    rows = []
    for case in MEMORY_CASES:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--memory-case", case],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if r.returncode != 0:
            raise RuntimeError(f"--memory case {case} failed:\n{r.stderr[-2000:]}")
        rows.append(json.loads(
            [ln for ln in r.stdout.splitlines() if ln.startswith("{")][-1]))

    limit = rows[0].get("bytes_limit")
    train_peak = rows[0].get("peak_bytes")
    headroom = limit - train_peak if limit and train_peak else None
    # x_train is replicated per device (parallel/dp.py design note), f32:
    # the dataset-size ceiling is headroom over the per-row 784*4 bytes
    bound_rows = headroom // (784 * 4) if headroom else None
    print(json.dumps({
        "metric": "peak device memory (production shapes, one process per "
                  "case) + replicated x_train dataset bound",
        "memory_stats_available": bool(rows[0].get("memory_stats_available")),
        "device": rows[0].get("device"),
        "bytes_limit": limit,
        "rows": rows,
        "headroom_after_flagship_train_bytes": headroom,
        "replicated_x_train_max_rows": bound_rows,
        "replicated_x_train_bytes_per_row": 784 * 4,
    }))


#: the stated hot-loop acceptance target: >= 2x the r05 train MFU at the
#: paper config (BENCH_r05: mfu 0.135796 at k=50, batch 100, 2 layers, bf16)
_HOT_LOOP_TARGET = {
    "train_mfu": 0.2716,
    "source": "2x BENCH_r05 train MFU 0.135796 (paper config, bf16 peak)",
}


def _roofline_stamp(peak, peak_source, step_flops, eval_flops,
                    serving_row_flops=None):
    """The recorded MFU denominator + numerators (ISSUE 6 acceptance),
    plus the ``iwae-cost`` static estimate stamped beside the measured
    figures (ISSUE 11): per phase, the trace-time peak HBM bytes,
    arithmetic-intensity interval, roofline verdict, and the MFU ceiling
    the roofline admits AT THE MEASURED SHAPES — so a measured MFU can be
    read against what the program statically allows on this chip, not
    against a context-free 1.0."""
    stamp = {
        "peak_flops": peak,
        "peak_flops_source": peak_source,
        "numerator": "analytic matmul FLOPs from utils/flops.py "
                     "(train: fwd + 2x bwd; eval/serving: fwd only)",
        "train_flops_per_step": step_flops,
        "eval_flops_per_image": eval_flops,
    }
    if serving_row_flops is not None:
        stamp["serving_flops_per_row"] = serving_row_flops
    if peak is None:
        stamp["mfu_null_reason"] = peak_source
    stamp["static_cost"] = _static_cost_stamp()
    return stamp


def _static_cost_stamp():
    """Trace-only (no compile) static cost of the three measured phases at
    the bench's own shapes, via analysis/audit/cost.py. Fail-soft: a bench
    must keep producing measured numbers even if the analyzer cannot trace
    on this host — the estimate is then stamped unavailable, never faked.
    """
    try:
        import jax
        import jax.numpy as jnp

        from iwae_replication_project_tpu.analysis.audit.cost import (
            CostAnalyzer, resolve_chip, roofline)
        from iwae_replication_project_tpu.evaluation.metrics import (
            streaming_log_px)
        from iwae_replication_project_tpu.models import ModelConfig
        from iwae_replication_project_tpu.objectives import ObjectiveSpec
        from iwae_replication_project_tpu.serving.programs import score_rows
        from iwae_replication_project_tpu.training import create_train_state
        from iwae_replication_project_tpu.training.train_step import (
            make_train_step)

        cfg = ModelConfig.two_layer(likelihood="logits",
                                    compute_dtype="bfloat16")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        eval_key, serve_key = jax.random.split(jax.random.PRNGKey(1))
        xb = jnp.zeros((BATCH, cfg.x_dim), jnp.float32)
        step = make_train_step(ObjectiveSpec("IWAE", k=K), cfg, donate=False)
        serve_bucket = 32
        traces = {
            "train_step": jax.make_jaxpr(step)(state, xb),
            # the chunked-NLL scorer: the eval suite's dominant shape
            "eval_scorer": jax.make_jaxpr(
                lambda p, ky, x: streaming_log_px(p, cfg, ky, x, k=EVAL_K,
                                                  chunk=EVAL_CHUNK))(
                state.params, eval_key, xb),
            # serving pins the unfused path (engine gate) — trace what
            # production serves (cfg is already unfused + bf16-matmul, the
            # same variant the measured serving leg dispatches)
            "serving_score": jax.make_jaxpr(
                lambda p, ky, s, x: score_rows(p, cfg, ky, s, x, K))(
                state.params, serve_key,
                jnp.zeros((serve_bucket,), jnp.int32),
                jnp.zeros((serve_bucket, cfg.x_dim), jnp.float32)),
        }
        from iwae_replication_project_tpu.utils import flops as _flops

        chip, chip_source = resolve_chip(None)
        analyzer = CostAnalyzer()
        out = {"chip": chip, "chip_source": chip_source,
               # the resident floor under every phase's peak_bytes (the
               # train step holds 3x: params + both Adam moments)
               "param_bytes": _flops.model_param_bytes(cfg),
               "variant": "unfused bf16-matmul composition (production "
                          "serving path / the 'before' train leg; matmul "
                          "FLOPs are identical for the fused variant and "
                          "its kernel interior is VMEM-opaque to the "
                          "memory pass)",
               "shapes": {"train_step": {"batch": BATCH, "k": K},
                          "eval_scorer": {"batch": BATCH, "k": EVAL_K,
                                          "chunk": EVAL_CHUNK},
                          "serving_score": {"bucket": serve_bucket, "k": K}}}
        for name, jaxpr in traces.items():
            rec, _ = analyzer.analyze_jaxpr(name, jaxpr)
            rl = roofline(rec, chip)
            out[name] = {
                "peak_bytes": rec.peak_bytes,
                "matmul_flops": rec.matmul_flops,
                "intensity": rec.intensity,
                "intensity_fused": rec.intensity_fused,
                "verdict": rl.get("verdict"),
                "static_mfu_ceiling": rl.get("static_mfu_ceiling"),
            }
        return out
    except Exception as e:
        return {"unavailable": f"{type(e).__name__}: {e}"}


def _serving_dispatch_cfg(cfg, k: int, bucket: int, on_tpu: bool):
    """``(dispatch cfg, path, tile)`` the serving engine's lifted gate
    resolves at one (k, bucket) — the SAME shared resolve-then-bake helper
    production dispatches through (ops/hot_loop.serving_dispatch_config),
    so direct program benches measure exactly what an engine serves."""
    from iwae_replication_project_tpu.ops.hot_loop import (
        serving_dispatch_config)

    return serving_dispatch_config(cfg, k, bucket, on_tpu=on_tpu)


def _write_hot_loop_results(out: dict) -> None:
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "hot_loop_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


def bench_hot_loop():
    """``--hot-loop``: the full before/after sweep of ISSUE 6 at the paper
    config (IWAE k=50, batch 100, 2 stochastic layers) — train, the chunked
    k=5000 eval scorer, and the serving ``score`` program, each measured
    with the blocked hot-loop dispatcher off (``before``: the pure XLA
    composition) and on (``after``: trace-time selection — Pallas /
    blocked-scan / reference per shape). Each phase reports throughput AND
    MFU with the roofline denominator stamped; one JSON line +
    results/hot_loop_bench.json.

    Sizes shrink via ``BENCH_HOT_LOOP_N_TRAIN`` / ``BENCH_HOT_LOOP_EVAL_N``
    for constrained hosts (the defaults keep a CPU run under ~10 min).
    """
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.evaluation.metrics import dataset_scalars
    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.ops.hot_loop import (
        path_code_for_model, path_counters)
    from iwae_replication_project_tpu.serving.programs import score_rows
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.training.epoch import make_epoch_fn
    from iwae_replication_project_tpu.utils import flops

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    n_train = int(os.environ.get("BENCH_HOT_LOOP_N_TRAIN", 12800))
    eval_n = int(os.environ.get("BENCH_HOT_LOOP_EVAL_N", 500))
    # fail at the parse site with the constraint, not mid-sweep with an
    # opaque reshape error: both sizes batch at 100 rows
    for name, val in (("BENCH_HOT_LOOP_N_TRAIN", n_train),
                      ("BENCH_HOT_LOOP_EVAL_N", eval_n)):
        if val <= 0 or val % 100 != 0:
            raise SystemExit(f"{name}={val}: must be a positive multiple of "
                             f"100 (the paper-config batch size)")
    serve_bucket = 32
    spec = ObjectiveSpec("IWAE", k=K)
    peak, peak_source = peak_flops()
    base_cfg = ModelConfig.two_layer(likelihood="logits")
    step_flops = flops.train_step_flops(base_cfg, BATCH, K)
    eval_flops = flops.eval_suite_flops_per_image(base_cfg, K, EVAL_K,
                                                 EVAL_CHUNK)
    row_flops = flops.serving_score_flops_per_row(base_cfg, K)
    x_train = jnp.asarray(make_data(n_train))
    xe = jnp.asarray(make_data(eval_n)).reshape(-1, 100, 784)
    xs = jnp.asarray(make_data(serve_bucket))
    seeds = jnp.arange(serve_bucket, dtype=jnp.int32)

    phases = {}
    for leg, fused in (("before", False), ("after", True)):
        cfg = ModelConfig.two_layer(likelihood="logits",
                                    fused_likelihood=fused,
                                    compute_dtype="bfloat16")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        epoch = make_epoch_fn(spec, cfg, n_train, BATCH, donate=False)
        state, losses = epoch(state, x_train)     # compile + warmup
        np.asarray(losses)
        steps = n_train // BATCH
        t_rates = []
        for _ in range(2):
            t0 = time.perf_counter()
            state, losses = epoch(state, x_train)
            np.asarray(losses)                    # honest completion sync
            t_rates.append(steps / (time.perf_counter() - t0))
        # best-of reps: the noise-robust estimator on a contended box (the
        # serving bench's established policy) — a co-tenant can halve one
        # rep and a mean would misreport the before/after ratio
        train_sps = float(max(t_rates))
        # stamp the selection for THIS leg's own config/shape — never the
        # trace-order gauge (the unfused leg traces no selection at all)
        train_path = path_code_for_model(cfg, K, BATCH, on_tpu=on_tpu)

        key = jax.random.PRNGKey(1)
        np.asarray(dataset_scalars(state.params, cfg, key, xe, K,
                                   EVAL_K, EVAL_CHUNK))  # compile
        e_rates = []
        for _ in range(2):
            t0 = time.perf_counter()
            np.asarray(dataset_scalars(state.params, cfg, key, xe, K,  # iwaelint: disable=key-reuse -- timing reps deliberately re-run the IDENTICAL program (same key) so only dispatch variance is measured
                                       EVAL_K, EVAL_CHUNK))
            e_rates.append(eval_n / (time.perf_counter() - t0))
        eval_ips = float(max(e_rates))            # best-of, as above
        # the chunked-NLL pass (the suite's dominant shape) at batch 100
        eval_path = path_code_for_model(cfg, EVAL_CHUNK, 100, on_tpu=on_tpu)

        # serving leg (the pin is LIFTED — ISSUE 12): `before` measures the
        # historical pinned program (reference composition), `after` the
        # config the engine's probe gate resolves at this (k, bucket) —
        # identical programs on hosts where the gate falls back (this CPU
        # box), diverging exactly where the fused path is admitted (TPU)
        cfg_serve = ModelConfig.two_layer(likelihood="logits",
                                          compute_dtype="bfloat16")
        if fused:
            cfg_serve, serve_path, _tile = _serving_dispatch_cfg(
                cfg_serve, K, serve_bucket, on_tpu)
        else:
            serve_path = "reference"
        sk = jax.random.PRNGKey(2)
        np.asarray(score_rows(state.params, cfg_serve, sk, seeds, xs, K))  # compile
        reps, t0 = 20, time.perf_counter()
        for _ in range(reps):
            np.asarray(score_rows(state.params, cfg_serve, sk, seeds, xs, K))  # iwaelint: disable=key-reuse -- timing reps deliberately re-run the IDENTICAL program (same key) so only dispatch variance is measured
        serve_rps = reps * serve_bucket / (time.perf_counter() - t0)
        phases[leg] = {
            "train_steps_per_sec": round(train_sps, 2),
            "train_mfu": (round(train_sps * step_flops / peak, 6)
                          if peak else None),
            "train_kernel_path": train_path,
            "eval_images_per_sec": round(eval_ips, 2),
            "eval_mfu": (round(eval_ips * eval_flops / peak, 6)
                         if peak else None),
            "eval_kernel_path": eval_path,
            "serving_rows_per_sec": round(serve_rps, 2),
            "serving_mfu": (round(serve_rps * row_flops / peak, 6)
                            if peak else None),
            "serving_kernel_path": serve_path,
        }

    out = {
        "metric": "hot-loop before/after at the paper config (IWAE k=50, "
                  "batch 100, 2 stochastic layers)",
        "mode": "--hot-loop (train/eval/serving, each before and after)",
        "config": {"k": K, "batch": BATCH, "n_train": n_train,
                   "eval_n": eval_n, "eval_k": EVAL_K,
                   "eval_chunk": EVAL_CHUNK, "serve_bucket": serve_bucket,
                   "compute_dtype": "bfloat16", "on_tpu": on_tpu},
        "before": phases["before"],
        "after": phases["after"],
        "speedup": {
            p: round(phases["after"][f"{p}_{u}"] / phases["before"][f"{p}_{u}"], 3)
            for p, u in (("train", "steps_per_sec"),
                         ("eval", "images_per_sec"),
                         ("serving", "rows_per_sec"))
        },
        "serving_note": "the serving pin is lifted (ISSUE 12): the after "
                        "leg runs the config the engine's probe gate "
                        "resolves at this (k, bucket) — on hosts where "
                        "the gate falls back (CPU: no native pallas, "
                        "small working set) it is the same reference "
                        "program as before, stamped per leg in "
                        "serving_kernel_path; bench.py --autotune carries "
                        "the dedicated pinned-vs-unpinned comparison",
        "kernel_path_counters": path_counters(),
        "roofline": _roofline_stamp(peak, peak_source, step_flops,
                                    eval_flops, row_flops),
        "target": _HOT_LOOP_TARGET,
    }
    print(json.dumps(out))
    _write_hot_loop_results(out)


AUTOTUNE_ROWS = 320            # rows per pinned-vs-unpinned closed-loop rep
AUTOTUNE_REPS = 5              # paired reps per engine mode (best-of)
AUTOTUNE_BUCKET = 32           # the serving op point's one pinned bucket


def bench_autotune():
    """``--autotune``: the ISSUE 12 sweep — pinned-vs-unpinned serving and
    the autotuned-vs-hand-picked tile search, at the paper config (k=50,
    batch 100).

    Three blocks, one JSON line + results/autotune_bench.json:

    * **serving comparison** — closed-loop ``score`` rows/sec through REAL
      engines: the historical pin (``kernel_path='reference'``), the
      lifted probe-gated auto engine, and the forced fused blocked-scan
      engine, all bitwise-compared request-by-request (the lift's safety
      contract) with each leg's kernel stamp and measured-vs-statically-
      estimated MFU side by side;
    * **tile sweep** — ``ops/autotune.tune`` over the fwd kernel at the
      paper train shape, the serving row composition at the bucket, and
      the scan remat ladder: every candidate's measured wall + static
      roofline prior committed, the winner against the hand-picked
      configuration (the winner can only meet or beat it — the hand pick
      is IN the search space; pinned by assertion);
    * **warm-cache proof** — a second tuning run over the same keys must
      be pure lookup: zero searches, zero probe compiles (the committed
      counters prove the once-per-fleet contract).

    Off-TPU, pallas candidates are excluded from MEASUREMENT (interpret
    timings would rank the interpreter, not the kernel) and the artifact
    stamps that honestly; the TPU bench round regenerates with the full
    tile space.
    """
    import jax

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.ops import autotune
    from iwae_replication_project_tpu.ops.hot_loop import PATH_CODES
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)
    from iwae_replication_project_tpu.utils.flops import (
        serving_score_flops_per_row)
    from iwae_replication_project_tpu.telemetry.registry import get_registry

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    peak, peak_source = peak_flops()
    cfg = ModelConfig.two_layer(likelihood="logits")
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    params = state.params
    h1_dim, hid, n_pixels = autotune.dims_for_model(cfg)
    row_flops = serving_score_flops_per_row(cfg, K)

    def _counter(name):
        return get_registry().counter(f"autotune/{name}").value

    # -- 1) pinned vs unpinned serving (real engines, closed loop) ----------
    rng = np.random.RandomState(5)
    stream = (rng.rand(AUTOTUNE_ROWS, 784) > 0.5).astype(np.float32)
    modes = {
        "pinned_reference": "reference",
        "unpinned_auto": None,
        "forced_blocked_scan": "blocked_scan",
    }
    engines, outs, walls = {}, {}, {name: [] for name in modes}
    for name, force in modes.items():
        eng = ServingEngine(params=params, model_config=cfg, k=K,
                            ladder=None, max_batch=AUTOTUNE_BUCKET,
                            timeout_s=None, kernel_path=force)
        eng.warmup(ops=("score",))
        engines[name] = eng
        outs[name] = np.concatenate(
            [eng.score(stream[i:i + AUTOTUNE_BUCKET])
             for i in range(0, AUTOTUNE_ROWS, AUTOTUNE_BUCKET)])
    s0 = cache_stats()
    for rep in range(AUTOTUNE_REPS):
        order = list(modes) if rep % 2 else list(modes)[::-1]
        for name in order:                      # paired, alternating order
            eng = engines[name]
            t0 = time.perf_counter()
            for i in range(0, AUTOTUNE_ROWS, AUTOTUNE_BUCKET):
                eng.score(stream[i:i + AUTOTUNE_BUCKET])
            walls[name].append(time.perf_counter() - t0)
    d = stats_delta(s0)
    bitwise = {name: bool(np.array_equal(outs[name],
                                         outs["pinned_reference"]))
               for name in modes}
    est = _serving_static_mfu(cfg, K, AUTOTUNE_BUCKET, on_tpu)
    serving_cmp = {}
    for name in modes:
        rps = AUTOTUNE_ROWS / min(walls[name])
        snap = engines[name].metrics.snapshot()
        stamp = snap["kernel"].get(f"score/b{AUTOTUNE_BUCKET}/k{K}", {})
        serving_cmp[name] = {
            "rows_per_sec": round(rps, 2),
            "wall_seconds": [round(w, 4) for w in walls[name]],
            "kernel_path": stamp.get("path"),
            "kernel_tile": stamp.get("tile"),
            "bitwise_identical_to_pinned": bitwise[name],
            # measured-vs-estimated, side by side (ISSUE 12 satellite)
            "mfu_measured": (round(rps * row_flops / peak, 6)
                             if peak else None),
            "static_mfu_ceiling": est.get("static_mfu_ceiling"),
        }
    unpinned_over_pinned = round(
        min(walls["pinned_reference"]) / min(walls["unpinned_auto"]), 3)

    # -- 2) the tile sweep: autotuned vs hand-picked ------------------------
    sweeps = {}
    hand_ms = {}
    for kind, b in (("fwd", BATCH), ("serving_row", AUTOTUNE_BUCKET),
                    ("scan", BATCH)):
        rec = autotune.tune(kind, K, b, h1_dim, hid, n_pixels, reps=3,
                            force=True)
        # the hand-picked configuration inside the measured space: the
        # dispatcher's pre-autotune choice for this kind at this shape
        hand = _hand_picked_label(kind, K, b, h1_dim, hid, n_pixels, on_tpu)
        hand_row = next((r for r in rec["all_measured"]
                         if r["candidate"] == hand), None)
        hand_ms[kind] = hand_row["measured_ms"] if hand_row else None
        sweeps[kind] = {
            "k": K, "b": b,
            "winner": {key: rec[key] for key in
                       ("path", "tile", "block_k", "measured_ms",
                        "estimated_ms")},
            "hand_picked": {"candidate": hand,
                            "measured_ms": hand_ms[kind]},
            "winner_meets_or_beats_hand_picked": (
                hand_ms[kind] is None
                or rec["measured_ms"] <= hand_ms[kind]),
            "candidates_measured": rec["measured_candidates"],
            "all_measured": rec["all_measured"],
        }
        # the acceptance pin: the hand pick is in the space, so the
        # measured winner can only meet or beat it
        assert sweeps[kind]["winner_meets_or_beats_hand_picked"], sweeps

    # -- 3) warm-cache proof: the second tuning run is free -----------------
    autotune.reload_store()
    before = {n: _counter(n) for n in ("searches", "probe_compiles")}
    for kind, b in (("fwd", BATCH), ("serving_row", AUTOTUNE_BUCKET),
                    ("scan", BATCH)):
        rec = autotune.tune(kind, K, b, h1_dim, hid, n_pixels)
        assert rec["cache"] == "hit", rec
    second = {f"second_run_{n}": _counter(n) - before[n]
              for n in ("searches", "probe_compiles")}

    out = {
        "metric": "autotune: pinned-vs-unpinned serving + measured tile "
                  "search at the paper config (IWAE k=50, batch 100)",
        "config": {"k": K, "batch": BATCH, "serve_bucket": AUTOTUNE_BUCKET,
                   "rows": AUTOTUNE_ROWS, "reps": AUTOTUNE_REPS,
                   "on_tpu": on_tpu},
        "serving_comparison": serving_cmp,
        "unpinned_over_pinned": unpinned_over_pinned,
        "tile_sweep": sweeps,
        "pallas_candidates_measured": on_tpu,
        "pallas_note": None if on_tpu else (
            "CPU host: pallas tile candidates are excluded from "
            "measurement (interpret-mode wall time ranks the interpreter, "
            "not the kernel) and the probe gate resolves reference, so "
            "the committed comparison is reference-vs-scan variants; the "
            "TPU bench round regenerates this artifact with the full "
            "(tk, tb) space measured natively"),
        "second_tune_run": {**second, "all_cache_hits": True},
        "autotune_cache_path": autotune.cache_path(),
        "autotune_version": autotune.AUTOTUNE_VERSION,
        "chip": autotune.chip_kind(),
        "vmem_budget": autotune._budget(),
        "mfu_config": {"peak_flops": peak,
                       "peak_flops_source": peak_source,
                       "flops_per_row": row_flops,
                       "numerator": "analytic matmul FLOPs, forward only"},
        "post_warmup_aot_misses": int(d["aot_misses"]),
        "post_warmup_recompiles": int(d["persistent_cache_misses"]),
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "autotune_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


def _hand_picked_label(kind, k, b, h1_dim, hid, n_pixels, on_tpu):
    """The label (autotune.Candidate.label grammar) of the configuration
    the dispatcher picks WITHOUT a winner cache — the sweep's baseline."""
    from iwae_replication_project_tpu.ops.hot_loop import (
        _scan_block_k, select_block)

    if kind == "scan":
        return f"blocked_scan(bk={_scan_block_k(k, b, hid, n_pixels)})"
    if kind == "fwd" and on_tpu:
        tile = select_block(k, b, h1_dim, hid, n_pixels)
        if tile is not None:
            return f"pallas{tile}"
    return "reference"


def _serving_static_mfu(cfg, k, bucket, on_tpu):
    """Static roofline estimate of the serving score program at the
    measured shape (trace-only; fail-soft to an empty dict)."""
    try:
        import jax
        import jax.numpy as jnp

        from iwae_replication_project_tpu.analysis.audit.cost import (
            CostAnalyzer, resolve_chip, roofline)
        from iwae_replication_project_tpu.serving.programs import score_rows
        from iwae_replication_project_tpu.training import create_train_state

        state = create_train_state(jax.random.PRNGKey(0), cfg)
        dcfg, _, _ = _serving_dispatch_cfg(cfg, k, bucket, on_tpu)
        closed = jax.make_jaxpr(
            lambda p, ky, s, x: score_rows(p, dcfg, ky, s, x, k))(
            state.params, jax.random.PRNGKey(1),
            jnp.zeros((bucket,), jnp.int32),
            jnp.zeros((bucket, cfg.x_dim), jnp.float32))
        rec, _ = CostAnalyzer().analyze_jaxpr("serving_score", closed)
        chip, _src = resolve_chip(None)
        return roofline(rec, chip)
    except Exception as e:
        return {"unavailable": f"{type(e).__name__}: {e}"}


def bench_precision():
    """``--precision``: the ISSUE 16 sweep — per-precision serving at the
    paper config (k=50), one leg per policy, all gated by the statistical
    parity contract (telemetry/parity.py).

    Legs, each a REAL warm engine closed-loop over the same row stream:

    * ``unpolicied`` — the historical no-policy engine (the oracle);
    * ``fp32`` — the explicit policy: must be BITWISE identical to the
      oracle (pinning, not a new program; asserted);
    * ``bf16`` — bf16 operands / fp32 accumulation;
    * ``int8_forced`` — the weight-only-quantized program, admission
      forced (``IWAE_SERVING_INT8=force``) so the quantized path is
      measured even where the gate would reject;
    * ``int8_auto`` — the production admission path: the measured-win
      gate decides, the committed record carries the verdict reason
      (off-TPU with no persisted winner this leg honestly serves — and
      measures — the exact fp32 program).

    Per leg: rows/sec, wall spread, kernel stamp, measured MFU vs the
    static roofline ceiling (per-precision traced program where the trace
    models it; an honest null + reason where it does not). bf16/int8
    additionally carry the statistical-parity verdict of their ``[k, B]``
    log-weights against the fp32 oracle over one paper-shaped batch.
    Committed to ``results/precision_bench.json``.
    """
    import dataclasses
    import sys

    import jax

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.ops.hot_loop import quantize_out_block
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.telemetry.parity import (
        DEFAULT_TOLERANCES, statistical_parity)
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.utils.flops import (
        serving_score_flops_per_row)

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    peak, peak_source = peak_flops()
    cfg = ModelConfig.two_layer(likelihood="logits")
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    params = state.params
    row_flops = serving_score_flops_per_row(cfg, K)

    rng = np.random.RandomState(11)
    stream = (rng.rand(AUTOTUNE_ROWS, cfg.x_dim) > 0.5).astype(np.float32)

    # ---- statistical parity of the low-precision programs (one paper-
    # shaped batch, shared key: the legs must differ only in arithmetic)
    xb = (rng.rand(BATCH, cfg.x_dim) > 0.5).astype(np.float32)
    params_q = {name: val for name, val in params.items() if name != "out"}
    params_q["out_q"] = quantize_out_block(params["out"])
    plegs = {"fp32": (params, cfg),
             "bf16": (params, dataclasses.replace(
                 cfg, compute_dtype="bfloat16")),
             "int8": (params_q, cfg)}
    log_w = {leg: np.asarray(model.log_weights(
                 p, c, jax.random.PRNGKey(3), xb, K))
             for leg, (p, c) in plegs.items()}
    parity = {leg: statistical_parity(log_w["fp32"], log_w[leg],
                                      DEFAULT_TOLERANCES[leg])
              for leg in ("bf16", "int8")}
    assert all(v["accepted"] for v in parity.values()), \
        {leg: v["failures"] for leg, v in parity.items()}

    # ---- the engine legs (paired closed loops over one stream)
    modes = {"unpolicied": (None, None), "fp32": ("fp32", None),
             "bf16": ("bf16", None), "int8_forced": ("int8", "force"),
             "int8_auto": ("int8", None)}
    engines, outs, admission = {}, {}, {}
    walls = {name: [] for name in modes}
    saved = os.environ.get("IWAE_SERVING_INT8")
    try:
        for name, (precision, env) in modes.items():
            if env is not None:
                os.environ["IWAE_SERVING_INT8"] = env
            elif saved is None:
                os.environ.pop("IWAE_SERVING_INT8", None)
            else:
                os.environ["IWAE_SERVING_INT8"] = saved
            eng = ServingEngine(params=params, model_config=cfg, k=K,
                                ladder=None, max_batch=AUTOTUNE_BUCKET,
                                timeout_s=None, precision=precision)
            eng.warmup(ops=("score",))
            engines[name] = eng
            outs[name] = np.concatenate(
                [eng.score(stream[i:i + AUTOTUNE_BUCKET])
                 for i in range(0, AUTOTUNE_ROWS, AUTOTUNE_BUCKET)])
            admission[name] = {
                "/".join(str(part) for part in key): reason
                for key, reason in eng.int8_admission.items()}
            for rep in range(AUTOTUNE_REPS):
                t0 = time.perf_counter()
                for i in range(0, AUTOTUNE_ROWS, AUTOTUNE_BUCKET):
                    eng.score(stream[i:i + AUTOTUNE_BUCKET])
                walls[name].append(time.perf_counter() - t0)
    finally:
        if saved is None:
            os.environ.pop("IWAE_SERVING_INT8", None)
        else:
            os.environ["IWAE_SERVING_INT8"] = saved

    # fp32 policy is a pin, not a program change — the hard bitwise gate
    assert np.array_equal(outs["fp32"], outs["unpolicied"]), \
        "explicit fp32 policy diverged from the no-policy engine"

    est = {"unpolicied": _serving_static_mfu(cfg, K, AUTOTUNE_BUCKET,
                                             on_tpu)}
    est["fp32"] = est["unpolicied"]
    est["bf16"] = _serving_static_mfu(
        dataclasses.replace(cfg, compute_dtype="bfloat16"), K,
        AUTOTUNE_BUCKET, on_tpu)
    # the static trace scores the fp32 params tree; the int8 program's
    # smaller weight traffic is not modeled there — null with the reason
    # rather than a wrong ceiling
    est["int8_forced"] = est["int8_auto"] = {
        "unavailable": "static roofline traces the fp32 params tree; the "
                       "int8 program's weight bytes are not modeled"}
    legs = {}
    for name, (precision, env) in modes.items():
        rps = AUTOTUNE_ROWS / min(walls[name])
        snap = engines[name].metrics.snapshot()
        stamp_key = f"score/b{AUTOTUNE_BUCKET}/k{K}" + \
            (f"/{precision}" if precision else "")
        stamp = snap["kernel"].get(stamp_key, {})
        delta = float(np.max(np.abs(outs[name] - outs["unpolicied"])))
        legs[name] = {
            "precision": precision, "env_override": env,
            "rows_per_sec": round(rps, 2),
            "wall_seconds": [round(w, 4) for w in walls[name]],
            "kernel_path": stamp.get("path"),
            "kernel_tile": stamp.get("tile"),
            "bitwise_identical_to_unpolicied": bool(
                np.array_equal(outs[name], outs["unpolicied"])),
            "row_abs_max_vs_unpolicied": delta,
            "mfu_measured": (round(rps * row_flops / peak, 6)
                             if peak else None),
            "static_mfu_ceiling": est[name].get("static_mfu_ceiling"),
            "static_mfu_note": est[name].get("unavailable"),
            "int8_admission": admission[name] or None,
        }

    out = {
        "metric": "precision: per-policy serving latency + statistical "
                  "parity at the paper config (IWAE k=50)",
        "config": {"k": K, "parity_batch": BATCH,
                   "serve_bucket": AUTOTUNE_BUCKET, "rows": AUTOTUNE_ROWS,
                   "reps": AUTOTUNE_REPS, "on_tpu": on_tpu},
        "legs": legs,
        "parity": {leg: {**parity[leg],
                         "tolerances": dataclasses.asdict(
                             DEFAULT_TOLERANCES[leg])}
                   for leg in parity},
        "int8_auto_note": None if on_tpu else (
            "CPU host: the auto leg has no measured win (the admission "
            "gate requires one), so it serves — and measures — the exact "
            "fp32 program; the TPU bench round regenerates this artifact "
            "with a real serving_int8 autotune verdict"),
        "mfu_note": None if peak else (
            "no peak-FLOPs figure for this host (BENCH_PEAK_FLOPS / "
            "--peak-flops unset off-TPU), so mfu_measured is null; the "
            "TPU bench round fills it"),
        "mfu_config": {"peak_flops": peak, "peak_flops_source": peak_source,
                       "flops_per_row": row_flops,
                       "numerator": "analytic matmul FLOPs, forward only"},
    }
    print(json.dumps(out))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "precision_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass


AUTOSCALE_SIZES = (1, 2, 3)   # static fleet sizes on the curve
AUTOSCALE_ROWS = 96           # pipelined single-row requests per leg
AUTOSCALE_WINDOW = 8          # client in-flight window (the offered load)
AUTOSCALE_K = 256             # per-request k: compute-bound, ms-scale service
AUTOSCALE_CAL_REPS = 15       # warm single-row reps for the objective calibration


def bench_autoscale():
    """``--autoscale``: SLO-vs-fleet-size curves for the elastic fleet
    (serving/fleet/ — ISSUE 18).

    The same fixed stream — AUTOSCALE_ROWS single-row ``score`` requests
    pipelined through one connection with an AUTOSCALE_WINDOW in-flight
    window — is offered to:

    * **static fleets of 1..3 replicas** (compute-bound k=AUTOSCALE_K
      engines over SHARED params, max_batch=1 so batching never launders
      queue wait), each with its own SLOMonitor under a host-calibrated
      latency objective (2x the warm single-row p50, so the number means
      the same on any machine): throughput + whole-leg latency burn per
      size is the curve the autoscaler's thresholds sit on;
    * **an elastic fleet** starting at 1 replica with the FleetManager
      control thread live (short burn windows, min=1 max=3): the same
      stream, plus the decision log, the replica trajectory, and the
      post-idle shrink back to min.

    Results are a pure function of (weights, payload, seed, k) and seeds
    are minted at admission, so every leg — static or elastic — must
    return bitwise-identical values; the bench asserts it. Burn windows
    for the static legs are longer than any leg's wall time, so their
    burn is the whole-leg violation fraction over the error budget, not a
    trailing sample.

    In-process replicas share the host's XLA CPU thread pool: on a host
    with fewer cores than max(AUTOSCALE_SIZES) the fleet CANNOT scale
    compute, so the burn curve is honestly flat and ``host.note`` says so
    (the precision bench's CPU-host pattern) — a multi-core/TPU bench
    round resolves the slope. Prints one JSON line and writes
    results/autoscale_bench.json.
    """
    import jax

    from iwae_replication_project_tpu.models import iwae as tiny_model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.serving.fleet import (
        AutoscaleConfig, FleetManager)
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)
    from iwae_replication_project_tpu.telemetry.slo import (
        SLOMonitor, SLOObjective, peak_burns, window_requests)

    D = 128
    mcfg = tiny_model.ModelConfig(x_dim=D, n_hidden_enc=(64, 32),
                                  n_latent_enc=(16, 8),
                                  n_hidden_dec=(32, 64),
                                  n_latent_dec=(16, D))
    params = tiny_model.init_params(jax.random.PRNGKey(0), mcfg)

    def engine():
        return ServingEngine(params=params, model_config=mcfg,
                             k=AUTOSCALE_K, max_batch=1, max_inflight=2,
                             timeout_s=30.0)

    n = AUTOSCALE_ROWS
    rows = (np.random.RandomState(0).rand(n, D) > 0.5).astype(np.float32)

    # calibrate the objective on THIS host: the unloaded warm single-row
    # p50, doubled. Under the pipelined window the queue wait dominates
    # that threshold on a 1-replica fleet and fades as replicas join —
    # which is exactly the shape a fleet-size curve must resolve.
    cal = engine()
    cal.warmup(ops=("score",))
    lat = []
    for _ in range(AUTOSCALE_CAL_REPS):
        t0 = time.perf_counter()
        cal.score(rows[0])
        lat.append(time.perf_counter() - t0)
    lat.sort()
    obj_s = 2.0 * lat[len(lat) // 2]
    objective = SLOObjective(latency_s=obj_s)

    def run_stream(port):
        """Windowed closed loop on one connection (admission order ==
        submit order, so seeds — and results — line up across legs)."""
        vals = []
        with TierClient("127.0.0.1", port, timeout_s=60.0) as cli:
            pending = []
            nxt = 0
            t0 = time.perf_counter()
            while len(vals) < n:
                while nxt < n and len(pending) < AUTOSCALE_WINDOW:
                    pending.append(
                        cli.submit("score", [rows[nxt].tolist()]))
                    nxt += 1
                # wait() raises TierError on any non-ok response — a lost
                # or shed request fails the bench loudly
                vals.append(cli.wait(pending.pop(0))[0])
            wall = time.perf_counter() - t0
        return vals, wall

    # -- static legs: one point per fleet size ------------------------------
    curve = []
    ref = None
    for size in AUTOSCALE_SIZES:
        # windows longer than the leg: burn == whole-leg violation fraction
        slo = SLOMonitor(default=objective,
                         windows=((120.0, "5m"), (240.0, "1h")))
        tier = ServingTier([engine() for _ in range(size)], slo=slo,
                           monitor_interval_s=0.05)
        tier.warmup(ops=("score",))
        tier.start()
        try:
            vals, wall = run_stream(tier.port)
            snap = slo.snapshot()
        finally:
            tier.stop(timeout_s=30)
        if ref is None:
            ref = vals
        assert vals == ref, \
            f"fleet size {size} changed results — seeds must not move"
        burns = peak_burns(snap)
        curve.append({
            "replicas": size,
            "requests": n,
            "wall_seconds": round(wall, 3),
            "rows_per_sec": round(n / wall, 2),
            "latency_burn": round(burns.get("5m", 0.0), 3),
            "violation_fraction": round(
                burns.get("5m", 0.0) * (1.0 - objective.latency_target), 4),
        })

    # -- elastic leg: same stream, autoscaler live --------------------------
    # short burn windows so idle actually rotates clean and the post-load
    # shrink is observable within the bench's budget
    fast_s, slow_s = 2.0, 4.0
    slo = SLOMonitor(default=objective,
                     windows=((fast_s, "5m"), (slow_s, "1h")))
    tier = ServingTier([engine()], slo=slo, monitor_interval_s=0.05)
    tier.warmup(ops=("score",))
    tier.start()
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=max(AUTOSCALE_SIZES),
                          scale_up_burn=1.0, scale_down_burn=0.25,
                          up_cooldown_s=0.3, down_cooldown_s=0.5,
                          interval_s=0.05, seed=0)
    mgr = FleetManager(tier, engine, cfg, warmup_ops=("score",),
                       drain_timeout_s=20.0)
    mgr.start()
    try:
        vals, wall = run_stream(tier.port)
        assert vals == ref, \
            "elastic fleet changed results — seeds must not move"
        peak_replicas = max((max(r["replicas"], r["target"])
                             for r in mgr.decision_log), default=1)
        # idle: wait for the shrink back to min (burn rotates clean in
        # fast_s; then the down-cooldown must lapse per drop)
        deadline = time.monotonic() + fast_s + 30.0
        while time.monotonic() < deadline:
            live = [s for s in tier.router.replica_states()
                    if s["healthy"] and not s["draining"]]
            if len(live) == cfg.min_replicas:
                break
            time.sleep(0.05)
        final_replicas = len([s for s in tier.router.replica_states()
                              if s["healthy"] and not s["draining"]])
    finally:
        mgr.stop()
        tier.stop(timeout_s=30)
    actions = [r["action"] for r in mgr.decision_log if r["action"] != "hold"]
    elastic = {
        "requests": n,
        "wall_seconds": round(wall, 3),
        "rows_per_sec": round(n / wall, 2),
        "start_replicas": 1,
        "peak_replicas": peak_replicas,
        "final_replicas": final_replicas,
        "scale_events": [
            {"t": round(r["t"], 3), "action": r["action"],
             "rule": r["rule"], "replicas": r["replicas"],
             "target": r["target"], "victim": r["victim"],
             "burn_fast": round(r["inputs"]["burn_fast"], 3)}
            for r in mgr.decision_log if r["action"] != "hold"],
        "placements": mgr.placement_log,
    }

    out = {
        "metric": "SLO burn + throughput vs fleet size under a fixed "
                  "pipelined load (serving/fleet autoscaler)",
        "config": {
            "rows": n, "window": AUTOSCALE_WINDOW, "k": AUTOSCALE_K,
            "x_dim": D, "max_batch": 1,
            "objective_latency_s": round(obj_s, 6),
            "objective_note": "calibrated: 2x warm single-row p50 on this "
                              "host, so burns compare across machines",
            "latency_target": objective.latency_target,
            "autoscale": {"scale_up_burn": cfg.scale_up_burn,
                          "scale_down_burn": cfg.scale_down_burn,
                          "up_cooldown_s": cfg.up_cooldown_s,
                          "down_cooldown_s": cfg.down_cooldown_s,
                          "fast_window_s": fast_s, "slow_window_s": slow_s},
        },
        "static_curve": curve,
        "elastic": elastic,
        "bitwise_parity_across_legs": True,
        "host": {
            "cpu_count": os.cpu_count(),
            "note": None if (os.cpu_count() or 1) >= max(AUTOSCALE_SIZES)
            else (f"{os.cpu_count()}-core host: in-process replicas share "
                  f"one XLA CPU thread pool, so fleet size cannot add "
                  f"compute here — the burn curve is honestly flat and "
                  f"the elastic leg's trajectory/parity are the signal; "
                  f"a multi-core/TPU bench round resolves the slope"),
        },
    }
    print(json.dumps({"metric": out["metric"],
                      "static_curve": curve,
                      "elastic": {k: elastic[k] for k in (
                          "rows_per_sec", "peak_replicas",
                          "final_replicas")},
                      "scale_events": len(elastic["scale_events"])}))
    res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    try:
        os.makedirs(res_dir, exist_ok=True)
        with open(os.path.join(res_dir, "autoscale_bench.json"), "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    except OSError:
        pass


def main():
    import sys

    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, setup_persistent_cache)

    # persistent XLA cache for repeated bench runs (same programs every run);
    # repo-local dir, IWAE_COMPILE_CACHE overrides, "off" disables
    setup_persistent_cache(base_dir=os.path.dirname(os.path.abspath(__file__)))
    if "--peak-flops" in sys.argv:
        # CLI form of the documented BENCH_PEAK_FLOPS override (peak_flops):
        # the denominator for every mfu figure this run. Validate HERE, not
        # deep inside the sweep.
        idx = sys.argv.index("--peak-flops") + 1
        if idx >= len(sys.argv):
            raise SystemExit("--peak-flops needs a value (FLOP/s, e.g. "
                             "197e12)")
        try:
            float(sys.argv[idx])
        except ValueError:
            raise SystemExit(f"--peak-flops {sys.argv[idx]!r}: not a number "
                             f"(FLOP/s, e.g. 197e12)")
        os.environ["BENCH_PEAK_FLOPS"] = sys.argv[idx]
    if "--hot-loop" in sys.argv:
        bench_hot_loop()
        return
    if "--autotune" in sys.argv:
        bench_autotune()
        return
    if "--memory-case" in sys.argv:  # per-case subprocess of bench_memory
        print(json.dumps(_memory_case(sys.argv[sys.argv.index("--memory-case")
                                               + 1])))
        return
    if "--memory" in sys.argv:
        bench_memory()
        return
    if "--scaling" in sys.argv:
        bench_scaling()
        return
    if "--serving" in sys.argv:
        bench_serving()
        return
    if "--multi-model" in sys.argv:
        bench_multi_model()
        return
    if "--large-k-child" in sys.argv:  # per-device-count subprocess leg
        _large_k_child(int(sys.argv[sys.argv.index("--large-k-child") + 1]))
        return
    if "--large-k" in sys.argv:
        bench_large_k()
        return
    if "--adaptive-k" in sys.argv:
        bench_adaptive_k()
        return
    if "--telemetry" in sys.argv:
        bench_telemetry()
        return
    if "--tracing" in sys.argv:
        bench_tracing()
        return
    if "--profiling" in sys.argv:
        bench_profiling()
        return
    if "--precision" in sys.argv:
        bench_precision()
        return
    if "--autoscale" in sys.argv:
        bench_autoscale()
        return
    rates, rates_f32, rates_before, eval_rates, compile_info = bench_jax()
    base_sps, base_n = bench_baseline()
    mean_sps = float(np.mean(rates))
    f32_sps = float(np.mean(rates_f32))
    # the before/after ratio uses best-of for BOTH legs (bench_hot_loop's
    # noise-robust policy: a co-tenant halving one rep must not fake a
    # speedup); the headline `value` stays the mean with spread visible
    best_sps = float(np.max(rates))
    before_sps = float(np.max(rates_before))
    eval_ips = float(np.mean(eval_rates))
    peak, peak_source = peak_flops()
    step_flops = train_step_flops(BATCH, K)
    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.ops.hot_loop import path_counters
    from iwae_replication_project_tpu.utils.flops import (
        eval_suite_flops_per_image)
    eval_flops = eval_suite_flops_per_image(
        ModelConfig.two_layer(likelihood="logits"), K, EVAL_K, EVAL_CHUNK)
    mfu = round(mean_sps * step_flops / peak, 6) if peak else None
    mfu_f32 = round(f32_sps * step_flops / peak, 6) if peak else None
    mfu_best = round(best_sps * step_flops / peak, 6) if peak else None
    mfu_before = round(before_sps * step_flops / peak, 6) if peak else None
    eval_mfu = round(eval_ips * eval_flops / peak, 6) if peak else None
    _write_hot_loop_results({
        "metric": "hot-loop before/after at the paper config (IWAE k=50, "
                  "batch 100, 2 stochastic layers)",
        "mode": "default bench (train before/after + eval after; "
                "bench.py --hot-loop adds eval-before and serving legs); "
                "both train legs are best-of-reps",
        "train_steps_per_sec": {"before_unfused": round(before_sps, 2),
                                "after_hot_loop": round(best_sps, 2)},
        "train_mfu": {"before_unfused": mfu_before,
                      "after_hot_loop": mfu_best},
        "train_speedup": round(best_sps / before_sps, 3),
        "eval_images_per_sec_after": round(eval_ips, 2),
        "eval_mfu_after": eval_mfu,
        "kernel_path_counters": path_counters(),
        "roofline": _roofline_stamp(peak, peak_source, step_flops,
                                    eval_flops),
        "target": _HOT_LOOP_TARGET,
    })
    print(json.dumps({
        "metric": "IWAE-k50-2L train throughput (batch 100, whole-epoch scan)",
        "value": round(mean_sps, 2),
        "unit": "steps/sec",
        "vs_baseline": round(mean_sps / base_sps, 2),
        "spread": {"min": round(min(rates), 2), "max": round(max(rates), 2),
                   "n_reps": len(rates)},
        "compute_dtype": "bfloat16",  # headline = production default (r5+);
        # rounds <=4 benched f32 as the headline
        "steps_per_sec_f32": round(f32_sps, 2),
        "eval_images_per_sec": round(float(np.mean(eval_rates)), 2),
        "eval_spread": {"min": round(min(eval_rates), 2),
                        "max": round(max(eval_rates), 2),
                        "n_reps": len(eval_rates)},
        "eval_config": {"k": EVAL_K, "chunk": EVAL_CHUNK, "batch": EVAL_BATCH,
                        "n_images": EVAL_N,
                        # batch 500 is past the Pallas forward VMEM gate, so
                        # the per-batch likelihood runs the unfused XLA path
                        # (measured faster at this batch — RESULTS.md §4)
                        "suite": "full per-batch scalar suite"},
        "epochs_per_dispatch": EPOCHS,  # production-cadence batching (r5+;
        # rounds <=4 dispatched per-epoch)
        # compile vs execute split (warm-path engine, utils/compile_cache.py):
        # compile_seconds_train is the lower+compile wall of the headline
        # program (collapses to cache deserialization when the persistent
        # cache is warm); recompiles counts true XLA compiles during it —
        # 0 on a warm start
        "compile_seconds_train": round(
            float(compile_info["aot_compile_seconds"]), 3),
        "recompiles_during_warmup": int(
            compile_info["persistent_cache_misses"]),
        "cache": {k: (round(v, 3) if isinstance(v, float) else v)
                  for k, v in cache_stats().items()},
        "mfu": mfu,
        "mfu_f32": mfu_f32,
        # the hot-loop before leg (same dtype, dispatcher off) — the r05
        # comparison and the >=2x MFU target live in hot_loop_bench.json
        "steps_per_sec_unfused": round(before_sps, 2),
        "mfu_unfused": mfu_before,
        "eval_mfu": eval_mfu,
        # all mfu figures share the detected bf16 peak denominator (no
        # published separate f32 matmul peak to divide by)
        "peak_flops": peak,
        "peak_flops_source": peak_source,
        # which hot-loop paths the compiled programs selected
        # (ops/hot_loop.PATH_CODES; counters over every traced shape)
        "kernel_path_counters": path_counters(),
        "baseline_steps_per_sec": round(base_sps, 3),
        "baseline_steps": base_n,
    }))


if __name__ == "__main__":
    main()
