"""The reference's experiment script, runnable unchanged on the TPU backend.

This mirrors `/root/reference/experiment_example.py` — same constants, same
model construction, same stage loop (fit → statistics → tensorboard → save) —
with its Colab-export defects repaired (the reference script as committed has
an undefined `dataset_name` at :61, transposed positional args at :60-61, and
a lost loop body at :75-83; see SURVEY.md §2.4). The BASELINE.json north star
asks exactly for this: the reference experiment flow, unchanged, behind a
``backend=`` switch.

Run it:

    python examples/experiment_example.py                 # full 8-stage run
    python examples/experiment_example.py --smoke         # 2 stages, tiny k
    python examples/experiment_example.py --backend torch # eager CPU oracle
"""

import argparse
import datetime
import os
import pickle
import sys

# `python examples/experiment_example.py` puts examples/ (not the repo root)
# on sys.path; make the script runnable without an install
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from iwae_replication_project_tpu import FlexibleModel  # noqa: E402
from iwae_replication_project_tpu.data import load_dataset
from iwae_replication_project_tpu.training import burda_stages

parser = argparse.ArgumentParser()
parser.add_argument("--backend", default="jax", choices=["jax", "torch", "tf2"])
parser.add_argument("--smoke", action="store_true",
                    help="2 stages, k=8, small eval (CI-sized)")
parser.add_argument("--dataset", default="binarized_mnist")
parser.add_argument("--out-dir", default="runs/experiment_example")
args = parser.parse_args()

# data load (reference :25-31 — tfds.load(batch_size=-1) becomes the local
# data layer; synthetic fallback announces itself loudly if files are absent)
ds = load_dataset(args.dataset, data_dir="data")
x_train, x_test = ds.x_train, ds.x_test

# training constants (reference :35-40; Adam eps=1e-4 matches Burda)
batch_size = 100

# architecture constants — the 2L flagship (reference :48-51)
n_hidden_encoder = [200, 100]
n_hidden_decoder = [100, 200]
n_latent_encoder = [100, 50]
n_latent_decoder = [100, 784]

# loss constants (reference :54-58)
loss_function = "IWAE"
k = 8 if args.smoke else 50
p = 1
alpha = 1
beta = 0.05

# model build + compile (reference :60-63, with the arg transposition fixed:
# the ctor order is (..., loss_function, k, p, alpha, beta))
mdl = FlexibleModel(n_hidden_encoder, n_hidden_decoder,
                    n_latent_encoder, n_latent_decoder,
                    dataset_bias=None, pixel_means=ds.bias_means,
                    loss_function=loss_function, k=k, p=p, alpha=alpha,
                    beta=beta, backend=args.backend)
mdl.compile()

# TensorBoard setup (reference :67-70)
log_dir = os.path.join(
    args.out_dir, datetime.datetime.now().strftime("%Y%m%d-%H%M%S"))

# the 8-stage Burda schedule (reference :75-77 intent; PDF §3.4:
# lr = 1e-4 * round(10^(1-(i-1)/7), 1), 3^(i-1) passes per stage)
n_stages = 2 if args.smoke else 8
results_history = []
eval_k = k
nll_k = 64 if args.smoke else 5000
nll_chunk = 32 if args.smoke else 250  # the production default (utils/config.py)
x_eval = x_test[:100] if args.smoke else x_test

for i, lr, passes in burda_stages(n_stages):
    mdl.set_learning_rate(lr)
    # train + eval + persist (reference :82-97)
    mdl.fit(x_train, epochs=passes, batch_size=batch_size,
            binarization=ds.binarization)
    res, res2 = mdl.get_training_statistics(
        x_eval, eval_k, nll_k=nll_k, nll_chunk=nll_chunk,
        activity_samples=100 if args.smoke else 1000)
    print(f"stage {i}: " + ", ".join(f"{name}={v:.4f}"
                                     for name, v in res.items()))
    mdl.tensorboard_log(res, epoch_n=i, logdir=log_dir)
    results_history.append((res, res2["number_of_active_units"]))
    mdl.save_weights(os.path.join(
        log_dir, f"{loss_function}-{len(n_hidden_encoder)}L-k_{k}-epoch_{i}"))
    with open(os.path.join(log_dir, "results.pkl"), "wb") as f:
        pickle.dump(results_history, f)

print(f"done: {n_stages} stages, artifacts under {log_dir}")
