"""TPU-native importance-weighted autoencoder framework.

A brand-new JAX/XLA/pjit framework with the capabilities of the reference
``CharlesArnal/IWAE_replication_project`` (see /root/repo/SURVEY.md): training and
evaluating VAEs/IWAEs and their variants on (binarized) MNIST, Fashion-MNIST and
Omniglot, with TPU-first execution — ``jit`` + batched-k compute on the MXU,
data-parallel and sample-parallel sharding over a `jax.sharding.Mesh`, and
streaming large-k evaluation.

The design spine (reference: flexible_IWAE.py:327-430): every objective is a
reduction of a ``[k, batch]`` log-importance-weight tensor. Here that tensor is
produced by one pure function, :func:`models.log_weights`, and every estimator in
:mod:`objectives` is a pure reduction of it.
"""

__version__ = "0.1.0"

from iwae_replication_project_tpu.models import iwae as models  # noqa: F401
from iwae_replication_project_tpu import objectives  # noqa: F401

__all__ = ["models", "objectives", "FlexibleModel", "__version__"]


def __getattr__(name):
    # lazy: the facade pulls in backend modules, which plain library users
    # (models/objectives only) should not pay for at import time
    if name == "FlexibleModel":
        from iwae_replication_project_tpu.api import FlexibleModel
        return FlexibleModel
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
