from iwae_replication_project_tpu.experiment import main

if __name__ == "__main__":
    main()
