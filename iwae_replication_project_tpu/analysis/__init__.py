"""Static analysis for the framework's JAX-specific correctness hazards.

``python -m iwae_replication_project_tpu.analysis [paths]`` (or the
``iwae-lint`` console script) runs every registered rule; see
``--list-rules``. Library entry points below; rule policy lives in
``[tool.iwaelint]`` (pyproject.toml); runtime sanitizers (transfer-guard +
NaN checking around marked tests) live in tests/conftest.py ``--sanitize``.
"""

from iwae_replication_project_tpu.analysis.config import LintConfig, load_config
from iwae_replication_project_tpu.analysis.core import (
    BARE_SUPPRESSION,
    USELESS_SUPPRESSION,
    Finding,
    Rule,
    all_rules,
    lint_file,
    lint_paths,
    register,
)

__all__ = [
    "BARE_SUPPRESSION",
    "USELESS_SUPPRESSION",
    "Finding",
    "LintConfig",
    "Rule",
    "all_rules",
    "lint_file",
    "lint_paths",
    "load_config",
    "register",
]
