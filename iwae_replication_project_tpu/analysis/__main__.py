import sys

from iwae_replication_project_tpu.analysis.cli import main

sys.exit(main())
