"""iwae-audit: jaxpr-level program auditor (the lint suite's deeper twin).

Where analysis/rules/ checks the SOURCE, this package checks the TRACED
PROGRAMS — the jaxprs XLA compiles — for the hazard classes that live below
the AST: donation vs the persistent-cache executables (RESULTS.md §5),
padded rows reaching the IWAE logsumexp unmasked, host callbacks inside hot
programs, and cache-fragmenting call signatures. See core.py for the
framework, passes.py for the four built-in passes, taint.py for the padding
dataflow engine, programs.py for the audited production-program suite, and
cost.py for the ``iwae-cost`` static cost analyzer (live-range peak memory,
FLOP/byte roofline accounting, per-mesh-axis collective profiles) over the
same traced suite.
"""

from iwae_replication_project_tpu.analysis.audit.core import (
    BARE_WAIVER,
    AuditEnv,
    AuditFinding,
    AuditPass,
    AuditProgram,
    all_passes,
    register,
    run_audit,
    select_passes,
)
from iwae_replication_project_tpu.analysis.audit.jaxprs import (
    iter_eqns,
    primitive_histogram,
    signature,
)
from iwae_replication_project_tpu.analysis.audit.programs import (
    PROGRAM_NAMES,
    build_programs,
)
from iwae_replication_project_tpu.analysis.audit.taint import TaintEngine

__all__ = [
    "BARE_WAIVER", "AuditEnv", "AuditFinding", "AuditPass", "AuditProgram",
    "all_passes", "register", "run_audit", "select_passes",
    "iter_eqns", "primitive_histogram", "signature",
    "PROGRAM_NAMES", "build_programs", "TaintEngine",
]
