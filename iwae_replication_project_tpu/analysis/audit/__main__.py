import sys

from iwae_replication_project_tpu.analysis.audit.cli import main

sys.exit(main())
