"""Audit CLI: ``python -m iwae_replication_project_tpu.analysis.audit`` /
the ``iwae-audit`` console script.

Exit codes match the lint CLI's contract and are load-bearing for
scripts/check.py: **0** = every pass clean on every program, **1** =
findings, **2** = internal/usage error (the analyzer itself failed — check.py
reports this as a crash, never as findings). ``--format json`` emits one
machine-readable object (findings + per-pass counts + the audited program
list); the default human format is one finding per line plus a per-pass
tally and the per-program trace table.
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback
from collections import Counter
from typing import List, Optional

from iwae_replication_project_tpu.analysis.audit import core
from iwae_replication_project_tpu.analysis.audit.jaxprs import signature
from iwae_replication_project_tpu.analysis.audit.programs import (
    PROGRAM_NAMES,
    build_programs,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="iwae-audit",
        description="Jaxpr-level program auditor: donation safety, padding "
                    "taint, in-graph host transfers, and recompile "
                    "cardinality over the repo's real traced programs.")
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument("--list-passes", action="store_true",
                   help="print the registered passes and exit")
    p.add_argument("--select", default=None,
                   help="comma-separated pass names to run (only these)")
    p.add_argument("--programs", default=None,
                   help=f"comma-separated subset of the audited programs "
                        f"(default: all of {', '.join(PROGRAM_NAMES)})")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.list_passes:
            passes = core.all_passes()
            width = max(len(n) for n in passes)
            for name in sorted(passes):
                print(f"{name:<{width}}  {passes[name].summary}")
            return 0

        # tracing may trigger tiny init compiles (model params); route them
        # through the shared persistent cache like every other entry point
        from iwae_replication_project_tpu.utils.compile_cache import (
            setup_persistent_cache)
        setup_persistent_cache(None)

        passes = core.select_passes(
            [s.strip() for s in args.select.split(",") if s.strip()]
            if args.select else None)
        include = [s.strip() for s in args.programs.split(",") if s.strip()] \
            if args.programs else None
        programs = build_programs(include)
        env = core.AuditEnv.current(include_registry=True)
        findings = core.run_audit(programs, passes, env)
    except (ValueError, FileNotFoundError) as e:
        print(f"iwae-audit: error: {e}", file=sys.stderr)
        return 2
    except Exception:
        print("iwae-audit: internal error:", file=sys.stderr)
        traceback.print_exc()
        return 2

    counts = dict(Counter(f.rule for f in findings))
    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "counts": counts,
            "total": len(findings),
            "passes": sorted(passes),
            "programs": {p.name: signature(p.jaxpr) for p in programs},
            "env": {"backend": env.backend, "cache_dir": env.cache_dir},
        }, indent=2))
    else:
        for f in findings:
            print(f.human())
        print(f"audited {len(programs)} program(s) with "
              f"{len(passes)} pass(es) on backend={env.backend}")
        for p in programs:
            sig = signature(p.jaxpr)
            print(f"  {p.name:<24} {sig['eqn_count']:>5} eqns, "
                  f"{len(sig['primitives'])} distinct primitives"
                  + (f", {len(p.taints)} tainted input(s)" if p.taints
                     else ""))
        if findings:
            tally = ", ".join(f"{rule}: {n}"
                              for rule, n in sorted(counts.items()))
            print(f"\n{len(findings)} finding(s) ({tally})")
        else:
            print("iwae-audit: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
