"""Audit-framework core: findings, the pass registry, waivers, the runner.

The AST lint suite (analysis/rules/) guards the *source*; this framework
guards the *traced programs* — the jaxprs XLA actually compiles. The hazards
it exists for are the ones that bit this repo below the AST: the
donation-vs-persistent-cache executable corruption (RESULTS.md §5), padded
rows reaching the IWAE ``logsumexp`` unmasked (a silently biased bound,
Burda et al. arXiv:1509.00519), host callbacks inside hot programs, and
signature shapes that fragment the jit/AOT caches under serving traffic.
The diagnostics rationale follows Rainforth et al. (arXiv:1802.04537):
verify the estimator *machinery*, not only its outputs.

Mirrors analysis/core.py deliberately:

* a **pass** subclasses :class:`AuditPass`, registers via :func:`register`,
  and yields :class:`AuditFinding`s for one :class:`AuditProgram`;
* **waivers** are the audit's suppressions: a program registration may carry
  ``waivers={"pass-name": "why this is safe"}``. A waiver with an empty
  justification is itself a finding (``bare-waiver``) — same policy as the
  lint suite's mandatory ``-- why`` tails;
* the **runner** (:func:`run_audit`) times every pass under a
  ``span/audit/<pass>`` span and lands per-pass finding counts on the
  process metric registry (``audit/<pass>/findings``), so CI gate runs are
  observable like everything else.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Type

#: finding id for a waiver with no justification text (not waivable itself)
BARE_WAIVER = "bare-waiver"


@dataclasses.dataclass(frozen=True, order=True)
class AuditFinding:
    """One pass violation in one traced program (`location` is an equation
    path like ``pjit[0]/scan[2]/reduce_sum[4]``, or a named non-jaxpr site
    such as ``signature`` / ``registry:<program>``)."""

    program: str
    rule: str
    location: str
    message: str

    def human(self) -> str:
        return f"{self.program}: [{self.rule}] {self.location}: {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class AuditProgram:
    """One traced program under audit.

    `jaxpr` is the ``jax.make_jaxpr`` output; `taints` maps flat input index
    -> ``{axis: real_extent}`` (rows >= extent are padding); `sig_args` is
    the representative ``(args, kwargs)`` the caller would dispatch with —
    the recompile-cardinality pass audits the AOT-registry key they produce;
    `hot` marks per-step/per-dispatch programs (host-transfer pass scope);
    `waivers` maps pass name -> justification.
    """

    name: str
    jaxpr: object
    taints: Dict[int, Dict[int, Optional[int]]] = \
        dataclasses.field(default_factory=dict)
    sig_args: Optional[tuple] = None
    hot: bool = True
    waivers: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AuditEnv:
    """Execution environment facts the passes condition on (injectable so
    fixtures can audit counterfactual platforms)."""

    backend: str
    cache_dir: Optional[str]
    #: (name, build_key, signature) rows of the live AOT registry, or None
    #: to skip registry auditing (fixture runs — the process registry holds
    #: unrelated programs from other tests)
    registry: Optional[list] = None

    @staticmethod
    def current(include_registry: bool = False) -> "AuditEnv":
        import jax

        from iwae_replication_project_tpu.utils.compile_cache import (
            registry_signatures)
        return AuditEnv(
            backend=jax.default_backend(),
            cache_dir=getattr(jax.config, "jax_compilation_cache_dir", None),
            registry=registry_signatures() if include_registry else None)


class AuditPass:
    """Base class. Subclasses set ``name``/``summary`` and implement
    :meth:`check`, yielding findings for one program. Cross-program state
    (the live AOT registry) is audited in :meth:`check_env` instead — run
    ONCE per audit, not once per program, and deliberately outside the
    per-program waiver scope (one program's waiver must not silence a
    registry-wide hazard)."""

    name: str = ""
    summary: str = ""

    def check(self, prog: AuditProgram, env: AuditEnv
              ) -> Iterator[AuditFinding]:
        raise NotImplementedError

    def check_env(self, env: AuditEnv) -> Iterator[AuditFinding]:
        return iter(())


_REGISTRY: Dict[str, AuditPass] = {}


def register(cls: Type[AuditPass]) -> Type[AuditPass]:
    if not cls.name:
        raise ValueError(f"pass {cls.__name__} has no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate pass name {cls.name!r}")
    _REGISTRY[cls.name] = cls()
    return cls


def all_passes() -> Dict[str, AuditPass]:
    """Name -> pass instance (importing ``passes`` registers the built-ins)."""
    import iwae_replication_project_tpu.analysis.audit.passes  # noqa: F401
    return dict(_REGISTRY)


def select_passes(select: Optional[Sequence[str]] = None
                  ) -> Dict[str, AuditPass]:
    passes = all_passes()
    if select:
        unknown = set(select) - set(passes)
        if unknown:
            raise ValueError(f"unknown pass(es): {sorted(unknown)}; "
                             f"known: {sorted(passes)}")
        passes = {n: p for n, p in passes.items() if n in select}
    return passes


def run_audit(programs: Sequence[AuditProgram],
              passes: Optional[Dict[str, AuditPass]] = None,
              env: Optional[AuditEnv] = None,
              progress: Optional[Callable[[str], None]] = None
              ) -> List[AuditFinding]:
    """Run every pass over every program; returns sorted findings.

    Waived findings are dropped (and counted as ``audit/<pass>/waived``);
    a waiver with no justification adds a ``bare-waiver`` finding instead.
    """
    from iwae_replication_project_tpu.telemetry.registry import get_registry
    from iwae_replication_project_tpu.telemetry.spans import span

    passes = passes if passes is not None else all_passes()
    env = env or AuditEnv.current()
    reg = get_registry()
    findings: List[AuditFinding] = []

    for prog in programs:
        for pname, justification in prog.waivers.items():
            if pname in passes and not (justification or "").strip():
                findings.append(AuditFinding(
                    program=prog.name, rule=BARE_WAIVER, location="waivers",
                    message=f"waiver for pass '{pname}' has no justification"
                            f" — every silenced hazard must carry its "
                            f"argument"))

    for pname, p in passes.items():
        if progress:
            progress(pname)
        with span(f"audit/{pname}"):
            for prog in programs:
                got = list(p.check(prog, env))
                if pname in prog.waivers and \
                        (prog.waivers[pname] or "").strip():
                    reg.counter(f"audit/{pname}/waived").inc(len(got))
                    continue
                findings.extend(got)
                reg.counter(f"audit/{pname}/findings").inc(len(got))
            # cross-program state: once per pass, unwaivable per-program
            env_got = list(p.check_env(env))
            findings.extend(env_got)
            reg.counter(f"audit/{pname}/findings").inc(len(env_got))
        reg.counter(f"audit/{pname}/runs").inc()

    return sorted(set(findings))
