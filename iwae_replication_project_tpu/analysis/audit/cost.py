"""``iwae-cost``: jaxpr-level memory / FLOP / collective cost analyzer.

The auditor next door (passes.py) proves *safety* facts about the traced
programs; this module computes their *cost* facts — statically, from the
same ``jax.make_jaxpr`` traces (no compile, no execution), so the full
suite analyzes in seconds on any host. Three linked passes per program:

1. **live-range peak memory** — a linear scan over equations computing
   per-buffer birth/death: frame inputs (and closure consts) are resident
   for the whole call frame, an intermediate dies at its last use, a
   donated operand (``donated_invars``) is released *before* the callee's
   outputs allocate, a ``scan``/``while`` body's working set is counted
   once (the carry is reused across iterations, not multiplied), and
   ``pallas_call`` interiors are opaque (their tiles live in scoped VMEM —
   ``ops/fused_likelihood.fits_vmem`` is that budget's owner; only the
   kernel's HBM-visible outputs are charged here). Reports peak HBM bytes
   per program plus a ``memory-blowup`` finding when any single
   intermediate exceeds a configurable multiple of the program's input
   bytes — the static form of the OOM class the k=5000 eval exists to
   avoid (its whole design is O(chunk) memory, arXiv:1509.00519 eval).

2. **FLOP + byte accounting** — per-primitive FLOPs (``dot_general``/conv
   from dimension numbers, elementwise/reductions by element count as an
   honest 1-FLOP lower bound) with ``scan`` lengths multiplied through,
   and HBM traffic bracketed from both sides: ``bytes_accessed`` assumes
   no fusion (every equation round-trips HBM), ``bytes_accessed_fused``
   assumes perfect fusion (only program I/O moves). Matmul FLOPs must
   reconcile **bit-exactly** with ``utils/flops.py``'s analytic tables on
   the flagship config — pinned by tests/test_cost.py, so the two
   accountings cross-check each other — and the two traffic bounds give
   an arithmetic-intensity interval whose position against the chip's
   ridge point (``peak_flops_for_kind`` / ``peak_hbm_bytes_for_kind``)
   yields the roofline verdict: compute-bound, memory-bound, or
   fusion-dependent.

3. **collective accounting** — every ``psum``/``pmax``/``all_gather``/
   ``ppermute``/... counted and sized per mesh axis. The sharded score
   program's "ONE pmax + ONE psum" merge contract (PR 9) becomes a
   machine-checked invariant (test-pinned, and loud in the golden
   collective histograms), and bandwidth-shaped collectives that
   materialize a gathered axis on every device (``all_gather``,
   ``all_to_all``) are findings — an accidental reshard in a per-request
   program is a serving-latency cliff, not a style problem.

Results flow outward: ``utils/compile_cache`` stamps a ``static_cost``
record on every AOT registry entry at compile time (the capacity-bounded
executable store's budget input — ROADMAP item 1), ``bench.py`` stamps the
static roofline estimate beside every measured MFU, and ``scripts/check.py``
runs the CLI as a gate stage writing ``results/cost_report.json``.

Exit codes match the lint/audit CLIs: **0** clean, **1** findings,
**2** internal error — scripts/check.py classifies them the same way.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import sys
import traceback
from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

from iwae_replication_project_tpu.analysis.audit.core import (
    BARE_WAIVER,
    AuditFinding,
    AuditProgram,
)
from iwae_replication_project_tpu.analysis.audit.jaxprs import (
    COLLECTIVE_PRIMS,
    core_types,
    open_jaxpr,
    sub_jaxprs,
)
from iwae_replication_project_tpu.utils.dtypes import aval_bytes

#: bandwidth-shaped collectives: these materialize a gathered/resharded
#: axis on every device — a finding, not just a count (the merge contract
#: for the sharded score program is pure pmax+psum of [B]-vectors)
_FLAGGED_COLLECTIVES = frozenset({
    "all_gather", "all_gather_invariant", "all_to_all", "pgather",
})

#: control-flow / call primitives the walk recurses through structurally
_LOOP_PRIMS = ("scan", "while", "cond")

#: the two finding rules this analyzer can emit (waivable per program with
#: the audit framework's justified-waiver semantics)
RULE_MEMORY_BLOWUP = "memory-blowup"
RULE_ACCIDENTAL_GATHER = "accidental-allgather"

#: default memory-blowup threshold: an intermediate this many times the
#: program's own inputs is a materialized fan-out (the flagship suite's
#: honest worst case — the eval scorer's [chunk, B, 784] block — sits
#: near 6x, so 16x only fires on genuine blowups)
DEFAULT_BLOWUP_FACTOR = 16.0


@dataclasses.dataclass
class CostRecord:
    """Static cost facts of one traced program (all byte figures HBM)."""

    program: str
    input_bytes: int = 0
    output_bytes: int = 0
    peak_bytes: int = 0
    largest_intermediate_bytes: int = 0
    largest_intermediate_site: str = ""
    flops: float = 0.0
    matmul_flops: float = 0.0
    bytes_accessed: float = 0.0        # no-fusion upper bound on traffic
    bytes_accessed_fused: float = 0.0  # perfect-fusion lower bound (I/O)
    #: prim -> mesh-axis-tuple (comma-joined) -> {count, bytes}
    collectives: Dict[str, Dict[str, Dict[str, float]]] = \
        dataclasses.field(default_factory=dict)
    collective_bytes: float = 0.0
    #: while-loops whose trip count is a traced value: their bodies are
    #: counted ONCE (an honest lower bound, stamped rather than guessed)
    dynamic_while_loops: int = 0
    opaque_kernels: int = 0

    @property
    def intensity(self) -> Optional[float]:
        """FLOPs per HBM byte assuming no fusion (lower bound)."""
        return self.flops / self.bytes_accessed if self.bytes_accessed \
            else None

    @property
    def intensity_fused(self) -> Optional[float]:
        """FLOPs per HBM byte at perfect fusion (upper bound)."""
        return self.flops / self.bytes_accessed_fused \
            if self.bytes_accessed_fused else None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["intensity"] = self.intensity
        d["intensity_fused"] = self.intensity_fused
        return d


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

def resolve_chip(chip: Optional[str] = None) -> Tuple[str, str]:
    """``(device_kind, source)`` for the roofline tables: explicit ``--chip``
    wins; a TPU host contributes its real kind; any other host assumes v5e
    with the assumption stamped (never silently) — mirroring bench.py's
    peak-FLOPs detection contract."""
    if chip:
        return chip, "explicit --chip"
    import jax

    dev = jax.devices()[0]
    if dev.platform == "tpu":
        kind = getattr(dev, "device_kind", "tpu")
        return kind, f"detected device_kind {kind!r}"
    return "v5e", (f"host platform {dev.platform!r} has no TPU: assuming "
                   f"v5e — pass --chip to analyze for another generation")


def roofline(record: CostRecord, chip: str) -> dict:
    """The verdict: where the program's intensity interval sits against the
    chip's ridge point, plus the matmul-MFU ceiling the roofline admits
    (``matmul_flops / max(total_flops, fused_bytes * ridge)`` — what the
    bench's measured MFU is bounded by on this chip)."""
    from iwae_replication_project_tpu.utils.flops import (
        peak_flops_for_kind,
        peak_hbm_bytes_for_kind,
    )

    peak, peak_src = peak_flops_for_kind(chip)
    bw, bw_src = peak_hbm_bytes_for_kind(chip)
    out = {"chip": chip, "peak_flops": peak, "hbm_bytes_per_s": bw}
    if peak is None or bw is None:
        out["verdict"] = None
        out["verdict_null_reason"] = peak_src if peak is None else bw_src
        return out
    ridge = peak / bw
    out["ridge_flops_per_byte"] = ridge
    lo, hi = record.intensity, record.intensity_fused
    if lo is not None and lo >= ridge:
        out["verdict"] = "compute-bound"
    elif hi is not None and hi <= ridge:
        out["verdict"] = "memory-bound"
    else:
        out["verdict"] = "fusion-dependent"
    denom = max(record.flops, record.bytes_accessed_fused * ridge)
    out["static_mfu_ceiling"] = record.matmul_flops / denom if denom else None
    return out


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------

class CostAnalyzer:
    """One analyzer instance = one configuration (blow-up threshold);
    :meth:`analyze` is reusable across programs."""

    def __init__(self, blowup_factor: float = DEFAULT_BLOWUP_FACTOR):
        self.blowup_factor = float(blowup_factor)

    # -- entry points -------------------------------------------------------

    def analyze(self, prog: AuditProgram
                ) -> Tuple[CostRecord, List[AuditFinding]]:
        """Cost record + findings for one audited program, honoring the
        program's waivers with the audit framework's semantics (a justified
        waiver silences, a bare one is itself a finding)."""
        rec, findings = self.analyze_jaxpr(prog.name, prog.jaxpr)
        kept: List[AuditFinding] = []
        for rule, justification in prog.waivers.items():
            if rule in (RULE_MEMORY_BLOWUP, RULE_ACCIDENTAL_GATHER) and \
                    not (justification or "").strip():
                kept.append(AuditFinding(
                    program=prog.name, rule=BARE_WAIVER, location="waivers",
                    message=f"waiver for '{rule}' has no justification — "
                            f"every silenced hazard must carry its argument"))
        waived = {rule for rule, j in prog.waivers.items()
                  if (j or "").strip()}
        kept.extend(f for f in findings if f.rule not in waived)
        return rec, kept

    def analyze_jaxpr(self, name: str, jaxpr: Any
                      ) -> Tuple[CostRecord, List[AuditFinding]]:
        """Analyze one ``make_jaxpr`` trace (no waiver filtering)."""
        rec = CostRecord(program=name)
        findings: List[AuditFinding] = []
        self._walk(jaxpr, "", 1.0, rec, findings)
        rec.peak_bytes = self._frame_peak(jaxpr, "", rec)
        j = open_jaxpr(jaxpr)
        rec.input_bytes = sum(aval_bytes(v.aval) for v in
                              list(j.invars) + list(j.constvars))
        rec.output_bytes = sum(aval_bytes(v.aval) for v in j.outvars
                               if hasattr(v, "aval"))
        rec.bytes_accessed_fused = float(rec.input_bytes + rec.output_bytes)
        # the no-fusion bound can never be tighter than program I/O
        rec.bytes_accessed = max(rec.bytes_accessed, rec.bytes_accessed_fused)
        if rec.input_bytes and rec.largest_intermediate_bytes > \
                self.blowup_factor * rec.input_bytes:
            ratio = rec.largest_intermediate_bytes / rec.input_bytes
            findings.append(AuditFinding(
                program=name, rule=RULE_MEMORY_BLOWUP,
                location=rec.largest_intermediate_site,
                message=f"intermediate of "
                        f"{rec.largest_intermediate_bytes:,} bytes is "
                        f"{ratio:.1f}x the program's {rec.input_bytes:,} "
                        f"input bytes (threshold {self.blowup_factor:g}x) — "
                        f"a materialized fan-out this size is an OOM cliff "
                        f"at production k/batch; stream it through a "
                        f"scan/logsumexp carry or a blocked kernel"))
        return rec, sorted(set(findings))

    # -- pass 2 + 3: flops / traffic / collectives --------------------------

    def _walk(self, jaxpr: Any, path: str, mult: float,
              rec: CostRecord, findings: List[AuditFinding],
              in_kernel: bool = False) -> None:
        for i, eqn in enumerate(open_jaxpr(jaxpr).eqns):
            name = eqn.primitive.name
            loc = f"{path}/{name}[{i}]" if path else f"{name}[{i}]"

            if name in COLLECTIVE_PRIMS:
                self._collective(eqn, loc, mult, rec, findings)

            if name == "dot_general":
                f = mult * _dot_general_flops(eqn)
                rec.flops += f
                rec.matmul_flops += f
                if not in_kernel:
                    rec.bytes_accessed += mult * _eqn_io_bytes(eqn)
            elif name == "conv_general_dilated":
                f = mult * _conv_flops(eqn)
                rec.flops += f
                rec.matmul_flops += f
                if not in_kernel:
                    rec.bytes_accessed += mult * _eqn_io_bytes(eqn)
            elif name == "scan":
                length = float(eqn.params.get("length", 1))
                self._walk(eqn.params["jaxpr"], loc, mult * length,
                           rec, findings, in_kernel)
            elif name == "while":
                # trip count is a traced value: count the body ONCE and
                # stamp the approximation instead of inventing a trip count
                rec.dynamic_while_loops += 1
                self._walk(eqn.params["cond_jaxpr"], loc, mult,
                           rec, findings, in_kernel)
                self._walk(eqn.params["body_jaxpr"], loc, mult,
                           rec, findings, in_kernel)
            elif name == "cond":
                # exactly ONE branch executes per dispatch: every cost
                # field takes the branch-wise MAXIMUM (each independently —
                # the result is a bound, never a sum over exclusive paths,
                # which would e.g. double-count a psum present in both
                # branches of a guarded merge). Findings from EVERY branch
                # are kept: a hazard on any executable path is real.
                subs = []
                for branch in eqn.params["branches"]:
                    sub = CostRecord(program=rec.program)
                    self._walk(branch, loc, mult, sub, findings, in_kernel)
                    subs.append(sub)
                rec.flops += max(s.flops for s in subs)
                rec.matmul_flops += max(s.matmul_flops for s in subs)
                rec.bytes_accessed += max(s.bytes_accessed for s in subs)
                rec.collective_bytes += max(s.collective_bytes
                                            for s in subs)
                rec.dynamic_while_loops += max(s.dynamic_while_loops
                                               for s in subs)
                rec.opaque_kernels += max(s.opaque_kernels for s in subs)
                merged: Dict[Tuple[str, str], Dict[str, float]] = {}
                for s in subs:
                    for prim, axes in s.collectives.items():
                        for ax, c in axes.items():
                            slot = merged.setdefault(
                                (prim, ax), {"count": 0.0, "bytes": 0.0})
                            slot["count"] = max(slot["count"], c["count"])
                            slot["bytes"] = max(slot["bytes"], c["bytes"])
                for (prim, ax), c in merged.items():
                    slot = rec.collectives.setdefault(prim, {}).setdefault(
                        ax, {"count": 0.0, "bytes": 0.0})
                    slot["count"] += c["count"]
                    slot["bytes"] += c["bytes"]
                for s in subs:
                    if s.largest_intermediate_bytes > \
                            rec.largest_intermediate_bytes:
                        rec.largest_intermediate_bytes = \
                            s.largest_intermediate_bytes
                        rec.largest_intermediate_site = \
                            s.largest_intermediate_site
            elif name == "pallas_call":
                # opaque kernel: its interior lives in scoped VMEM, never
                # HBM (that is the point of the fused hot loop) — charge
                # only the HBM-visible operands/results, and approximate
                # its FLOPs by walking the kernel body per grid step
                rec.opaque_kernels += 1
                if not in_kernel:
                    rec.bytes_accessed += mult * _eqn_io_bytes(eqn)
                for _, sub in sub_jaxprs(eqn):
                    grid = eqn.params.get("grid_mapping", None)
                    steps = math.prod(getattr(grid, "grid", ()) or (1,))
                    self._walk(sub, loc, mult * steps, rec, findings,
                               in_kernel=True)
            elif _has_sub_jaxpr(eqn):
                for _, sub in sub_jaxprs(eqn):
                    self._walk(sub, loc, mult, rec, findings, in_kernel)
            else:
                rec.flops += mult * _pointwise_flops(eqn)
                if not in_kernel:
                    rec.bytes_accessed += mult * _eqn_io_bytes(eqn)

            if not in_kernel:
                # kernel-interior tiles are VMEM-resident (bounded by
                # ops/fused_likelihood.fits_vmem), not HBM intermediates
                self._note_intermediates(eqn, loc, rec)

    def _collective(self, eqn, loc: str, mult: float, rec: CostRecord,
                    findings: List[AuditFinding]) -> None:
        name = eqn.primitive.name
        axes = eqn.params.get("axes",
                              eqn.params.get("axis_name", ()))
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        key = ",".join(str(a) for a in axes) or "?"
        nbytes = mult * sum(aval_bytes(v.aval) for v in eqn.invars
                            if hasattr(v, "aval"))
        slot = rec.collectives.setdefault(name, {}).setdefault(
            key, {"count": 0.0, "bytes": 0.0})
        slot["count"] += mult
        slot["bytes"] += nbytes
        rec.collective_bytes += nbytes
        if name in _FLAGGED_COLLECTIVES:
            findings.append(AuditFinding(
                program=rec.program, rule=RULE_ACCIDENTAL_GATHER,
                location=loc,
                message=f"'{name}' over mesh axis ({key}) materializes the "
                        f"gathered axis on every device "
                        f"({int(nbytes):,} bytes per dispatch) — the "
                        f"sharded merge contract is reduction-shaped "
                        f"(pmax/psum of per-row scalars); an accidental "
                        f"reshard here is a serving-latency cliff"))

    def _note_intermediates(self, eqn, loc: str, rec: CostRecord) -> None:
        for v in eqn.outvars:
            if not hasattr(v, "aval") or type(v).__name__ == "DropVar":
                continue
            b = aval_bytes(v.aval)
            if b > rec.largest_intermediate_bytes:
                rec.largest_intermediate_bytes = b
                rec.largest_intermediate_site = loc

    # -- pass 1: live-range peak memory -------------------------------------

    def _frame_peak(self, jaxpr: Any, path: str, rec: CostRecord) -> int:
        """Peak resident HBM bytes of one call frame: frame inputs (and
        consts) live for the whole frame, intermediates die at last use,
        donation releases early, loop bodies count once."""
        _, _, Var, _ = core_types()
        j = open_jaxpr(jaxpr)
        n = len(j.eqns)
        last: Dict[Any, int] = {}
        for i, eqn in enumerate(j.eqns):
            for v in eqn.invars:
                if isinstance(v, Var):
                    last[v] = i
        for v in j.outvars:
            if isinstance(v, Var):
                last[v] = n
        frame_inputs = {v for v in list(j.invars) + list(j.constvars)}
        current = sum(aval_bytes(v.aval) for v in frame_inputs)
        peak = current
        for i, eqn in enumerate(j.eqns):
            donated = eqn.params.get("donated_invars") or ()
            freed_early: set = set()
            for d, v in zip(donated, eqn.invars):
                # a donated operand's buffer is handed to the callee: it is
                # reusable for outputs before they allocate — release it
                # ahead of the allocation if this call is its last use
                if d and isinstance(v, Var) and last.get(v) == i:
                    freed_early.add(v)
            current -= sum(aval_bytes(v.aval) for v in freed_early)
            out_alloc = sum(
                aval_bytes(v.aval) for v in eqn.outvars
                if isinstance(v, Var) and v in last)  # DCE'd outputs free
            peak = max(peak, current + out_alloc
                       + self._interior_bytes(eqn, path, rec))
            current += out_alloc
            for v in {v for v in eqn.invars if isinstance(v, Var)}:
                if last.get(v) == i and v not in frame_inputs \
                        and v not in freed_early:
                    current -= aval_bytes(v.aval)
        return peak

    def _interior_bytes(self, eqn, path: str, rec: CostRecord) -> int:
        """Transient working set a call-like equation holds BEYOND its own
        operands and results (both already counted in the caller's scan):
        the sub-frame's peak minus its I/O, clamped at zero. ``scan`` and
        ``while`` bodies count once — the carry/working buffers are reused
        across iterations, which is exactly the reuse the streaming eval
        scorer's O(chunk) memory contract relies on."""
        name = eqn.primitive.name
        if name == "pallas_call":
            return 0  # scoped VMEM, not HBM (fits_vmem owns that budget)
        interior = 0
        if name == "cond":
            return max((self._sub_transient(b, path, rec)
                        for b in eqn.params["branches"]), default=0)
        for _, sub in sub_jaxprs(eqn):
            interior += self._sub_transient(sub, path, rec)
        return interior

    def _sub_transient(self, sub: Any, path: str, rec: CostRecord) -> int:
        j = open_jaxpr(sub)
        io = sum(aval_bytes(v.aval) for v in
                 list(j.invars) + list(j.constvars)) + \
            sum(aval_bytes(v.aval) for v in j.outvars if hasattr(v, "aval"))
        return max(0, self._frame_peak(sub, path, rec) - io)


# ---------------------------------------------------------------------------
# per-primitive FLOP models
# ---------------------------------------------------------------------------

def _shape(v) -> Tuple[int, ...]:
    return tuple(getattr(getattr(v, "aval", None), "shape", ()) or ())


def _eqn_io_bytes(eqn) -> float:
    return float(sum(aval_bytes(v.aval) for v in eqn.invars
                     if hasattr(v, "aval"))
                 + sum(aval_bytes(v.aval) for v in eqn.outvars
                       if hasattr(v, "aval")))


def _dot_general_flops(eqn) -> float:
    """2 FLOPs per MAC from the dimension numbers — the same convention as
    utils/flops.py's analytic tables (the reconciliation tests pin the two
    equal on the flagship programs)."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = _shape(eqn.invars[0]), _shape(eqn.invars[1])
    batch = math.prod(lhs[a] for a in lb) if lb else 1
    contract = math.prod(lhs[a] for a in lc) if lc else 1
    m = math.prod(lhs[a] for a in range(len(lhs))
                  if a not in lc and a not in lb)
    n = math.prod(rhs[a] for a in range(len(rhs))
                  if a not in rc and a not in rb)
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    """2 * output elements * kernel taps * in-features / groups."""
    out = math.prod(_shape(eqn.outvars[0]))
    rhs = _shape(eqn.invars[1])
    dn = eqn.params["dimension_numbers"]
    rhs_spec = getattr(dn, "rhs_spec", None)
    if rhs_spec is None or not rhs:
        return 2.0 * out  # unknown layout: honest minimum
    taps = math.prod(rhs[a] for a in rhs_spec[2:]) if len(rhs_spec) > 2 else 1
    in_feat = rhs[rhs_spec[1]]
    groups = eqn.params.get("feature_group_count", 1)
    return 2.0 * out * taps * in_feat / max(groups, 1)


def _pointwise_flops(eqn) -> float:
    """1 FLOP per output element for compute prims, 0 for pure data
    movement — an honest lower bound in the utils/flops.py spirit (matmuls
    dominate; elementwise work rides along)."""
    name = eqn.primitive.name
    if name in _DATA_MOVEMENT:
        return 0.0
    if name.startswith("reduce_") or name in ("argmax", "argmin"):
        return float(math.prod(_shape(eqn.invars[0])))
    return float(sum(math.prod(_shape(v)) for v in eqn.outvars))


_DATA_MOVEMENT = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "expand_dims",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "rev", "gather", "scatter", "copy", "convert_element_type",
    "bitcast_convert_type", "stop_gradient", "iota", "random_wrap",
    "random_unwrap", "device_put", "split",
})


def _has_sub_jaxpr(eqn) -> bool:
    return next(sub_jaxprs(eqn), None) is not None


# ---------------------------------------------------------------------------
# suite + registry front doors
# ---------------------------------------------------------------------------

def analyze_programs(include: Optional[List[str]] = None,
                     blowup_factor: float = DEFAULT_BLOWUP_FACTOR
                     ) -> Tuple[Dict[str, CostRecord], List[AuditFinding]]:
    """Cost records + findings for the audited program suite (or a named
    subset — unknown names raise the registry's ValueError listing the
    valid programs, shared with ``iwae-audit --programs``)."""
    from iwae_replication_project_tpu.analysis.audit.programs import (
        build_programs)
    from iwae_replication_project_tpu.telemetry.spans import span

    analyzer = CostAnalyzer(blowup_factor=blowup_factor)
    records: Dict[str, CostRecord] = {}
    findings: List[AuditFinding] = []
    for prog in build_programs(include):
        with span(f"cost/{prog.name}"):
            rec, got = analyzer.analyze(prog)
        records[prog.name] = rec
        findings.extend(got)
    return records, findings


def registry_static_costs() -> List[dict]:
    """The live AOT registry's ``static_cost`` records (stamped by
    utils/compile_cache at compile time) — the executable store's
    per-entry budget inputs, surfaced through the CLI."""
    from iwae_replication_project_tpu.utils.compile_cache import (
        static_cost_records)

    out = []
    for name, build_key, sig, cost in static_cost_records():
        out.append({"name": name, "build_key": repr(build_key),
                    "static_cost": cost})
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="iwae-cost",
        description="Jaxpr-level cost analyzer: live-range peak memory, "
                    "FLOP/byte accounting with a roofline verdict, and "
                    "per-mesh-axis collective profiles over the repo's "
                    "real traced programs (trace-only — no compile).")
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument("--programs", default=None,
                   help="comma-separated subset of the audited programs "
                        "(default: the full suite)")
    p.add_argument("--blowup-factor", type=float,
                   default=DEFAULT_BLOWUP_FACTOR,
                   help="memory-blowup threshold: flag any intermediate "
                        "larger than this multiple of the program's input "
                        "bytes (default %(default)s)")
    p.add_argument("--chip", default=None,
                   help="device_kind substring for the roofline tables "
                        "(default: the host TPU's kind, or v5e with the "
                        "assumption stamped)")
    p.add_argument("--report", default=None,
                   help="also write the per-program cost report JSON here "
                        "(the results/cost_report.json artifact)")
    p.add_argument("--registry", action="store_true",
                   help="include static_cost records of the live AOT "
                        "registry (in-process entries only)")
    return p


def _report_payload(records: Dict[str, CostRecord],
                    findings: List[AuditFinding], chip: str,
                    chip_source: str, registry: Optional[List[dict]]
                    ) -> dict:
    payload = {
        "chip": {"kind": chip, "source": chip_source},
        "programs": {
            name: {**rec.to_dict(), "roofline": roofline(rec, chip)}
            for name, rec in records.items()},
        "findings": [f.to_dict() for f in findings],
        "counts": dict(Counter(f.rule for f in findings)),
        "total": len(findings),
    }
    if registry is not None:
        payload["registry"] = registry
    return payload


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        # tracing may trigger tiny init compiles (model params); route them
        # through the shared persistent cache like every other entry point
        from iwae_replication_project_tpu.utils.compile_cache import (
            setup_persistent_cache)
        setup_persistent_cache(None)

        include = [s.strip() for s in args.programs.split(",") if s.strip()] \
            if args.programs else None
        records, findings = analyze_programs(
            include, blowup_factor=args.blowup_factor)
        chip, chip_source = resolve_chip(args.chip)
        registry = registry_static_costs() if args.registry else None
        payload = _report_payload(records, findings, chip, chip_source,
                                  registry)
        if args.report:
            with open(args.report, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=2)
                f.write("\n")
    except (ValueError, FileNotFoundError, OSError) as e:
        print(f"iwae-cost: error: {e}", file=sys.stderr)
        return 2
    except Exception:
        print("iwae-cost: internal error:", file=sys.stderr)
        traceback.print_exc()
        return 2

    if args.format == "json":
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.human())
        print(f"analyzed {len(records)} program(s) for chip {chip} "
              f"({chip_source})")
        hdr = (f"  {'program':<24} {'peak MB':>9} {'GFLOP':>9} "
               f"{'matmul%':>8} {'AI (fus)':>9}  verdict / collectives")
        print(hdr)
        for name, rec in records.items():
            rl = roofline(rec, chip)
            coll = "; ".join(
                f"{prim}[{ax}] x{int(c['count'])}"
                for prim, axes in sorted(rec.collectives.items())
                for ax, c in sorted(axes.items())) or "-"
            pct = (100.0 * rec.matmul_flops / rec.flops) if rec.flops else 0.0
            ai = rec.intensity_fused
            print(f"  {name:<24} {rec.peak_bytes / 1e6:>9.2f} "
                  f"{rec.flops / 1e9:>9.3f} {pct:>7.1f}% "
                  f"{(ai if ai is not None else 0):>9.1f}  "
                  f"{rl.get('verdict')} / {coll}")
        if findings:
            tally = ", ".join(
                f"{rule}: {n}" for rule, n in
                sorted(Counter(f.rule for f in findings).items()))
            print(f"\n{len(findings)} finding(s) ({tally})")
        else:
            print("iwae-cost: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
