"""Jaxpr plumbing shared by every audit pass: version-portable access to the
core types, recursive equation iteration (descending into the sub-jaxprs that
``pjit``/``scan``/``cond``/``custom_vjp``/``pallas_call`` carry in their
params), and the structural program signature the golden snapshot tests pin.

The audit deliberately works on *traced* programs (``jax.make_jaxpr``
output): that is the representation XLA actually compiles, so dataflow facts
proven here hold for the executable — unlike the AST rules next door, which
see only the source text that *produced* the trace.
"""

from __future__ import annotations

import functools
from collections import Counter
from typing import Any, Iterator, List, Tuple


#: cross-device communication primitives — the collective sub-histogram of
#: :func:`signature` and the cost analyzer's per-axis accounting
#: (analysis/audit/cost.py) share this one definition
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_gather_invariant", "all_to_all", "reduce_scatter", "pgather",
    "pbroadcast", "psum_scatter",
})


@functools.lru_cache(maxsize=1)
def core_types() -> Tuple[type, type, type, type]:
    """``(Jaxpr, ClosedJaxpr, Var, Literal)`` for the running jax version."""
    import jax

    c = jax.core
    return c.Jaxpr, c.ClosedJaxpr, c.Var, c.Literal


def open_jaxpr(j: Any) -> Any:
    """The plain ``Jaxpr`` under a ``ClosedJaxpr`` (identity otherwise)."""
    return j.jaxpr if hasattr(j, "jaxpr") else j


def sub_jaxprs(eqn: Any) -> Iterator[Tuple[str, Any]]:
    """``(param_key, Jaxpr | ClosedJaxpr)`` for every sub-program an equation
    carries — ``pjit``/``remat2`` (``jaxpr``), ``scan``/``while`` bodies,
    ``cond`` ``branches``, ``custom_vjp_call_jaxpr`` (``fun_jaxpr``),
    ``pallas_call`` kernels. Non-jaxpr params (thunks, shardings) are skipped.
    """
    Jaxpr, ClosedJaxpr, _, _ = core_types()
    for key in sorted(eqn.params):
        val = eqn.params[key]
        items = val if isinstance(val, (tuple, list)) else (val,)
        for item in items:
            if isinstance(item, (Jaxpr, ClosedJaxpr)):
                yield key, item


def iter_eqns(jaxpr: Any, path: str = "") -> Iterator[Tuple[str, Any]]:
    """Every equation in the program, depth-first, with a human-readable
    location path like ``pjit[0]/scan[1]/reduce_sum[4]``."""
    for i, eqn in enumerate(open_jaxpr(jaxpr).eqns):
        here = f"{path}/{eqn.primitive.name}[{i}]" if path \
            else f"{eqn.primitive.name}[{i}]"
        yield here, eqn
        for _, sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub, here)


def used_vars(jaxpr: Any) -> set:
    """Vars of THIS jaxpr that are consumed: referenced by some equation or
    returned as an output. (Sub-jaxprs own their vars; an outer var feeding a
    sub-call appears in that call equation's invars, so one level suffices.)
    """
    _, _, Var, _ = core_types()
    j = open_jaxpr(jaxpr)
    used = {v for v in j.outvars if isinstance(v, Var)}
    for eqn in j.eqns:
        used.update(v for v in eqn.invars if isinstance(v, Var))
    return used


def primitive_histogram(jaxpr: Any) -> Counter:
    """Recursive ``{primitive name: count}`` over the whole program."""
    return Counter(eqn.primitive.name for _, eqn in iter_eqns(jaxpr))


def signature(jaxpr: Any) -> dict:
    """Structural fingerprint for the golden snapshot tests: total equation
    count, the primitive histogram, and the collective-primitive histogram
    broken out on its own key. Shape-free on purpose — ``k``/batch scaling
    changes array extents, not program structure, so the goldens stay
    stable across problem sizes and only genuine program drift (new
    primitives, changed composition) trips them.

    The ``collectives`` sub-histogram repeats information already in
    ``primitives`` deliberately: the sharded score program's merge contract
    is exactly ONE ``pmax`` + ONE ``psum`` (PR 9), and an extra reshard
    must fail CI as a *named* collective drift, not as a mystery +1 in a
    200-entry histogram diff — cost drift should read as cost drift."""
    hist = primitive_histogram(jaxpr)
    return {"eqn_count": int(sum(hist.values())),
            "primitives": {name: int(n) for name, n in sorted(hist.items())},
            "collectives": {name: int(n) for name, n in sorted(hist.items())
                            if name in COLLECTIVE_PRIMS}}


def outer_avals(closed_jaxpr: Any) -> List[Any]:
    """Abstract values of the program's top-level inputs."""
    return [v.aval for v in open_jaxpr(closed_jaxpr).invars]
