"""The built-in audit passes. Importing this module registers all four.

Each pass is the jaxpr-level twin of a hazard this repo has actually hit:

* ``donation-safety``      — RESULTS.md §5: donated buffers + CPU persistent
  cache corrupt memory; and a donated-but-unconsumed input invalidates the
  caller's buffer for nothing.
* ``padding-taint``        — the IWAE bound is one unmasked padded weight
  away from silent bias (ops/taint.py carries the dataflow engine).
* ``host-transfer``        — callbacks/infeed inside per-step programs stall
  the dispatch pipeline from *inside* the graph, where the AST host-sync
  rule cannot see them.
* ``recompile-cardinality`` — weak types, python-scalar signature leaves,
  and scalar closure leaks each mint gratuitous executables; under serving
  traffic that is a compile storm (and an unbounded AOT registry).
"""

from __future__ import annotations

from typing import Iterator, List

from iwae_replication_project_tpu.analysis.audit.core import (
    AuditEnv,
    AuditFinding,
    AuditPass,
    AuditProgram,
    register,
)
from iwae_replication_project_tpu.analysis.audit.jaxprs import (
    iter_eqns,
    open_jaxpr,
    outer_avals,
    used_vars,
)
from iwae_replication_project_tpu.analysis.audit.taint import TaintEngine

#: jaxpr-level primitives that move data or control to the host mid-program
_HOST_PRIM_NAMES = {"infeed", "outfeed", "debug_print"}


def _is_host_prim(name: str) -> bool:
    return name in _HOST_PRIM_NAMES or "callback" in name


@register
class DonationSafetyPass(AuditPass):
    name = "donation-safety"
    summary = ("every donated input is consumed, and donation never rides a "
               "CPU persistent-cache executable (RESULTS.md §5)")

    def check(self, prog: AuditProgram, env: AuditEnv
              ) -> Iterator[AuditFinding]:
        donating_sites: List[str] = []
        for loc, eqn in iter_eqns(prog.jaxpr):
            donated = eqn.params.get("donated_invars")
            if not donated or not any(donated):
                continue
            donating_sites.append(loc)
            sub = eqn.params.get("jaxpr")
            if sub is None:
                continue
            used = used_vars(sub)
            invars = open_jaxpr(sub).invars
            for i, d in enumerate(donated):
                if d and i < len(invars) and invars[i] not in used:
                    yield AuditFinding(
                        program=prog.name, rule=self.name, location=loc,
                        message=f"input #{i} is donated but never consumed "
                                f"by the program — the caller's buffer is "
                                f"invalidated for nothing (and any later "
                                f"read of it is backend-dependent garbage)")
        if donating_sites and env.backend == "cpu" and env.cache_dir:
            yield AuditFinding(
                program=prog.name, rule=self.name,
                location=donating_sites[0],
                message="program donates buffers while the persistent "
                        "compilation cache is active on the CPU backend — "
                        "cache-deserialized XLA:CPU executables mishandle "
                        "input-output aliasing (RESULTS.md §5); gate the "
                        "donation on utils.compile_cache.donation_safe()")


@register
class PaddingTaintPass(AuditPass):
    name = "padding-taint"
    summary = ("padded rows (declared inputs + pad equations) provably never "
               "reach a reduce/logsumexp/contraction unmasked")

    def check(self, prog: AuditProgram, env: AuditEnv
              ) -> Iterator[AuditFinding]:
        from iwae_replication_project_tpu.telemetry.registry import (
            get_registry)

        findings: List[AuditFinding] = []
        engine = TaintEngine(report=lambda loc, msg: findings.append(
            AuditFinding(program=prog.name, rule=self.name, location=loc,
                         message=msg)))
        engine.run(prog.jaxpr, prog.taints)
        reg = get_registry()
        if engine.stats.default_propagation:
            reg.counter("audit/padding-taint/default-propagation").inc(
                engine.stats.default_propagation)
        if engine.stats.opaque_calls:
            reg.counter("audit/padding-taint/opaque-kernels").inc(
                engine.stats.opaque_calls)
        if engine.stats.unverified_mask_discharges:
            reg.counter("audit/padding-taint/unverified-mask-discharges").inc(
                engine.stats.unverified_mask_discharges)
        yield from findings


@register
class HostTransferPass(AuditPass):
    name = "host-transfer"
    summary = ("no callbacks/infeed/outfeed inside hot programs — the "
               "jaxpr-level twin of the AST host-sync rule")

    def check(self, prog: AuditProgram, env: AuditEnv
              ) -> Iterator[AuditFinding]:
        if not prog.hot:
            return
        for loc, eqn in iter_eqns(prog.jaxpr):
            name = eqn.primitive.name
            if _is_host_prim(name):
                yield AuditFinding(
                    program=prog.name, rule=self.name, location=loc,
                    message=f"'{name}' inside a hot program forces a "
                            f"device<->host round-trip on every dispatch — "
                            f"move the transfer to the driver layer (or "
                            f"waive with justification for a debug build)")


@register
class RecompileCardinalityPass(AuditPass):
    name = "recompile-cardinality"
    summary = ("no weak types, python-scalar signature leaves, or scalar "
               "closure leaks that fragment the jit/AOT caches")

    def check(self, prog: AuditProgram, env: AuditEnv
              ) -> Iterator[AuditFinding]:
        yield from self._check_avals(prog)
        yield from self._check_consts(prog)
        if prog.sig_args is not None:
            from iwae_replication_project_tpu.utils.compile_cache import (
                _abstract_signature)
            yield from self._check_signature(
                prog.name, "signature", _abstract_signature(prog.sig_args))

    def check_env(self, env: AuditEnv) -> Iterator[AuditFinding]:
        # the live AOT registry: once per audit, never behind a per-program
        # waiver (and counted once, not once per audited program)
        for name, build_key, sig in (env.registry or ()):
            yield from self._check_signature(
                f"aot:{name}", "registry", sig)

    def _check_avals(self, prog: AuditProgram) -> Iterator[AuditFinding]:
        for i, aval in enumerate(outer_avals(prog.jaxpr)):
            if getattr(aval, "weak_type", False):
                yield AuditFinding(
                    program=prog.name, rule=self.name, location=f"invar[{i}]",
                    message=f"program input #{i} is weak-typed ({aval}) — "
                            f"weak and committed dtypes trace to distinct "
                            f"executables; pass a committed array "
                            f"(jnp.asarray with an explicit dtype)")

    def _check_consts(self, prog: AuditProgram) -> Iterator[AuditFinding]:
        import jax

        consts = getattr(prog.jaxpr, "consts", None) or ()
        for i, c in enumerate(consts):
            try:
                aval = jax.core.get_aval(c)
            except Exception:
                continue
            if getattr(aval, "weak_type", False) and \
                    getattr(aval, "shape", None) == ():
                yield AuditFinding(
                    program=prog.name, rule=self.name, location=f"const[{i}]",
                    message=f"python scalar captured by closure as a traced "
                            f"constant (value {c!r}) — every distinct value "
                            f"rebuilds/re-traces the program; thread it as "
                            f"an argument or commit it to an array")

    def _check_signature(self, program: str, loc: str, sig
                         ) -> Iterator[AuditFinding]:
        # leaf grammar is compile_cache._abstract_signature's: arrays are
        # (shape tuple, dtype str, sharding str, weak bool); python scalars
        # are (type name, repr)
        _, leaves = sig
        for i, leaf in enumerate(leaves):
            if len(leaf) == 2:
                tname, rep = leaf
                if tname not in ("int", "float", "bool", "complex"):
                    # kwarg NAMES flatten into the signature pytree as str
                    # leaves — fixed structure per program, not per-value
                    # fragmentation; only numeric/bool scalars mint an
                    # executable per value
                    continue
                yield AuditFinding(
                    program=program, rule=self.name,
                    location=f"{loc}:leaf[{i}]",
                    message=f"python {tname} scalar ({rep}) in the dispatch "
                            f"signature — the AOT registry compiles one "
                            f"executable PER VALUE; make it a device array "
                            f"or a deliberate static in the build key")
            elif len(leaf) >= 4 and leaf[3]:
                shape, dtype = leaf[0], leaf[1]
                yield AuditFinding(
                    program=program, rule=self.name,
                    location=f"{loc}:leaf[{i}]",
                    message=f"weak-typed {dtype}{list(shape)} leaf in the "
                            f"dispatch signature — weak/committed variants "
                            f"register separate executables and double the "
                            f"warm path's cache footprint")
