"""The audited program suite: the repo's REAL programs, traced.

Builders return :class:`~.core.AuditProgram`s for exactly the programs the
production stack dispatches — the jitted train step (training/train_step.py),
the chunked k=5000 eval scorer (evaluation/metrics.streaming_log_px), the
three serving programs (serving/programs.py, with their declared padded-row
taints), and all three ops/hot_loop.py paths composed with the
``iwae_per_example`` reduction they feed. Tracing is ``jax.make_jaxpr`` only:
no compile, no execution, so the full suite builds in seconds on any host.

Shapes are audit-representative, not production-sized: taint/donation/
transfer findings are properties of program *structure*, which k and batch
scale without changing (the same fact that keeps the golden jaxpr signatures
shape-free). The hot-loop shapes are chosen with pairwise-distinct padded
axis sizes so the opaque-kernel size-matching rule (taint.py) cannot
conflate axes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from iwae_replication_project_tpu.analysis.audit.core import AuditProgram

#: program names in build order (the CLI's default suite)
PROGRAM_NAMES = (
    "train_step",
    "eval_scorer_k5000",
    "serve_score",
    "serve_encode",
    "serve_decode",
    "serve_score_fused",
    "serve_score_sharded",
    "hot_loop_reference",
    "hot_loop_blocked_scan",
    "hot_loop_pallas",
)


def _taint_indices(args: tuple, tainted: Sequence, spec: Dict[int, Optional[int]]
                   ) -> Dict[int, Dict[int, Optional[int]]]:
    """Flat-invar taint map: leaves of `args` that are (identically) one of
    `tainted` get `spec`. Identity matching is exact — builders pass the
    same array objects they trace with."""
    import jax

    out: Dict[int, Dict[int, Optional[int]]] = {}
    for i, leaf in enumerate(jax.tree.leaves(args)):
        if any(leaf is t for t in tainted):
            out[i] = dict(spec)
    return out


def _model_state():
    """One small flagship-architecture model shared by every builder (init
    runs a handful of tiny CPU programs; cached per process)."""
    global _STATE_CACHE
    if _STATE_CACHE is None:
        import jax

        from iwae_replication_project_tpu.models.iwae import ModelConfig
        from iwae_replication_project_tpu.training.train_step import (
            create_train_state)
        cfg = ModelConfig.two_layer(likelihood="logits")
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        _STATE_CACHE = (cfg, state)
    return _STATE_CACHE


_STATE_CACHE = None


def build_train_step() -> AuditProgram:
    """The jitted training step, donation mirroring the driver: donate is
    the executable store's donation_allowed() gate exactly as experiment.py
    asks it, so auditing on a TPU host audits the donating program and on
    CPU the cache-safe one.
    """
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.training.train_step import (
        make_train_step)
    from iwae_replication_project_tpu.utils.compile_cache import (
        donation_allowed)

    cfg, state = _model_state()
    step = make_train_step(ObjectiveSpec(name="IWAE", k=8), cfg,
                           donate=donation_allowed())
    batch = jnp.zeros((16, cfg.x_dim), jnp.float32)
    return AuditProgram(
        name="train_step",
        jaxpr=jax.make_jaxpr(step)(state, batch),
        sig_args=((state, batch), {}))


def build_eval_scorer() -> AuditProgram:
    """The paper-grade chunked NLL scorer: k=5000 in 250-sample blocks
    through the online-logsumexp scan carry."""
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.evaluation.metrics import (
        streaming_log_px)

    cfg, state = _model_state()
    key = jax.random.PRNGKey(1)
    x = jnp.zeros((16, cfg.x_dim), jnp.float32)

    def scorer(params, key, x):
        return streaming_log_px(params, cfg, key, x, k=5000, chunk=250)

    return AuditProgram(
        name="eval_scorer_k5000",
        jaxpr=jax.make_jaxpr(scorer)(state.params, key, x),
        sig_args=((state.params, key, x), {}))


def build_serving(op: str) -> AuditProgram:
    """One serving program at a padded bucket: bucket 8 holding 5 real rows,
    with the op's declared padded-row kwargs tainted beyond row 5."""
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.serving.programs import (
        PADDED_ROW_KWARGS,
        PROGRAMS,
    )

    cfg, state = _model_state()
    cfg = dataclasses.replace(cfg, fused_likelihood=False)  # the engine's pin
    program, takes_k = PROGRAMS[op]
    bucket, real = 8, 5
    base_key = jax.random.PRNGKey(2)
    seeds = jnp.zeros((bucket,), jnp.int32)
    dim = cfg.n_latent_enc[-1] if op == "decode" else cfg.x_dim
    payload = jnp.zeros((bucket, dim), jnp.float32)
    kwargs = {"base_key": base_key, "seeds": seeds,
              ("h_top" if op == "decode" else "x"): payload}
    static = {"cfg": cfg, **({"k": 4} if takes_k else {})}

    def fn(params, base_key, seeds, payload):
        kw = dict(kwargs)
        kw["base_key"], kw["seeds"] = base_key, seeds
        kw["h_top" if op == "decode" else "x"] = payload
        return program(params, **kw, **static)

    args = (state.params, base_key, seeds, payload)
    tainted = [kwargs[name] for name in PADDED_ROW_KWARGS[op]]
    return AuditProgram(
        name=f"serve_{op}",
        jaxpr=jax.make_jaxpr(fn)(*args),
        taints=_taint_indices(args, tainted, {0: real}),
        sig_args=(((state.params,),
                   tuple(sorted(kwargs.items(), key=lambda kv: kv[0]))), {}))


def build_serving_fused(path: str = "blocked_scan") -> AuditProgram:
    """The UNPINNED serving score program (ISSUE 12): the same row-vmapped
    composition as ``serve_score``, under the dispatch config the lifted
    engine gate bakes when the probe admits a fused path — here the
    blocked-scan pin, which traces identically on every host (the pallas
    pin's kernel interior is opaque to the taint pass anyway) and routes
    the per-row decoder block through the remat'd hot-loop dispatcher the
    padding-taint pass must prove clean."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.serving.programs import (
        PADDED_ROW_KWARGS,
        PROGRAMS,
    )

    cfg, state = _model_state()
    # the engine gate's fused dispatch config (serving/engine._resolve_kernel)
    cfg = _dc.replace(cfg, likelihood="logits", fused_likelihood=True,
                      hot_loop_path=path)
    program, _ = PROGRAMS["score"]
    bucket, real = 8, 5
    base_key = jax.random.PRNGKey(2)
    seeds = jnp.zeros((bucket,), jnp.int32)
    payload = jnp.zeros((bucket, cfg.x_dim), jnp.float32)
    kwargs = {"base_key": base_key, "seeds": seeds, "x": payload}
    static = {"cfg": cfg, "k": 4}

    def fn(params, base_key, seeds, payload):
        return program(params, base_key=base_key, seeds=seeds, x=payload,
                       **static)

    args = (state.params, base_key, seeds, payload)
    tainted = [kwargs[name] for name in PADDED_ROW_KWARGS["score_fused"]]
    return AuditProgram(
        name="serve_score_fused",
        jaxpr=jax.make_jaxpr(fn)(*args),
        taints=_taint_indices(args, tainted, {0: real}),
        sig_args=(((state.params,),
                   tuple(sorted(kwargs.items(), key=lambda kv: kv[0]))), {}))


def build_serving_sharded() -> AuditProgram:
    """The mesh-sharded dynamic-k score program (ShardedScoreEngine's
    dispatch) at a padded bucket: bucket 8 holding 5 real rows on a 1x1
    mesh, k=10 over k_chunk=4 blocks — so the traced program carries the
    dynamic fori_loop (ragged final block masked in-graph) AND both
    declared padded axes, exactly the dataflow the taint pass must prove
    clean through the shard_map + while-loop carry."""
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.parallel.mesh import make_mesh
    from iwae_replication_project_tpu.serving.programs import (
        PADDED_ROW_KWARGS,
        make_sharded_score_rows,
    )

    cfg, state = _model_state()
    cfg = dataclasses.replace(cfg, fused_likelihood=False)  # the engine's pin
    mesh = make_mesh(dp=1, sp=1, devices=jax.devices()[:1])
    program = make_sharded_score_rows(cfg, mesh, k_chunk=4)
    bucket, real = 8, 5
    base_key = jax.random.PRNGKey(5)
    seeds = jnp.zeros((bucket,), jnp.int32)
    payload = jnp.zeros((bucket, cfg.x_dim), jnp.float32)
    k_arr = jnp.int32(10)

    def fn(params, base_key, seeds, payload, k_arr):
        return program(params, base_key, seeds, payload, k_arr)

    args = (state.params, base_key, seeds, payload, k_arr)
    kwargs = {"seeds": seeds, "x": payload}
    tainted = [kwargs[name] for name in PADDED_ROW_KWARGS["score_sharded"]]
    return AuditProgram(
        name="serve_score_sharded",
        jaxpr=jax.make_jaxpr(fn)(*args),
        taints=_taint_indices(args, tainted, {0: real}),
        sig_args=((state.params, base_key, seeds, payload, k_arr), {}))


def build_hot_loop(path: str) -> AuditProgram:
    """One hot-loop path composed with the estimator reduction it feeds
    (``iwae_per_example``'s logsumexp over k) — the padded-tile dataflow
    (pad -> kernel -> slice -> logsumexp) is exactly what the taint pass
    must prove clean. Shape sizes are pairwise distinct (see module doc)."""
    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.objectives.estimators import (
        iwae_per_example)
    from iwae_replication_project_tpu.ops.hot_loop import decoder_score

    k, b, h1_dim, hid, pix = 12, 24, 20, 40, 30
    out_params = {
        "l1": {"w": jnp.zeros((h1_dim, hid)), "b": jnp.zeros((hid,))},
        "l2": {"w": jnp.zeros((hid, hid)), "b": jnp.zeros((hid,))},
        "out": {"w": jnp.zeros((hid, pix)), "b": jnp.zeros((pix,))},
    }
    x = jnp.zeros((b, pix), jnp.float32)
    h1 = jnp.zeros((k, b, h1_dim), jnp.float32)

    def fn(out_params, x, h1):
        lw = decoder_score(out_params, x, h1, on_tpu=False, force_path=path)
        return iwae_per_example(lw)

    return AuditProgram(
        name=f"hot_loop_{path}",
        jaxpr=jax.make_jaxpr(fn)(out_params, x, h1),
        sig_args=((out_params, x, h1), {}))


def build_programs(include: Optional[Sequence[str]] = None
                   ) -> List[AuditProgram]:
    """The full audited suite (or the named subset), in PROGRAM_NAMES order."""
    builders = {
        "train_step": build_train_step,
        "eval_scorer_k5000": build_eval_scorer,
        "serve_score": lambda: build_serving("score"),
        "serve_encode": lambda: build_serving("encode"),
        "serve_decode": lambda: build_serving("decode"),
        "serve_score_fused": build_serving_fused,
        "serve_score_sharded": build_serving_sharded,
        "hot_loop_reference": lambda: build_hot_loop("reference"),
        "hot_loop_blocked_scan": lambda: build_hot_loop("blocked_scan"),
        "hot_loop_pallas": lambda: build_hot_loop("pallas"),
    }
    names = list(include) if include else list(PROGRAM_NAMES)
    unknown = set(names) - set(builders)
    if unknown:
        raise ValueError(f"unknown program(s): {sorted(unknown)}; "
                         f"known: {sorted(builders)}")
    from iwae_replication_project_tpu.telemetry.spans import span

    out = []
    for name in names:
        with span(f"audit/trace/{name}"):
            out.append(builders[name]())
    return out
