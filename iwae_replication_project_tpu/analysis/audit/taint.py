"""Padding-taint dataflow over jaxprs.

The IWAE estimator is an average of ``K`` importance weights through a
``logsumexp`` (Burda et al., arXiv:1509.00519): ONE unmasked padded weight
entering that reduction biases the bound silently — ``exp(0) = 1`` is a
perfectly plausible weight, so nothing NaNs, the number is just wrong. The
same failure class applies to every padded axis this framework manufactures:
serving's bucket padding (rows), the hot-loop kernels' tile padding (k,
batch, pixels), and any future kernel path. PR 6 pinned these with runtime
parity tests; this pass turns the property into a *static proof obligation*
on the traced program.

Model: a **taint** is ``{axis: real_extent}`` on an array — indices
``>= real_extent`` along ``axis`` may be padding (``None`` = unknown, the
whole axis is suspect). Taint enters a program two ways:

* declared on program inputs (serving programs declare their padded-row
  kwargs in ``serving/programs.PADDED_ROW_KWARGS``);
* seeded automatically at every ``pad`` equation — the tile padding inside
  ``ops/hot_loop.py``/``ops/fused_likelihood.py`` needs no declaration.

Propagation is per-primitive; the two *discharge* rules are

* ``select_n`` whose predicate is a **comparison against an iota** along the
  tainted axis, with the polarity checked: the case the *padded* region
  selects (``pred`` False for ``iota < n``-style masks, True for
  ``iota >= n``-style) must itself be clean on that axis — the
  ``jnp.where(iota < n, x, neutral)`` masking idiom. A raw iota that never
  went through a comparison, or an inverted mask that hands padded rows the
  data operand, discharges nothing. When the comparison bound is a literal
  it is additionally checked against the taint's real extent (a wrong
  boundary like ``iota < padded_size`` keeps padded rows and discharges
  nothing); traced bounds are trusted and counted
  (``unverified-mask-discharges``); and
* ``slice`` with ``start 0, limit <= real_extent`` (the ``out[:k, :b]``
  unpad idiom) clears it exactly.

A **finding** is any combining operation over a still-tainted axis: a
``reduce_*``, a ``dot_general`` contraction, a ``sort`` (order statistics
admit padded values), or a ``scan`` whose xs are tainted along the scan axis
(padded elements fold into the carry).

Known approximations (each deliberately conservative *for this repo's
program shapes*, and counted on the telemetry registry so drift is visible):

* ``pallas_call`` is opaque — kernel interiors are covered by the runtime
  parity pins (tests/test_hot_loop.py padding-never-leaks), so outputs
  inherit operand taint by exact axis-size matching and the XLA-level
  dataflow around the kernel (pad -> kernel -> slice -> logsumexp) is what
  gets proven;
* a reshape that merges a tainted axis taints the merged axis with unknown
  extent; gather/scatter and unrecognized primitives fall back to a
  conservative all-axes taint (``default-propagation`` counter).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from iwae_replication_project_tpu.analysis.audit.jaxprs import (
    core_types,
    open_jaxpr,
)

#: axis -> first padded index (None = unknown; the whole axis is suspect)
Taint = Dict[int, Optional[int]]

#: primitives that are value-wise elementwise over equal-shaped operands
#: (scalars ride along as rank-0); output taint = axiswise union
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "rem", "pow", "max", "min", "atan2",
    "nextafter", "and", "or", "xor", "not", "shift_left",
    "shift_right_logical", "shift_right_arithmetic",
    "eq", "ne", "lt", "le", "gt", "ge",
    "neg", "sign", "abs", "floor", "ceil", "round", "is_finite",
    "exp", "exp2", "expm1", "log", "log1p", "tanh", "logistic", "erf",
    "erfc", "erf_inv", "sqrt", "rsqrt", "cbrt", "square", "integer_pow",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "convert_element_type", "stop_gradient", "copy", "clamp",
    "population_count", "clz", "reduce_precision", "real", "imag",
}

#: reductions: combining every index of the reduced axes — tainted axis in
#: `axes` without a prior discharge is THE hazard this pass exists for
_REDUCES = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
            "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin"}

#: same-shape cumulative ops: corrupted prefix stays inside the padded
#: region for forward cumulation (taint preserved, not discharged); with
#: ``reverse=True`` the padded tail accumulates INTO every real row, so the
#: whole axis becomes suspect (extent -> None, undischargeable by slicing)
_CUMULATIVE = {"cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp"}

#: elementwise prims a raw iota mark rides through: structural copies ONLY.
#: Arithmetic (even monotone: add shifts the indices) drops the mark —
#: a raw mark must mean "this IS the index along that axis" so that a later
#: literal comparison threshold can be checked against the taint extent
_IOTA_TRANSPARENT = {"convert_element_type", "copy", "stop_gradient",
                     "reduce_precision"}

#: comparisons that mint a polarity-carrying mask from a raw iota operand
_CMP_PRIMS = {"lt", "le", "gt", "ge"}

#: single-sub-jaxpr call-like primitives with 1:1 (or tail-aligned) invars
_CALL_PRIMS = {"pjit", "closed_call", "core_call", "remat2", "remat",
               "custom_jvp_call", "custom_jvp_call_jaxpr",
               "custom_vjp_call", "custom_vjp_call_jaxpr", "shard_map",
               "checkpoint", "custom_lin"}


def _merge_extent(a: Optional[int], b: Optional[int]) -> Optional[int]:
    return None if a is None or b is None else min(a, b)


def _union(*taints: Taint) -> Taint:
    out: Taint = {}
    for t in taints:
        for ax, ext in t.items():
            out[ax] = _merge_extent(out[ax], ext) if ax in out else ext
    return out


@dataclasses.dataclass
class TaintStats:
    """Honesty counters: how often the engine had to approximate."""

    default_propagation: int = 0
    opaque_calls: int = 0
    #: select_n discharges whose mask threshold (or taint extent) was a
    #: traced value the engine could not compare statically — the runtime
    #: parity pins' jurisdiction, counted so the trust surface is visible
    unverified_mask_discharges: int = 0


class TaintEngine:
    """One propagation run over one closed jaxpr (recursing into subs).

    `report(location, message)` is called for every unmasked combine over a
    tainted axis. Findings are deduplicated by (location, message) so scan
    fixpoint iterations do not multiply them.
    """

    def __init__(self, report: Callable[[str, str], None]):
        self._seen: set = set()
        self._report = report
        self._quiet = 0  # >0 inside fixpoint warm-up iterations
        self.stats = TaintStats()

    # -- plumbing -----------------------------------------------------------

    def finding(self, loc: str, msg: str) -> None:
        if self._quiet:
            return
        if (loc, msg) not in self._seen:
            self._seen.add((loc, msg))
            self._report(loc, msg)

    @staticmethod
    def _fmt(t: Taint, axis: int) -> str:
        ext = t.get(axis)
        return f"axis {axis} (padding at rows >= {ext})" if ext is not None \
            else f"axis {axis} (padded region unknown)"

    # -- the walk -----------------------------------------------------------

    def run(self, jaxpr: Any,
            invar_taints: Dict[int, Taint],
            invar_iotas: Optional[Dict[int, set]] = None,
            path: str = "") -> Tuple[List[Taint], List[set]]:
        """Propagate; returns (taint, iota-axes) per program output."""
        _, _, Var, Literal = core_types()
        j = open_jaxpr(jaxpr)
        taint: Dict[Any, Taint] = {}
        iota: Dict[Any, set] = {}
        for i, v in enumerate(j.invars):
            t = invar_taints.get(i)
            if t:
                taint[v] = dict(t)
            io = (invar_iotas or {}).get(i)
            if io:
                iota[v] = set(io)

        def rd(v) -> Taint:
            return {} if isinstance(v, Literal) else taint.get(v, {})

        def rdi(v) -> set:
            return set() if isinstance(v, Literal) else iota.get(v, set())

        for i, eqn in enumerate(j.eqns):
            loc = f"{path}/{eqn.primitive.name}[{i}]" if path \
                else f"{eqn.primitive.name}[{i}]"
            outs = self._eqn(eqn, loc, [rd(v) for v in eqn.invars],
                             [rdi(v) for v in eqn.invars])
            for v, (t, io) in zip(eqn.outvars, outs):
                if t:
                    taint[v] = t
                if io:
                    iota[v] = io

        return ([rd(v) for v in j.outvars], [rdi(v) for v in j.outvars])

    # -- iota / mask marks ---------------------------------------------------
    #
    # A mark set holds two kinds of element: a bare ``int`` axis (this value
    # IS the index along that axis — an iota, through structural copies only)
    # and a tuple ``(axis, polarity, threshold)`` (this bool came from
    # comparing such an iota: polarity "low" = True exactly on indices
    # ``< threshold``, i.e. ``iota < n``-shaped; "high" = True exactly on
    # indices ``>= threshold``, i.e. ``iota >= n``-shaped; threshold is the
    # comparison's literal bound, or None when it was a traced value). Only
    # tuple marks can discharge a taint at select_n — with the polarity that
    # hands the padded region the clean operand, and a threshold that does
    # not exceed the taint's real extent (a literal bound that keeps padded
    # rows is a wrong-boundary mask, not a discharge).

    @staticmethod
    def _raw(marks: set) -> set:
        return {m for m in marks if not isinstance(m, tuple)}

    @staticmethod
    def _bool(marks: set) -> set:
        return {m for m in marks if isinstance(m, tuple)}

    @staticmethod
    def _literal_int(invar) -> Optional[int]:
        v = getattr(invar, "val", None)
        try:
            return int(v) if v is not None and getattr(
                v, "shape", ()) in ((), None) and int(v) == v else None
        except (TypeError, ValueError):
            return None

    @staticmethod
    def _remap_marks(marks: set, axmap) -> set:
        """Re-index every mark's axis through ``axmap`` (None drops it)."""
        out = set()
        for m in marks:
            if isinstance(m, tuple):
                new = axmap(m[0])
                if new is not None:
                    out.add((new,) + m[1:])
            else:
                new = axmap(m)
                if new is not None:
                    out.add(new)
        return out

    def _marks(self, eqn, iin: List[set]) -> set:
        name = eqn.primitive.name
        if not iin:
            return set()
        if name in _CMP_PRIMS and len(iin) == 2:
            # iota-on-lhs of lt/le is True on low indices; gt/ge flips;
            # swapping the operands flips again. le/ge shift the exclusive
            # threshold by one relative to lt/gt
            lo, hi = (0, 1) if name in ("lt", "le") else (1, 0)
            both = self._raw(iin[0]) & self._raw(iin[1])
            out = set()
            for side, pol in ((lo, "low"), (hi, "high")):
                axes = self._raw(iin[side]) - both
                if not axes:
                    continue
                thresh = self._literal_int(eqn.invars[1 - side])
                if thresh is not None and (
                        (pol == "low" and name in ("le", "ge")) or
                        (pol == "high" and name in ("lt", "gt"))):
                    thresh += 1  # inclusive bound -> exclusive threshold
                out |= {(ax, pol, thresh) for ax in axes}
            return out
        if name == "not":
            return {(ax, "high" if pol == "low" else "low", th)
                    for ax, pol, th in self._bool(iin[0])}
        if name == "and":
            # True only where EVERY operand is: each "low" guarantee (False
            # past the threshold) survives any conjunction, but a "high"
            # guarantee (True past it) survives only if ALL operands carry it
            lows = set().union(*({m for m in self._bool(s) if m[1] == "low"}
                                 for s in iin))
            highs = {m for m in self._bool(iin[0]) if m[1] == "high"}
            for s in iin[1:]:
                highs &= self._bool(s)
            return lows | highs
        if name == "or":
            # True wherever ANY operand is: the mirror image of "and"
            highs = set().union(*({m for m in self._bool(s) if m[1] == "high"}
                                  for s in iin))
            lows = {m for m in self._bool(iin[0]) if m[1] == "low"}
            for s in iin[1:]:
                lows &= self._bool(s)
            return highs | lows
        if name in _IOTA_TRANSPARENT:
            return set().union(*iin)
        return set()

    # -- per-equation transfer ----------------------------------------------

    def _eqn(self, eqn, loc: str, tin: List[Taint], iin: List[set]
             ) -> List[Tuple[Taint, set]]:
        name = eqn.primitive.name
        n_out = len(eqn.outvars)

        if name == "iota":
            return [({}, {eqn.params["dimension"]})] * n_out

        if name in _CUMULATIVE:
            t = _union(*tin)
            ax = eqn.params.get("axis")
            if eqn.params.get("reverse") and ax in t:
                t[ax] = None  # the padded tail folds into every real row
            return [(t, set())] * n_out

        if name in _ELEMENTWISE:
            return [(_union(*tin), self._marks(eqn, iin))] * n_out

        if name == "select_n":
            pred_t, pred_marks = tin[0], iin[0]
            cases = tin[1:]
            out = _union(pred_t, *cases)
            for m in pred_marks:
                if not isinstance(m, tuple):
                    continue  # raw iota, never compared: proves nothing
                ax, pol, thresh = m
                if ax not in out or ax in pred_t:
                    continue  # nothing to discharge / predicate itself
                    #           garbage in the padded region
                # the case the PADDED region selects must be clean on the
                # axis (pred False there for 'low' masks, True for 'high')
                padded_case = cases[0] if pol == "low" else cases[-1]
                if ax in padded_case:
                    continue
                ext = out[ax]
                if thresh is not None and ext is not None and thresh > ext:
                    continue  # wrong boundary: the mask keeps padded rows
                if thresh is None or ext is None:
                    # traced/unknown bound: discharged on trust, counted
                    self.stats.unverified_mask_discharges += 1
                out.pop(ax, None)
            return [(out, set())] * n_out

        if name in _REDUCES:
            axes = tuple(eqn.params.get("axes", ()))
            t = tin[0]
            for ax in axes:
                if ax in t:
                    self.finding(loc, f"{name} over tainted {self._fmt(t, ax)}"
                                      f" — padded entries enter the reduction"
                                      f" unmasked")
            kept = sorted(ax for ax in t if ax not in axes)
            remap = {ax: ax - sum(1 for r in axes if r < ax) for ax in kept}
            return [({remap[ax]: t[ax] for ax in kept}, set())] * n_out

        if name == "sort":
            dim = eqn.params.get("dimension", -1)
            for t in tin:
                if dim in t:
                    self.finding(loc, f"sort along tainted {self._fmt(t, dim)}"
                                      f" — padded values enter the order "
                                      f"statistics")
            return [(_union(*tin), set())] * n_out

        if name == "dot_general":
            return [self._dot_general(eqn, loc, tin)] * n_out

        if name == "pad":
            return [(self._pad(eqn, tin[0]), set())] * n_out

        if name == "broadcast_in_dim":
            bd = eqn.params["broadcast_dimensions"]
            t = {bd[ax]: ext for ax, ext in tin[0].items() if ax < len(bd)}
            io = self._remap_marks(iin[0], lambda ax: bd[ax]
                                   if ax < len(bd) else None)
            return [(t, io)] * n_out

        if name == "transpose":
            perm = list(eqn.params["permutation"])
            t = {perm.index(ax): ext for ax, ext in tin[0].items()}
            io = self._remap_marks(iin[0], perm.index)
            return [(t, io)] * n_out

        if name == "reshape":
            return [(self._reshape(eqn, tin[0]), set())] * n_out

        if name == "squeeze":
            dims = set(eqn.params["dimensions"])
            t = {}
            for ax, ext in tin[0].items():
                if ax not in dims:
                    t[ax - sum(1 for d in dims if d < ax)] = ext
            return [(t, set())] * n_out

        if name == "expand_dims":
            dims = sorted(eqn.params["dimensions"])
            t = {}
            for ax, ext in tin[0].items():
                new = ax
                for d in dims:
                    if d <= new:
                        new += 1
                t[new] = ext
            return [(t, set())] * n_out

        if name == "slice":
            return [(self._slice(eqn, tin[0]), set())] * n_out

        if name == "concatenate":
            d = eqn.params["dimension"]
            out = _union(*tin)
            if any(d in t for t in tin):
                out[d] = None  # padding position shifts across the seam
            return [(out, set())] * n_out

        if name == "rev":
            dims = set(eqn.params["dimensions"])
            t = {ax: (None if ax in dims else ext)
                 for ax, ext in tin[0].items()}
            return [(t, set())] * n_out

        if name in ("dynamic_slice", "dynamic_update_slice", "gather",
                    "scatter", "scatter_add", "scatter_max", "scatter_min"):
            if any(tin):
                self.stats.default_propagation += 1
                rank = _rank(eqn.outvars[0])
                return [({ax: None for ax in range(rank)}, set())] * n_out
            return [({}, set())] * n_out

        if name == "scan":
            return self._scan(eqn, loc, tin, iin)

        if name == "while":
            return self._while(eqn, loc, tin, iin)

        if name == "cond":
            return self._cond(eqn, loc, tin, iin)

        if name in _CALL_PRIMS:
            return self._call(eqn, loc, tin, iin)

        if name == "pallas_call":
            return self._pallas(eqn, tin)

        return self._default(eqn, tin)

    # -- structured handlers ------------------------------------------------

    def _dot_general(self, eqn, loc: str, tin: List[Taint]
                     ) -> Tuple[Taint, set]:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs_t, rhs_t = tin[0], tin[1]
        lhs_rank, rhs_rank = _rank(eqn.invars[0]), _rank(eqn.invars[1])
        lhs_free = [a for a in range(lhs_rank) if a not in lc and a not in lb]
        rhs_free = [a for a in range(rhs_rank) if a not in rc and a not in rb]
        out: Taint = {}
        for side, t, contract, batch, free, base in (
                ("lhs", lhs_t, lc, lb, lhs_free, len(lb)),
                ("rhs", rhs_t, rc, rb, rhs_free, len(lb) + len(lhs_free))):
            for ax, ext in t.items():
                if ax in contract:
                    self.finding(
                        loc, f"dot_general contracts tainted {side} "
                             f"{self._fmt(t, ax)} — padded entries are "
                             f"summed into every output element unmasked")
                elif ax in batch:
                    out[list(batch).index(ax)] = _merge_extent(
                        out.get(list(batch).index(ax), ext), ext)
                else:
                    pos = base + free.index(ax)
                    out[pos] = _merge_extent(out.get(pos, ext), ext)
        return out, set()

    def _pad(self, eqn, t: Taint) -> Taint:
        out = dict(t)
        for ax, (lo, hi, interior) in enumerate(eqn.params["padding_config"]):
            if lo > 0 or interior > 0:
                out[ax] = None  # padding at the front / interleaved
            elif hi > 0:
                real = _shape(eqn.invars[0])[ax]
                out[ax] = _merge_extent(out.get(ax, real), real)
        return out

    def _slice(self, eqn, t: Taint) -> Taint:
        starts = eqn.params["start_indices"]
        limits = eqn.params["limit_indices"]
        strides = eqn.params.get("strides") or (1,) * len(starts)
        out: Taint = {}
        for ax, ext in t.items():
            if strides[ax] != 1 or ext is None:
                if starts[ax] != 0 or strides[ax] != 1 or \
                        limits[ax] != _shape(eqn.invars[0])[ax]:
                    out[ax] = None
                else:
                    out[ax] = ext
                continue
            if starts[ax] == 0 and limits[ax] <= ext:
                continue  # the unpad idiom: the padded tail is sliced off
            new_ext = max(ext - starts[ax], 0)
            if limits[ax] - starts[ax] > new_ext:
                out[ax] = new_ext
            # else fully inside the real region: clean
        return out

    def _scan(self, eqn, loc: str, tin: List[Taint], iin: List[set]
              ) -> List[Tuple[Taint, set]]:
        p = eqn.params
        nc, nk = p["num_consts"], p["num_carry"]
        body = p["jaxpr"]
        consts, carry, xs = tin[:nc], tin[nc:nc + nk], tin[nc + nk:]
        xs_elt: List[Taint] = []
        for i, t in enumerate(xs):
            if 0 in t:
                self.finding(
                    loc, f"scan consumes xs operand {i} tainted along the "
                         f"scan axis ({self._fmt(t, 0)}) — padded elements "
                         f"fold into the loop carry")
                xs_elt.append({ax: None for ax in
                               range(max(_rank(eqn.invars[nc + nk + i]) - 1,
                                         0))})
            else:
                xs_elt.append({ax - 1: ext for ax, ext in t.items()})

        carry_t = [dict(t) for t in carry]
        self._quiet += 1
        try:
            for _ in range(8):  # fixpoint on the carry taint
                ins = {i: t for i, t in
                       enumerate(consts + carry_t + xs_elt) if t}
                outs, _ = self.run(body, ins, path=loc)
                new_carry = [_union(a, b) for a, b in zip(carry_t, outs[:nk])]
                if new_carry == carry_t:
                    break
                carry_t = new_carry
        finally:
            self._quiet -= 1
        ins = {i: t for i, t in enumerate(consts + carry_t + xs_elt) if t}
        outs, _ = self.run(body, ins, path=loc)  # reporting pass
        result = [(t, set()) for t in outs[:nk]]
        for t in outs[nk:]:  # per-iteration outputs stack along a new axis 0
            result.append(({ax + 1: ext for ax, ext in t.items()}, set()))
        return result

    def _while(self, eqn, loc: str, tin: List[Taint], iin: List[set]
               ) -> List[Tuple[Taint, set]]:
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        body = p["body_jaxpr"]
        bconsts = tin[cn:cn + bn]
        carry_t = [dict(t) for t in tin[cn + bn:]]
        self._quiet += 1
        try:
            for _ in range(8):
                ins = {i: t for i, t in enumerate(bconsts + carry_t) if t}
                outs, _ = self.run(body, ins, path=loc)
                new_carry = [_union(a, b) for a, b in zip(carry_t, outs)]
                if new_carry == carry_t:
                    break
                carry_t = new_carry
        finally:
            self._quiet -= 1
        ins = {i: t for i, t in enumerate(bconsts + carry_t) if t}
        outs, _ = self.run(body, ins, path=loc)
        return [(t, set()) for t in outs]

    def _cond(self, eqn, loc: str, tin: List[Taint], iin: List[set]
              ) -> List[Tuple[Taint, set]]:
        ops_t = {i: t for i, t in enumerate(tin[1:]) if t}
        ops_i = {i: io for i, io in enumerate(iin[1:]) if io}
        merged: Optional[List[Taint]] = None
        for branch in eqn.params["branches"]:
            outs, _ = self.run(branch, ops_t, ops_i, path=loc)
            merged = outs if merged is None else \
                [_union(a, b) for a, b in zip(merged, outs)]
        return [(t, set()) for t in (merged or [])]

    def _call(self, eqn, loc: str, tin: List[Taint], iin: List[set]
              ) -> List[Tuple[Taint, set]]:
        subs = [v for key in ("jaxpr", "call_jaxpr", "fun_jaxpr")
                if (v := eqn.params.get(key)) is not None]
        if not subs:
            return self._default(eqn, tin)
        body = subs[0]
        n_in = len(open_jaxpr(body).invars)
        # pjit/shard_map align 1:1; const-carrying callers align to the tail
        offset = len(tin) - n_in
        ins = {i - offset: t for i, t in enumerate(tin) if t and i >= offset}
        ios = {i - offset: io for i, io in enumerate(iin)
               if io and i >= offset}
        outs, oios = self.run(body, ins, ios, path=loc)
        return list(zip(outs, oios))

    def _pallas(self, eqn, tin: List[Taint]) -> List[Tuple[Taint, set]]:
        """Opaque kernel boundary: outputs inherit operand taint by exact
        axis-size matching (the kernel interior is covered by the runtime
        parity pins — see the module docstring)."""
        self.stats.opaque_calls += 1
        tainted_sizes: Dict[int, Optional[int]] = {}
        for v, t in zip(eqn.invars, tin):
            shape = _shape(v)
            for ax, ext in t.items():
                size = shape[ax]
                tainted_sizes[size] = _merge_extent(
                    tainted_sizes[size], ext) if size in tainted_sizes else ext
        outs = []
        for v in eqn.outvars:
            t = {ax: tainted_sizes[s] for ax, s in enumerate(_shape(v))
                 if s in tainted_sizes}
            outs.append((t, set()))
        return outs

    def _reshape(self, eqn, t: Taint) -> Taint:
        if not t:
            return {}
        old = list(_shape(eqn.invars[0]))
        new = list(_shape(eqn.outvars[0]))
        segs = _reshape_segments(old, new)
        out: Taint = {}
        for ax, ext in t.items():
            seg = next((s for s in segs if ax in s[0]), None)
            if seg and len(seg[0]) == 1 and len(seg[1]) == 1:
                out[seg[1][0]] = _merge_extent(out.get(seg[1][0], ext), ext)
            elif seg:
                for nax in seg[1]:  # merged/split: extent unknowable
                    out[nax] = None
            else:
                for nax in range(len(new)):
                    out[nax] = None
        return out

    def _default(self, eqn, tin: List[Taint]) -> List[Tuple[Taint, set]]:
        """Unknown primitive: preserve taint where the axis size matches at
        the same position, otherwise go conservative (all axes suspect)."""
        outs = []
        for out_v in eqn.outvars:
            out_shape = _shape(out_v)
            t: Taint = {}
            conservative = False
            for v, tn in zip(eqn.invars, tin):
                shape = _shape(v)
                for ax, ext in tn.items():
                    if ax < len(out_shape) and ax < len(shape) and \
                            out_shape[ax] == shape[ax]:
                        t[ax] = _merge_extent(t.get(ax, ext), ext)
                    else:
                        conservative = True
            if conservative:
                self.stats.default_propagation += 1
                t = {ax: None for ax in range(len(out_shape))}
            outs.append((t, set()))
        return outs


def _shape(v) -> Tuple[int, ...]:
    return tuple(getattr(v.aval, "shape", ()))


def _rank(v) -> int:
    return len(_shape(v))


def _reshape_segments(old: List[int], new: List[int]
                      ) -> List[Tuple[List[int], List[int]]]:
    """Factor a reshape into minimal (old axes, new axes) segments with equal
    element products — the 1:1 segments are the axes a taint can ride through
    exactly."""
    segs: List[Tuple[List[int], List[int]]] = []
    i = j = 0
    while i < len(old) and j < len(new):
        oi, nj = [i], [j]
        po, pn = old[i], new[j]
        i, j = i + 1, j + 1
        while po != pn:
            if po < pn:
                if i >= len(old):
                    break
                po *= old[i]
                oi.append(i)
                i += 1
            else:
                if j >= len(new):
                    break
                pn *= new[j]
                nj.append(j)
                j += 1
        segs.append((oi, nj))
    if i < len(old) or j < len(new):
        segs.append((list(range(i, len(old))), list(range(j, len(new)))))
    return segs
