"""Lint CLI: ``python -m iwae_replication_project_tpu.analysis [paths]``.

Exit codes: 0 = clean, 1 = findings, 2 = usage/config error. ``--format
json`` emits one machine-readable object (findings + counts) for CI;
the default human format is one ``path:line:col: [rule] message`` per line,
with a per-rule tally. Paths default to the ``[tool.iwaelint]`` ``paths``
(the production tree: package, scripts, bench, graft entry).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from typing import List, Optional

from iwae_replication_project_tpu.analysis import core
from iwae_replication_project_tpu.analysis.config import LintConfig, load_config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m iwae_replication_project_tpu.analysis",
        description="JAX correctness lint suite (iwaelint): PRNG linearity, "
                    "donation, compile discipline, host syncs, dtype policy, "
                    "warm-path and import hygiene.")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "[tool.iwaelint] paths)")
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    p.add_argument("--select", default=None,
                   help="comma-separated rule names to run (only these)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rule names to skip")
    p.add_argument("--no-config", action="store_true",
                   help="ignore [tool.iwaelint]; built-in defaults only")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.no_config:
            config, src = LintConfig(), None
        else:
            config, src = load_config()
        if args.select:
            config.select = [s.strip() for s in args.select.split(",")
                             if s.strip()]
        if args.ignore:
            config.disable = list(config.disable) + [
                s.strip() for s in args.ignore.split(",") if s.strip()]

        if args.list_rules:
            rules = core.all_rules()
            width = max(len(n) for n in rules)
            for name in sorted(rules):
                print(f"{name:<{width}}  {rules[name].summary}")
            print(f"{core.BARE_SUPPRESSION:<{width}}  (meta) suppression "
                  f"comment lacks a '-- justification' tail")
            print(f"{core.USELESS_SUPPRESSION:<{width}}  (meta) suppressed "
                  f"rule does not fire at the suppression's scope")
            return 0

        paths = args.paths or config.paths
        findings = core.lint_paths(paths, config)
    except (ValueError, FileNotFoundError) as e:
        print(f"iwaelint: error: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "counts": dict(Counter(f.rule for f in findings)),
            "total": len(findings),
            "config": src,
        }, indent=2))
    else:
        for f in findings:
            print(f.human())
        if findings:
            tally = ", ".join(f"{rule}: {n}" for rule, n in
                              sorted(Counter(f.rule for f in findings).items()))
            print(f"\n{len(findings)} finding(s) ({tally})")
        else:
            print("iwaelint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
