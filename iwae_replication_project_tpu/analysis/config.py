"""Lint configuration: defaults + the ``[tool.iwaelint]`` pyproject stanza.

The defaults ARE this repo's production policy (hot-path directories, the
compile-cache entry points, the shard_map shim location); the pyproject stanza
exists so the policy is visible and editable next to the pytest/setuptools
config rather than buried in rule code.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Tuple

try:  # py3.11+
    import tomllib as _toml
except ImportError:  # py3.10: the vendored backport present in this image
    try:
        import tomli as _toml  # type: ignore[no-redef]
    except ImportError:  # no TOML parser at all: defaults-only operation
        _toml = None  # type: ignore[assignment]


def _default_paths() -> List[str]:
    return ["iwae_replication_project_tpu", "scripts", "bench.py",
            "__graft_entry__.py"]


def _default_exclude() -> List[str]:
    return []


def _default_hot_paths() -> List[str]:
    # implicit host syncs are hazards where code runs per-step / per-dispatch
    # (analysis/audit rides along: the auditor only traces, never executes,
    # so a host sync in IT is a bug too — the analyzer lints the analyzer)
    return ["iwae_replication_project_tpu/training",
            "iwae_replication_project_tpu/parallel",
            "iwae_replication_project_tpu/ops",
            "iwae_replication_project_tpu/serving/frontend",
            "iwae_replication_project_tpu/analysis/audit"]


def _default_entry_points() -> List[str]:
    # executable entry points that must enable the persistent compile cache
    # via the shared helper (utils/compile_cache.setup_persistent_cache) —
    # migrated from tests/test_compile_cache.py's ad-hoc guard
    return ["iwae_replication_project_tpu/experiment.py",
            "iwae_replication_project_tpu/serving/cli.py",
            "iwae_replication_project_tpu/analysis/audit/cli.py", "bench.py",
            "scripts/dress_rehearsal.py", "scripts/warm_start_check.py",
            "scripts/serving_tier_smoke.py", "__graft_entry__.py"]


def _default_cache_owners() -> List[str]:
    # the only files allowed to touch jax_compilation_cache_dir directly
    return ["iwae_replication_project_tpu/utils/compile_cache.py"]


def _default_import_shims() -> List[str]:
    # the only files allowed to import version-fragile jax modules directly
    return ["iwae_replication_project_tpu/parallel/mesh.py"]


def _default_concurrency_paths() -> List[str]:
    # files the concurrency checker (lock-order / unlocked-shared-state /
    # swallowed-exception) analyzes: the pipelined serving engine's thread
    # triangle (dispatcher, completion, metric scrapes), the registry they
    # all report through, and the fault-injection layer whose schedule
    # state every instrumented thread mutates
    return ["iwae_replication_project_tpu/serving/engine.py",
            "iwae_replication_project_tpu/serving/batcher.py",
            "iwae_replication_project_tpu/serving/faults.py",
            "iwae_replication_project_tpu/serving/frontend",
            "iwae_replication_project_tpu/telemetry/registry.py",
            "iwae_replication_project_tpu/utils/faults.py"]


def _default_leak_paths() -> List[str]:
    # files the static leak pass (leaked-future / leaked-span / leaked-pin,
    # analysis/race/leaks.py) proves release-shapes over: the serving
    # control plane that acquires futures, tracing spans, and executable-
    # store pins on the request path
    return ["iwae_replication_project_tpu/serving/engine.py",
            "iwae_replication_project_tpu/serving/batcher.py",
            "iwae_replication_project_tpu/serving/sharded.py",
            "iwae_replication_project_tpu/serving/frontend",
            "iwae_replication_project_tpu/telemetry/tracing.py"]


def _default_fragile_imports() -> List[str]:
    # modules whose import location / signature moved across jax releases;
    # PR 1's seed breakage ('from jax import shard_map' on jax 0.4.37, six
    # test collections down) is the motivating incident
    return ["jax.experimental.shard_map", "jax.shard_map",
            "jax.experimental.maps", "jax.experimental.host_callback",
            "jax.experimental.pjit"]


@dataclasses.dataclass
class LintConfig:
    """Everything rule behavior can be steered by. Field names match the
    ``[tool.iwaelint]`` TOML keys one-to-one."""

    #: default lint targets when the CLI gets no paths
    paths: List[str] = dataclasses.field(default_factory=_default_paths)
    #: substring patterns (root-relative posix) excluded from the walk
    exclude: List[str] = dataclasses.field(default_factory=_default_exclude)
    #: run only these rules (empty = all registered)
    select: List[str] = dataclasses.field(default_factory=list)
    #: never run these rules
    disable: List[str] = dataclasses.field(default_factory=list)
    #: directories where implicit host syncs are flagged (host-sync rule)
    hot_paths: List[str] = dataclasses.field(default_factory=_default_hot_paths)
    #: files that must call setup_persistent_cache (cache-setup rule)
    entry_points: List[str] = dataclasses.field(
        default_factory=_default_entry_points)
    #: files allowed to configure jax_compilation_cache_dir directly
    cache_owners: List[str] = dataclasses.field(
        default_factory=_default_cache_owners)
    #: files allowed to import fragile jax modules directly (the shims)
    import_shims: List[str] = dataclasses.field(
        default_factory=_default_import_shims)
    #: fragile module names (fragile-import rule)
    fragile_imports: List[str] = dataclasses.field(
        default_factory=_default_fragile_imports)
    #: files the lock-order / unlocked-shared-state rules analyze
    concurrency_paths: List[str] = dataclasses.field(
        default_factory=_default_concurrency_paths)
    #: files the static leak pass (leaked-future/span/pin) analyzes
    leak_paths: List[str] = dataclasses.field(
        default_factory=_default_leak_paths)
    #: repo root all relative paths above resolve against
    root: Optional[str] = None


def find_pyproject(start: str) -> Optional[str]:
    """Nearest pyproject.toml at or above `start` (a file or directory)."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, "pyproject.toml")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def load_config(start: Optional[str] = None,
                pyproject: Optional[str] = None) -> Tuple[LintConfig, Optional[str]]:
    """Config from the nearest pyproject's ``[tool.iwaelint]`` table merged
    over the defaults; returns ``(config, pyproject_path_or_None)``. Unknown
    keys raise — a typo'd policy knob must not silently revert to default.
    """
    if pyproject is None:
        pyproject = find_pyproject(start or os.getcwd())
    cfg = LintConfig()
    if pyproject is None or _toml is None:
        return cfg, None
    with open(pyproject, "rb") as f:
        data = _toml.load(f)
    table = data.get("tool", {}).get("iwaelint", {})
    known = {f.name for f in dataclasses.fields(LintConfig)}
    unknown = set(table) - known
    if unknown:
        raise ValueError(
            f"unknown [tool.iwaelint] key(s) in {pyproject}: {sorted(unknown)}"
            f"; known keys: {sorted(known)}")
    for key, value in table.items():
        setattr(cfg, key, value)
    if cfg.root is None:
        cfg.root = os.path.dirname(os.path.abspath(pyproject))
    return cfg, pyproject
