"""Lint-framework core: findings, the rule registry, suppressions, the runner.

The hazards this framework exists for are the ones that *train fine and
converge to subtly wrong bounds* (ISSUE 2): PRNG key reuse silently correlates
the K importance samples the IWAE bound averages over (Burda et al.,
arXiv:1509.00519), a donated buffer read after its dispatch is backend-
dependent garbage, and a missing stop-gradient in a DReG-style estimator
changes the gradient, not the loss (arXiv:1810.04152). None of these raise.
Static rules over the AST are the only guard that runs before the science does.

Design:

* a **rule** is a subclass of :class:`Rule` registered via :func:`register`;
  its ``check(ctx)`` yields :class:`Finding`s for one parsed file;
* **suppression** is per-line, per-rule:
  ``# iwaelint: disable=rule-a,rule-b -- why this is safe`` on the flagged
  line (or ``disable-file=`` on its own line for whole-file scope). The
  justification after ``--`` is mandatory — a suppression without one is
  itself a finding (``bare-suppression``), so every silenced hazard carries
  its argument in the diff;
* the **runner** (:func:`lint_paths`) walks files, parses once, runs every
  enabled rule, applies suppressions, and returns findings sorted by
  location — the CLI layers output formatting and exit codes on top.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import (
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
    Type,
)

from iwae_replication_project_tpu.analysis.config import LintConfig

#: suppression comment grammar (the `--` separator guards the justification)
_SUPPRESS_RE = re.compile(
    r"#\s*iwaelint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\s-]+?)"
    r"(?:\s*--\s*(?P<why>\S.*))?\s*$")

#: meta-rule id for suppressions missing a justification (not suppressible)
BARE_SUPPRESSION = "bare-suppression"
#: meta-rule id for suppressions whose rule would not have fired where they
#: sit (not suppressible) — keeps the justified-suppression inventory honest
#: as code moves: a stale suppression is a pre-authorized future hazard
USELESS_SUPPRESSION = "useless-suppression"
#: pseudo-rule id for files the parser rejects
PARSE_ERROR = "parse-error"


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one source location (1-based line, 0-based col)."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def human(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class FileContext:
    """Everything a rule may look at for one file: source, AST, config, and
    the file's path relative to the lint root (posix separators, so rule
    config like ``hot_paths`` matches identically on every OS)."""

    def __init__(self, path: str, rel_path: str, source: str,
                 tree: ast.Module, config: LintConfig):
        self.path = path
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.config = config

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(path=self.rel_path, line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0), rule=rule,
                       message=message)


class Rule:
    """Base class for lint rules. Subclasses set ``name`` (the registry id and
    the token used in suppression comments) and ``summary`` (one line for
    ``--list-rules``), and implement :meth:`check`."""

    name: str = ""
    summary: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    # -- shared AST helpers -------------------------------------------------

    @staticmethod
    def call_name(node: ast.Call) -> str:
        """Dotted name of a call's callee ('' when not a plain name chain):
        ``jax.random.split(k)`` -> ``"jax.random.split"``."""
        return Rule.dotted(node.func)

    @staticmethod
    def dotted(node: ast.AST) -> str:
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return ""

    @staticmethod
    def terminal(name: str) -> str:
        """Last attribute of a dotted name: ``jax.random.split`` -> ``split``."""
        return name.rsplit(".", 1)[-1] if name else ""


_REGISTRY: Dict[str, Rule] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a rule (by its ``name``) to the registry."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls()
    return cls


def all_rules() -> Dict[str, Rule]:
    """Name -> rule instance for every registered rule (import side effect of
    the ``rules`` package registers the built-ins)."""
    import iwae_replication_project_tpu.analysis.rules  # noqa: F401
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Suppression:
    line: int            # 1-based source line the comment sits on
    rules: List[str]     # rule names (or ["all"])
    file_scope: bool
    justified: bool

    def covers(self, rule: str) -> bool:
        return rule not in (BARE_SUPPRESSION, USELESS_SUPPRESSION) and \
            ("all" in self.rules or rule in self.rules)


def parse_suppressions(source: str) -> List[Suppression]:
    out: List[Suppression] = []
    for i, text in _comments(source):
        m = _SUPPRESS_RE.search(text)
        if m is None:
            continue
        rules = [r.strip() for r in m.group("rules").split(",") if r.strip()]
        out.append(Suppression(line=i, rules=rules,
                               file_scope=m.group("scope") is not None,
                               justified=bool(m.group("why"))))
    return out


def _comments(source: str) -> List[Tuple[int, str]]:
    """``(lineno, text)`` for every real COMMENT token. Tokenizing (instead
    of a per-line regex) keeps suppression grammar shown inside docstrings —
    this module's own, for one — from parsing as live suppressions."""
    try:
        return [(tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(io.StringIO(source)
                                                    .readline)
                if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # un-tokenizable source never reaches the rules either (parse-error);
        # fall back to the line scan so suppression *parsing* stays total
        return list(enumerate(source.splitlines(), start=1))


def apply_suppressions(findings: Iterable[Finding], sups: List[Suppression],
                       rel_path: str,
                       active_rules: Optional[set] = None,
                       complete_run: bool = False,
                       known_rules: Optional[set] = None) -> List[Finding]:
    """Drop suppressed findings; add a ``bare-suppression`` finding for every
    suppression comment with no ``-- justification`` tail, and a
    ``useless-suppression`` finding for every suppressed rule that did not
    actually fire at the suppression's scope.

    `active_rules` is the set of rule names that RAN on this file: a token
    is only judged useless when its rule had the chance to fire (a
    ``--select`` subset must not condemn the other rules' suppressions).
    An ``all`` token can only be judged when EVERY registered rule ran
    (`complete_run`): under any subset, a rule the subset skipped may be
    what the suppression exists for. A token naming NO registered rule at
    all (`known_rules`: misspelled, or the rule was renamed/removed) is
    reported unconditionally — it can never become live, so no run subset
    can vindicate it.
    """
    file_rules = [(i, s) for i, s in enumerate(sups) if s.file_scope]
    by_line: Dict[int, List[Tuple[int, Suppression]]] = {}
    for i, s in enumerate(sups):
        if not s.file_scope:
            by_line.setdefault(s.line, []).append((i, s))
    used: List[set] = [set() for _ in sups]

    kept: List[Finding] = []
    for f in findings:
        matched = False
        for i, s in file_rules:
            if s.covers(f.rule):
                used[i].add(f.rule)
                matched = True
        for i, s in by_line.get(f.line, []):
            if s.covers(f.rule):
                used[i].add(f.rule)
                matched = True
        if not matched:
            kept.append(f)
    for i, s in enumerate(sups):
        if not s.justified:
            kept.append(Finding(
                path=rel_path, line=s.line, col=0, rule=BARE_SUPPRESSION,
                message="suppression has no justification; write "
                        "'# iwaelint: disable=<rule> -- <why this is safe>'"))
        for token in s.rules:
            if token == "all":
                if complete_run and not used[i]:
                    kept.append(Finding(
                        path=rel_path, line=s.line, col=0,
                        rule=USELESS_SUPPRESSION,
                        message="'disable=all' suppresses nothing here — "
                                "no rule fires at this scope; remove it"))
            elif known_rules is not None and token not in known_rules:
                kept.append(Finding(
                    path=rel_path, line=s.line, col=0,
                    rule=USELESS_SUPPRESSION,
                    message=f"suppression names unknown rule '{token}' — "
                            f"misspelled or removed; it can never fire, so "
                            f"this suppression suppresses nothing"))
            elif active_rules is not None and token in active_rules \
                    and token not in used[i]:
                scope = "file" if s.file_scope else "line"
                kept.append(Finding(
                    path=rel_path, line=s.line, col=0,
                    rule=USELESS_SUPPRESSION,
                    message=f"suppression of '{token}' is useless: the rule "
                            f"does not fire on this {scope} — remove it (a "
                            f"stale suppression silently pre-authorizes the "
                            f"next real violation here)"))
    return kept


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def iter_python_files(paths: Sequence[str], config: LintConfig,
                      root: str) -> Iterator[str]:
    """Expand files/dirs into .py files, honoring config.exclude (matched
    against root-relative posix paths as substrings)."""
    def excluded(p: str) -> bool:
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        return any(pat in rel for pat in config.exclude)

    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py") and not excluded(p):
                yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith(".")
                                     and not excluded(os.path.join(dirpath, d)))
                for fname in sorted(filenames):
                    full = os.path.join(dirpath, fname)
                    if fname.endswith(".py") and not excluded(full):
                        yield full
        else:
            raise FileNotFoundError(f"lint target does not exist: {p}")


def lint_file(path: str, config: LintConfig, root: Optional[str] = None,
              rules: Optional[Dict[str, Rule]] = None) -> List[Finding]:
    root = root or config.root or os.getcwd()
    rel = os.path.relpath(os.path.abspath(path), root)
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path=rel.replace(os.sep, "/"),
                        line=e.lineno or 1, col=(e.offset or 1) - 1,
                        rule=PARSE_ERROR, message=f"syntax error: {e.msg}")]
    ctx = FileContext(path, rel, source, tree, config)
    active = rules if rules is not None else enabled_rules(config)
    findings: List[Finding] = []
    for rule in active.values():
        findings.extend(rule.check(ctx))
    findings = apply_suppressions(
        findings, parse_suppressions(source), ctx.rel_path,
        active_rules=set(active),
        complete_run=set(active) == set(all_rules()),
        known_rules=(set(all_rules()) |
                     {BARE_SUPPRESSION, USELESS_SUPPRESSION, PARSE_ERROR}))
    # one finding per (rule, location): visitors that re-walk loop bodies to
    # model second iterations would otherwise duplicate
    return sorted(set(findings))


def enabled_rules(config: LintConfig) -> Dict[str, Rule]:
    rules = all_rules()
    unknown = (set(config.select or []) | set(config.disable)) - set(rules)
    if unknown:
        raise ValueError(f"unknown rule(s) in config: {sorted(unknown)}; "
                         f"known: {sorted(rules)}")
    if config.select:
        rules = {n: r for n, r in rules.items() if n in config.select}
    return {n: r for n, r in rules.items() if n not in config.disable}


def lint_paths(paths: Sequence[str], config: Optional[LintConfig] = None,
               root: Optional[str] = None) -> List[Finding]:
    """Lint files/directories; returns all findings sorted by location."""
    config = config or LintConfig()
    root = root or config.root or os.getcwd()
    rules = enabled_rules(config)
    findings: List[Finding] = []
    for path in iter_python_files(paths, config, root):
        findings.extend(lint_file(path, config, root=root, rules=rules))
    return sorted(findings)
