"""``iwae-race``: the serving stack's race detector and leak prover.

The static lint rules (analysis/rules/concurrency.py) see *source*; this
package sees *interleavings*. It is the dynamic twin of the concurrency
checker, exactly as ``pytest --sanitize`` is the runtime twin of the JAX
lint rules:

* :mod:`model` — the detector core: an Eraser-style lockset algorithm
  hybridized with vector-clock happens-before (thread start/join, future
  completion, queue transfer, and event set are HB edges; lock
  acquire/release contributes locksets only, so accidental lock timing
  never hides a race);
* :mod:`instrument` — the injectable instrumented-sync layer: traced
  Lock/RLock/Condition/Event/Thread/Future/Queue swapped in at the
  ``concurrency_paths`` modules' import sites, plus per-class attribute
  tracing. Uninstalled, the production modules run the byte-identical
  pre-instrumentation code path (test-pinned);
* :mod:`fuzz` — deterministic schedule fuzzing: a seeded cooperative
  scheduler (fixtures: same seed => same interleaving => byte-identical
  report, every race report is a repro) and a seeded perturb mode for the
  real socket-threaded serving stack;
* :mod:`escape` — static thread-escape analysis (which ``self.X`` cross a
  thread boundary), consumed by the upgraded ``unlocked-shared-state``
  lint rule;
* :mod:`leaks` — the static future/span/pin leak pass: every
  ``Future()``/``start_span``/``pin_prefix`` acquisition in the serving
  control plane is proven completed/finished/released on all exception
  paths — the "zero silence" drain contract, machine-checked;
* :mod:`cli` — the ``iwae-race`` console script (same 0/1/2 exit
  contract as iwae-lint/iwae-audit/iwae-cost).
"""

from iwae_replication_project_tpu.analysis.race.model import (  # noqa: F401
    Access,
    RaceDetector,
    RaceReport,
    VectorClock,
)
from iwae_replication_project_tpu.analysis.race.instrument import (  # noqa: F401
    Instrumentation,
)
from iwae_replication_project_tpu.analysis.race.fuzz import (  # noqa: F401
    CooperativeScheduler,
    PerturbFuzzer,
    SchedulerDeadlock,
)
