"""``python -m iwae_replication_project_tpu.analysis.race`` entry point."""

import sys

from iwae_replication_project_tpu.analysis.race.cli import main

if __name__ == "__main__":
    sys.exit(main())
