"""``iwae-race``: the race-detector CLI.

``python -m iwae_replication_project_tpu.analysis.race [paths]`` runs the
**static leak pass** (``leaked-future`` / ``leaked-span`` / ``leaked-pin``,
see :mod:`.leaks`) over the configured ``leak_paths`` — the serving control
plane's future/span/pin acquisition sites — with the shared lint
framework's suppression grammar and config.

``--self-test`` additionally runs the **dynamic detector battery**: the
lockset + happens-before detector (:mod:`.model`) driven by the
cooperative seeded scheduler (:mod:`.fuzz`) over built-in fixture pairs —
a racy counter that MUST be caught (with a reproducing seed), its locked
and HB-ordered twins that MUST stay clean, and a same-seed determinism
check (two runs, byte-identical reports). A battery failure means the
detector itself is broken and exits 2 (internal error), never 1: a broken
detector must not masquerade as a findings list.

Exit codes (the iwae-lint/audit/cost contract): 0 = clean, 1 = findings,
2 = usage/config/internal error. ``--format json`` emits one
machine-readable object (findings + counts + self-test verdicts) for
``scripts/check.py``.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from typing import Dict, List, Optional

from iwae_replication_project_tpu.analysis import core
from iwae_replication_project_tpu.analysis.config import (
    LintConfig,
    load_config,
)

_LEAK_RULES = ["leaked-future", "leaked-span", "leaked-pin"]

#: seeds the self-test battery schedules the racy fixture under; the racy
#: write pair is adjacent in program order, so nearly any preemption at
#: the access yield points exposes it — a handful of seeds is plenty
_SELF_TEST_SEEDS = (0, 1, 2, 3, 4)


# ---------------------------------------------------------------------------
# the dynamic self-test battery (fixtures built from the instrumented-sync
# layer itself: the detector checks the detector)
# ---------------------------------------------------------------------------

def _run_fixture(seed: int, variant: str) -> dict:
    """One cooperative scheduled run of the named fixture variant; returns
    the detector's deterministic report."""
    from iwae_replication_project_tpu.analysis.race import (
        CooperativeScheduler,
        Instrumentation,
        RaceDetector,
    )

    det = RaceDetector()
    sched = CooperativeScheduler(seed)
    sched.bind(det)
    ins = Instrumentation(detector=det, fuzz=sched)

    class Shared:
        def __init__(self):
            self.n = 0

    obj = Shared()
    ins.track(obj)
    lock = ins.lock()

    def bump_racy():
        obj.n = obj.n + 1

    def bump_locked():
        with lock:
            obj.n = obj.n + 1

    def driver():
        body = bump_locked if variant == "locked" else bump_racy
        t1 = ins.thread(target=body, name="w1")
        t2 = ins.thread(target=body, name="w2")
        if variant == "hb":
            # join before the second start: the join edge orders the pair
            t1.start()
            t1.join()
            t2.start()
            t2.join()
        else:
            t1.start()
            t2.start()
            t1.join()
            t2.join()

    sched.run(driver)
    return det.report()


def run_self_test() -> Dict[str, object]:
    """The battery. Returns a verdict dict; ``ok`` False = detector broken."""
    verdicts: Dict[str, object] = {}
    caught_seeds = []
    for seed in _SELF_TEST_SEEDS:
        if _run_fixture(seed, "racy")["total"] > 0:
            caught_seeds.append(seed)
    verdicts["racy_caught_seeds"] = caught_seeds
    verdicts["racy_caught"] = len(caught_seeds) > 0
    verdicts["locked_clean"] = all(
        _run_fixture(seed, "locked")["total"] == 0
        for seed in _SELF_TEST_SEEDS)
    verdicts["hb_clean"] = all(
        _run_fixture(seed, "hb")["total"] == 0
        for seed in _SELF_TEST_SEEDS)
    if caught_seeds:
        seed = caught_seeds[0]
        a = json.dumps(_run_fixture(seed, "racy"), sort_keys=True)
        b = json.dumps(_run_fixture(seed, "racy"), sort_keys=True)
        verdicts["deterministic"] = a == b
    else:
        verdicts["deterministic"] = False
    verdicts["ok"] = bool(verdicts["racy_caught"] and
                          verdicts["locked_clean"] and
                          verdicts["hb_clean"] and
                          verdicts["deterministic"])
    return verdicts


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m iwae_replication_project_tpu.analysis.race",
        description="iwae-race: static future/span/pin leak pass over the "
                    "serving control plane, plus the lockset+happens-before "
                    "detector's self-test battery.")
    p.add_argument("paths", nargs="*",
                   help="files/directories for the leak pass (default: the "
                        "[tool.iwaelint] leak_paths)")
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument("--list-rules", action="store_true",
                   help="print the leak-pass rules and exit")
    p.add_argument("--self-test", action="store_true",
                   help="also run the dynamic detector battery (exit 2 on "
                        "battery failure: a broken detector is an internal "
                        "error, not a findings list)")
    p.add_argument("--no-config", action="store_true",
                   help="ignore [tool.iwaelint]; built-in defaults only")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.no_config:
            config, src = LintConfig(), None
        else:
            config, src = load_config()
        config.select = list(_LEAK_RULES)

        if args.list_rules:
            rules = core.all_rules()
            width = max(len(n) for n in _LEAK_RULES)
            for name in _LEAK_RULES:
                print(f"{name:<{width}}  {rules[name].summary}")
            return 0

        paths = args.paths or config.leak_paths
        findings = core.lint_paths(paths, config)
        self_test = run_self_test() if args.self_test else None
    except (ValueError, FileNotFoundError) as e:
        print(f"iwae-race: error: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        payload = {
            "findings": [f.to_dict() for f in findings],
            "counts": dict(Counter(f.rule for f in findings)),
            "total": len(findings),
            "config": src,
        }
        if self_test is not None:
            payload["self_test"] = self_test
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.human())
        if findings:
            tally = ", ".join(
                f"{rule}: {n}" for rule, n in
                sorted(Counter(f.rule for f in findings).items()))
            print(f"\n{len(findings)} finding(s) ({tally})")
        else:
            print("iwae-race: leak pass clean")
        if self_test is not None:
            print(f"iwae-race: self-test "
                  f"{'ok' if self_test['ok'] else 'FAILED'} "
                  f"(racy caught under seeds "
                  f"{self_test['racy_caught_seeds']}, locked twin "
                  f"{'clean' if self_test['locked_clean'] else 'DIRTY'}, "
                  f"hb twin "
                  f"{'clean' if self_test['hb_clean'] else 'DIRTY'}, "
                  f"same-seed report "
                  f"{'byte-identical' if self_test['deterministic'] else 'DIVERGED'})")
    if self_test is not None and not self_test["ok"]:
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
