"""Static thread-escape analysis: which ``self.X`` cross a thread boundary.

The ``unlocked-shared-state`` lint rule's original model was purely
lock-relative: an attribute written both under a lock and bare is flagged.
That model has two blind spots this pass closes:

* an attribute touched only by ONE internal thread (a dispatcher loop's
  private scratch) cannot race no matter how its writes mix with lock
  holds — flagging it forces waivers for code that is correct by
  construction (**thread-confined** state);
* an attribute shared between a thread body and the external API with no
  lock *anywhere* never trips the lock-relative rule at all — yet that is
  the barest possible race (**escaping** state, bare writes).

The reconstruction is per class, over three escape mechanisms:

* ``Thread(target=self.m)`` — ``m`` (and every same-class method reachable
  from it) runs on its own thread root;
* ``fut.add_done_callback(self.m)`` — ``m`` runs on whichever thread
  completes the future (a distinct root);
* **payload handoff** — ``self.X`` passed in ``Thread(..., args=...)``,
  ``put()`` on a queue, or ``set_result()`` of a future escapes to the
  receiving thread even though no method-reachability edge says so.

Everything not reachable from an internal root is the **external** root:
the public API, callable from arbitrary caller threads. An attribute is
*confined* when every access lands in exactly one internal root;
*escaping* when its accesses span two or more roots (or any handoff).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["ClassEscape", "classify_class"]

#: root name for the public API (arbitrary caller threads)
EXTERNAL = "external"
#: pseudo-root for queue/future/thread-args payload handoff
HANDOFF = "handoff"

#: method names that push their argument to another thread
_HANDOFF_CALLS = {"put", "put_nowait", "set_result"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _MethodWalk(ast.NodeVisitor):
    """One method's attr reads/writes and same-class calls."""

    def __init__(self):
        self.reads: Set[str] = set()
        self.writes: Set[str] = set()
        self.calls: Set[str] = set()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self.writes.add(attr)
            else:
                self.reads.add(attr)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self.X[k] = v / del self.X[k] mutate X in place
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr is not None:
                self.writes.add(attr)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target)
        if attr is not None:
            self.writes.add(attr)
            self.reads.add(attr)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.calls.add(node.func.attr)
            # in-place mutation of self.X counts as a write
            attr = _self_attr(recv)
            if attr is not None and node.func.attr in (
                    "append", "appendleft", "extend", "insert", "pop",
                    "popleft", "remove", "clear", "update", "add",
                    "discard", "setdefault", "sort", "reverse"):
                self.writes.add(attr)
        self.generic_visit(node)


def _thread_targets(cls: ast.ClassDef) -> Tuple[Set[str], Set[str]]:
    """(thread target methods, payload-handoff attrs) found anywhere in the
    class: Thread(target=self.m, args=(self.X,)), cb(self.m), put(self.X)."""
    targets: Set[str] = set()
    handoff: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        callee_name = callee.attr if isinstance(callee, ast.Attribute) \
            else (callee.id if isinstance(callee, ast.Name) else "")
        if callee_name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    m = _self_attr(kw.value)
                    if m is not None:
                        targets.add(m)
                elif kw.arg == "args":
                    for sub in ast.walk(kw.value):
                        a = _self_attr(sub)
                        if a is not None:
                            handoff.add(a)
        elif callee_name == "add_done_callback":
            for arg in node.args:
                m = _self_attr(arg)
                if m is not None:
                    targets.add(m)
        elif callee_name in _HANDOFF_CALLS:
            for arg in node.args:
                for sub in ast.walk(arg):
                    a = _self_attr(sub)
                    if a is not None:
                        handoff.add(a)
    return targets, handoff


def _reachable(start: Set[str], calls: Dict[str, Set[str]]) -> Set[str]:
    seen = set(start)
    frontier = list(start)
    while frontier:
        m = frontier.pop()
        for callee in calls.get(m, ()):
            if callee not in seen:
                seen.add(callee)
                frontier.append(callee)
    return seen


@dataclasses.dataclass
class ClassEscape:
    """The escape classification of one class's attributes."""

    #: every root: EXTERNAL plus one ``thread:<m>`` per internal entry
    roots: Set[str]
    #: attr -> the roots whose reachable methods access it (plus HANDOFF)
    attr_roots: Dict[str, Set[str]]
    #: attrs written (incl. augmented/mutating) outside __init__
    written: Set[str]

    def roots_of(self, attr: str) -> Set[str]:
        return self.attr_roots.get(attr, {EXTERNAL})

    def confined(self, attr: str) -> bool:
        """Accessed from exactly one internal thread root: cannot race."""
        roots = self.roots_of(attr)
        return len(roots) == 1 and next(iter(roots)) != EXTERNAL

    def escaping(self, attr: str) -> bool:
        """Accessed from >= 2 roots, at least one internal/handoff — the
        attribute genuinely crosses a thread boundary."""
        roots = self.roots_of(attr)
        return len(roots) >= 2 and any(r != EXTERNAL for r in roots)


def classify_class(cls: ast.ClassDef,
                   skip_attrs: Optional[Set[str]] = None) -> ClassEscape:
    """Escape-classify `cls` (``skip_attrs``: lock attributes — they are
    synchronization, not shared data)."""
    skip = skip_attrs or set()
    walks: Dict[str, _MethodWalk] = {}
    init_names = {"__init__", "__post_init__"}
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w = _MethodWalk()
            for stmt in item.body:
                w.visit(stmt)
            walks[item.name] = w

    calls = {m: w.calls for m, w in walks.items()}
    targets, handoff = _thread_targets(cls)
    targets &= set(walks)           # only same-class methods root a thread

    root_reach: Dict[str, Set[str]] = {}
    for m in sorted(targets):
        root_reach[f"thread:{m}"] = _reachable({m}, calls)
    external_entries = {m for m in walks
                        if m not in targets and m not in init_names}
    root_reach[EXTERNAL] = _reachable(external_entries, calls)

    attr_roots: Dict[str, Set[str]] = {}
    written: Set[str] = set()
    for root, methods in root_reach.items():
        for m in methods:
            w = walks.get(m)
            if w is None or m in init_names:
                continue
            for attr in (w.reads | w.writes) - skip:
                attr_roots.setdefault(attr, set()).add(root)
            for attr in w.writes - skip:
                written.add(attr)
    for attr in handoff - skip:
        attr_roots.setdefault(attr, set()).add(HANDOFF)

    return ClassEscape(roots=set(root_reach) | ({HANDOFF} if handoff
                                                else set()),
                       attr_roots=attr_roots, written=written)
