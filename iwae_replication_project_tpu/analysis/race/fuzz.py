"""Deterministic schedule fuzzing.

Two modes, one contract — the schedule is a pure function of the seed:

* :class:`CooperativeScheduler` (fixtures): a single-baton scheduler.
  Exactly one thread runs at any moment; every instrumented sync op is a
  yield point where the seeded RNG picks the next runnable thread from the
  deterministically-ordered candidate set (thread ids are registration
  ordinals, registration order is itself scheduled). Blocking traced ops
  never really block while holding the baton — they deschedule with a
  wake predicate instead. Because only the baton holder consumes the RNG,
  the whole interleaving — and therefore the detector's report — is
  byte-identical across same-seed runs: **every race report is a repro**.

* :class:`PerturbFuzzer` (the real socket-threaded serving stack): the
  ``utils/faults.py`` seeded-schedule idiom. Each thread draws from its
  own stream (``Random(seed * 1_000_003 + tid)``) and injects short
  sleeps at sync ops per that stream's decisions. The *decision schedule*
  is deterministic per thread; the achieved interleaving is best-effort
  (threads blocked in uninstrumented ops — ``socket.accept`` — cannot be
  descheduled cooperatively), which is exactly the honest contract for
  fuzzing a stack that talks to real sockets.

A cooperative run where every thread is descheduled with no satisfiable
wake predicate raises :class:`SchedulerDeadlock` — a deadlock is a
verdict, not a hang.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional

__all__ = ["CooperativeScheduler", "PerturbFuzzer", "SchedulerDeadlock"]


class SchedulerDeadlock(RuntimeError):
    """Every scheduled thread is descheduled and no wake predicate holds."""


class PerturbFuzzer:
    """Seeded per-thread sleep injection at instrumented sync ops."""

    cooperative = False

    def __init__(self, seed: int, rate: float = 0.25,
                 max_sleep_s: float = 0.002):
        self.seed = int(seed)
        self.rate = float(rate)
        self.max_sleep_s = float(max_sleep_s)
        self._mu = threading.Lock()
        self._streams: Dict[int, random.Random] = {}
        self.det = None
        self.ops = 0

    def bind(self, det) -> None:
        self.det = det
        det.seed = self.seed

    def on_op(self, kind: str) -> None:
        tid = self.det.current_tid()
        with self._mu:
            rng = self._streams.get(tid)
            if rng is None:
                rng = random.Random(self.seed * 1_000_003 + tid)
                self._streams[tid] = rng
            self.ops += 1
            fire = rng.random() < self.rate
            dur = rng.random() * self.max_sleep_s
        if fire:
            time.sleep(dur)


class CooperativeScheduler:
    """Single-baton deterministic scheduler over traced threads."""

    cooperative = True

    #: wall-clock bound on "nobody can run" before declaring deadlock; the
    #: only asynchronous wake this grace period exists for is a detached
    #: thread's interpreter bootstrap flipping ``is_alive`` to False
    DEADLOCK_GRACE_S = 5.0

    def __init__(self, seed: int):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self._cv = threading.Condition(threading.Lock())
        self._state: Dict[int, str] = {}         # tid -> runnable|done
        self._preds: Dict[int, Optional[Callable[[], bool]]] = {}
        self._registered: set = set()            # thread objects seen
        self._current: Optional[int] = None
        self.det = None

    def bind(self, det) -> None:
        self.det = det
        det.seed = self.seed

    # -- lifecycle ----------------------------------------------------------

    def run(self, fn: Callable[[], object]):
        """Run `fn` (the fixture driver) as the scheduled root thread."""
        tid = self.det.register_thread("driver")
        with self._cv:
            self._state[tid] = "runnable"
            self._preds[tid] = None
            self._current = tid
        try:
            return fn()
        finally:
            with self._cv:
                self._state[tid] = "done"
                self._preds.pop(tid, None)
                if self._current == tid:
                    self._pick_locked()

    def register_child(self, thread, tid: int) -> None:
        """Called by a traced thread's run() before any user code: join the
        schedule, then wait for the baton."""
        with self._cv:
            self._state[tid] = "runnable"
            self._preds[tid] = None
            self._registered.add(id(thread))
            self._cv.notify_all()
            self._await_baton_locked(tid)

    def wait_child_registered(self, thread) -> None:
        """The parent (baton holder) blocks in start() until the child has
        joined the schedule — child registration order is thereby the
        deterministic program order of start() calls."""
        with self._cv:
            self._cv.wait_for(lambda: id(thread) in self._registered,
                              timeout=self.DEADLOCK_GRACE_S)

    def detach(self, tid: int) -> None:
        with self._cv:
            self._state[tid] = "done"
            self._preds.pop(tid, None)
            if self._current == tid:
                self._pick_locked()

    # -- yield points -------------------------------------------------------

    def on_op(self, kind: str) -> None:
        me = self.det.current_tid()
        with self._cv:
            if self._state.get(me) != "runnable":
                return              # unscheduled thread (e.g. pytest main)
            if self._current != me:
                # an unscheduled wake (timed waits in perturbed libraries);
                # fall into the normal baton wait
                self._await_baton_locked(me)
                return
            self._pick_locked()
            self._await_baton_locked(me)

    def block_until(self, pred: Callable[[], bool]) -> None:
        """Deschedule the caller until `pred` holds AND the seeded choice
        hands it the baton again. `pred` must be side-effect free and must
        touch raw (untraced) state only."""
        me = self.det.current_tid()
        with self._cv:
            if self._state.get(me) != "runnable":
                # unscheduled thread: poll outside the scheduler
                pass
            else:
                self._preds[me] = pred
                if self._current == me:
                    self._pick_locked()
                self._await_baton_locked(me)
                return
        deadline = time.monotonic() + self.DEADLOCK_GRACE_S
        while not pred():
            if time.monotonic() > deadline:
                raise SchedulerDeadlock(
                    "unscheduled thread's wake predicate never held")
            time.sleep(0.001)

    # -- internals (self._cv held) ------------------------------------------

    def _runnable_locked(self):
        out = []
        for tid in sorted(self._state):
            if self._state[tid] != "runnable":
                continue
            pred = self._preds.get(tid)
            if pred is None or pred():
                out.append(tid)
        return out

    def _pick_locked(self) -> None:
        cands = self._runnable_locked()
        if cands:
            self._current = self.rng.choice(cands)
        else:
            self._current = None        # probed again by waiting threads
        self._cv.notify_all()

    def _await_baton_locked(self, me: int) -> None:
        stalled_since = None
        while True:
            if self._current == me:
                self._preds[me] = None
                return
            granted = self._cv.wait(timeout=0.05)
            if self._current == me:
                self._preds[me] = None
                return
            if self._current is None:
                # nobody holds the baton: re-evaluate predicates (an
                # asynchronous flip — a detached thread finishing — is the
                # only way forward now)
                cands = self._runnable_locked()
                if cands:
                    self._current = self.rng.choice(cands)
                    self._cv.notify_all()
                    stalled_since = None
                    continue
                now = time.monotonic()
                if stalled_since is None:
                    stalled_since = now
                elif now - stalled_since > self.DEADLOCK_GRACE_S:
                    self._state[me] = "done"
                    raise SchedulerDeadlock(
                        f"all scheduled threads are descheduled and no "
                        f"wake predicate holds (seed {self.seed}) — the "
                        f"schedule found a deadlock")
            elif granted is False:
                stalled_since = None
