"""The injectable instrumented-sync layer.

:class:`Instrumentation` swaps traced twins of the stdlib concurrency
primitives into the *module attributes* of the serving stack's
``concurrency_paths`` modules — ``mod.threading`` becomes a proxy whose
``Lock/RLock/Condition/Event/Thread`` construct traced objects,
``mod.Future`` becomes a traced Future subclass, ``mod.queue`` a traced
Queue factory. Production code is untouched at the byte level: the swap is
a handful of module-dict entries, and :meth:`Instrumentation.uninstall`
restores the exact original objects, so instrumentation-off is the
byte-identical pre-instrumentation code path (a test pins this).

Attribute-level sharing is traced by patching ``__setattr__`` /
``__getattribute__`` on an explicit list of tracked classes: every
instance-attribute read/write reports to the :class:`RaceDetector` with
the accessing thread's current lockset. Objects get deterministic labels
(per-class creation ordinals), so reports are stable across same-seed
runs.

Every traced operation is also a **fuzz point**: when a fuzzer is bound
(fuzz.py), the op first offers the scheduler a chance to preempt — that
is what makes an interleaving a function of the seed.
"""

from __future__ import annotations

import contextlib
import queue as _real_queue
import threading as _real_threading
from concurrent.futures import Future as _RealFuture
from typing import Dict, Iterable, List, Optional, Tuple

from iwae_replication_project_tpu.analysis.race.model import RaceDetector

__all__ = ["Instrumentation"]


class _TracedLock:
    """threading.Lock twin: lockset bookkeeping + fuzz points, no HB."""

    _KIND = "Lock"

    def __init__(self, ins: "Instrumentation", name: Optional[str] = None):
        self._ins = ins
        self._raw = self._make_raw()
        self.name = name or ins.next_name(self._KIND)

    def _make_raw(self):
        return _real_threading.Lock()

    def _try_acquire(self) -> bool:
        return self._raw.acquire(blocking=False)

    def _free(self) -> bool:
        return not self._raw.locked()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ins = self._ins
        ins.op("lock_acquire")
        if blocking and ins.cooperative:
            # cooperative mode: never really block while holding the baton —
            # deschedule until the raw lock is free, then retry
            while not self._try_acquire():
                ins.fuzz.block_until(self._free)
            got = True
        elif timeout is not None and timeout >= 0:
            got = self._raw.acquire(blocking, timeout)
        else:
            got = self._raw.acquire(blocking)
        if got:
            ins.det.lock_acquired(self.name)
        return got

    def release(self) -> None:
        self._ins.det.lock_released(self.name)
        self._raw.release()
        self._ins.op("lock_release")

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class _TracedRLock(_TracedLock):
    _KIND = "RLock"

    def _make_raw(self):
        return _real_threading.RLock()

    def _try_acquire(self) -> bool:
        return self._raw.acquire(blocking=False)

    def _free(self) -> bool:
        # RLock exposes no .locked() before 3.12; a cooperative waiter just
        # stays runnable and retries (the seeded choice rotates the baton)
        return True


class _TracedCondition:
    """threading.Condition twin. Aliases its lock (a Condition built on a
    traced lock IS that lock for lockset purposes — the engine's
    ``_cv``/``_lock`` pair). ``wait`` drops the lockset entry for its
    blocked span; notify carries no HB edge (mutual exclusion is not
    ordering; the state handed over is protected by the shared lock)."""

    def __init__(self, ins: "Instrumentation", lock=None):
        self._ins = ins
        if lock is None:
            lock = _TracedLock(ins, name=ins.next_name("Condition"))
        self._lock = lock
        raw = lock._raw if isinstance(lock, _TracedLock) else lock
        self._raw_cond = _real_threading.Condition(raw)
        self.name = getattr(lock, "name", ins.next_name("Condition"))
        self._gen = 0                     # notify generation (cooperative)

    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self):
        return self._lock.release()

    def __enter__(self):
        return self._lock.__enter__()

    def __exit__(self, *exc):
        return self._lock.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None) -> bool:
        ins = self._ins
        ins.det.lock_released(self.name)
        ins.op("cond_wait")
        try:
            if ins.cooperative:
                gen = self._gen
                # release the raw lock for the blocked span so the notifier
                # can enter the critical section; the re-acquire must also
                # be cooperative (a real blocking acquire here can hold the
                # baton while the notifier still holds the raw lock)
                raw = self._raw_cond._lock \
                    if hasattr(self._raw_cond, "_lock") else None
                self._raw_cond.release()
                ins.fuzz.block_until(lambda: self._gen != gen)
                while not self._raw_cond.acquire(blocking=False):
                    ins.fuzz.block_until(
                        (lambda: not raw.locked()) if raw is not None
                        and hasattr(raw, "locked") else (lambda: True))
                return True
            return self._raw_cond.wait(timeout)
        finally:
            ins.det.lock_acquired(self.name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # re-implemented over self.wait so the lockset bookkeeping (and the
        # cooperative path) is shared; predicate runs holding the lock
        import time as _time
        endtime = None if timeout is None else _time.monotonic() + timeout
        result = predicate()
        while not result:
            if endtime is not None:
                remaining = endtime - _time.monotonic()
                if remaining <= 0.0:
                    break
                self.wait(remaining)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._gen += 1
        self._raw_cond.notify(n)
        self._ins.op("cond_notify")

    def notify_all(self) -> None:
        self._gen += 1
        self._raw_cond.notify_all()
        self._ins.op("cond_notify")


class _TracedEvent:
    """threading.Event twin; ``set -> (successful wait | is_set)`` is an HB
    edge (an observed flag publishes everything the setter did first)."""

    def __init__(self, ins: "Instrumentation"):
        self._ins = ins
        self._raw = _real_threading.Event()
        self._eid = ins.next_id()

    def set(self) -> None:
        self._ins.det.event_set(self._eid)
        self._raw.set()
        self._ins.op("event_set")

    def clear(self) -> None:
        self._raw.clear()

    def is_set(self) -> bool:
        s = self._raw.is_set()
        if s:
            self._ins.det.event_observed(self._eid)
        return s

    def wait(self, timeout: Optional[float] = None) -> bool:
        ins = self._ins
        ins.op("event_wait")
        if ins.cooperative:
            if timeout is None:
                ins.fuzz.block_until(self._raw.is_set)
            else:
                # a timed wait is a pacing sleep in this codebase's loops
                # (e.g. ``_stop_evt.wait(interval)``): model it as a zero-
                # length sleep plus a yield so the loop keeps spinning
                ins.op("event_wait_timeout")
            ok = self._raw.is_set()
        else:
            ok = self._raw.wait(timeout)
        if ok:
            self._ins.det.event_observed(self._eid)
        return ok


class _ThreadingProxy:
    """Stands in for the ``threading`` module inside instrumented modules:
    sync factories build traced twins, everything else passes through."""

    def __init__(self, ins: "Instrumentation"):
        self._ins = ins

    def Lock(self):
        return _TracedLock(self._ins)

    def RLock(self):
        return _TracedRLock(self._ins)

    def Condition(self, lock=None):
        return _TracedCondition(self._ins, lock)

    def Event(self):
        return _TracedEvent(self._ins)

    def Thread(self, *args, **kwargs):
        return self._ins.thread_cls(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(_real_threading, name)


def _make_thread_cls(ins: "Instrumentation"):
    class TracedThread(_real_threading.Thread):
        """Thread twin: start/join are HB edges; under the cooperative
        scheduler the child waits for the baton before running user code."""

        def start(self):
            self._race_parent = ins.det.current_tid()
            ins.op("thread_start")
            super().start()
            if ins.cooperative:
                ins.fuzz.wait_child_registered(self)

        def run(self):
            tid = ins.det.register_thread(self.name)
            self._race_tid = tid
            ins.det.thread_started(self._race_parent, tid)
            if ins.cooperative:
                ins.fuzz.register_child(self, tid)
            try:
                super().run()
            finally:
                ins.det.thread_exited(tid)
                if ins.cooperative:
                    ins.fuzz.detach(tid)

        def join(self, timeout: Optional[float] = None):
            ins.op("thread_join")
            if ins.cooperative and timeout is None:
                ins.fuzz.block_until(lambda: not self.is_alive())
            super().join(timeout)
            if not self.is_alive() and hasattr(self, "_race_tid"):
                ins.det.thread_joined(self._race_tid)

    return TracedThread


def _make_future_cls(ins: "Instrumentation"):
    class TracedFuture(_RealFuture):
        """Future twin: completion -> observation is an HB edge (the
        dispatcher->completion handoff, router reroutes, done-callbacks)."""

        def __init__(self):
            super().__init__()
            self._race_fid = ins.next_id()

        def set_result(self, result):
            ins.det.future_completed(self._race_fid)
            ins.op("future_set")
            super().set_result(result)

        def set_exception(self, exception):
            ins.det.future_completed(self._race_fid)
            ins.op("future_set")
            super().set_exception(exception)

        def result(self, timeout: Optional[float] = None):
            ins.op("future_get")
            if ins.cooperative and timeout is None:
                ins.fuzz.block_until(self.done)
            r = super().result(timeout)
            ins.det.future_observed(self._race_fid)
            return r

        def exception(self, timeout: Optional[float] = None):
            ins.op("future_get")
            if ins.cooperative and timeout is None:
                ins.fuzz.block_until(self.done)
            e = super().exception(timeout)
            ins.det.future_observed(self._race_fid)
            return e

        def add_done_callback(self, fn):
            # registration -> invocation is itself an HB edge: the callback
            # (and the closure state it captures) runs strictly after this
            # call, in whichever thread completes the future
            ins.det.future_registered(self._race_fid)
            ins.op("future_register")

            def _traced_cb(fut, _fn=fn):
                ins.det.future_observed(self._race_fid)
                _fn(fut)
            super().add_done_callback(_traced_cb)

    return TracedFuture


def _make_queue_cls(ins: "Instrumentation"):
    class TracedQueue(_real_queue.Queue):
        """Queue twin: ``put -> the get that receives that item`` is an HB
        edge (FIFO-paired clock transfer)."""

        def __init__(self, maxsize: int = 0):
            super().__init__(maxsize)
            self._race_qid = ins.next_id()

        def put(self, item, block: bool = True,
                timeout: Optional[float] = None):
            ins.op("queue_put")
            super().put(item, block, timeout)
            ins.det.queue_put(self._race_qid)

        def get(self, block: bool = True, timeout: Optional[float] = None):
            ins.op("queue_get")
            if ins.cooperative and block and timeout is None:
                while True:
                    try:
                        item = super().get(block=False)
                        break
                    except _real_queue.Empty:
                        ins.fuzz.block_until(lambda: not self.empty())
            else:
                item = super().get(block, timeout)
            ins.det.queue_got(self._race_qid)
            return item

    return TracedQueue


class _QueueModuleProxy:
    def __init__(self, ins: "Instrumentation"):
        self._ins = ins

    def Queue(self, maxsize: int = 0):
        return self._ins.queue_cls(maxsize)

    def __getattr__(self, name):
        return getattr(_real_queue, name)


#: attribute VALUES that are synchronization, not shared data: reading the
#: lock/condition/event/queue/future/thread handle off an object is how a
#: thread synchronizes — recording those reads would report "races" on
#: every lock attribute (all threads read it bare by construction)
_SYNC_TYPES = (
    _TracedLock, _TracedCondition, _TracedEvent,
    type(_real_threading.Lock()), type(_real_threading.RLock()),
    _real_threading.Condition, _real_threading.Event,
    _real_threading.Semaphore, _real_threading.Thread,
    _real_queue.Queue, _RealFuture,
)


def _is_sync(value) -> bool:
    return isinstance(value, _SYNC_TYPES)


class Instrumentation:
    """One detector + its traced primitives + the install/uninstall state."""

    def __init__(self, detector: Optional[RaceDetector] = None, fuzz=None):
        self.det = detector or RaceDetector()
        self.fuzz = fuzz
        if fuzz is not None:
            fuzz.bind(self.det)
        self._mu = _real_threading.Lock()
        self._name_counts: Dict[str, int] = {}
        self._next = 0
        self._labels: Dict[int, str] = {}
        self._label_refs: List[object] = []     # keep labeled objects alive:
        # id() reuse during a run would alias two objects into one label
        self._module_saves: List[Tuple[object, str, object]] = []
        self._field_saves: List[object] = []    # dataclass Field objects
        self._class_saves: List[Tuple[type, dict]] = []
        self.threading = _ThreadingProxy(self)
        self.queue = _QueueModuleProxy(self)
        self.thread_cls = _make_thread_cls(self)
        self.future_cls = _make_future_cls(self)
        self.queue_cls = _make_queue_cls(self)

    @property
    def cooperative(self) -> bool:
        return self.fuzz is not None and getattr(self.fuzz, "cooperative",
                                                 False)

    # -- ids / labels -------------------------------------------------------

    def next_name(self, kind: str) -> str:
        with self._mu:
            n = self._name_counts.get(kind, 0)
            self._name_counts[kind] = n + 1
            return f"{kind}#{n}"

    def next_id(self) -> int:
        with self._mu:
            self._next += 1
            return self._next

    def _label_of(self, obj) -> str:
        key = id(obj)
        with self._mu:
            label = self._labels.get(key)
            if label is None:
                label = self.det.label_object(type(obj).__name__)
                self._labels[key] = label
                self._label_refs.append(obj)
            return label

    # -- fuzz hook ----------------------------------------------------------

    def op(self, kind: str) -> None:
        if self.fuzz is not None:
            self.fuzz.on_op(kind)

    # -- direct construction (fixtures) -------------------------------------

    def lock(self, name: Optional[str] = None) -> _TracedLock:
        return _TracedLock(self, name)

    def rlock(self, name: Optional[str] = None) -> _TracedRLock:
        return _TracedRLock(self, name)

    def condition(self, lock=None) -> _TracedCondition:
        return _TracedCondition(self, lock)

    def event(self) -> _TracedEvent:
        return _TracedEvent(self)

    def thread(self, *args, **kwargs):
        return self.thread_cls(*args, **kwargs)

    def future(self):
        return self.future_cls()

    def make_queue(self, maxsize: int = 0):
        return self.queue_cls(maxsize)

    # -- injection ----------------------------------------------------------

    def install(self, modules: Iterable[object] = (),
                classes: Iterable[type] = ()) -> None:
        """Swap traced twins into `modules`' globals (every reference to
        the real ``threading``/``queue`` module or ``Future`` class) and
        patch attribute tracing onto `classes`."""
        for mod in modules:
            for name, val in list(vars(mod).items()):
                repl = None
                if val is _real_threading:
                    repl = self.threading
                elif val is _real_queue:
                    repl = self.queue
                elif val is _RealFuture:
                    repl = self.future_cls
                if repl is not None:
                    self._module_saves.append((mod, name, val))
                    setattr(mod, name, repl)
            # a dataclass ``field(default_factory=Future)`` captured the
            # REAL class at class-definition time — the module-global swap
            # can't reach it (batcher.Request.future is minted this way).
            # The factory lives in TWO places: the Field object (metadata)
            # and a closure cell of the generated __init__ (``_dflt_<name>``
            # — the one the constructor actually calls)
            for val in vars(mod).values():
                if not (isinstance(val, type)
                        and val.__module__ == mod.__name__):
                    continue
                fields = getattr(val, "__dataclass_fields__", {})
                if not any(f.default_factory is _RealFuture
                           for f in fields.values()):
                    continue
                for f in fields.values():
                    if f.default_factory is _RealFuture:
                        self._field_saves.append((f, "default_factory"))
                        f.default_factory = self.future_cls
                for cell in val.__init__.__closure__ or ():
                    if cell.cell_contents is _RealFuture:
                        self._field_saves.append((cell, "cell_contents"))
                        cell.cell_contents = self.future_cls
        for cls in classes:
            self._patch_class(cls)

    def uninstall(self) -> None:
        """Restore the exact original objects — the uninstrumented modules
        and classes are byte-identical to their pre-install state."""
        for mod, name, val in reversed(self._module_saves):
            setattr(mod, name, val)
        self._module_saves.clear()
        for obj, attr in self._field_saves:
            setattr(obj, attr, _RealFuture)
        self._field_saves.clear()
        for cls, saved in reversed(self._class_saves):
            for name, orig in saved.items():
                if orig is None:
                    if name in cls.__dict__:
                        delattr(cls, name)
                else:
                    setattr(cls, name, orig)
        self._class_saves.clear()

    @contextlib.contextmanager
    def active(self, modules: Iterable[object] = (),
               classes: Iterable[type] = ()):
        self.install(modules, classes)
        try:
            yield self
        finally:
            self.uninstall()

    def track(self, obj):
        """Track one object's class (fixture convenience); returns `obj`."""
        cls = type(obj)
        if not any(c is cls for c, _ in self._class_saves):
            self._patch_class(cls)
        return obj

    def _patch_class(self, cls: type) -> None:
        ins = self
        saved = {
            "__setattr__": cls.__dict__.get("__setattr__"),
            "__getattribute__": cls.__dict__.get("__getattribute__"),
        }
        orig_set = cls.__setattr__
        orig_get = cls.__getattribute__

        def __setattr__(self, name, value):
            if not name.startswith("_race_") and not _is_sync(value):
                ins.det.access(f"{ins._label_of(self)}.{name}", write=True)
            orig_set(self, name, value)

        def __getattribute__(self, name):
            value = orig_get(self, name)
            if not name.startswith(("__", "_race_")):
                try:
                    is_instance_attr = name in orig_get(self, "__dict__")
                except AttributeError:
                    is_instance_attr = name in getattr(
                        type(self), "__slots__", ())
                if is_instance_attr and not _is_sync(value):
                    ins.det.access(f"{ins._label_of(self)}.{name}",
                                   write=False)
            return value

        cls.__setattr__ = __setattr__
        cls.__getattribute__ = __getattribute__
        self._class_saves.append((cls, saved))
