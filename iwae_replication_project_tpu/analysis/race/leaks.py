"""Static leak pass: futures completed, spans finished, pins released.

The serving control plane's drain contract ("zero silence": every accepted
request is answered, every trace finalizes, every executable pin returns
to the store) has so far been *test-sampled* — the chaos smoke proves it
for the schedules it runs. This pass machine-checks the structural half:
every acquisition of a leakable resource in the ``leak_paths`` files —

* ``X = Future()``            (completed by ``set_result/set_exception``),
* ``X = ...start_span(...)``  (closed by ``X.finish(...)``),
* ``X = ...pin_prefix(...)``  (returned by ``X.release()``),

— must be **safely held** on every exception path. A site passes when:

* the value is stored/handed off at the acquisition itself (assigned into
  an attribute/container, passed as a call argument, returned) — the
  receiving structure owns the lifecycle (its own drain paths are in this
  pass's scope too); or
* the acquisition sits inside a ``try`` whose ``finally`` — or an
  except-all (``except``/``except Exception``/``except BaseException``)
  handler — *names* the resource (releasing it, completing it, or handing
  it to the completion helper), AND a success-path sink exists later in
  the function; or
* nothing that can raise (a call, a subscript, a raise/assert, a compound
  header) stands between the acquisition and the first sink.

The check is structural, not path-sensitive: it proves the release
*shape* exists, the runtime leak check in ``scripts/race_smoke.py``
(open-span count, pinned-entry count, futures done) proves the shape
works under fuzzed schedules.

Rules ``leaked-future`` / ``leaked-span`` / ``leaked-pin`` register with
the lint framework (suppression grammar, ``--select``), and the
``iwae-race`` CLI runs exactly this family as its static stage.

:func:`acquisitions_in` is also consumed by the ``swallowed-exception``
rule: a best-effort ``except OSError`` drop in a function this pass
proves acquisition-free cannot leak a future/span/pin, so it no longer
needs a waiver (the PR-10 suppression inventory re-audit).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)

__all__ = ["analyze_file", "acquisitions_in",
           "LeakedFutureRule", "LeakedSpanRule", "LeakedPinRule"]

#: acquisition call terminal -> (kind, release verbs)
_ACQUIRE = {
    "Future": ("future", {"set_result", "set_exception", "cancel"}),
    "start_span": ("span", {"finish"}),
    "pin_prefix": ("pin", {"release"}),
}

#: statement types that cannot raise between acquisition and sink
_SAFE_STMTS = (ast.Pass, ast.Global, ast.Nonlocal, ast.Break, ast.Continue)

_EXCEPT_ALL = {"", "Exception", "BaseException"}


def _terminal_of(call: ast.Call) -> str:
    return Rule.terminal(Rule.call_name(call))


def _acquisition_kind(value: ast.AST) -> Optional[str]:
    """kind when `value` is *top-level* an acquisition call (a nested
    acquisition is already in the enclosing expression's hands)."""
    if isinstance(value, ast.Call):
        term = _terminal_of(value)
        if term in _ACQUIRE:
            if term == "Future" and (value.args or value.keywords):
                return None
            return _ACQUIRE[term][0]
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_sink(stmt: ast.stmt, var: str, release_verbs: Set[str]) -> bool:
    """Whether `stmt` safely disposes of `var`: releases/completes it,
    hands it to a call, stores it, or returns/yields it."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call):
            # var.release() / var.finish(...) / var.set_result(...)
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == var and \
                    node.func.attr in release_verbs:
                return True
            # var handed to any call (complete_future(var), _Pending(...,
            # span=var), pending.append(var)) — the callee owns it now
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if var in _names_in(arg):
                    return True
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            if node.value is not None and var in _names_in(node.value):
                return True
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name) and var in _names_in(
                        node.value):
                    return True      # self.Y = var / d[k] = var
    return False


def _can_raise(stmt: ast.stmt) -> bool:
    if isinstance(stmt, _SAFE_STMTS):
        return False
    if isinstance(stmt, ast.AnnAssign):
        # the annotation itself (Optional[X] is a Subscript node) never
        # evaluates at runtime under lazy annotations — only the value
        # and a subscripted target can raise
        roots = [n for n in (stmt.value, stmt.target) if n is not None]
    else:
        roots = [stmt]
    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, (ast.Call, ast.Raise, ast.Assert,
                                 ast.Subscript)):
                return True
    return False


def _handler_is_all(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    return Rule.terminal(Rule.dotted(handler.type) or "?") in _EXCEPT_ALL


def _try_protects(try_node: ast.Try, var: str) -> bool:
    """A try protects `var` when its finally, or an except-all handler,
    names the resource (release/complete/handoff all count via naming)."""
    for stmt in try_node.finalbody:
        if var in _names_in(stmt):
            return True
    for handler in try_node.handlers:
        if _handler_is_all(handler):
            for stmt in handler.body:
                if var in _names_in(stmt):
                    return True
    return False


class _Acquisition:
    __slots__ = ("var", "kind", "node", "protected")

    def __init__(self, var: Optional[str], kind: str, node: ast.AST,
                 protected: bool):
        self.var = var
        self.kind = kind
        self.node = node
        self.protected = protected


def _walk_function(func: ast.AST) -> List[_Acquisition]:
    """Acquisitions inside `func` (nested defs excluded — they are their
    own functions), each stamped with its enclosing-try protection."""
    out: List[_Acquisition] = []

    def visit(stmts: List[ast.stmt], tries: List[ast.Try]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                kind = _acquisition_kind(stmt.value)
                if kind is not None:
                    tgt = stmt.targets[0]
                    if isinstance(tgt, ast.Name):
                        protected = any(
                            _try_protects(t, tgt.id) for t in tries)
                        out.append(_Acquisition(tgt.id, kind, stmt.value,
                                                protected))
                    # non-Name target: stored at birth — a handoff sink
            elif isinstance(stmt, ast.Expr):
                kind = _acquisition_kind(stmt.value)
                if kind is not None:
                    out.append(_Acquisition(None, kind, stmt.value,
                                            protected=False))
            # recurse into compound bodies
            if isinstance(stmt, ast.Try):
                visit(stmt.body, tries + [stmt])
                for handler in stmt.handlers:
                    visit(handler.body, tries)
                visit(stmt.orelse, tries)
                visit(stmt.finalbody, tries)
            else:
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        visit(sub, tries)

    visit(getattr(func, "body", []), [])
    return out


def _flat_stmts(func: ast.AST) -> List[ast.stmt]:
    """Every statement in `func` (nested defs excluded), line order."""
    out: List[ast.stmt] = []

    def visit(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.append(stmt)
            for field in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(stmt, field, None)
                if not sub:
                    continue
                for item in sub:
                    if isinstance(item, ast.ExceptHandler):
                        visit(item.body)
                    else:
                        visit([item])

    visit(getattr(func, "body", []))
    return sorted(out, key=lambda s: (s.lineno, s.col_offset))


def analyze_file(tree: ast.Module) -> List[Tuple[str, ast.AST, str]]:
    """All leak findings for one parsed file: ``(kind, node, message)``."""
    findings: List[Tuple[str, ast.AST, str]] = []
    for func in [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        acqs = _walk_function(func)
        if not acqs:
            continue
        stmts = _flat_stmts(func)
        for acq in acqs:
            verbs = next(v for k, (kind, v) in _ACQUIRE.items()
                         if kind == acq.kind)
            noun = {"future": "future", "span": "span",
                    "pin": "executable-store pin"}[acq.kind]
            if acq.var is None:
                findings.append((
                    acq.kind, acq.node,
                    f"{noun} created and dropped: the handle is never "
                    f"bound, so nothing can ever complete/close/release "
                    f"it — bind it and manage its lifecycle"))
                continue
            later = [s for s in stmts
                     if (s.lineno, s.col_offset) >
                     (acq.node.lineno, acq.node.col_offset)]
            sink_at = None
            for i, s in enumerate(later):
                if _is_sink(s, acq.var, verbs):
                    sink_at = i
                    break
            if sink_at is None:
                findings.append((
                    acq.kind, acq.node,
                    f"{noun} '{acq.var}' is never completed, handed off, "
                    f"or released after this acquisition — it leaks on "
                    f"every path through '{func.name}'"))
                continue
            if acq.protected:
                continue
            for s in later[:sink_at]:
                if _can_raise(s):
                    findings.append((
                        acq.kind, acq.node,
                        f"{noun} '{acq.var}' leaks if line {s.lineno} "
                        f"raises before the handoff/release at line "
                        f"{later[sink_at].lineno}: wrap the window in "
                        f"try/finally (or an except-all handler that "
                        f"completes '{acq.var}' and re-raises)"))
                    break
    return findings


def acquisitions_in(func: ast.AST) -> int:
    """How many leakable acquisitions `func` makes (0 = the leak pass
    proves an exception drop here cannot leak a future/span/pin)."""
    n = 0
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and _terminal_of(node) in _ACQUIRE:
            if _terminal_of(node) == "Future" and (node.args or
                                                   node.keywords):
                continue
            n += 1
    return n


def _in_leak_paths(ctx: FileContext) -> bool:
    return any(ctx.rel_path == p or
               ctx.rel_path.startswith(p.rstrip("/") + "/")
               for p in ctx.config.leak_paths)


class _LeakRuleBase(Rule):
    kind = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_leak_paths(ctx):
            return
        for kind, node, message in analyze_file(ctx.tree):
            if kind == self.kind:
                yield ctx.finding(self.name, node, message)


@register
class LeakedFutureRule(_LeakRuleBase):
    name = "leaked-future"
    kind = "future"
    summary = ("a Future acquired in a leak_paths file is not provably "
               "completed/handed off on all exception paths — a leaked "
               "future is a request that never answers (the drain "
               "contract's 'zero silence')")


@register
class LeakedSpanRule(_LeakRuleBase):
    name = "leaked-span"
    kind = "span"
    summary = ("a tracing Span opened in a leak_paths file is not provably "
               "finished on all exception paths — a leaked span is a trace "
               "that can only expire as abandoned")


@register
class LeakedPinRule(_LeakRuleBase):
    name = "leaked-pin"
    kind = "pin"
    summary = ("an ExecutableStore pin taken in a leak_paths file is not "
               "provably released on all exception paths — a leaked pin "
               "permanently shrinks the store's evictable budget")
