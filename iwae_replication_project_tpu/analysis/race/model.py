"""Detector core: vector clocks, locksets, and the race report.

The algorithm is the classic hybrid (Eraser's lockset refined by
happens-before, the shape TSan and FastTrack settled on):

* every thread carries a **vector clock**; the synchronization operations
  that *transfer* work between threads are HB edges — ``Thread.start``
  (parent -> child), ``Thread.join`` (child -> joiner), future
  ``set_result/set_exception`` -> ``result/exception/done-callback`` plus
  ``add_done_callback`` registration -> callback invocation,
  ``Queue.put`` -> the ``get`` that receives that item (FIFO pairing),
  ``Event.set`` -> a successful ``wait``/``is_set``, and lock ``release``
  -> a later ``acquire`` of the same lock (each lock carries a sync
  clock, TSan's happens-before mode). The lock edge is what accepts the
  serving stack's ownership-handoff idiom — transfer a request's
  exclusive owner under the router lock, then let the new owner touch it
  lock-free — at the known cost that a publish racing an *earlier*
  same-lock section in a different interleaving is summarized away
  (Eraser's pure-lockset mode would catch it; TSan's hb mode, and this
  one, trade it for not flagging every handoff in callback-driven code);
* every access to a tracked shared attribute records the accessing
  thread's current **lockset** (the traced locks it holds);
* a **data race** is two accesses to the same attribute from different
  threads, at least one a write, with an empty common lockset and no HB
  order between them — reported with both stacks, both locksets, and the
  schedule seed that produced the interleaving (the repro).

Access history is FastTrack-style bounded: per variable, the last
read and the last write per thread. With HB edges joining clocks on every
real handoff, that summary loses no race this codebase's idioms can
produce (the dispatcher->completion pipeline, router reroutes, connection
callback fans).

Everything here is deterministic given a deterministic schedule: thread
ids are registration-order ordinals, object labels are per-class creation
ordinals, and :meth:`RaceDetector.report` sorts — so the cooperative
fuzzer's same-seed runs serialize to byte-identical reports.
"""

from __future__ import annotations

import threading
import traceback
from collections import deque
from typing import Deque, Dict, FrozenSet, List, Optional, Tuple

__all__ = ["VectorClock", "Access", "RaceReport", "RaceDetector"]

#: frames from these path fragments are noise in an access stack (the
#: instrumentation layer itself, the interpreter's threading bootstrap)
_STACK_SKIP = ("analysis/race/", "lib/python", "importlib")


class VectorClock:
    """A mapping ``tid -> logical time``; absent entries are 0."""

    __slots__ = ("c",)

    def __init__(self, c: Optional[Dict[int, int]] = None):
        self.c: Dict[int, int] = dict(c) if c else {}

    def tick(self, tid: int) -> None:
        self.c[tid] = self.c.get(tid, 0) + 1

    def join(self, other: "VectorClock") -> None:
        for t, v in other.c.items():
            if v > self.c.get(t, 0):
                self.c[t] = v

    def copy(self) -> "VectorClock":
        return VectorClock(self.c)

    def time_of(self, tid: int) -> int:
        return self.c.get(tid, 0)

    def dominates(self, tid: int, t: int) -> bool:
        """Whether this clock has seen thread ``tid``'s time ``t`` (i.e. an
        event stamped ``(tid, t)`` happens-before the holder's present)."""
        return self.c.get(tid, 0) >= t

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"VC({self.c})"


class Access:
    """One recorded access: who, what kind, under which locks, when, where."""

    __slots__ = ("tid", "write", "lockset", "epoch", "stack")

    def __init__(self, tid: int, write: bool, lockset: FrozenSet[str],
                 epoch: int, stack: Tuple[str, ...]):
        self.tid = tid
        self.write = write
        self.lockset = lockset
        self.epoch = epoch          # accessing thread's own clock component
        self.stack = stack

    def describe(self) -> dict:
        return {
            "thread": self.tid,
            "op": "write" if self.write else "read",
            "lockset": sorted(self.lockset),
            "stack": list(self.stack),
        }


class RaceReport:
    """One detected race: a variable plus the two unordered accesses."""

    def __init__(self, var: str, prior: Access, current: Access,
                 thread_names: Dict[int, str]):
        self.var = var
        self.prior = prior
        self.current = current
        self.thread_names = thread_names

    def key(self) -> Tuple:
        """Dedup key: the same pair of program points races once per run."""
        return (self.var, self.prior.write, self.current.write,
                self.prior.stack, self.current.stack)

    def to_dict(self) -> dict:
        def side(a: Access) -> dict:
            d = a.describe()
            d["thread_name"] = self.thread_names.get(a.tid, f"t{a.tid}")
            return d
        return {"var": self.var, "first": side(self.prior),
                "second": side(self.current)}

    def human(self) -> str:
        a, b = self.prior, self.current
        lines = [f"RACE on {self.var}:"]
        for tag, acc in (("first", a), ("second", b)):
            name = self.thread_names.get(acc.tid, f"t{acc.tid}")
            held = ", ".join(sorted(acc.lockset)) or "no locks"
            lines.append(f"  {tag}: {'write' if acc.write else 'read'} by "
                         f"thread {acc.tid} ({name}) holding {held}")
            for frame in acc.stack:
                lines.append(f"    {frame}")
        return "\n".join(lines)


class _VarState:
    """Bounded access history for one variable (per-thread last read/write)."""

    __slots__ = ("reads", "writes")

    def __init__(self):
        self.reads: Dict[int, Access] = {}
        self.writes: Dict[int, Access] = {}


class RaceDetector:
    """The event sink every traced primitive and tracked attribute reports to.

    Thread-safe (one internal real lock — the detector is never itself
    traced). All ids handed out are deterministic under a deterministic
    schedule: thread ids and object labels are allocation ordinals.
    """

    def __init__(self, capture_stacks: bool = True, stack_depth: int = 5):
        self._mu = threading.Lock()
        self.capture_stacks = capture_stacks
        self.stack_depth = stack_depth
        # threads
        self._tids: Dict[int, int] = {}          # ident -> tid
        self._names: Dict[int, str] = {}         # tid -> name
        self._clocks: Dict[int, VectorClock] = {}
        self._final: Dict[int, VectorClock] = {}  # exited threads' clocks
        # sync objects
        self._locksets: Dict[int, List[str]] = {}  # tid -> held lock names
        self._lock_clocks: Dict[str, VectorClock] = {}
        self._future_clocks: Dict[int, VectorClock] = {}
        self._queue_clocks: Dict[int, Deque[VectorClock]] = {}
        self._event_clocks: Dict[int, VectorClock] = {}
        # shared state
        self._vars: Dict[str, _VarState] = {}
        self._races: Dict[Tuple, RaceReport] = {}
        self._label_counts: Dict[str, int] = {}
        self.seed: Optional[int] = None          # stamped by the fuzzer

    # -- threads ------------------------------------------------------------

    def register_thread(self, name: Optional[str] = None) -> int:
        """Register the calling OS thread; idempotent. Returns its tid."""
        ident = threading.get_ident()
        with self._mu:
            tid = self._tids.get(ident)
            if tid is None:
                tid = len(self._names)
                self._tids[ident] = tid
                self._names[tid] = name or threading.current_thread().name
                vc = VectorClock()
                vc.tick(tid)
                self._clocks[tid] = vc
            return tid

    def current_tid(self) -> int:
        return self.register_thread()

    def thread_started(self, parent_tid: int, child_tid: int) -> None:
        """HB edge parent -> child: everything the parent did before
        ``start()`` happens-before everything the child does."""
        with self._mu:
            self._clocks[child_tid].join(self._clocks[parent_tid])
            self._clocks[parent_tid].tick(parent_tid)
            self._clocks[child_tid].tick(child_tid)

    def thread_exited(self, tid: int) -> None:
        ident = threading.get_ident()
        with self._mu:
            self._final[tid] = self._clocks[tid].copy()
            self._locksets.pop(tid, None)
            # the OS recycles idents: a thread created after this one fully
            # exits can receive the same ident, and must get a FRESH tid —
            # aliasing two threads into one tid hides every race between
            # them (and whether recycling happens is OS timing, so leaving
            # the mapping would also break same-seed determinism)
            if self._tids.get(ident) == tid:
                del self._tids[ident]

    def thread_joined(self, child_tid: int) -> None:
        """HB edge child -> joiner: a completed ``join()`` publishes the
        child's whole history to the joining thread."""
        me = self.current_tid()
        with self._mu:
            src = self._final.get(child_tid) or self._clocks.get(child_tid)
            if src is not None:
                self._clocks[me].join(src)
            self._clocks[me].tick(me)

    # -- locks (locksets + release->acquire sync clocks) ---------------------

    def lock_acquired(self, lock_name: str) -> None:
        tid = self.current_tid()
        with self._mu:
            self._locksets.setdefault(tid, []).append(lock_name)
            # acquire side of the release->acquire HB edge: join everything
            # published by prior critical sections on this lock
            clk = self._lock_clocks.get(lock_name)
            if clk is not None:
                self._clocks[tid].join(clk)

    def lock_released(self, lock_name: str) -> None:
        tid = self.current_tid()
        with self._mu:
            held = self._locksets.get(tid, [])
            if lock_name in held:
                # remove the innermost matching hold (RLock reentrancy)
                for i in range(len(held) - 1, -1, -1):
                    if held[i] == lock_name:
                        del held[i]
                        break
            # release side: publish this thread's history to the lock
            clk = self._lock_clocks.setdefault(lock_name, VectorClock())
            clk.join(self._clocks[tid])
            self._clocks[tid].tick(tid)

    def held_locks(self) -> FrozenSet[str]:
        tid = self.current_tid()
        with self._mu:
            return frozenset(self._locksets.get(tid, ()))

    # -- futures / queues / events (HB edges) --------------------------------

    def future_completed(self, fid: int) -> None:
        tid = self.current_tid()
        with self._mu:
            clk = self._future_clocks.setdefault(fid, VectorClock())
            clk.join(self._clocks[tid])
            self._clocks[tid].tick(tid)

    def future_observed(self, fid: int) -> None:
        tid = self.current_tid()
        with self._mu:
            clk = self._future_clocks.get(fid)
            if clk is not None:
                self._clocks[tid].join(clk)

    def future_registered(self, fid: int) -> None:
        """HB edge registrant -> callback: ``add_done_callback`` publishes
        the registering thread's history to the callback invocation (CPython
        runs the callback in the completing thread strictly after the
        registration, or synchronously in the registrant itself). Without
        this edge every object handed to a done-callback via its closure
        looks unordered with the thread that built it."""
        self.future_completed(fid)

    def queue_put(self, qid: int) -> None:
        tid = self.current_tid()
        with self._mu:
            q = self._queue_clocks.setdefault(qid, deque())
            q.append(self._clocks[tid].copy())
            self._clocks[tid].tick(tid)

    def queue_got(self, qid: int) -> None:
        tid = self.current_tid()
        with self._mu:
            q = self._queue_clocks.get(qid)
            if q:
                self._clocks[tid].join(q.popleft())

    def event_set(self, eid: int) -> None:
        tid = self.current_tid()
        with self._mu:
            clk = self._event_clocks.setdefault(eid, VectorClock())
            clk.join(self._clocks[tid])
            self._clocks[tid].tick(tid)

    def event_observed(self, eid: int) -> None:
        tid = self.current_tid()
        with self._mu:
            clk = self._event_clocks.get(eid)
            if clk is not None:
                self._clocks[tid].join(clk)

    # -- shared-state accesses ----------------------------------------------

    def label_object(self, cls_name: str) -> str:
        """Deterministic object label: per-class creation ordinal."""
        with self._mu:
            n = self._label_counts.get(cls_name, 0)
            self._label_counts[cls_name] = n + 1
            return f"{cls_name}#{n}"

    def _stack(self) -> Tuple[str, ...]:
        if not self.capture_stacks:
            return ()
        frames = traceback.extract_stack()
        out: List[str] = []
        for fr in frames:
            fn = fr.filename.replace("\\", "/")
            if any(s in fn for s in _STACK_SKIP):
                continue
            short = "/".join(fn.rsplit("/", 2)[-2:])
            out.append(f"{short}:{fr.lineno} in {fr.name}")
        return tuple(out[-self.stack_depth:])

    def access(self, var: str, write: bool) -> None:
        """Record a read/write of ``var`` by the calling thread and check it
        against the bounded history for lockset+HB races."""
        tid = self.current_tid()
        stack = self._stack()
        with self._mu:
            my_clock = self._clocks[tid]
            lockset = frozenset(self._locksets.get(tid, ()))
            acc = Access(tid, write, lockset, my_clock.time_of(tid), stack)
            st = self._vars.setdefault(var, _VarState())
            # a write races prior reads and writes; a read races prior writes
            prior_pools = (st.writes,) if not write else (st.writes, st.reads)
            for pool in prior_pools:
                for other_tid, prior in pool.items():
                    if other_tid == tid:
                        continue
                    if my_clock.dominates(other_tid, prior.epoch):
                        continue                    # HB-ordered
                    if prior.lockset & lockset:
                        continue                    # common lock
                    report = RaceReport(var, prior, acc, dict(self._names))
                    self._races.setdefault(report.key(), report)
            (st.writes if write else st.reads)[tid] = acc
            my_clock.tick(tid)

    # -- results ------------------------------------------------------------

    @property
    def races(self) -> List[RaceReport]:
        return [self._races[k] for k in sorted(self._races,
                                               key=lambda k: repr(k))]

    def report(self) -> dict:
        """The run's verdict as one deterministic document (sorted; under
        the cooperative scheduler, same seed => byte-identical)."""
        return {
            "seed": self.seed,
            "threads": {str(t): self._names[t] for t in sorted(self._names)},
            "races": [r.to_dict() for r in self.races],
            "total": len(self._races),
        }
