"""``iwae-prof``: the profiling plane's statistical perf-regression gate.

The continuous profiler (telemetry/profiling.py) answers *"is this
process slower than its own recent past?"* at runtime; this CLI answers
the release-time version — *"is this TREE slower than the committed
baseline?"* — by diffing the bench artifacts ``bench.py`` writes under
``results/``:

* every numeric leaf in a pair of artifacts is a candidate metric; keys
  whose names carry a direction (``*_seconds``/``wall``/``overhead``/
  ``latency`` are lower-better, ``*_per_sec``/``throughput``/``speedup``
  higher-better) are compared, everything else (config echo, counters of
  unknown polarity) is skipped AND counted — a silent skip would read as
  "covered";
* numeric LISTS are treated as paired-rep spreads (the ``*_pairs`` /
  per-rep arrays the benches already record): the comparison is
  median-vs-median, gated by a hand-rolled two-sided rank-sum test
  (Mann-Whitney normal approximation with tie correction — no scipy);
* the **noise floor** is learned from the artifacts themselves: the
  relative IQR of the metric's own spread and of sibling spreads under
  the same JSON parent, floored at ``--min-rel``. A scalar-only metric
  (no spread anywhere near it) must clear the wider ``--scalar-min-rel``
  bar instead of a significance test;
* a **regression** is a bad-direction median shift that clears the noise
  floor AND (when both sides have >= 3 reps) the rank test at
  ``--alpha``. Improvements are reported but never gate.

Exit codes: 0 = no regressions, 1 = at least one regression (each
finding names the artifact and the metric key), 2 = usage/internal
error. ``scripts/check.py`` runs ``--diff results/perf_baseline.json
results/*_bench.json`` as a stage; refresh the baseline with
``--collect`` after an intentional perf change::

    iwae-prof --collect results/*_bench.json --out results/perf_baseline.json
    iwae-prof --diff results/perf_baseline.json results/*_bench.json
    iwae-prof --diff old_bench.json new_bench.json --json

``--json`` emits the shared CLI envelope (``{"tool", "schema", "mode",
"ok", "findings", "data"}``) that ``iwae-trace --json`` also uses;
schema pinned in tests/test_telemetry.py.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ENVELOPE_SCHEMA",
    "BASELINE_KIND",
    "make_envelope",
    "extract_metrics",
    "direction_for",
    "rank_sum_p",
    "diff_artifacts",
    "diff_bundles",
    "collect_bundle",
    "main",
]

#: version of the shared ``--json`` CLI envelope (iwae-prof AND iwae-trace)
ENVELOPE_SCHEMA = 1

#: ``kind`` tag of a --collect bundle (results/perf_baseline.json)
BASELINE_KIND = "iwae-perf-baseline"


def make_envelope(tool: str, mode: str, *, ok: bool,
                  findings: Sequence[dict] = (), data=None) -> dict:
    """The one ``--json`` output convention every iwae observability CLI
    shares: tool name, envelope schema version, the subcommand that ran,
    an overall ok bit, typed findings, and the tool-specific payload."""
    return {"tool": str(tool), "schema": ENVELOPE_SCHEMA,
            "mode": str(mode), "ok": bool(ok),
            "findings": list(findings), "data": data}


# -- metric extraction -------------------------------------------------------

def extract_metrics(doc, prefix: str = "") -> Dict[str, List[float]]:
    """Flatten an artifact to ``slash/path -> samples``.

    A numeric scalar becomes a 1-sample series; a homogeneous numeric
    list becomes its recorded spread (the benches' ``*_pairs`` / per-rep
    arrays — the raw material for both the rank test and the noise
    floor). Bools are config, not metrics. List-of-dict elements keep
    their index in the path so sweep rows stay distinct keys."""
    out: Dict[str, List[float]] = {}

    def _num(v) -> bool:
        return isinstance(v, (int, float)) and not isinstance(v, bool)

    def walk(node, path):
        if _num(node):
            out[path] = [float(node)]
        elif isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], f"{path}/{k}" if path else str(k))
        elif isinstance(node, list):
            if node and all(_num(v) for v in node):
                out[path] = [float(v) for v in node]
            else:
                for i, v in enumerate(node):
                    walk(v, f"{path}[{i}]")

    walk(doc, prefix)
    return out


def direction_for(key: str) -> int:
    """-1 = lower is better, +1 = higher is better, 0 = unknown (skip).

    Polarity lives in the leaf name, by the repo's bench conventions.
    The higher-better tokens are checked first so ``rows_per_sec`` does
    not fall into the ``_sec`` suffix trap."""
    leaf = key.rsplit("/", 1)[-1].lower()
    for tok in ("per_sec", "per_second", "throughput", "speedup"):
        if tok in leaf:
            return 1
    if leaf.endswith(("_s", "_sec", "_seconds", "_us", "_ms", "_ns")) \
            or "seconds" in leaf or "latency" in leaf \
            or "overhead" in leaf or "wall" in leaf:
        return -1
    return 0


# -- statistics (stdlib only — no scipy in the image) ------------------------

def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _quantile(xs: Sequence[float], q: float) -> float:
    s = sorted(xs)
    if len(s) == 1:
        return s[0]
    pos = q * (len(s) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (pos - lo) * (s[hi] - s[lo])


def _rel_iqr(xs: Sequence[float]) -> float:
    """Relative interquartile range — the spread-derived noise unit."""
    if len(xs) < 2:
        return 0.0
    med = _median(xs)
    if abs(med) < 1e-12:
        return 0.0
    return (_quantile(xs, 0.75) - _quantile(xs, 0.25)) / abs(med)


def rank_sum_p(a: Sequence[float], b: Sequence[float]) -> float:
    """Two-sided Mann-Whitney rank-sum p-value, normal approximation
    with tie correction and continuity correction.

    Exact for our purposes (bench reps are n ~ 5-12; the gate only needs
    "is this shift distinguishable from rep noise", not a publication
    p-value). Returns 1.0 when every observation ties (zero variance —
    nothing is distinguishable)."""
    n1, n2 = len(a), len(b)
    if n1 == 0 or n2 == 0:
        return 1.0
    pooled = sorted((v, 0) for v in a) + sorted((v, 1) for v in b)
    pooled.sort(key=lambda t: t[0])
    # average ranks over tie groups
    ranks = [0.0] * len(pooled)
    tie_term = 0.0
    i = 0
    while i < len(pooled):
        j = i
        while j + 1 < len(pooled) and pooled[j + 1][0] == pooled[i][0]:
            j += 1
        avg = (i + j) / 2.0 + 1.0
        for k in range(i, j + 1):
            ranks[k] = avg
        t = j - i + 1
        tie_term += t ** 3 - t
        i = j + 1
    r1 = sum(r for r, (_, side) in zip(ranks, pooled) if side == 0)
    u = r1 - n1 * (n1 + 1) / 2.0
    mu = n1 * n2 / 2.0
    n = n1 + n2
    var = (n1 * n2 / 12.0) * ((n + 1) - tie_term / (n * (n - 1)))
    if var <= 0:
        return 1.0
    z = (abs(u - mu) - 0.5) / math.sqrt(var)
    if z < 0:
        z = 0.0
    return max(0.0, min(1.0, 2.0 * (1.0 - 0.5 * (1.0 + math.erf(
        z / math.sqrt(2.0))))))


# -- the diff ---------------------------------------------------------------

def _sibling_noise(path: str, *metric_maps: Dict[str, List[float]]) -> float:
    """Noise floor for the metric at ``path`` from recorded spreads: the
    metric's own reps plus any >=3-sample series under the same JSON
    parent (the benches put ``*_pairs`` next to the medians they
    support)."""
    parent = path.rsplit("/", 1)[0] if "/" in path else ""
    noise = 0.0
    for metrics in metric_maps:
        own = metrics.get(path)
        if own is not None:
            noise = max(noise, _rel_iqr(own))
        for k, xs in metrics.items():
            if len(xs) >= 3 and \
                    (k.rsplit("/", 1)[0] if "/" in k else "") == parent:
                noise = max(noise, _rel_iqr(xs))
    return noise


def diff_artifacts(old_doc, new_doc, *, artifact: str = "",
                   alpha: float = 0.05, min_rel: float = 0.05,
                   scalar_min_rel: float = 0.10
                   ) -> Tuple[List[dict], dict]:
    """Compare two artifacts; return (findings, stats).

    Findings are regressions only (``kind: "perf/regression"``);
    improvements and skips land in stats. Each finding names the
    artifact and the full metric key — the "program" the gate flags."""
    old_m = extract_metrics(old_doc)
    new_m = extract_metrics(new_doc)
    findings: List[dict] = []
    stats = {"compared": 0, "skipped_unknown_direction": 0,
             "skipped_zero_baseline": 0, "only_old": 0, "only_new": 0,
             "improvements": []}
    for key in sorted(old_m):
        if key not in new_m:
            stats["only_old"] += 1
            continue
        direction = direction_for(key)
        if direction == 0:
            stats["skipped_unknown_direction"] += 1
            continue
        old_xs, new_xs = old_m[key], new_m[key]
        old_med, new_med = _median(old_xs), _median(new_xs)
        if abs(old_med) < 1e-12:
            stats["skipped_zero_baseline"] += 1
            continue
        stats["compared"] += 1
        rel = (new_med - old_med) / abs(old_med)
        bad = rel > 0 if direction < 0 else rel < 0
        mag = abs(rel)
        noise = _sibling_noise(key, old_m, new_m)
        paired = len(old_xs) >= 3 and len(new_xs) >= 3
        p = rank_sum_p(old_xs, new_xs) if paired else None
        if paired:
            floor = max(noise, min_rel)
            is_reg = bad and mag > floor and p < alpha
        else:
            floor = max(noise, scalar_min_rel)
            is_reg = bad and mag > floor
        record = {
            "artifact": artifact, "key": key,
            "old_median": old_med, "new_median": new_med,
            "rel_change": rel, "noise_floor": floor,
            "p_value": p, "n_old": len(old_xs), "n_new": len(new_xs),
        }
        if is_reg:
            record["kind"] = "perf/regression"
            findings.append(record)
        elif not bad and mag > floor and (p is None or p < alpha):
            stats["improvements"].append(record)
    stats["only_new"] = sum(1 for k in new_m if k not in old_m)
    return findings, stats


def collect_bundle(paths: Sequence[str]) -> dict:
    """Bundle artifacts into a baseline document keyed by filename stem
    (``results/tracing_bench.json`` -> ``tracing_bench``)."""
    artifacts = {}
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            artifacts[_stem(p)] = json.load(f)
    return {"kind": BASELINE_KIND, "schema": ENVELOPE_SCHEMA,
            "artifacts": artifacts}


def _stem(path: str) -> str:
    return os.path.splitext(os.path.basename(path))[0]


def _load_side(paths: Sequence[str]) -> Dict[str, dict]:
    """One diff side: each path is either a --collect bundle (its
    artifacts merge in under their own stems) or a bare artifact (keyed
    by its filename stem) — so ``--diff baseline.json results/*_bench
    .json`` and ``--diff old.json new.json`` both just work."""
    out: Dict[str, dict] = {}
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if isinstance(doc, dict) and doc.get("kind") == BASELINE_KIND:
            out.update(doc.get("artifacts", {}))
        else:
            out[_stem(p)] = doc
    return out


def diff_bundles(old: Dict[str, dict], new: Dict[str, dict], *,
                 alpha: float = 0.05, min_rel: float = 0.05,
                 scalar_min_rel: float = 0.10) -> Tuple[List[dict], dict]:
    """Diff every artifact stem present on BOTH sides; stems on one side
    only are counted (a new bench has no baseline yet — not a failure,
    but not silent either)."""
    findings: List[dict] = []
    per_artifact: Dict[str, dict] = {}
    shared = sorted(set(old) & set(new))
    for name in shared:
        # when the two sides are literally the same document, short-
        # circuit: identical is identical, no statistics needed
        if old[name] == new[name]:
            per_artifact[name] = {"identical": True, "compared": 0}
            continue
        f, stats = diff_artifacts(old[name], new[name], artifact=name,
                                  alpha=alpha, min_rel=min_rel,
                                  scalar_min_rel=scalar_min_rel)
        findings.extend(f)
        per_artifact[name] = stats
    stats = {
        "artifacts_compared": len(shared),
        "artifacts_only_old": sorted(set(old) - set(new)),
        "artifacts_only_new": sorted(set(new) - set(old)),
        "per_artifact": per_artifact,
    }
    return findings, stats


# -- CLI --------------------------------------------------------------------

def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="iwae-prof",
        description="profiling-plane CLI: statistical perf-regression "
                    "gate over bench artifacts, and baseline collection")
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--diff", nargs="+", metavar="PATH",
                      help="OLD NEW [NEW...]: diff the first artifact/"
                           "bundle against the rest; exit 1 on any "
                           "statistically significant regression")
    mode.add_argument("--collect", nargs="+", metavar="PATH",
                      help="bundle artifacts into a baseline document "
                           "(write with --out)")
    ap.add_argument("--out", type=str, default=None,
                    help="write the output document here instead of stdout")
    ap.add_argument("--json", action="store_true",
                    help="emit the shared CLI envelope "
                         "(tool/schema/mode/ok/findings/data) on stdout")
    ap.add_argument("--alpha", type=float, default=0.05,
                    help="rank-test significance level (default 0.05)")
    ap.add_argument("--min-rel", type=float, default=0.05,
                    help="minimum relative shift to flag when reps "
                         "support a rank test (default 0.05)")
    ap.add_argument("--scalar-min-rel", type=float, default=0.10,
                    help="minimum relative shift for scalar-only metrics "
                         "with no recorded spread (default 0.10)")
    return ap


def _emit(args, doc: dict, text_lines: Sequence[str]) -> None:
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        for line in text_lines:
            print(line)


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    try:
        if args.collect:
            bundle = collect_bundle(args.collect)
            out_text = json.dumps(bundle, indent=2, sort_keys=True)
            if args.out:
                with open(args.out, "w", encoding="utf-8") as f:
                    f.write(out_text + "\n")
            env = make_envelope(
                "iwae-prof", "collect", ok=True,
                data={"out": args.out,
                      "artifacts": sorted(bundle["artifacts"])})
            lines = [f"iwae-prof: collected {len(bundle['artifacts'])} "
                     f"artifact(s)"
                     + (f" -> {args.out}" if args.out else "")]
            if not args.out and not args.json:
                lines = [out_text]
            _emit(args, env, lines)
            return 0

        if len(args.diff) < 2:
            print("iwae-prof: --diff needs OLD and at least one NEW path",
                  file=sys.stderr)
            return 2
        old = _load_side(args.diff[:1])
        new = _load_side(args.diff[1:])
        if len(old) == 1 and len(new) == 1 and set(old) != set(new):
            # the plain two-artifact form (`--diff old.json new.json`):
            # one doc a side is an explicit pairing — filename stems need
            # not match (bundle-vs-tree diffs still match by stem)
            (odoc,), (nname,) = old.values(), new.keys()
            old = {nname: odoc}
        findings, stats = diff_bundles(
            old, new, alpha=args.alpha, min_rel=args.min_rel,
            scalar_min_rel=args.scalar_min_rel)
        ok = not findings
        env = make_envelope("iwae-prof", "diff", ok=ok,
                            findings=findings, data=stats)
        lines = []
        for f in findings:
            direction = "slower" if f["rel_change"] > 0 else "worse"
            p_txt = (f", p={f['p_value']:.4f}" if f["p_value"] is not None
                     else ", scalar")
            lines.append(
                f"REGRESSION {f['artifact']}:{f['key']} "
                f"{f['old_median']:.6g} -> {f['new_median']:.6g} "
                f"({f['rel_change']:+.1%} {direction}, "
                f"floor {f['noise_floor']:.1%}{p_txt})")
        n_cmp = sum(s.get("compared", 0)
                    for s in stats["per_artifact"].values())
        lines.append(
            f"iwae-prof: {len(findings)} regression(s) across "
            f"{stats['artifacts_compared']} artifact(s) "
            f"({n_cmp} directional metrics compared)")
        for name in stats["artifacts_only_new"]:
            lines.append(f"iwae-prof: note: {name} has no baseline entry")
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(json.dumps(env, indent=2) + "\n")
        _emit(args, env, lines)
        return 0 if ok else 1
    except (OSError, json.JSONDecodeError) as e:
        print(f"iwae-prof: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
