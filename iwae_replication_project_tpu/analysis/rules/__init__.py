"""Built-in rule modules. Importing this package registers every rule with
the core registry (``core.all_rules`` triggers the import)."""

from iwae_replication_project_tpu.analysis.rules import (  # noqa: F401
    concurrency,
    dtype,
    entrypoints,
    host,
    imports,
    jit,
    prng,
)
# the static leak pass (leaked-future / leaked-span / leaked-pin) lives in
# the race-detector package but registers with the same rule registry so
# suppressions and --select work uniformly across iwae-lint and iwae-race
from iwae_replication_project_tpu.analysis.race import leaks  # noqa: F401,E402
