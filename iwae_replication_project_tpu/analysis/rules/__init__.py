"""Built-in rule modules. Importing this package registers every rule with
the core registry (``core.all_rules`` triggers the import)."""

from iwae_replication_project_tpu.analysis.rules import (  # noqa: F401
    concurrency,
    dtype,
    entrypoints,
    host,
    imports,
    jit,
    prng,
)
