"""Rules ``lock-order``, ``unlocked-shared-state``,
``blocking-call-under-lock``, and ``swallowed-exception``: the serving
concurrency checker.

The serving engine is a three-thread system — the dispatcher coalesces and
enqueues, the completion thread fetches and completes, and metric scrapes
read from arbitrary threads (Prometheus endpoint, bench loops). The two
failure classes that matter there are classic: two locks taken in opposite
orders on two paths (deadlock under the right interleaving — which closed-
loop serving traffic will eventually find), and an attribute that is
guarded on one path and bare on another (a torn/stale publish under the
GIL's instruction-level interleaving). Both are *cross-function* properties
no unit test reliably catches, so they are checked statically over a small
CFG walk of the configured ``concurrency_paths`` (serving/engine.py,
serving/batcher.py, telemetry/registry.py).

Model (deliberately scoped to this codebase's locking idiom):

* a **lock attribute** is ``self.X`` assigned from
  ``threading.Lock/RLock/Condition/(Bounded)Semaphore`` anywhere in the
  class, or assigned from a parameter named ``lock`` (the registry's shared-
  lock pattern). ``threading.Condition(self.Y)`` ALIASES Y — the engine's
  ``_cv``/``_lock`` pair is one lock, not two;
* an **acquisition** is ``with self.X:`` (the only form these modules use);
* analysis is per class: edges ``held -> acquired`` from nested with-blocks
  plus one level of same-class method calls made while holding a lock; a
  cycle in that graph is a ``lock-order`` finding at each participating
  acquisition site;
* a write (``self.Y = ...``, ``self.Y op= ...``, ``self.Y[...] = ...``, or
  a mutating method call ``self.Y.append/pop/...(...)``) is **guarded** when
  it executes under any ``with self.<lock>``; an attribute with both guarded
  and bare writes outside ``__init__`` gets an ``unlocked-shared-state``
  finding at each bare site — UNLESS the thread-escape analysis
  (``analysis/race/escape.py``) proves the attribute **thread-confined**
  (every access lands in exactly one internal thread root), in which case
  the mixed regime cannot race and no waiver is needed;
* conversely, an attribute the escape analysis proves **escaping** (its
  accesses span two or more thread roots, or it is handed off through a
  queue/future/thread-args payload) whose writes are *never* guarded is
  flagged too — even in a class with no lock anywhere, which the
  lock-relative rule alone cannot see. Writes in lifecycle methods (those
  that call ``.start()`` or ``.join()``) are exempt: the thread start/join
  edge happens-before-orders them;
* ``blocking-call-under-lock``: a call that can block indefinitely —
  ``future.result()``, socket send/recv/accept/connect, ``queue.get/put``
  with no timeout, ``time.sleep``, a thread ``.join()``, an event
  ``.wait()`` — made while holding a ``with self.<lock>`` stalls every
  thread contending for that lock (and under the engine's completion/
  dispatch triangle, stalls the whole tier). Checked directly and one
  level through same-class calls made under a lock.

``swallowed-exception`` adds the third failure class of a callback-driven
serving stack: an ``except`` handler that drops the error on the floor. In
a request/response system every exception is somebody's *outcome* — a
future to error-complete, a typed response to write, a replica to mark
unhealthy — and a handler that does none of that turns a failure into
silence (the lost-future bug class the chaos harness exists to catch). A
handler counts as HANDLING when its body re-raises, returns, breaks or
continues (an explicit control-flow decision), or *uses the bound
exception value* (``except X as e`` with ``e`` flowing into a completion
call, a typed response, or a message). A deliberate best-effort drop
(``sock.shutdown`` on teardown) carries a justified suppression — the
inventory of intentional swallows stays reviewable in the diff.

One shape is exempt without a waiver: ``except OSError`` whose body only
sets a constant flag or passes, inside a function the static leak pass
(``analysis/race/leaks.py``) proves acquisition-free — such a teardown
drop cannot leak a future, span, or pin, so demanding a justification
adds review noise, not safety (the PR-10 suppression inventory re-audit
retired four waivers through exactly this verdict).
``contextlib.suppress(...)`` is the OTHER sanctioned idiom: it cannot
contain logic, so it is intentional by construction (and greppable); the
rule deliberately leaves it alone rather than demanding a second marker.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)
from iwae_replication_project_tpu.analysis.race import escape as _escape
from iwae_replication_project_tpu.analysis.race.leaks import acquisitions_in

#: threading factory callables whose result is a lockable
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}

#: container methods that mutate their receiver in place
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "remove", "clear", "update", "add", "discard", "setdefault",
             "sort", "reverse"}

#: socket methods that block on the peer / the kernel
_SOCKET_BLOCKERS = {"send", "sendall", "recv", "recv_into", "accept",
                    "connect", "sendto", "recvfrom", "makefile"}

#: receiver spellings the queue get/put heuristic treats as queues
def _queueish(recv_name: str) -> bool:
    last = recv_name.rsplit(".", 1)[-1].lower().lstrip("_")
    return "queue" in last or last == "q" or last.endswith("_q")


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (None otherwise)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _in_paths(ctx: FileContext, paths: List[str]) -> bool:
    return any(ctx.rel_path == p or ctx.rel_path.startswith(p.rstrip("/") + "/")
               for p in paths)


def _blocking_what(node: ast.Call, locks: Dict[str, str]) -> Optional[str]:
    """A short description when `node` is a potentially-unbounded blocking
    call, else None. Scoped to the blockers this codebase can actually
    reach: future results, socket I/O, un-timeouted queue ops, sleeps,
    thread joins, and event waits (a Condition's own wait releases the
    lock it is called under, so lock-attr receivers are exempt)."""
    if not isinstance(node.func, ast.Attribute):
        name = Rule.call_name(node)
        return "time.sleep()" if Rule.terminal(name) == "sleep" else None
    meth = node.func.attr
    dotted = Rule.call_name(node)          # '' for non-name receiver chains
    recv = dotted.rsplit(".", 1)[0] if "." in dotted else ""
    kwargs = {kw.arg for kw in node.keywords}
    if meth == "sleep":
        return "time.sleep()"
    if meth == "result":
        return "future .result()"
    if meth in _SOCKET_BLOCKERS:
        return f"socket .{meth}()"
    if meth == "join":
        numeric = (len(node.args) == 1 and
                   isinstance(node.args[0], ast.Constant) and
                   isinstance(node.args[0].value, (int, float)))
        if not node.args and (not kwargs or kwargs == {"timeout"}):
            return "thread .join()"
        if numeric:
            return "thread .join()"
        return None                        # str.join(iterable) etc.
    if meth in ("get", "put") and _queueish(recv):
        if "timeout" in kwargs or len(node.args) >= 2:
            return None
        for kw in node.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return None
        if node.args and meth == "get" and \
                isinstance(node.args[0], ast.Constant) and \
                node.args[0].value is False:
            return None
        return f"queue .{meth}() with no timeout"
    if meth == "wait":
        attr = _self_attr(node.func.value)
        if attr is not None and attr not in locks:
            return "event .wait()"
    return None


def _lock_attrs(cls: ast.ClassDef) -> Dict[str, str]:
    """Lock attribute -> canonical lock name (Condition aliases collapse)."""
    canon: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        attr = _self_attr(node.targets[0])
        if attr is None:
            continue
        for call in [n for n in ast.walk(node.value)
                     if isinstance(n, ast.Call)]:
            name = Rule.terminal(Rule.call_name(call))
            if name in _LOCK_FACTORIES:
                alias = None
                if name == "Condition" and call.args:
                    alias = _self_attr(call.args[0])
                canon[attr] = canon.get(alias, alias) if alias else attr
                break
        else:
            if isinstance(node.value, ast.Name) and \
                    node.value.id.endswith("lock"):
                canon[attr] = attr  # shared-lock injection (registry pattern)
    return canon


class _FuncWalk(ast.NodeVisitor):
    """One function's lock behavior: acquisition edges, per-lock acquisition
    sites, writes (guarded or bare), and same-class calls under a lock."""

    def __init__(self, locks: Dict[str, str]):
        self.locks = locks
        self.held: List[str] = []
        #: (held_lock, acquired_lock, node) for nested acquisitions
        self.edges: List[Tuple[str, str, ast.AST]] = []
        #: canonical lock -> first acquisition node (for reporting)
        self.acquired: Dict[str, ast.AST] = {}
        #: attr -> [(guarded?, node)]
        self.writes: Dict[str, List[Tuple[bool, ast.AST]]] = {}
        #: (held_lock, method_name, call node) for one-level interprocedural
        self.calls_under_lock: List[Tuple[str, str, ast.AST]] = []
        #: (held?, node, what) for potentially-unbounded blocking calls
        self.blocking: List[Tuple[bool, ast.AST, str]] = []
        #: calls .start()/.join(): a thread lifecycle method — its bare
        #: writes are ordered by the start/join happens-before edge
        self.lifecycle = False

    def _record_write(self, attr: str, node: ast.AST) -> None:
        self.writes.setdefault(attr, []).append((bool(self.held), node))

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.locks and item.optional_vars is None:
                lock = self.locks[attr]
                self.acquired.setdefault(lock, item.context_expr)
                for held in self.held:
                    if held != lock:
                        self.edges.append((held, lock, item.context_expr))
                self.held.append(lock)
                entered.append(lock)
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in entered:
            self.held.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            base = tgt.value if isinstance(tgt, ast.Subscript) else tgt
            attr = _self_attr(base)
            if attr is not None and attr not in self.locks:
                self._record_write(attr, tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        base = node.target.value if isinstance(node.target, ast.Subscript) \
            else node.target
        attr = _self_attr(base)
        if attr is not None and attr not in self.locks:
            self._record_write(attr, node.target)
        self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            attr = _self_attr(recv)
            if attr is not None and node.func.attr in _MUTATORS and \
                    attr not in self.locks:
                self._record_write(attr, node)
            if isinstance(recv, ast.Name) and recv.id == "self" and self.held:
                for held in self.held:
                    self.calls_under_lock.append((held, node.func.attr, node))
            if node.func.attr in ("start", "join"):
                self.lifecycle = True
        what = _blocking_what(node, self.locks)
        if what is not None:
            self.blocking.append((bool(self.held), node, what))
        self.generic_visit(node)


def _path(adj: Dict[str, Set[str]], src: str, dst: str
          ) -> Optional[List[str]]:
    """BFS path ``src -> ... -> dst`` through held->acquired edges (None if
    unreachable); the caller prepends the edge that closes the cycle."""
    frontier, parents = [src], {src: None}
    while frontier:
        nxt: List[str] = []
        for n in frontier:
            if n == dst:
                path = []
                while n is not None:
                    path.append(n)
                    n = parents[n]
                return path[::-1]
            for m in adj.get(n, ()):
                if m not in parents:
                    parents[m] = n
                    nxt.append(m)
        frontier = nxt
    return None


def _analyze_class(cls: ast.ClassDef):
    locks = _lock_attrs(cls)
    walks: Dict[str, _FuncWalk] = {}
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w = _FuncWalk(locks)
            for stmt in item.body:
                w.visit(stmt)
            walks[item.name] = w
    return locks, walks


@register
class LockOrderRule(Rule):
    name = "lock-order"
    summary = ("locks acquired in a cyclic order (direct inversion or a "
               "longer cycle) across paths of a concurrency_paths class — "
               "a deadlock under the right thread interleaving")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_paths(ctx, ctx.config.concurrency_paths):
            return
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            locks, walks = _analyze_class(cls)
            if len(set(locks.values())) < 2:
                continue  # one canonical lock cannot invert
            # direct nested-with edges + one level of held-lock method calls
            edges: Dict[Tuple[str, str], ast.AST] = {}
            for w in walks.values():
                for held, got, node in w.edges:
                    edges.setdefault((held, got), node)
                for held, meth, _ in w.calls_under_lock:
                    callee = walks.get(meth)
                    if callee is None:
                        continue
                    for got, node in callee.acquired.items():
                        if got != held:
                            edges.setdefault((held, got), node)
            adj: Dict[str, Set[str]] = {}
            for a, b in edges:
                adj.setdefault(a, set()).add(b)
            for (a, b), node in sorted(edges.items()):
                cycle = _path(adj, b, a)  # edge on a cycle iff b reaches a
                if cycle is None:
                    continue
                if (b, a) in edges:
                    if a < b:  # report each direct inversion once
                        other = edges[(b, a)]
                        yield ctx.finding(
                            self.name, node,
                            f"'{cls.name}' acquires lock '{b}' while "
                            f"holding '{a}' here, but the opposite order at "
                            f"line {getattr(other, 'lineno', '?')} — two "
                            f"threads taking the pair concurrently deadlock;"
                            f" pick one global order")
                else:  # longer cycle: every edge inside it is a hold point
                    chain = " -> ".join([a] + cycle)
                    yield ctx.finding(
                        self.name, node,
                        f"'{cls.name}' acquires lock '{b}' while holding "
                        f"'{a}' here, closing the cyclic lock order "
                        f"{chain} — threads advancing around the cycle "
                        f"concurrently deadlock; pick one global order")


def _teardown_drop(handler: ast.ExceptHandler) -> bool:
    """An ``except OSError`` whose body only passes or sets a constant flag
    (``self._dead = True``) — the best-effort-teardown shape. Exempt from
    ``swallowed-exception`` when the enclosing function is acquisition-free
    per the static leak pass (nothing a dropped error could leak)."""
    if handler.type is None or \
            Rule.terminal(Rule.dotted(handler.type) or "?") != "OSError":
        return False
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.value, ast.Constant) and \
                _self_attr(stmt.targets[0]) is not None:
            continue
        return False
    return True


def _handler_funcs(tree: ast.Module) -> Dict[ast.ExceptHandler, ast.AST]:
    """Each except handler -> its innermost enclosing function."""
    out: Dict[ast.ExceptHandler, ast.AST] = {}
    for func in [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        for node in ast.walk(func):
            if isinstance(node, ast.ExceptHandler):
                out[node] = func        # inner functions visited later win
    return out


def _handler_handles(handler: ast.ExceptHandler) -> bool:
    """Whether the handler's body re-raises, makes an explicit control-flow
    decision (return/continue/break), or uses the bound exception value —
    the three shapes that count as handling (module docstring)."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Return, ast.Continue,
                                 ast.Break)):
                return True
            if handler.name is not None and isinstance(node, ast.Name) \
                    and node.id == handler.name \
                    and isinstance(node.ctx, ast.Load):
                return True
    return False


@register
class SwallowedExceptionRule(Rule):
    name = "swallowed-exception"
    summary = ("except-and-drop in a concurrency_paths file: the handler "
               "neither re-raises, returns/continues/breaks, nor uses the "
               "caught exception — a dropped error is a lost future / "
               "silent failure in a callback-driven serving stack")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_paths(ctx, ctx.config.concurrency_paths):
            return
        funcs = _handler_funcs(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _handler_handles(node):
                continue
            func = funcs.get(node)
            if func is not None and _teardown_drop(node) and \
                    acquisitions_in(func) == 0:
                continue    # leak-pass verdict: this drop cannot leak
            caught = (Rule.dotted(node.type) or "...") \
                if node.type is not None else "BaseException"
            yield ctx.finding(
                self.name, node,
                f"'except {caught}' swallows the error: complete a future "
                f"or typed response with it, re-raise, or make the drop an "
                f"explicit control-flow decision (return/continue/break) — "
                f"a deliberate best-effort drop needs a justified "
                f"suppression")


@register
class UnlockedSharedStateRule(Rule):
    name = "unlocked-shared-state"
    summary = ("attribute written bare in a concurrency_paths class where "
               "it can race: mixed guarded/bare writes, or never-guarded "
               "writes to state the escape analysis proves crosses a "
               "thread boundary")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_paths(ctx, ctx.config.concurrency_paths):
            return
        init_names = ("__init__", "__post_init__")
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            locks, walks = _analyze_class(cls)
            esc = _escape.classify_class(cls, skip_attrs=set(locks))
            guarded: Set[str] = set()
            for name, w in walks.items():
                if name in init_names:
                    continue
                for attr, sites in w.writes.items():
                    if any(g for g, _ in sites):
                        guarded.add(attr)
            for name, w in walks.items():
                if name in init_names:
                    continue
                for attr, sites in w.writes.items():
                    # mixed regime: guarded elsewhere, bare here — unless
                    # the attribute never leaves one internal thread
                    if attr in guarded:
                        if esc.confined(attr):
                            continue
                        for g, node in sites:
                            if not g:
                                yield ctx.finding(
                                    self.name, node,
                                    f"'{cls.name}.{attr}' is written under "
                                    f"a lock elsewhere but bare in '{name}'"
                                    f" — either every write holds the lock "
                                    f"or none does; a mixed regime "
                                    f"publishes torn/stale state to the "
                                    f"guarded threads")
                        continue
                    # never guarded anywhere: flag only when the escape
                    # analysis proves the attribute crosses a thread
                    # boundary (lifecycle methods are start/join-ordered)
                    if w.lifecycle or not esc.escaping(attr):
                        continue
                    roots = ", ".join(sorted(esc.roots_of(attr)))
                    for g, node in sites:
                        yield ctx.finding(
                            self.name, node,
                            f"'{cls.name}.{attr}' is written in '{name}' "
                            f"with no lock held anywhere, but escapes to "
                            f"multiple thread roots ({roots}) — a bare "
                            f"write to thread-escaping state races every "
                            f"other root; guard it or confine it to one "
                            f"thread")


@register
class BlockingCallUnderLockRule(Rule):
    name = "blocking-call-under-lock"
    summary = ("a potentially-unbounded blocking call (future .result(), "
               "socket I/O, un-timeouted queue get/put, time.sleep, thread "
               ".join, event .wait) made while holding a lock in a "
               "concurrency_paths class — every thread contending for that "
               "lock stalls behind the peer/kernel/scheduler")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_paths(ctx, ctx.config.concurrency_paths):
            return
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            locks, walks = _analyze_class(cls)
            if not locks:
                continue
            for name, w in walks.items():
                for held, node, what in w.blocking:
                    if held:
                        yield ctx.finding(
                            self.name, node,
                            f"'{cls.name}.{name}' makes a blocking {what} "
                            f"while holding a lock — move the blocking op "
                            f"outside the critical section (snapshot under "
                            f"the lock, block outside), or bound it with a "
                            f"timeout")
                # one level interprocedural: a held-lock call into a method
                # that blocks (in its own unheld context) blocks here too
                for held, meth, node in w.calls_under_lock:
                    callee = walks.get(meth)
                    if callee is None:
                        continue
                    for c_held, _, what in callee.blocking:
                        if not c_held:
                            yield ctx.finding(
                                self.name, node,
                                f"'{cls.name}.{name}' calls '{meth}' while "
                                f"holding lock '{held}', and '{meth}' makes "
                                f"a blocking {what} — the lock is held "
                                f"across the block; move the call outside "
                                f"the critical section or bound it with a "
                                f"timeout")
                            break
