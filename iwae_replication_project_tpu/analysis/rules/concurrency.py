"""Rules ``lock-order``, ``unlocked-shared-state``, and
``swallowed-exception``: the serving concurrency checker.

The serving engine is a three-thread system — the dispatcher coalesces and
enqueues, the completion thread fetches and completes, and metric scrapes
read from arbitrary threads (Prometheus endpoint, bench loops). The two
failure classes that matter there are classic: two locks taken in opposite
orders on two paths (deadlock under the right interleaving — which closed-
loop serving traffic will eventually find), and an attribute that is
guarded on one path and bare on another (a torn/stale publish under the
GIL's instruction-level interleaving). Both are *cross-function* properties
no unit test reliably catches, so they are checked statically over a small
CFG walk of the configured ``concurrency_paths`` (serving/engine.py,
serving/batcher.py, telemetry/registry.py).

Model (deliberately scoped to this codebase's locking idiom):

* a **lock attribute** is ``self.X`` assigned from
  ``threading.Lock/RLock/Condition/(Bounded)Semaphore`` anywhere in the
  class, or assigned from a parameter named ``lock`` (the registry's shared-
  lock pattern). ``threading.Condition(self.Y)`` ALIASES Y — the engine's
  ``_cv``/``_lock`` pair is one lock, not two;
* an **acquisition** is ``with self.X:`` (the only form these modules use);
* analysis is per class: edges ``held -> acquired`` from nested with-blocks
  plus one level of same-class method calls made while holding a lock; a
  cycle in that graph is a ``lock-order`` finding at each participating
  acquisition site;
* a write (``self.Y = ...``, ``self.Y op= ...``, ``self.Y[...] = ...``, or
  a mutating method call ``self.Y.append/pop/...(...)``) is **guarded** when
  it executes under any ``with self.<lock>``; an attribute with both guarded
  and bare writes outside ``__init__`` gets an ``unlocked-shared-state``
  finding at each bare site.

``swallowed-exception`` adds the third failure class of a callback-driven
serving stack: an ``except`` handler that drops the error on the floor. In
a request/response system every exception is somebody's *outcome* — a
future to error-complete, a typed response to write, a replica to mark
unhealthy — and a handler that does none of that turns a failure into
silence (the lost-future bug class the chaos harness exists to catch). A
handler counts as HANDLING when its body re-raises, returns, breaks or
continues (an explicit control-flow decision), or *uses the bound
exception value* (``except X as e`` with ``e`` flowing into a completion
call, a typed response, or a message). A deliberate best-effort drop
(``sock.shutdown`` on teardown) carries a justified suppression — the
inventory of intentional swallows stays reviewable in the diff.
``contextlib.suppress(...)`` is the OTHER sanctioned idiom: it cannot
contain logic, so it is intentional by construction (and greppable); the
rule deliberately leaves it alone rather than demanding a second marker.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)

#: threading factory callables whose result is a lockable
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}

#: container methods that mutate their receiver in place
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "remove", "clear", "update", "add", "discard", "setdefault",
             "sort", "reverse"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (None otherwise)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _in_paths(ctx: FileContext, paths: List[str]) -> bool:
    return any(ctx.rel_path == p or ctx.rel_path.startswith(p.rstrip("/") + "/")
               for p in paths)


def _lock_attrs(cls: ast.ClassDef) -> Dict[str, str]:
    """Lock attribute -> canonical lock name (Condition aliases collapse)."""
    canon: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        attr = _self_attr(node.targets[0])
        if attr is None:
            continue
        for call in [n for n in ast.walk(node.value)
                     if isinstance(n, ast.Call)]:
            name = Rule.terminal(Rule.call_name(call))
            if name in _LOCK_FACTORIES:
                alias = None
                if name == "Condition" and call.args:
                    alias = _self_attr(call.args[0])
                canon[attr] = canon.get(alias, alias) if alias else attr
                break
        else:
            if isinstance(node.value, ast.Name) and \
                    node.value.id.endswith("lock"):
                canon[attr] = attr  # shared-lock injection (registry pattern)
    return canon


class _FuncWalk(ast.NodeVisitor):
    """One function's lock behavior: acquisition edges, per-lock acquisition
    sites, writes (guarded or bare), and same-class calls under a lock."""

    def __init__(self, locks: Dict[str, str]):
        self.locks = locks
        self.held: List[str] = []
        #: (held_lock, acquired_lock, node) for nested acquisitions
        self.edges: List[Tuple[str, str, ast.AST]] = []
        #: canonical lock -> first acquisition node (for reporting)
        self.acquired: Dict[str, ast.AST] = {}
        #: attr -> [(guarded?, node)]
        self.writes: Dict[str, List[Tuple[bool, ast.AST]]] = {}
        #: (held_lock, method_name) calls for one-level interprocedural edges
        self.calls_under_lock: List[Tuple[str, str]] = []

    def _record_write(self, attr: str, node: ast.AST) -> None:
        self.writes.setdefault(attr, []).append((bool(self.held), node))

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.locks and item.optional_vars is None:
                lock = self.locks[attr]
                self.acquired.setdefault(lock, item.context_expr)
                for held in self.held:
                    if held != lock:
                        self.edges.append((held, lock, item.context_expr))
                self.held.append(lock)
                entered.append(lock)
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in entered:
            self.held.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            base = tgt.value if isinstance(tgt, ast.Subscript) else tgt
            attr = _self_attr(base)
            if attr is not None and attr not in self.locks:
                self._record_write(attr, tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        base = node.target.value if isinstance(node.target, ast.Subscript) \
            else node.target
        attr = _self_attr(base)
        if attr is not None and attr not in self.locks:
            self._record_write(attr, node.target)
        self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            attr = _self_attr(recv)
            if attr is not None and node.func.attr in _MUTATORS and \
                    attr not in self.locks:
                self._record_write(attr, node)
            if isinstance(recv, ast.Name) and recv.id == "self" and self.held:
                for held in self.held:
                    self.calls_under_lock.append((held, node.func.attr))
        self.generic_visit(node)


def _path(adj: Dict[str, Set[str]], src: str, dst: str
          ) -> Optional[List[str]]:
    """BFS path ``src -> ... -> dst`` through held->acquired edges (None if
    unreachable); the caller prepends the edge that closes the cycle."""
    frontier, parents = [src], {src: None}
    while frontier:
        nxt: List[str] = []
        for n in frontier:
            if n == dst:
                path = []
                while n is not None:
                    path.append(n)
                    n = parents[n]
                return path[::-1]
            for m in adj.get(n, ()):
                if m not in parents:
                    parents[m] = n
                    nxt.append(m)
        frontier = nxt
    return None


def _analyze_class(cls: ast.ClassDef):
    locks = _lock_attrs(cls)
    walks: Dict[str, _FuncWalk] = {}
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w = _FuncWalk(locks)
            for stmt in item.body:
                w.visit(stmt)
            walks[item.name] = w
    return locks, walks


@register
class LockOrderRule(Rule):
    name = "lock-order"
    summary = ("locks acquired in a cyclic order (direct inversion or a "
               "longer cycle) across paths of a concurrency_paths class — "
               "a deadlock under the right thread interleaving")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_paths(ctx, ctx.config.concurrency_paths):
            return
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            locks, walks = _analyze_class(cls)
            if len(set(locks.values())) < 2:
                continue  # one canonical lock cannot invert
            # direct nested-with edges + one level of held-lock method calls
            edges: Dict[Tuple[str, str], ast.AST] = {}
            for w in walks.values():
                for held, got, node in w.edges:
                    edges.setdefault((held, got), node)
                for held, meth in w.calls_under_lock:
                    callee = walks.get(meth)
                    if callee is None:
                        continue
                    for got, node in callee.acquired.items():
                        if got != held:
                            edges.setdefault((held, got), node)
            adj: Dict[str, Set[str]] = {}
            for a, b in edges:
                adj.setdefault(a, set()).add(b)
            for (a, b), node in sorted(edges.items()):
                cycle = _path(adj, b, a)  # edge on a cycle iff b reaches a
                if cycle is None:
                    continue
                if (b, a) in edges:
                    if a < b:  # report each direct inversion once
                        other = edges[(b, a)]
                        yield ctx.finding(
                            self.name, node,
                            f"'{cls.name}' acquires lock '{b}' while "
                            f"holding '{a}' here, but the opposite order at "
                            f"line {getattr(other, 'lineno', '?')} — two "
                            f"threads taking the pair concurrently deadlock;"
                            f" pick one global order")
                else:  # longer cycle: every edge inside it is a hold point
                    chain = " -> ".join([a] + cycle)
                    yield ctx.finding(
                        self.name, node,
                        f"'{cls.name}' acquires lock '{b}' while holding "
                        f"'{a}' here, closing the cyclic lock order "
                        f"{chain} — threads advancing around the cycle "
                        f"concurrently deadlock; pick one global order")


def _handler_handles(handler: ast.ExceptHandler) -> bool:
    """Whether the handler's body re-raises, makes an explicit control-flow
    decision (return/continue/break), or uses the bound exception value —
    the three shapes that count as handling (module docstring)."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Return, ast.Continue,
                                 ast.Break)):
                return True
            if handler.name is not None and isinstance(node, ast.Name) \
                    and node.id == handler.name \
                    and isinstance(node.ctx, ast.Load):
                return True
    return False


@register
class SwallowedExceptionRule(Rule):
    name = "swallowed-exception"
    summary = ("except-and-drop in a concurrency_paths file: the handler "
               "neither re-raises, returns/continues/breaks, nor uses the "
               "caught exception — a dropped error is a lost future / "
               "silent failure in a callback-driven serving stack")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_paths(ctx, ctx.config.concurrency_paths):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _handler_handles(node):
                continue
            caught = (Rule.dotted(node.type) or "...") \
                if node.type is not None else "BaseException"
            yield ctx.finding(
                self.name, node,
                f"'except {caught}' swallows the error: complete a future "
                f"or typed response with it, re-raise, or make the drop an "
                f"explicit control-flow decision (return/continue/break) — "
                f"a deliberate best-effort drop needs a justified "
                f"suppression")


@register
class UnlockedSharedStateRule(Rule):
    name = "unlocked-shared-state"
    summary = ("attribute written both under a lock and bare in a "
               "concurrency_paths class — the bare write races the guarded "
               "readers/writers")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _in_paths(ctx, ctx.config.concurrency_paths):
            return
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            locks, walks = _analyze_class(cls)
            if not locks:
                continue  # lock-free classes are synchronized by their owner
            guarded: Set[str] = set()
            for name, w in walks.items():
                if name in ("__init__", "__post_init__"):
                    continue
                for attr, sites in w.writes.items():
                    if any(g for g, _ in sites):
                        guarded.add(attr)
            for name, w in walks.items():
                if name in ("__init__", "__post_init__"):
                    continue
                for attr, sites in w.writes.items():
                    if attr not in guarded:
                        continue
                    for g, node in sites:
                        if not g:
                            yield ctx.finding(
                                self.name, node,
                                f"'{cls.name}.{attr}' is written under a "
                                f"lock elsewhere but bare in '{name}' — "
                                f"either every write holds the lock or none "
                                f"does; a mixed regime publishes torn/stale "
                                f"state to the guarded threads")
