"""Rule ``dtype-promotion``: float64 / x64 hazards against the production
numerics.

The framework runs with x64 disabled and ``compute_dtype="bfloat16"`` as the
licensed production default (RESULTS.md round-5 convergence study). Any
``float64`` reference is therefore one of two bugs waiting: under default
config jax silently *downcasts* to f32 (so the annotation lies), and if
anything flips ``jax_enable_x64`` the promotion rules drag whole expressions
to f64 — 4x the bytes of bf16 through the MXU-free VPU path. Likewise
``dtype=float`` means f64 to numpy and "weak f32" to jax: whichever the
author meant, one reader is wrong.
"""

from __future__ import annotations

import ast
from typing import Iterator

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)

_F64_ATTRS = {"float64", "double", "complex128"}


@register
class DtypePromotionRule(Rule):
    name = "dtype-promotion"
    summary = ("float64/x64 dtype reference in production code — the "
               "framework's numerics are bf16/f32 with x64 disabled")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and node.attr in _F64_ATTRS:
                base = Rule.dotted(node.value)
                if base.split(".")[0] in ("np", "numpy", "jnp", "jax", "onp"):
                    yield ctx.finding(
                        self.name, node,
                        f"'{base}.{node.attr}' under x64-disabled production "
                        f"numerics: jax silently downcasts it to f32, and "
                        f"with x64 on it quadruples bf16 memory traffic")
            elif isinstance(node, ast.Call):
                name = Rule.call_name(node)
                if Rule.terminal(name) == "update" and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        node.args[0].value == "jax_enable_x64":
                    yield ctx.finding(
                        self.name, node,
                        "enabling x64 flips global promotion semantics for "
                        "every module in the process — production code must "
                        "not toggle it")
                for kw in node.keywords:
                    if kw.arg != "dtype":
                        continue
                    if isinstance(kw.value, ast.Constant) and \
                            kw.value.value in ("float64", "double",
                                               "complex128"):
                        yield ctx.finding(
                            self.name, kw.value,
                            f"dtype={kw.value.value!r} — f64 under "
                            f"x64-disabled numerics")
                    elif isinstance(kw.value, ast.Name) and \
                            kw.value.id == "float":
                        yield ctx.finding(
                            self.name, kw.value,
                            "dtype=float is f64 to numpy but weak-f32 to "
                            "jax — spell the intended dtype explicitly")
