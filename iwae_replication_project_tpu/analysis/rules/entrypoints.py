"""Rule ``cache-setup``: warm-path discipline for executable entry points.

Two obligations, both previously enforced by ad-hoc string greps in
``tests/test_compile_cache.py`` (migrated here so the check has ONE
implementation and the test suite asserts against the framework):

1. every configured entry point (``[tool.iwaelint] entry_points``) must call
   ``setup_persistent_cache`` — an entry point that skips it silently re-pays
   the ~90 s of recompiles the warm-path engine exists to eliminate, and a
   preemption-resume loses its whole point;
2. nobody but the owner module(s) (``cache_owners``, default
   ``utils/compile_cache.py``) may touch ``jax_compilation_cache_dir``
   directly — split-brain cache config is how the donation-corruption class
   of RESULTS.md §5 re-enters.
"""

from __future__ import annotations

import ast
from typing import Iterator

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)


@register
class CacheSetupRule(Rule):
    name = "cache-setup"
    summary = ("entry point missing setup_persistent_cache(), or "
               "jax_compilation_cache_dir configured outside "
               "utils/compile_cache.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        is_owner = ctx.rel_path in ctx.config.cache_owners
        if ctx.rel_path in ctx.config.entry_points:
            called = any(
                isinstance(node, ast.Call) and
                Rule.terminal(Rule.call_name(node)) == "setup_persistent_cache"
                for node in ast.walk(ctx.tree))
            if not called:
                yield Finding(
                    path=ctx.rel_path, line=1, col=0, rule=self.name,
                    message="entry point never calls setup_persistent_cache()"
                            " — cold starts re-pay every XLA compile (wire it"
                            " through utils/compile_cache.py)")
        if is_owner:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    Rule.terminal(Rule.call_name(node)) == "update" and \
                    node.args and isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value in ("jax_compilation_cache_dir",
                                           "jax_persistent_cache_min_compile_time_secs",
                                           "jax_persistent_cache_min_entry_size_bytes"):
                yield ctx.finding(
                    self.name, node,
                    f"hand-rolled persistent-cache config "
                    f"('{node.args[0].value}') — utils/compile_cache.py is "
                    f"the single owner of the cache wiring")
