"""Rule ``host-sync``: implicit host synchronization in hot-path modules.

``np.asarray`` / ``.item()`` / ``float()`` / ``int()`` on a traced or device
value forces a device→host transfer and a pipeline flush. In driver code
that's a deliberate fetch; inside the per-step / per-dispatch modules
(``training/``, ``parallel/``, ``ops/`` — the config's ``hot_paths``) it
serializes the async dispatch queue the whole warm-path design leans on
(experiment.py dispatches whole PASS_BLOCK=27-epoch programs precisely to
amortize the tunnel). The runtime twin of this rule is the pytest
``--sanitize`` mode (tests/conftest.py), which runs marked tests under
``jax.transfer_guard("disallow")``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)

#: numpy-namespace callables that realize device values on host
_NUMPY_SYNCS = {"asarray", "array"}
_NUMPY_MODULES = {"np", "numpy", "onp"}


@register
class HostSyncRule(Rule):
    name = "host-sync"
    summary = ("implicit device->host sync (np.asarray/.item()/float()/"
               "jax.device_get) inside a hot-path module")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not any(ctx.rel_path.startswith(hp.rstrip("/") + "/")
                   or ctx.rel_path == hp for hp in ctx.config.hot_paths):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = Rule.call_name(node)
            parts = name.split(".")
            if len(parts) == 2 and parts[0] in _NUMPY_MODULES \
                    and parts[1] in _NUMPY_SYNCS:
                yield ctx.finding(
                    self.name, node,
                    f"'{name}' in a hot-path module forces a host transfer "
                    f"and drains the dispatch pipeline — keep data on device "
                    f"(jnp) or move the fetch to the driver layer")
            elif name == "jax.device_get":
                yield ctx.finding(
                    self.name, node,
                    "'jax.device_get' in a hot-path module — move the fetch "
                    "to the driver layer")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                yield ctx.finding(
                    self.name, node,
                    "'.item()' blocks on the device and transfers — hot "
                    "paths must stay async")
            elif name in ("float", "int", "bool") and node.args and \
                    isinstance(node.args[0], ast.Call) and \
                    Rule.call_name(node.args[0]).split(".")[0] in ("jnp",
                                                                   "jax"):
                # float(jnp.mean(x)) etc. — scalarizing a device computation
                # is the implicit-sync shape; float(n)/int(env) on python
                # values is not, so only jnp/jax call results are flagged
                yield ctx.finding(
                    self.name, node,
                    f"'{name}(...)' on a jax computation blocks and "
                    f"transfers — keep it a device array (or fetch in the "
                    f"driver layer)")
