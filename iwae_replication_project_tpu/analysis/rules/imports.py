"""Rule ``fragile-import``: version-sensitive jax imports outside the shim.

``from jax import shard_map`` worked on one jax release and broke six test
collections on 0.4.37 (PR 1); the fix was the version-portable shim in
``parallel/mesh.py`` that translates the ``check_rep``/``check_vma`` rename
too. This rule makes the shim load-bearing: any direct import of a module on
the ``fragile_imports`` list outside the configured ``import_shims`` files is
flagged, so the next version-fragile import can't creep back in.
"""

from __future__ import annotations

import ast
from typing import Iterator

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)


@register
class FragileImportRule(Rule):
    name = "fragile-import"
    summary = ("direct import of a version-fragile jax module (e.g. "
               "shard_map) — route through parallel/mesh.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.rel_path in ctx.config.import_shims:
            return
        fragile = set(ctx.config.fragile_imports)
        #: `from jax import X` forms covered by dotted entries ("jax.X")
        from_jax = {m.split(".", 1)[1] for m in fragile
                    if m.startswith("jax.") and m.count(".") == 1}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    hit = next((m for m in fragile
                                if alias.name == m
                                or alias.name.startswith(m + ".")), None)
                    if hit:
                        yield ctx.finding(
                            self.name, node,
                            f"'import {alias.name}' is version-fragile — "
                            f"use the shim in parallel/mesh.py")
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.module in fragile or any(
                        node.module.startswith(m + ".") for m in fragile):
                    yield ctx.finding(
                        self.name, node,
                        f"'from {node.module} import ...' is version-fragile"
                        f" — use the shim in parallel/mesh.py")
                elif node.module == "jax":
                    for alias in node.names:
                        if alias.name in from_jax:
                            yield ctx.finding(
                                self.name, node,
                                f"'from jax import {alias.name}' moved "
                                f"across jax releases (broke the seed on "
                                f"0.4.37) — use the shim in parallel/mesh.py")
