"""Compilation-discipline rules: donation, per-call compiles, static args.

* ``donated-after-call`` — a buffer donated to a jitted call is dead the
  moment the call is issued; reading it afterwards returns whatever the
  backend left in that memory (RESULTS.md §5 documents the XLA:CPU
  cache-deserialization variant of this corrupting real runs). JAX only
  *warns*, and only sometimes.
* ``jit-in-loop`` — ``jax.jit`` / ``jax.pmap`` / ``.lower().compile()``
  executed inside a loop builds a fresh program (and usually a fresh trace
  cache entry) per iteration: the warm-path engine (utils/compile_cache.py)
  exists precisely so programs are built once and dispatched many times.
* ``nonhashable-static`` — a list/dict/set passed at a ``static_argnums`` /
  ``static_argnames`` position raises ``ValueError: Non-hashable static
  arguments`` only at call time, typically deep inside a driver; the call
  site is statically visible.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pmap", "pmap"}


def _literal_int_positions(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """``0`` / ``(0, 2)`` / ``[1]`` -> positions; None when not literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int) \
                    and not isinstance(elt.value, bool):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _literal_str_names(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _jit_call_info(call: ast.Call) -> Optional[Dict]:
    """For a ``jax.jit(...)`` (or functools.partial(jax.jit, ...)) call,
    the donate/static keyword structure; None for other calls."""
    name = Rule.call_name(call)
    inner = None
    if Rule.terminal(name) == "partial" and call.args:
        inner_name = Rule.dotted(call.args[0])
        if inner_name in _JIT_NAMES:
            inner = inner_name
    if name not in _JIT_NAMES and inner is None:
        return None
    info: Dict = {"donate": None, "static_nums": None, "static_names": None}
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            info["donate"] = _literal_int_positions(kw.value) \
                if kw.arg == "donate_argnums" else ()
            if info["donate"] is None:
                info["donate"] = ()  # non-literal: donation exists, pos unknown
        elif kw.arg == "static_argnums":
            info["static_nums"] = _literal_int_positions(kw.value)
        elif kw.arg == "static_argnames":
            info["static_names"] = _literal_str_names(kw.value)
    return info


_NONHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                ast.SetComp, ast.GeneratorExp)


def _scopes(tree: ast.Module) -> Iterator[List[ast.stmt]]:
    yield tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body


def _walk_scope(body: List[ast.stmt]) -> Iterator[ast.stmt]:
    """Statements of one scope in source order, descending into compound
    statements but not into nested function/class scopes."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for field in ("body", "orelse", "finalbody"):
            yield from _walk_scope(getattr(stmt, field, []) or [])
        for handler in getattr(stmt, "handlers", []) or []:
            yield from _walk_scope(handler.body)


def _stmt_expr_roots(stmt: ast.stmt) -> List[ast.AST]:
    """The expression roots a statement evaluates AT its own position —
    compound statements contribute their headers only (their blocks are
    yielded separately by :func:`_walk_scope`), nested defs contribute
    nothing (their bodies are separate scopes)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return list(stmt.decorator_list)
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, ast.While) or isinstance(stmt, ast.If):
        return [stmt.test]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _shallow_walk(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested scopes (defs, lambdas)."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield from _shallow_walk(child)


def _stmt_nodes(stmt: ast.stmt) -> List[ast.AST]:
    out: List[ast.AST] = []
    for root in _stmt_expr_roots(stmt):
        out.extend(_shallow_walk(root))
    return out


@register
class DonatedAfterCallRule(Rule):
    name = "donated-after-call"
    summary = ("argument donated to a jitted call is read again afterwards — "
               "its buffer now holds backend garbage")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # pass 1 (module-wide): names bound to donating jitted callables
        donating: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                info = _jit_call_info(node.value)
                if info and info["donate"] is not None:
                    positions = info["donate"] or (0,)
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            donating[tgt.id] = positions
        if not donating:
            return
        # pass 2 (per scope, source order): donate -> dead until re-bound
        for body in _scopes(ctx.tree):
            dead: Dict[str, str] = {}  # var -> donating callee name
            for stmt in _walk_scope(body):
                nodes = _stmt_nodes(stmt)
                for call in [n for n in nodes if isinstance(n, ast.Call)]:
                    fname = Rule.call_name(call)
                    if fname not in donating:
                        continue
                    for pos in donating[fname]:
                        if pos < len(call.args) and \
                                isinstance(call.args[pos], ast.Name):
                            dead[call.args[pos].id] = fname
                # reads of dead vars (the donating call's own args were
                # consumed above before the var was marked, same statement)
                for name_node in [n for n in nodes
                                  if isinstance(n, ast.Name)
                                  and isinstance(n.ctx, ast.Load)]:
                    if name_node.id in dead:
                        # the donating call itself loads the var legally
                        if any(isinstance(c, ast.Call)
                               and Rule.call_name(c) == dead[name_node.id]
                               and name_node in ast.walk(c)
                               for c in nodes):
                            continue
                        yield ctx.finding(
                            self.name, name_node,
                            f"'{name_node.id}' was donated to "
                            f"'{dead[name_node.id]}' and read again before "
                            f"re-binding — donated buffers are invalidated "
                            f"by the call")
                # re-bindings revive
                targets: List[ast.AST] = []
                if isinstance(stmt, ast.Assign):
                    targets = list(stmt.targets)
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    targets = [stmt.target]
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    targets = [stmt.target]
                for tgt in targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            dead.pop(n.id, None)


@register
class JitInLoopRule(Rule):
    name = "jit-in-loop"
    summary = ("jax.jit/pmap or lower().compile() inside a loop — compiles "
               "per iteration instead of once (route through the AOT "
               "registry in utils/compile_cache.py)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._visit(ctx, ctx.tree, loop_depth=0)

    def _visit(self, ctx: FileContext, node: ast.AST,
               loop_depth: int) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # decorators evaluate at def time — a def inside a loop
                # re-runs its jit decorators every iteration
                if loop_depth:
                    for dec in child.decorator_list:
                        d = dec.func if isinstance(dec, ast.Call) else dec
                        if Rule.dotted(d) in _JIT_NAMES or (
                                isinstance(dec, ast.Call)
                                and _jit_call_info(dec) is not None):
                            yield ctx.finding(
                                self.name, dec,
                                "jit-decorated def inside a loop re-traces "
                                "and re-compiles every iteration")
                # body is a new call-time scope: loop depth resets
                yield from self._visit(ctx, child, loop_depth=0)
                continue
            child_depth = loop_depth + (
                1 if isinstance(child, (ast.For, ast.AsyncFor, ast.While))
                else 0)
            if loop_depth and isinstance(child, ast.Call):
                name = Rule.call_name(child)
                if name in _JIT_NAMES:
                    yield ctx.finding(
                        self.name, child,
                        f"'{name}' called inside a loop — the program is "
                        f"re-built every iteration")
                elif isinstance(child.func, ast.Attribute) \
                        and child.func.attr == "compile" \
                        and isinstance(child.func.value, ast.Call) \
                        and isinstance(child.func.value.func, ast.Attribute) \
                        and child.func.value.func.attr == "lower":
                    yield ctx.finding(
                        self.name, child,
                        ".lower().compile() inside a loop — AOT-compile once "
                        "outside (or use utils/compile_cache.aot_call, which "
                        "caches by signature)")
            yield from self._visit(ctx, child, child_depth)


@register
class NonHashableStaticRule(Rule):
    name = "nonhashable-static"
    summary = ("list/dict/set passed at a static_argnums/static_argnames "
               "position of a jitted function — raises at call time")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        jitted: Dict[str, Dict] = {}
        for node in ast.walk(ctx.tree):
            info = None
            fn_name = None
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                info = _jit_call_info(node.value)
                if info and isinstance(node.targets[0], ast.Name):
                    fn_name = node.targets[0].id
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        dec_info = _jit_call_info(dec)
                        if dec_info is not None:
                            info, fn_name = dec_info, node.name
            if info is None or fn_name is None:
                continue
            if info["static_nums"] or info["static_names"]:
                jitted[fn_name] = info
            # non-literal static_argnums is un-analyzable but legal; skip
        if not jitted:
            return
        for call in [n for n in ast.walk(ctx.tree) if isinstance(n, ast.Call)]:
            fname = Rule.call_name(call)
            if fname not in jitted:
                continue
            info = jitted[fname]
            for pos in info["static_nums"] or ():
                if pos < len(call.args) and \
                        isinstance(call.args[pos], _NONHASHABLE):
                    yield ctx.finding(
                        self.name, call.args[pos],
                        f"non-hashable literal at static position {pos} of "
                        f"'{fname}' — jit static args must be hashable "
                        f"(use a tuple / frozen dataclass)")
            for kw in call.keywords:
                if kw.arg in (info["static_names"] or ()) and \
                        isinstance(kw.value, _NONHASHABLE):
                    yield ctx.finding(
                        self.name, kw.value,
                        f"non-hashable literal for static argument "
                        f"'{kw.arg}' of '{fname}' — jit static args must "
                        f"be hashable (use a tuple / frozen dataclass)")
