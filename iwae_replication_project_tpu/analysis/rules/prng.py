"""Rule ``key-reuse``: non-linear PRNG key threading.

A JAX PRNG key is a *linear* resource: every consumer must get a fresh key via
``jax.random.split`` / ``fold_in``. Passing the same key to two samplers does
not error — it silently makes their draws identical, which for the IWAE bound
means the K importance samples are correlated and the logmeanexp is a biased
estimate of nothing in the paper (Burda et al., arXiv:1509.00519 — K
*independent* samples is the whole point). This is the canonical
trains-fine-wrong-answer JAX bug, hence a lint rule rather than a code-review
convention.

Detection (per function scope, statement order, no cross-function dataflow):

* a variable is *key-like* if it is assigned from ``jax.random.PRNGKey`` /
  ``split`` / ``fold_in`` / ``key`` / ``clone``, or its name looks like a key
  (``key`` / ``rng`` / ``*_key`` / ``*_rng`` / ``subkey``); arrays of keys
  (``keys[i]``) are not tracked — subscripted uses are distinct keys;
* a *consumer* use is the bare variable appearing as a call argument, except
  in the linearization calls themselves (``split`` / ``fold_in`` — deriving
  is not consuming) and key plumbing (``PRNGKey``, ``key_data``, ``clone``);
* two consumer uses with no intervening re-binding of the variable flag the
  second use. Loop bodies are walked twice (a second iteration re-uses
  whatever the body did not re-bind); ``if``/``elif``/``else`` branches are
  walked with forked counters merged by max (branches are alternatives, not
  sequences). ``try`` bodies/handlers are treated like branches.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from iwae_replication_project_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    register,
)

#: name shapes treated as PRNG keys even without a visible jax.random binding
_KEY_NAME = re.compile(r"^(sub_?key|key|rng|prng_?key)$|(_key|_rng)$")

#: callees that *derive or construct* keys — an argument position here is the
#: linear-threading idiom itself, not a consumption
_NON_CONSUMING = {"split", "fold_in", "PRNGKey", "key", "key_data",
                  "wrap_key_data", "clone", "key_impl"}


def _assigned_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(_assigned_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return _assigned_names(target.value)
    return []


def _is_key_rhs(value: ast.AST) -> bool:
    """Does this assigned value produce PRNG key(s)?"""
    if isinstance(value, ast.Call):
        term = Rule.terminal(Rule.call_name(value))
        return term in ("PRNGKey", "split", "fold_in", "key", "clone")
    if isinstance(value, (ast.Tuple, ast.List)):
        return any(_is_key_rhs(v) for v in value.elts)
    return False


class _ScopeLinter:
    """Statement-ordered walk of one function (or module) body, tracking
    consumer-use counts per key variable between re-bindings."""

    def __init__(self, ctx: FileContext, rule_name: str):
        self.ctx = ctx
        self.rule_name = rule_name
        self.counts: Dict[str, int] = {}      # uses since last (re)bind
        self.tracked: Set[str] = set()        # known key-like variables
        self.untracked: Set[str] = set()      # key-ish NAMES bound to non-keys
        self.findings: List[Finding] = []

    # -- state forks for branches ------------------------------------------

    def _snapshot(self) -> Tuple[Dict[str, int], Set[str], Set[str]]:
        return dict(self.counts), set(self.tracked), set(self.untracked)

    def _restore(self, snap: Tuple[Dict[str, int], Set[str], Set[str]]) -> None:
        self.counts, self.tracked, self.untracked = \
            dict(snap[0]), set(snap[1]), set(snap[2])

    def _merge_max(self, states: List[Tuple[Dict[str, int], Set[str],
                                            Set[str]]]) -> None:
        counts: Dict[str, int] = {}
        tracked: Set[str] = set()
        untracked: Set[str] = set()
        for c, t, u in states:
            tracked |= t
            untracked |= u
            for name, n in c.items():
                counts[name] = max(counts.get(name, 0), n)
        self.counts, self.tracked, self.untracked = counts, tracked, untracked

    @staticmethod
    def _terminates(body: List[ast.stmt]) -> bool:
        """A branch ending in return/raise/break/continue never falls
        through — its consumption state must not merge into the after-branch
        state (``if a: return f(key)`` + a later ``g(key)`` is one consumer
        per path, not two)."""
        return bool(body) and isinstance(
            body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))

    # -- the walk ----------------------------------------------------------

    def run(self, body: List[ast.stmt]) -> List[Finding]:
        for stmt in body:
            self._stmt(stmt)
        return self.findings

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes are linted separately
        if isinstance(stmt, ast.If):
            base = self._snapshot()
            self._block(stmt.body)
            after_true = base if self._terminates(stmt.body) \
                else self._snapshot()
            self._restore(base)
            self._block(stmt.orelse)
            after_false = base if self._terminates(stmt.orelse) \
                else self._snapshot()
            self._merge_max([after_true, after_false])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._uses_in(stmt.iter)
                for n in _assigned_names(stmt.target):
                    self._bind(n, key_like=_is_key_rhs(stmt.iter))
            else:
                self._uses_in(stmt.test)
            # two passes ≈ two iterations: anything consumed but not re-bound
            # inside the body trips the reuse counter on the second pass
            self._block(stmt.body)
            self._block(stmt.body)
            self._block(stmt.orelse)
            return
        if isinstance(stmt, (ast.Try,)):
            states = []
            base = self._snapshot()
            self._block(stmt.body)
            self._block(stmt.orelse)
            states.append(base if self._terminates(stmt.body)
                          or self._terminates(stmt.orelse)
                          else self._snapshot())
            for handler in stmt.handlers:
                self._restore(base)
                self._block(handler.body)
                states.append(base if self._terminates(handler.body)
                              else self._snapshot())
            self._merge_max(states)
            self._block(stmt.finalbody)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._uses_in(item.context_expr)
                if item.optional_vars is not None:
                    for n in _assigned_names(item.optional_vars):
                        self._bind(n, key_like=False)
            self._block(stmt.body)
            return

        # simple statement: consumer uses first, then bindings take effect
        self._uses_in(stmt)
        if isinstance(stmt, ast.Assign):
            key_rhs = _is_key_rhs(stmt.value)
            for target in stmt.targets:
                for n in _assigned_names(target):
                    self._bind(n, key_like=key_rhs)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            for n in _assigned_names(stmt.target):
                self._bind(n, key_like=_is_key_rhs(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            for n in _assigned_names(stmt.target):
                self._bind(n, key_like=False)

    def _block(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _bind(self, name: str, key_like: bool) -> None:
        """A binding is authoritative: assigning a non-key value to a
        key-looking name (``for key, value in table.items()``) un-tracks it
        until a key-producing re-bind."""
        self.counts[name] = 0
        if key_like:
            self.tracked.add(name)
            self.untracked.discard(name)
        else:
            self.tracked.discard(name)
            self.untracked.add(name)

    def _is_tracked(self, name: str) -> bool:
        if name in self.tracked:
            return True
        return name not in self.untracked and bool(_KEY_NAME.search(name))

    def _uses_in(self, node: ast.AST) -> None:
        """Record consumer uses of tracked keys in all Calls under `node`
        (skipping nested function/class bodies and lambdas)."""
        for call in self._calls(node):
            callee = Rule.call_name(call)
            if Rule.terminal(callee) in _NON_CONSUMING:
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            for arg in args:
                if isinstance(arg, ast.Name) and self._is_tracked(arg.id):
                    self._consume(arg.id, arg, callee or "<call>")

    def _calls(self, node: ast.AST) -> Iterator[ast.Call]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            yield from self._calls(child)
        if isinstance(node, ast.Call):
            yield node

    def _consume(self, name: str, node: ast.AST, callee: str) -> None:
        n = self.counts.get(name, 0) + 1
        self.counts[name] = n
        if n >= 2:
            self.findings.append(self.ctx.finding(
                self.rule_name, node,
                f"PRNG key '{name}' passed to consumer '{callee}' after an "
                f"earlier consumer with no intervening jax.random.split/"
                f"fold_in — reused keys silently correlate samples"))


@register
class KeyReuseRule(Rule):
    name = "key-reuse"
    summary = ("PRNG key passed to two consumers (or consumed in a loop) "
               "without split/fold_in between — draws become identical")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # module scope is a scope too (scripts consume keys at top level)
        scopes: List[List[ast.stmt]] = [ctx.tree.body]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            yield from _ScopeLinter(ctx, self.name).run(body)
