"""User-facing API: a ``FlexibleModel`` class mirroring the reference's surface.

The reference exposes everything through one Keras subclass
(``Flexible_Model``, flexible_IWAE.py:177-545). This facade keeps that
method-for-method surface — ``fit``/``train_step``/``get_L*``/``get_NLL``/
``get_training_statistics``/``tensorboard_log``/``save_weights`` — while the
implementation underneath is the functional TPU-native core, selected by a
``backend=`` switch (the BASELINE.json north-star requirement):

* ``backend="jax"``  — jit/SPMD execution (default). Accepts an optional
  device mesh for data/sample parallelism.
* ``backend="torch"``— eager CPU oracle with the same semantics; used for
  cross-backend parity tests and as the CPU-eager baseline in bench.py.
* ``backend="tf2"``  — the reference's own eager-TF2 execution style
  (backends/tf2_ref.py, TFP-free); raises with guidance when TensorFlow is
  not importable.

Ctor signature order follows the reference (flexible_IWAE.py:178-180):
``(..., dataset_bias, loss_function, k, p, alpha, beta)``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from iwae_replication_project_tpu.objectives.estimators import ObjectiveSpec


class FlexibleModel:
    def __new__(cls, *args, backend: str = "jax", **kwargs):
        if cls is not FlexibleModel:
            return super().__new__(cls)
        if backend == "jax":
            from iwae_replication_project_tpu.backends.jax_backend import JaxFlexibleModel
            return super().__new__(JaxFlexibleModel)
        if backend == "torch":
            from iwae_replication_project_tpu.backends.torch_ref import TorchFlexibleModel
            return super().__new__(TorchFlexibleModel)
        if backend == "tf2":
            try:
                import tensorflow  # noqa: F401
            except ImportError as e:
                raise ImportError(
                    "backend='tf2' requires TensorFlow, which is not installed "
                    "in this environment. Use backend='jax' (TPU) or "
                    "backend='torch' (eager CPU oracle).") from e
            from iwae_replication_project_tpu.backends.tf2_ref import TF2FlexibleModel
            return super().__new__(TF2FlexibleModel)
        raise ValueError(f"unknown backend {backend!r}; choose jax|torch|tf2")

    def __init__(self, n_hidden_encoder: Sequence[int],
                 n_hidden_decoder: Sequence[int],
                 n_latent_encoder: Sequence[int],
                 n_latent_decoder: Sequence[int],
                 dataset_bias="binarized_mnist",
                 loss_function: str = "VAE", k: int = 50, p: float = 1,
                 alpha: float = 1, beta: float = 0.5, *,
                 backend: str = "jax", k2: int = 1, seed: int = 0,
                 data_dir: str = "data"):
        """`dataset_bias` is either a dataset name (bias means resolved via the
        data layer, like flexible_IWAE.py:147-175 but without ctor-time network
        I/O — local files or synthetic fallback) or a ``[784]`` array of pixel
        means / a precomputed bias vector passed directly."""
        self.n_hidden_encoder = tuple(n_hidden_encoder)
        self.n_hidden_decoder = tuple(n_hidden_decoder)
        self.n_latent_encoder = tuple(n_latent_encoder)
        self.n_latent_decoder = tuple(n_latent_decoder)
        self.loss_function = loss_function
        self.k = k
        self.p = p
        self.alpha = alpha
        self.beta = beta
        self.k2 = k2
        self.seed = seed
        self.epoch = 0  # per-batch counter, reference-compatible name (flexible_IWAE.py:245)
        # per-EPOCH counter for the eager backends' fit() shuffle stream —
        # kept separate from `epoch` so the data order of fit(epochs=N) is
        # reproducible regardless of interleaved train_step() calls
        self._fit_epochs = 0
        self._logger = None
        self.dataset_bias = dataset_bias
        self._output_bias = self._resolve_bias(dataset_bias, data_dir)

    # -- shared helpers ----------------------------------------------------

    @staticmethod
    def _resolve_bias(dataset_bias, data_dir: str) -> Optional[np.ndarray]:
        from iwae_replication_project_tpu.data import (
            load_dataset, output_bias_from_pixel_means)
        if dataset_bias is None:
            return None
        if isinstance(dataset_bias, str):
            ds = load_dataset(dataset_bias, data_dir=data_dir, allow_synthetic=True)
            return ds.output_bias
        arr = np.asarray(dataset_bias, np.float32)
        if arr.ndim != 1:
            raise ValueError("dataset_bias array must be 1-D (pixel means or bias)")
        # heuristic: values in [0,1] are pixel means; otherwise already a bias
        if arr.min() >= 0.0 and arr.max() <= 1.0:
            return output_bias_from_pixel_means(arr)
        return arr

    def objective_spec(self, name: Optional[str] = None, k: Optional[int] = None,
                       **over) -> ObjectiveSpec:
        return ObjectiveSpec(
            name=name or self.loss_function, k=k if k is not None else self.k,
            p=over.get("p", self.p), alpha=over.get("alpha", self.alpha),
            beta=over.get("beta", self.beta), k2=over.get("k2", self.k2))

    def fit(self, x_train, epochs: int = 1, batch_size: int = 100,
            binarization: str = "none", shuffle: bool = True,
            verbose: bool = False):
        """Eager fit: one train_step per shuffled batch — the reference's
        ``keras.Model.fit`` loop (experiment_example.py:82), shared by the
        torch and tf2 backends. The jax backend overrides this with the
        whole-epoch compiled scan."""
        from iwae_replication_project_tpu.data import epoch_batches
        x_train = np.asarray(x_train, np.float32).reshape(len(x_train), -1)
        history = {"loss": []}
        for i in range(epochs):
            e = self._fit_epochs
            self._fit_epochs += 1
            losses = [self.train_step(b)[self.loss_function]
                      for b in epoch_batches(x_train, batch_size, epoch=e,
                                             seed=self.seed,
                                             binarization=binarization,
                                             shuffle=shuffle)]
            history["loss"].append(float(np.mean(losses)))
            if verbose:
                print(f"epoch {i + 1}/{epochs}: loss={history['loss'][-1]:.4f}")
        return history

    def _run_name(self) -> str:
        return f"{self.loss_function}-{len(self.n_hidden_encoder)}L-k_{self.k}"

    def tensorboard_log(self, res: dict, epoch_n: int = -1,
                        logdir: str = "runs"):
        """Write the eval scalars (reference schema via tf.summary,
        flexible_IWAE.py:529-545 — here the dependency-free wire-format
        writer, shared by every backend)."""
        from iwae_replication_project_tpu.utils.logging import MetricsLogger
        if self._logger is None:
            self._logger = MetricsLogger(logdir, run_name=self._run_name())
        self._logger.log(res, step=self.epoch if epoch_n == -1 else epoch_n)

    # -- weight I/O (reference surface: save_weights per stage, --------------
    # -- experiment_example.py:95) -------------------------------------------
    #
    # One payload format for all three backends: the weights as a pytree in
    # the models/iwae.init_params layout (kernels [in, out]), so a checkpoint
    # written by one backend loads into any other.

    def _weights_pytree(self):
        """Current weights as a pytree in the JAX layout (backend hook)."""
        raise NotImplementedError

    def _set_weights_pytree(self, tree):
        """Install a pytree in the JAX layout as this model's weights
        (backend hook)."""
        raise NotImplementedError

    def _arch_descr(self) -> dict:
        """The ctor lists — enough to name an architecture in error messages."""
        return {"n_hidden_encoder": list(self.n_hidden_encoder),
                "n_hidden_decoder": list(self.n_hidden_decoder),
                "n_latent_encoder": list(self.n_latent_encoder),
                "n_latent_decoder": list(self.n_latent_decoder)}

    def save_weights(self, path: str):
        import pickle
        import jax
        flat, treedef = jax.tree.flatten(self._weights_pytree())
        with open(path if path.endswith(".pkl") else path + ".pkl", "wb") as f:
            pickle.dump({"arrays": [np.asarray(a) for a in flat],
                         "treedef": str(treedef),
                         "arch": self._arch_descr()}, f)

    def load_weights(self, path: str):
        """Restore weights, refusing structure mismatches: treedef AND every
        leaf's shape/dtype must match this model (mirrors the Orbax path's
        config-identity guard, utils/checkpoint.py — a same-leaf-count
        checkpoint from a different architecture must not silently load
        transposed/mis-assigned weights; VERDICT r3 Weak #4)."""
        import pickle
        import jax
        with open(path if path.endswith(".pkl") else path + ".pkl", "rb") as f:
            payload = pickle.load(f)
        flat, treedef = jax.tree.flatten(self._weights_pytree())
        saved_arch = payload.get("arch", "<unknown: pre-r4 checkpoint>")

        def refuse(why: str):
            raise ValueError(
                f"checkpoint architecture mismatch ({why}): checkpoint was "
                f"saved from {saved_arch}, this model is {self._arch_descr()}")

        if len(flat) != len(payload["arrays"]):
            refuse(f"{len(payload['arrays'])} leaves vs {len(flat)}")
        if "treedef" in payload and payload["treedef"] != str(treedef):
            refuse("parameter tree structure differs")
        for i, (cur, saved) in enumerate(zip(flat, payload["arrays"])):
            if tuple(cur.shape) != tuple(saved.shape):
                refuse(f"leaf {i} shape {saved.shape} vs {tuple(cur.shape)}")
            if np.dtype(cur.dtype) != np.dtype(saved.dtype):
                refuse(f"leaf {i} dtype {saved.dtype} vs {cur.dtype}")
        self._set_weights_pytree(jax.tree.unflatten(treedef, payload["arrays"]))


def assemble_jax_tree(pairs):
    """Build a pytree in the models/iwae.init_params layout —
    ``{"enc": (blk...), "dec": (blk...), "out": {...}}`` — from
    ``(jax-tree-path, leaf)`` pairs as yielded by the eager backends'
    ``_iter_*_tree`` correspondence walks. One assembler for both eager
    backends' weight/gradient exports, so the checkpoint tree layout has a
    single definition."""
    tree = {"enc": [], "dec": [], "out": {}}
    for path, leaf in pairs:
        if path[0] == "out":
            tree["out"][path[1]] = leaf
        else:
            group, i, nm = path
            lst = tree[group]
            while len(lst) <= i:
                lst.append({})
            lst[i][nm] = leaf
    tree["enc"] = tuple(tree["enc"])
    tree["dec"] = tuple(tree["dec"])
    return tree
