"""User-facing API: a ``FlexibleModel`` class mirroring the reference's surface.

The reference exposes everything through one Keras subclass
(``Flexible_Model``, flexible_IWAE.py:177-545). This facade keeps that
method-for-method surface — ``fit``/``train_step``/``get_L*``/``get_NLL``/
``get_training_statistics``/``tensorboard_log``/``save_weights`` — while the
implementation underneath is the functional TPU-native core, selected by a
``backend=`` switch (the BASELINE.json north-star requirement):

* ``backend="jax"``  — jit/SPMD execution (default). Accepts an optional
  device mesh for data/sample parallelism.
* ``backend="torch"``— eager CPU oracle with the same semantics; used for
  cross-backend parity tests and as the CPU-eager baseline in bench.py.
* ``backend="tf2"``  — the reference's own eager-TF2 execution style
  (backends/tf2_ref.py, TFP-free); raises with guidance when TensorFlow is
  not importable.

Ctor signature order follows the reference (flexible_IWAE.py:178-180):
``(..., dataset_bias, loss_function, k, p, alpha, beta)``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from iwae_replication_project_tpu.objectives.estimators import ObjectiveSpec


class FlexibleModel:
    def __new__(cls, *args, backend: str = "jax", **kwargs):
        if cls is not FlexibleModel:
            return super().__new__(cls)
        if backend == "jax":
            from iwae_replication_project_tpu.backends.jax_backend import JaxFlexibleModel
            return super().__new__(JaxFlexibleModel)
        if backend == "torch":
            from iwae_replication_project_tpu.backends.torch_ref import TorchFlexibleModel
            return super().__new__(TorchFlexibleModel)
        if backend == "tf2":
            try:
                import tensorflow  # noqa: F401
            except ImportError as e:
                raise ImportError(
                    "backend='tf2' requires TensorFlow, which is not installed "
                    "in this environment. Use backend='jax' (TPU) or "
                    "backend='torch' (eager CPU oracle).") from e
            from iwae_replication_project_tpu.backends.tf2_ref import TF2FlexibleModel
            return super().__new__(TF2FlexibleModel)
        raise ValueError(f"unknown backend {backend!r}; choose jax|torch|tf2")

    def __init__(self, n_hidden_encoder: Sequence[int],
                 n_hidden_decoder: Sequence[int],
                 n_latent_encoder: Sequence[int],
                 n_latent_decoder: Sequence[int],
                 dataset_bias="binarized_mnist",
                 loss_function: str = "VAE", k: int = 50, p: float = 1,
                 alpha: float = 1, beta: float = 0.5, *,
                 backend: str = "jax", k2: int = 1, seed: int = 0,
                 data_dir: str = "data"):
        """`dataset_bias` is either a dataset name (bias means resolved via the
        data layer, like flexible_IWAE.py:147-175 but without ctor-time network
        I/O — local files or synthetic fallback) or a ``[784]`` array of pixel
        means / a precomputed bias vector passed directly."""
        self.n_hidden_encoder = tuple(n_hidden_encoder)
        self.n_hidden_decoder = tuple(n_hidden_decoder)
        self.n_latent_encoder = tuple(n_latent_encoder)
        self.n_latent_decoder = tuple(n_latent_decoder)
        self.loss_function = loss_function
        self.k = k
        self.p = p
        self.alpha = alpha
        self.beta = beta
        self.k2 = k2
        self.seed = seed
        self.epoch = 0  # per-batch counter, reference-compatible name (flexible_IWAE.py:245)
        self.dataset_bias = dataset_bias
        self._output_bias = self._resolve_bias(dataset_bias, data_dir)

    # -- shared helpers ----------------------------------------------------

    @staticmethod
    def _resolve_bias(dataset_bias, data_dir: str) -> Optional[np.ndarray]:
        from iwae_replication_project_tpu.data import (
            load_dataset, output_bias_from_pixel_means)
        if dataset_bias is None:
            return None
        if isinstance(dataset_bias, str):
            ds = load_dataset(dataset_bias, data_dir=data_dir, allow_synthetic=True)
            return ds.output_bias
        arr = np.asarray(dataset_bias, np.float32)
        if arr.ndim != 1:
            raise ValueError("dataset_bias array must be 1-D (pixel means or bias)")
        # heuristic: values in [0,1] are pixel means; otherwise already a bias
        if arr.min() >= 0.0 and arr.max() <= 1.0:
            return output_bias_from_pixel_means(arr)
        return arr

    def objective_spec(self, name: Optional[str] = None, k: Optional[int] = None,
                       **over) -> ObjectiveSpec:
        return ObjectiveSpec(
            name=name or self.loss_function, k=k if k is not None else self.k,
            p=over.get("p", self.p), alpha=over.get("alpha", self.alpha),
            beta=over.get("beta", self.beta), k2=over.get("k2", self.k2))
