"""User-facing API: a ``FlexibleModel`` class mirroring the reference's surface.

The reference exposes everything through one Keras subclass
(``Flexible_Model``, flexible_IWAE.py:177-545). This facade keeps that
method-for-method surface — ``fit``/``train_step``/``get_L*``/``get_NLL``/
``get_training_statistics``/``tensorboard_log``/``save_weights`` — while the
implementation underneath is the functional TPU-native core, selected by a
``backend=`` switch (the BASELINE.json north-star requirement):

* ``backend="jax"``  — jit/SPMD execution (default). Accepts an optional
  device mesh for data/sample parallelism.
* ``backend="torch"``— eager CPU oracle with the same semantics; used for
  cross-backend parity tests and as the CPU-eager baseline in bench.py.
* ``backend="tf2"``  — the reference's own eager-TF2 execution style
  (backends/tf2_ref.py, TFP-free); raises with guidance when TensorFlow is
  not importable.

Ctor signature order follows the reference (flexible_IWAE.py:178-180):
``(..., dataset_bias, loss_function, k, p, alpha, beta)``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from iwae_replication_project_tpu.objectives.estimators import ObjectiveSpec

#: 'not passed' sentinel for dataset_bias — distinguishes the implicit default
#: ("binarized_mnist", reference parity) from an explicit string, so an
#: explicit dataset name combined with pixel_means=/bias= errors consistently
_UNSET = object()


class FlexibleModel:
    def __new__(cls, *args, backend: str = "jax", **kwargs):
        if cls is not FlexibleModel:
            return super().__new__(cls)
        if backend == "jax":
            from iwae_replication_project_tpu.backends.jax_backend import JaxFlexibleModel
            return super().__new__(JaxFlexibleModel)
        if backend == "torch":
            from iwae_replication_project_tpu.backends.torch_ref import TorchFlexibleModel
            return super().__new__(TorchFlexibleModel)
        if backend == "tf2":
            try:
                import tensorflow  # noqa: F401
            except ImportError as e:
                raise ImportError(
                    "backend='tf2' requires TensorFlow, which is not installed "
                    "in this environment. Use backend='jax' (TPU) or "
                    "backend='torch' (eager CPU oracle).") from e
            from iwae_replication_project_tpu.backends.tf2_ref import TF2FlexibleModel
            return super().__new__(TF2FlexibleModel)
        raise ValueError(f"unknown backend {backend!r}; choose jax|torch|tf2")

    def __init__(self, n_hidden_encoder: Sequence[int],
                 n_hidden_decoder: Sequence[int],
                 n_latent_encoder: Sequence[int],
                 n_latent_decoder: Sequence[int],
                 dataset_bias=_UNSET,
                 loss_function: str = "VAE", k: int = 50, p: float = 1,
                 alpha: float = 1, beta: float = 0.5, *,
                 backend: str = "jax", k2: int = 1, seed: int = 0,
                 data_dir: str = "data", pixel_means=None, bias=None):
        """`dataset_bias` is either a dataset name (bias means resolved via the
        data layer, like flexible_IWAE.py:147-175 but without ctor-time network
        I/O — local files or synthetic fallback) or a ``[784]`` array of pixel
        means / a precomputed bias vector passed directly (deprecated for
        arrays — the meaning is guessed from the value range; pass
        ``pixel_means=`` or ``bias=`` instead, which are unambiguous:
        ``pixel_means`` goes through the logit-of-clipped-mean transform,
        ``bias`` is installed on the decoder output head as-is)."""
        self.n_hidden_encoder = tuple(n_hidden_encoder)
        self.n_hidden_decoder = tuple(n_hidden_decoder)
        self.n_latent_encoder = tuple(n_latent_encoder)
        self.n_latent_decoder = tuple(n_latent_decoder)
        self.loss_function = loss_function
        self.k = k
        self.p = p
        self.alpha = alpha
        self.beta = beta
        self.k2 = k2
        self.seed = seed
        self.epoch = 0  # per-batch counter, reference-compatible name (flexible_IWAE.py:245)
        # per-EPOCH counter for the eager backends' fit() shuffle stream —
        # kept separate from `epoch` so the data order of fit(epochs=N) is
        # reproducible regardless of interleaved train_step() calls
        self._fit_epochs = 0
        self._logger = None
        if dataset_bias is _UNSET:
            # the implicit reference-parity default — unless the explicit
            # kwargs take over, in which case no dataset bias is in play
            dataset_bias = (None if pixel_means is not None or bias is not None
                            else "binarized_mnist")
        self.dataset_bias = dataset_bias
        self._output_bias = self._resolve_bias(dataset_bias, data_dir,
                                               pixel_means=pixel_means,
                                               bias=bias)

    # -- shared helpers ----------------------------------------------------

    @staticmethod
    def _resolve_bias(dataset_bias, data_dir: str, *, pixel_means=None,
                      bias=None) -> Optional[np.ndarray]:
        from iwae_replication_project_tpu.data import (
            load_dataset, output_bias_from_pixel_means)

        def check_1d(a, what):
            arr = np.asarray(a, np.float32)
            if arr.ndim != 1:
                raise ValueError(f"{what} must be a 1-D array, got shape "
                                 f"{arr.shape}")
            return arr

        if pixel_means is not None or bias is not None:
            if pixel_means is not None and bias is not None:
                raise ValueError("pass pixel_means= OR bias=, not both")
            if dataset_bias is not None:  # __init__ maps the unset default to None
                raise ValueError(
                    "pixel_means=/bias= replace dataset_bias; leave "
                    "dataset_bias at its default (or None) when using them")
            if pixel_means is not None:
                arr = check_1d(pixel_means, "pixel_means")
                if arr.min() < 0.0 or arr.max() > 1.0:
                    raise ValueError(
                        f"pixel_means must lie in [0,1], got range "
                        f"[{arr.min():.3g}, {arr.max():.3g}] — if this is a "
                        "precomputed bias vector, pass it as bias= instead")
                return output_bias_from_pixel_means(arr)
            return check_1d(bias, "bias")
        if dataset_bias is None:
            return None
        if isinstance(dataset_bias, str):
            ds = load_dataset(dataset_bias, data_dir=data_dir, allow_synthetic=True)
            return ds.output_bias
        arr = check_1d(dataset_bias, "dataset_bias array")
        # DEPRECATED range heuristic: values in [0,1] are treated as pixel
        # means, anything else as an already-computed bias. A true bias vector
        # whose values happen to lie in [0,1] (pixel means in ~[.5,.73]) would
        # be double-transformed — the explicit kwargs cannot misfire.
        import warnings
        # stacklevel: _resolve_bias <- base __init__ <- backend subclass
        # __init__ <- the user's constructor call (every backend defines an
        # __init__ that chains to super())
        warnings.warn(
            "passing an array as dataset_bias guesses pixel-means vs bias "
            "from the value range; pass pixel_means= or bias= instead",
            DeprecationWarning, stacklevel=4)
        if arr.min() >= 0.0 and arr.max() <= 1.0:
            return output_bias_from_pixel_means(arr)
        return arr

    def objective_spec(self, name: Optional[str] = None, k: Optional[int] = None,
                       **over) -> ObjectiveSpec:
        return ObjectiveSpec(
            name=name or self.loss_function, k=k if k is not None else self.k,
            p=over.get("p", self.p), alpha=over.get("alpha", self.alpha),
            beta=over.get("beta", self.beta), k2=over.get("k2", self.k2))

    def fit(self, x_train, epochs: int = 1, batch_size: int = 100,
            binarization: str = "none", shuffle: bool = True,
            verbose: bool = False):
        """Eager fit: one train_step per shuffled batch — the reference's
        ``keras.Model.fit`` loop (experiment_example.py:82), shared by the
        torch and tf2 backends. The jax backend overrides this with the
        whole-epoch compiled scan."""
        from iwae_replication_project_tpu.data import epoch_batches
        x_train = np.asarray(x_train, np.float32).reshape(len(x_train), -1)
        history = {"loss": []}
        for i in range(epochs):
            e = self._fit_epochs
            self._fit_epochs += 1
            losses = [self.train_step(b)[self.loss_function]
                      for b in epoch_batches(x_train, batch_size, epoch=e,
                                             seed=self.seed,
                                             binarization=binarization,
                                             shuffle=shuffle)]
            history["loss"].append(float(np.mean(losses)))
            if verbose:
                print(f"epoch {i + 1}/{epochs}: loss={history['loss'][-1]:.4f}")
        return history

    def _run_name(self) -> str:
        return f"{self.loss_function}-{len(self.n_hidden_encoder)}L-k_{self.k}"

    def serving_engine(self, **knobs):
        """An online-inference :class:`~.serving.ServingEngine` over this
        model's current weights (dynamic micro-batching + AOT warm paths —
        see serving/engine.py). JAX backend only: the eager oracles have no
        compiled dispatch path to serve from."""
        raise NotImplementedError(
            "serving requires backend='jax' (the torch/tf2 oracles have no "
            "AOT warm path); build the model with backend='jax'")

    def tensorboard_log(self, res: dict, epoch_n: int = -1,
                        logdir: str = "runs"):
        """Write the eval scalars (reference schema via tf.summary,
        flexible_IWAE.py:529-545 — here the dependency-free wire-format
        writer, shared by every backend)."""
        from iwae_replication_project_tpu.utils.logging import MetricsLogger
        if self._logger is None:
            self._logger = MetricsLogger(logdir, run_name=self._run_name())
        self._logger.log(res, step=self.epoch if epoch_n == -1 else epoch_n)

    # -- weight I/O (reference surface: save_weights per stage, --------------
    # -- experiment_example.py:95) -------------------------------------------
    #
    # One payload format for all three backends: the weights as a pytree in
    # the models/iwae.init_params layout (kernels [in, out]), so a checkpoint
    # written by one backend loads into any other.

    def _weights_pytree(self):
        """Current weights as a pytree in the JAX layout (backend hook)."""
        raise NotImplementedError

    def _set_weights_pytree(self, tree):
        """Install a pytree in the JAX layout as this model's weights
        (backend hook)."""
        raise NotImplementedError

    def _arch_descr(self) -> dict:
        """The ctor lists — enough to name an architecture in error messages."""
        return {"n_hidden_encoder": list(self.n_hidden_encoder),
                "n_hidden_decoder": list(self.n_hidden_decoder),
                "n_latent_encoder": list(self.n_latent_encoder),
                "n_latent_decoder": list(self.n_latent_decoder)}

    @staticmethod
    def _flatten_with_keys(tree):
        """``(key-path strings, leaves, treedef)`` of a weights pytree.

        The key-path strings (``jax.tree_util.keystr``) are the structural
        fingerprint stored in checkpoints: unlike ``str(treedef)`` (whose repr
        is not stable across JAX versions — ADVICE r4) the paths are plain
        index/key sequences, so a checkpoint keeps loading after a JAX
        upgrade and still refuses a genuinely different structure."""
        import jax
        flat_kp, treedef = jax.tree_util.tree_flatten_with_path(tree)
        return ([jax.tree_util.keystr(kp) for kp, _ in flat_kp],
                [leaf for _, leaf in flat_kp], treedef)

    def save_weights(self, path: str):
        """Persist the weights as an ``.npz``: one entry per leaf plus a JSON
        metadata entry (key paths + architecture). Replaces the round-≤4
        pickle payload — same information, no arbitrary-code-execution surface
        on load (ADVICE r4). The reference surface is per-stage
        ``save_weights`` (experiment_example.py:95)."""
        import json
        keys, flat, _ = self._flatten_with_keys(self._weights_pytree())
        meta = {"paths": keys, "arch": self._arch_descr(), "format": 1}
        arrays = {f"leaf_{i}": np.asarray(a) for i, a in enumerate(flat)}
        if path.endswith(".pkl"):  # old-API callers: keep the round-trip
            path = path[:-len(".pkl")]
        out = path if path.endswith(".npz") else path + ".npz"
        # the old API wrote (and would have overwritten) `<stem>.pkl`; left
        # in place it would shadow this fresh .npz on a later
        # load_weights("<stem>.pkl"). It must move aside for BOTH save
        # spellings — but it may be the only copy of differently-trained
        # weights, so it is renamed to `<stem>.pkl.bak` (clobbering any older
        # .bak) rather than deleted, with a warning (ADVICE r5).
        stale = out[:-len(".npz")] + ".pkl"
        if os.path.exists(stale):
            import warnings
            warnings.warn(
                f"save_weights: a legacy pickle exists at {stale!r} and would "
                f"shadow the fresh {out!r} on load; renaming it to "
                f"{stale + '.bak'!r}", UserWarning, stacklevel=2)
            os.replace(stale, stale + ".bak")
        with open(out, "wb") as f:
            np.savez(f, __meta__=np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8), **arrays)

    def load_weights(self, path: str):
        """Restore weights, refusing structure mismatches: the key-path
        fingerprint AND every leaf's shape/dtype must match this model
        (mirrors the Orbax path's config-identity guard, utils/checkpoint.py —
        a same-leaf-count checkpoint from a different architecture must not
        silently load transposed/mis-assigned weights; VERDICT r3 Weak #4).
        Legacy ``.pkl`` payloads from rounds ≤4 still load (with a warning —
        pickle executes code from the file; re-save as .npz)."""
        import json
        import jax
        # resolve to ONE candidate file, then branch on its actual suffix: an
        # explicit .pkl path must never be fed to np.load, and detection must
        # open exactly the file it detected
        if path.endswith(".pkl") and not os.path.exists(path) \
                and os.path.exists(path[:-len(".pkl")] + ".npz"):
            # save_weights("x.pkl") now writes x.npz; keep the pair working
            fp = path[:-len(".pkl")] + ".npz"
        elif path.endswith((".npz", ".pkl")):
            fp = path
        elif os.path.exists(path + ".npz"):
            fp = path + ".npz"
        elif os.path.exists(path + ".pkl"):
            fp = path + ".pkl"
        else:
            fp = path  # a bare existing file is treated as npz (our format)
        saved_arch_dict = None
        legacy_treedef = None
        if not fp.endswith(".pkl"):
            with np.load(fp) as z:
                meta = json.loads(bytes(z["__meta__"]).decode())
                saved_paths = meta["paths"]
                saved_arch = meta.get("arch", "<unknown>")
                saved_arch_dict = meta.get("arch")
                arrays = [z[f"leaf_{i}"] for i in range(len(saved_paths))]
        else:
            import pickle
            import warnings
            warnings.warn("loading a legacy pickle checkpoint; re-save as "
                          ".npz (pickle executes code from the file)",
                          UserWarning, stacklevel=2)
            with open(fp, "rb") as f:
                payload = pickle.load(f)
            saved_paths = None  # pre-npz payloads carry str(treedef) only
            saved_arch = payload.get("arch", "<unknown: pre-r4 checkpoint>")
            if isinstance(payload.get("arch"), dict):
                saved_arch_dict = payload["arch"]
            legacy_treedef = payload.get("treedef")
            arrays = payload["arrays"]
        paths, flat, treedef = self._flatten_with_keys(self._weights_pytree())

        def refuse(why: str):
            raise ValueError(
                f"checkpoint architecture mismatch ({why}): checkpoint was "
                f"saved from {saved_arch}, this model is {self._arch_descr()}")

        # arch dicts are plain JSON on both sides — the structure guard that
        # works for legacy payloads too (their str(treedef) is version-bound)
        if saved_arch_dict is not None and saved_arch_dict != self._arch_descr():
            refuse("architecture lists differ")
        elif saved_arch_dict is None and saved_paths is None \
                and legacy_treedef is not None \
                and legacy_treedef != str(treedef):
            # pre-r4 payload without the arch dict: str(treedef) is the only
            # structure evidence it carries — version-bound, but better than
            # silently mis-assigning same-shape leaves
            refuse("parameter tree structure differs")

        if len(flat) != len(arrays):
            refuse(f"{len(arrays)} leaves vs {len(flat)}")
        if saved_paths is not None and saved_paths != paths:
            diff = next((f"{s!r} vs {c!r}" for s, c in zip(saved_paths, paths)
                         if s != c), "")
            refuse(f"parameter tree structure differs: {diff}")
        for i, (cur, saved) in enumerate(zip(flat, arrays)):
            if tuple(cur.shape) != tuple(saved.shape):
                refuse(f"leaf {i} ({paths[i]}) shape {tuple(saved.shape)} "
                       f"vs {tuple(cur.shape)}")
            if np.dtype(cur.dtype) != np.dtype(saved.dtype):
                refuse(f"leaf {i} ({paths[i]}) dtype {saved.dtype} "
                       f"vs {cur.dtype}")
        self._set_weights_pytree(jax.tree.unflatten(treedef, arrays))


def assemble_jax_tree(pairs):
    """Build a pytree in the models/iwae.init_params layout —
    ``{"enc": (blk...), "dec": (blk...), "out": {...}}`` — from
    ``(jax-tree-path, leaf)`` pairs as yielded by the eager backends'
    ``_iter_*_tree`` correspondence walks. One assembler for both eager
    backends' weight/gradient exports, so the checkpoint tree layout has a
    single definition."""
    tree = {"enc": [], "dec": [], "out": {}}
    for path, leaf in pairs:
        if path[0] == "out":
            tree["out"][path[1]] = leaf
        else:
            group, i, nm = path
            lst = tree[group]
            while len(lst) <= i:
                lst.append({})
            lst[i][nm] = leaf
    tree["enc"] = tuple(tree["enc"])
    tree["dec"] = tuple(tree["dec"])
    return tree
