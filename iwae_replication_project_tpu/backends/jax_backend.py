"""The JAX/TPU implementation behind the FlexibleModel facade.

Every reference method (flexible_IWAE.py:221-545) maps onto the functional
core: the class only holds state (params/opt/rng) and memoizes jitted
callables; all math lives in models/, objectives/, evaluation/.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from iwae_replication_project_tpu.api import FlexibleModel
import iwae_replication_project_tpu.evaluation.activity as au
import iwae_replication_project_tpu.evaluation.metrics as ev
from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import bound_from_log_weights
from iwae_replication_project_tpu.training import train_step as ts


class JaxFlexibleModel(FlexibleModel):
    def __init__(self, *args, mesh=None, mesh_sp: int = 1,
                 compute_dtype: Optional[str] = None, likelihood: str = "clamp",
                 **kwargs):
        # likelihood default is "clamp" HERE (bit-parity with the reference's
        # sigmoid+clamp, flexible_IWAE.py:102, and with the torch oracle this
        # facade is parity-tested against), while ExperimentConfig defaults to
        # the faster "logits" path (utils/config.py:71-78) — an intentional
        # divergence: the facade is the reference-parity surface, the
        # experiment driver is the production-throughput surface. NLL
        # neutrality between the two kernels on a trained model is asserted by
        # tests/test_convergence.py::test_likelihood_modes_nll_neutral.
        #
        # backend-specific kwargs are consumed above; everything else must be a
        # known base-ctor parameter (typos raise instead of silently training
        # with defaults)
        super().__init__(*args, **kwargs)
        self.cfg = model.ModelConfig(
            n_hidden_enc=self.n_hidden_encoder,
            n_latent_enc=self.n_latent_encoder,
            n_hidden_dec=self.n_hidden_decoder,
            n_latent_dec=self.n_latent_decoder,
            x_dim=self.n_latent_decoder[-1],
            likelihood=likelihood,
            compute_dtype=compute_dtype,
        )
        self.mesh = mesh
        self.mesh_sp = mesh_sp
        self._optimizer = None
        self.state: Optional[ts.TrainState] = None
        self._step_fn = None
        self._eval_key = jax.random.PRNGKey(self.seed + 1)

    # ------------------------------------------------------------------
    # training surface (reference: compile/fit/train_step)
    # ------------------------------------------------------------------

    def compile(self, optimizer=None, learning_rate: float = 1e-3):
        """Build params + optimizer state (Keras-API parity; reference
        compiles with Adam eps=1e-4, experiment_example.py:36-40)."""
        from iwae_replication_project_tpu.utils.compile_cache import warm_callable

        self._optimizer = optimizer or ts.make_adam(learning_rate)
        # registry identity of the optimizer's *program structure*: the
        # default make_adam is inject_hyperparams(adam) — every hyperparameter
        # (incl. learning_rate) is runtime state, so any default-built
        # instance compiles the identical step program and may share one AOT
        # executable across FlexibleModel instances. A user-supplied optimizer
        # is keyed by the GradientTransformation object ITSELF (a NamedTuple
        # of its init/update callables): equal functions -> same program, and
        # holding the object in the module-level registry key pins it alive,
        # so a freed optimizer's id can never be recycled onto a different
        # program (the failure mode of keying on id()).
        self._opt_key = ("default_adam",) if optimizer is None \
            else ("custom", optimizer)
        self.state = ts.create_train_state(
            jax.random.PRNGKey(self.seed), self.cfg,
            output_bias=self._output_bias, optimizer=self._optimizer)
        spec = self.objective_spec()
        if self.mesh is None and self.mesh_sp > 1:
            # honour the facade's sample-parallelism request: build a mesh with
            # the requested sp extent, dp absorbing the remaining devices
            from iwae_replication_project_tpu.parallel import make_mesh
            self.mesh = make_mesh(sp=self.mesh_sp)
        if self.mesh is not None:
            from iwae_replication_project_tpu.parallel import (
                dp as pdp, make_parallel_train_step)
            self._step_fn = make_parallel_train_step(
                spec, self.cfg, self.mesh, optimizer=self._optimizer, donate=False)
            self.state = pdp.replicate(self.mesh, self.state)
            self._place_batch = lambda b: pdp.shard_batch(self.mesh, b)
        else:
            self._step_fn = ts.make_train_step(spec, self.cfg,
                                               optimizer=self._optimizer, donate=False)
            self._place_batch = jnp.asarray
        # registry-wrapped: a rebuilt facade (new instance, re-compile()) with
        # the same (spec, cfg, optimizer structure, mesh) reuses the one AOT
        # executable instead of retracing. Per-call cost is the Python-side
        # signature hash (~tens of us) — noise next to the >= 1 ms step +
        # ~10-15 ms per-dispatch transport this facade path already pays.
        self._step_fn = warm_callable(
            "facade_step", self._step_fn,
            build_key=(spec, self.cfg, self._opt_key, self._mesh_key()))
        return self

    def _mesh_key(self):
        from iwae_replication_project_tpu.utils.compile_cache import (
            mesh_fingerprint)
        return mesh_fingerprint(self.mesh)

    def set_learning_rate(self, lr: float):
        self.state = ts.set_learning_rate(self.state, lr)

    def train_step(self, x) -> Dict[str, float]:
        """One optimizer step on one batch (parity: flexible_IWAE.py:221-247)."""
        self._require_compiled()
        x = self._place_batch(self._flatten(x))
        self.state, metrics = self._step_fn(self.state, x)
        self.epoch += 1
        return {self.loss_function: float(metrics["loss"])}

    def fit(self, x_train, epochs: int = 1, batch_size: int = 100,
            binarization: str = "none", shuffle: bool = True,
            verbose: bool = False) -> Dict[str, list]:
        """Train for `epochs` passes (replaces keras .fit, experiment_example.py:82).

        Each whole epoch runs as ONE compiled scan — training/epoch.py on a
        single device, parallel/dp.make_parallel_epoch_fn under a mesh — so
        data stays in HBM and shuffle + stochastic binarization + all
        optimizer steps happen on device. This is the same dispatch shape the
        experiment driver uses (experiment.py), keeping the two production
        surfaces in agreement (VERDICT r2 weak #3).
        """
        self._require_compiled()
        x_train = self._flatten(np.asarray(x_train))
        history = {"loss": []}
        epoch_fn = self._get_epoch_fn(x_train.shape[0], batch_size,
                                      binarization, shuffle)
        if self.mesh is not None:
            from iwae_replication_project_tpu.parallel.dp import replicate
            x_dev = replicate(self.mesh, jnp.asarray(x_train))
        else:
            x_dev = jnp.asarray(x_train)
        n_batches = x_train.shape[0] // batch_size
        for e in range(epochs):
            self.state, losses = epoch_fn(self.state, x_dev)
            self.epoch += n_batches
            history["loss"].append(float(jnp.mean(losses)))
            if verbose:
                print(f"epoch {e + 1}/{epochs}: loss={history['loss'][-1]:.4f}")
        return history

    def _get_epoch_fn(self, n_train: int, batch_size: int, binarization: str,
                      shuffle: bool):
        # the objective spec and optimizer identity are part of the key: a
        # re-compile() (new optimizer / changed loss attributes) must rebuild
        sig = (n_train, batch_size, binarization, shuffle,
               self.objective_spec(), id(self._optimizer), self.mesh)
        if getattr(self, "_epoch_sig", None) != sig:
            from iwae_replication_project_tpu.utils.compile_cache import (
                warm_callable)
            if self.mesh is not None:
                from iwae_replication_project_tpu.parallel.dp import (
                    make_parallel_epoch_fn)
                fn = make_parallel_epoch_fn(
                    self.objective_spec(), self.cfg, self.mesh, n_train,
                    batch_size,
                    stochastic_binarization=binarization == "stochastic",
                    optimizer=self._optimizer, shuffle=shuffle, donate=False)
            else:
                from iwae_replication_project_tpu.training.epoch import (
                    make_epoch_fn)
                fn = make_epoch_fn(
                    self.objective_spec(), self.cfg, n_train, batch_size,
                    stochastic_binarization=binarization == "stochastic",
                    optimizer=self._optimizer, shuffle=shuffle, donate=False)
            self._epoch_fn = warm_callable(
                "facade_epoch", fn,
                build_key=(self.objective_spec(), self.cfg, n_train,
                           batch_size, binarization, shuffle, self._opt_key,
                           self._mesh_key()))
            self._epoch_sig = sig
        return self._epoch_fn

    def serving_engine(self, **knobs):
        """Online-inference engine over the CURRENT weights (a snapshot:
        later train_steps do not retarget an already-built engine). Accepts
        every ServingEngine knob; `k` defaults to this model's k."""
        self._require_compiled()
        from iwae_replication_project_tpu.serving.engine import ServingEngine
        knobs.setdefault("k", self.k)
        return ServingEngine(params=self.params, model_config=self.cfg,
                             **knobs)

    # ------------------------------------------------------------------
    # objectives surface (reference get_L_* family)
    # ------------------------------------------------------------------

    def get_log_weights(self, x, n_samples: int):
        self._require_compiled()
        return model.log_weights(self.params, self.cfg, self._next_eval_key(),
                                 self._flatten(x), n_samples)

    def _bound(self, name: str, x, k: int, **over) -> jnp.ndarray:
        self._require_compiled()
        spec = self.objective_spec(name=name, k=k, **over)
        log_w, aux = model.log_weights_and_aux(
            self.params, self.cfg, self._next_eval_key(), self._flatten(x), k)
        return bound_from_log_weights(spec, log_w, aux)

    def get_L(self, x, k: int = 5000):
        return self._bound("VAE", x, k)

    def get_L_k(self, x, k: int):
        return self._bound("IWAE", x, k)

    def get_L_V1(self, x, n_samples: int):
        return self._bound("VAE_V1", x, n_samples)

    def get_L_alpha(self, x, n_samples: int, alpha: float):
        return self._bound("L_alpha", x, n_samples, alpha=alpha)

    def get_L_power_p(self, x, k: int, p: float):
        return self._bound("L_power_p", x, k, p=p)

    def get_L_median(self, x, k: int):
        return self._bound("L_median", x, k)

    def get_L_CIWAE(self, x, n_samples: int, beta: float):
        return self._bound("CIWAE", x, n_samples, beta=beta)

    def get_L_MIWAE(self, x, k1: int, k2: int):
        return self._bound("MIWAE", x, k1 * k2, k2=k2)

    # ------------------------------------------------------------------
    # evaluation surface
    # ------------------------------------------------------------------

    def get_NLL(self, x, k: int = 5000, chunk: int = 250):
        self._require_compiled()
        # clamp so small/odd k keeps working with the (round-4) 250 default;
        # the low-level streaming kernel still rejects non-divisors loudly
        chunk = ev.largest_divisor_leq(k, chunk)
        return ev.streaming_nll(self.params, self.cfg, self._next_eval_key(),
                                self._flatten(x), k=k, chunk=chunk)

    def reconstructed_x_probs(self, x):
        self._require_compiled()
        return model.reconstruct_probs(self.params, self.cfg,
                                       self._next_eval_key(), self._flatten(x))

    def get_reconstruction_loss(self, x):
        self._require_compiled()
        return ev.reconstruction_loss(self.params, self.cfg,
                                      self._next_eval_key(), self._flatten(x))

    def get_E_qhIx_log_pxIh(self, x, n_samples: int):
        self._require_compiled()
        _, aux = model.log_weights_and_aux(self.params, self.cfg,
                                           self._next_eval_key(),
                                           self._flatten(x), n_samples)
        return jnp.mean(aux["log_px_given_h"])

    def get_Dkl_qhIx_ph(self, x, k: int):
        """E_q[log p(x|h)] - L (flexible_IWAE.py:414-415), single pass."""
        self._require_compiled()
        log_w, aux = model.log_weights_and_aux(self.params, self.cfg,
                                               self._next_eval_key(),
                                               self._flatten(x), k)
        return jnp.mean(aux["log_px_given_h"]) - jnp.mean(log_w)

    def get_Dkl_qhIx_phIx(self, x, k: int):
        """L_5000 - L (flexible_IWAE.py:411-412)."""
        return -(self.get_L(x, k) + self.get_NLL(x))

    def get_levels_of_units_activity(self, x, n_samples: int):
        self._require_compiled()
        return au.posterior_mean_activity(self.params, self.cfg,
                                          self._next_eval_key(),
                                          self._flatten(x), n_samples=n_samples)

    def get_eigenvalues_PCA(self, data):
        return au.pca_eigenvalues(jnp.asarray(data))

    def get_active_units(self, variances, eigen_values, threshold: float = 0.01):
        return au.active_units(variances, eigen_values, threshold)

    def get_NLL_without_inactive_units(self, x, threshold: float = 0.01,
                                       n_samples: int = 5000,
                                       activity_samples: int = 1000):
        self._require_compiled()
        x = self._flatten(x)
        variances, eigvals = self.get_levels_of_units_activity(x, activity_samples)
        masks, _, _ = au.active_units(variances, eigvals, threshold)
        return au.nll_without_inactive_units(self.params, self.cfg,
                                             self._next_eval_key(), x, masks,
                                             k=n_samples)

    def get_training_statistics(self, x, k: int, batch_size: int = 100, **kw
                                ) -> Tuple[dict, dict]:
        # batch_size default stays 100 on the facade (stable RNG stream for
        # parity work); the production ExperimentConfig default is 500 since
        # round 5 (utils/config.py, RESULTS.md §4). The effective batch is
        # stamped as "eval_batch" in the returned scalars either way.
        self._require_compiled()
        return ev.training_statistics(self.params, self.cfg,
                                      self._next_eval_key(), self._flatten(x),
                                      k, batch_size=batch_size, **kw)

    def generate(self, n: int, key=None):
        """Ancestral samples from the prior -> pixel probs ``[n, x_dim]``."""
        self._require_compiled()
        key = key if key is not None else self._next_eval_key()
        k1, k2 = jax.random.split(key)
        h_top = jax.random.normal(k1, (1, n, self.cfg.n_latent_enc[-1]))
        return model.generate_x(self.params, self.cfg, k2, h_top)[0]

    # ------------------------------------------------------------------
    # observability / persistence
    # ------------------------------------------------------------------

    # tensorboard_log() is shared on the base facade (api.FlexibleModel).

    # weight I/O lives on the base facade (api.FlexibleModel.save_weights /
    # load_weights — shared payload + architecture guard); the hooks below
    # bind it to the compiled train state.

    def _weights_pytree(self):
        self._require_compiled()
        return self.params

    def _set_weights_pytree(self, tree):
        self.state = self.state._replace(
            params=jax.tree.map(jnp.asarray, tree))

    # ------------------------------------------------------------------

    @property
    def params(self):
        return self.state.params

    def _require_compiled(self):
        if self.state is None:
            raise RuntimeError("call .compile() before training/evaluation")

    def _next_eval_key(self):
        self._eval_key, sub = jax.random.split(self._eval_key)
        return sub

    @staticmethod
    def _flatten(x):
        x = jnp.asarray(x, jnp.float32)
        return x.reshape(x.shape[0], -1)
