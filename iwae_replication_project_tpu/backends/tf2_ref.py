"""Eager TF2 backend — the reference's own execution style, behind the facade.

The reference IS an eager-TF2/Keras/TFP class (flexible_IWAE.py:177-545, with
`@tf.function` deliberately commented out at :220). This backend restores that
path for the north-star sentence ("alongside the existing TF2 path"): the same
`FlexibleModel` surface running on TensorFlow eager ops, selected by
``backend="tf2"``.

Differences from the reference's internals, by design:

* no TFP dependency — Normal/Bernoulli log-densities are closed-form, with
  the same parity constants as every other backend (std floor 1e-6, prob
  clamp ``p*(1-1e-6)+1e-7``, flexible_IWAE.py:75,102);
* no Keras layers — parameters are plain ``tf.Variable``s in the JAX pytree
  layout (``w [in, out]``), so weight tying against the JAX path is a direct
  copy and the module has no Keras-version surface;
* gradients via ``tf.GradientTape`` (eager, per-op — the reference's
  execution model), including the modified-gradient estimators DReG/STL/PIWAE
  realized as surrogate scalars on score-stopped graphs, mirroring
  backends/torch_ref.py.

Tested: surface smoke + weight-tied statistical parity vs the JAX path in
tests/test_tf2_backend.py (skipped wholesale when TF is not importable).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from iwae_replication_project_tpu.api import FlexibleModel

_PCLAMP_SCALE = 1.0 - 1e-6
_PCLAMP_SHIFT = 1e-7
_STD_FLOOR = 1e-6
_LOG_2PI = float(np.log(2.0 * np.pi))


import tensorflow as tf  # the facade only imports this module after
# confirming TF is importable (api.FlexibleModel.__new__); a missing TF still
# surfaces as a clean ImportError here.


class TF2FlexibleModel(FlexibleModel):
    def __init__(self, *args, mesh=None, mesh_sp: int = 1, compute_dtype=None,
                 likelihood: str = "clamp", **kwargs):
        # accept (and ignore) the jax-backend execution kwargs so callers can
        # flip backend= without changing anything else; unknown kwargs raise
        super().__init__(*args, **kwargs)
        # seed BOTH streams: the Generator drives weight init; the global
        # op-level seed drives every tf.random.normal sampling call (same
        # whole-process semantics as torch_ref's torch.manual_seed)
        tf.random.set_seed(self.seed)
        rng = tf.random.Generator.from_seed(self.seed)

        def dense(in_dim, out_dim):
            lim = float(np.sqrt(6.0 / (in_dim + out_dim)))
            return {"w": tf.Variable(rng.uniform((in_dim, out_dim),
                                                 -lim, lim, tf.float32)),
                    "b": tf.Variable(tf.zeros((out_dim,), tf.float32))}

        def block(in_dim, hidden, latent):
            return {"l1": dense(in_dim, hidden), "l2": dense(hidden, hidden),
                    "mu": dense(hidden, latent), "lstd": dense(hidden, latent)}

        L = len(self.n_hidden_encoder)
        self.L = L
        x_dim = self.n_latent_decoder[-1]
        enc, in_dim = [], x_dim
        for i in range(L):
            enc.append(block(in_dim, self.n_hidden_encoder[i],
                             self.n_latent_encoder[i]))
            in_dim = self.n_latent_encoder[i]
        self.enc = enc
        dec, in_dim = [], self.n_latent_encoder[-1]
        for i in range(L - 1):
            dec.append(block(in_dim, self.n_hidden_decoder[i],
                             self.n_latent_decoder[i]))
            in_dim = self.n_latent_decoder[i]
        self.dec = dec
        self.out = {"l1": dense(in_dim, self.n_hidden_decoder[-1]),
                    "l2": dense(self.n_hidden_decoder[-1],
                                self.n_hidden_decoder[-1]),
                    "out": dense(self.n_hidden_decoder[-1], x_dim)}
        if self._output_bias is not None:
            self.out["out"]["b"].assign(
                np.asarray(self._output_bias, np.float32))
        self.optimizer = None

    # ------------------------------------------------------------------
    # parameter plumbing
    # ------------------------------------------------------------------

    def _iter_dense_tree(self):
        """``(dense-param dict, jax-tree-path)`` pairs — same correspondence
        contract as torch_ref._iter_linear_tree (layout already [in, out])."""
        for group, blocks in (("enc", self.enc), ("dec", self.dec)):
            for i, blk in enumerate(blocks):
                for nm in ("l1", "l2", "mu", "lstd"):
                    yield blk[nm], (group, i, nm)
        for nm in ("l1", "l2", "out"):
            yield self.out[nm], ("out", nm)

    def variables(self):
        out = []
        for d, _ in self._iter_dense_tree():
            out.extend([d["w"], d["b"]])
        return out

    def _param_groups(self):
        enc, rest = [], []
        for d, path in self._iter_dense_tree():
            (enc if path[0] == "enc" else rest).extend([d["w"], d["b"]])
        return enc, rest

    def load_jax_params(self, params) -> "TF2FlexibleModel":
        """Copy a JAX param pytree (models/iwae.init_params layout) into this
        backend — weight-tied cross-backend parity testing. Same [in, out]
        kernel layout, so the copy is direct."""
        for d, path in self._iter_dense_tree():
            node = params
            for pkey in path:
                node = node[pkey]
            d["w"].assign(np.asarray(node["w"], np.float32))
            d["b"].assign(np.asarray(node["b"], np.float32))
        return self

    def _weights_pytree(self):
        """Weights in the JAX layout (kernels are already [in, out]) — feeds
        the shared api.FlexibleModel.save_weights/load_weights payload."""
        from iwae_replication_project_tpu.api import assemble_jax_tree
        return assemble_jax_tree(
            (path, {"w": d["w"].numpy(), "b": d["b"].numpy()})
            for d, path in self._iter_dense_tree())

    def _set_weights_pytree(self, tree):
        self.load_jax_params(tree)

    # ------------------------------------------------------------------
    # model math (parity constants of flexible_IWAE.py:75,102)
    # ------------------------------------------------------------------

    def _dense(self, d, x):
        return tf.linalg.matmul(x, d["w"]) + d["b"]

    def _block(self, blk, x):
        y = tf.tanh(self._dense(blk["l1"], x))
        y = tf.tanh(self._dense(blk["l2"], y))
        mu = self._dense(blk["mu"], y)
        std = tf.exp(self._dense(blk["lstd"], y)) + _STD_FLOOR
        return mu, std

    @staticmethod
    def _normal_log_prob(x, mu, std):
        z = (x - mu) / std
        return -0.5 * z * z - tf.math.log(std) - 0.5 * _LOG_2PI

    def _encode(self, x, k: int, stop_q_score: bool = False, masks=None):
        """Encoder pass; `masks` zeroes inactive latent coords after sampling,
        densities evaluated at the masked values (flexible_IWAE.py:466-494
        semantics, = evaluation/activity.py)."""
        sg = tf.stop_gradient if stop_q_score else (lambda t: t)
        mu, std = self._block(self.enc[0], x)
        h1 = mu + std * tf.random.normal((k,) + tuple(mu.shape))
        if masks is not None:
            h1 = h1 * masks[0]
        log_q = tf.reduce_sum(self._normal_log_prob(h1, sg(mu), sg(std)), -1)
        h = [h1]
        q_last = (mu, std)
        for i in range(1, self.L):
            mu, std = self._block(self.enc[i], h[-1])
            hi = mu + std * tf.random.normal(tf.shape(mu))
            if masks is not None:
                hi = hi * masks[i]
            log_q = log_q + tf.reduce_sum(
                self._normal_log_prob(hi, sg(mu), sg(std)), -1)
            h.append(hi)
            q_last = (mu, std)
        return h, log_q, q_last

    def _decode_probs(self, h1):
        y = tf.tanh(self._dense(self.out["l1"], h1))
        y = tf.tanh(self._dense(self.out["l2"], y))
        probs = tf.sigmoid(self._dense(self.out["out"], y))
        return probs * _PCLAMP_SCALE + _PCLAMP_SHIFT

    def _log_weights_aux(self, x, k: int, stop_q_score: bool = False,
                         masks=None):
        h, log_q, q_last = self._encode(x, k, stop_q_score=stop_q_score,
                                        masks=masks)
        probs = self._decode_probs(h[0])
        log_pxIh = tf.reduce_sum(
            x * tf.math.log(probs) + (1 - x) * tf.math.log1p(-probs), -1)
        log_ph = tf.reduce_sum(-0.5 * h[-1] ** 2 - 0.5 * _LOG_2PI, -1)
        for i in range(self.L - 1):
            mu, std = self._block(self.dec[i], h[self.L - 1 - i])
            log_ph = log_ph + tf.reduce_sum(
                self._normal_log_prob(h[self.L - 2 - i], mu, std), -1)
        return log_ph + log_pxIh - log_q, {"log_px_given_h": log_pxIh,
                                           "q_last": q_last, "h": h}

    def get_log_weights(self, x, n_samples: int):
        return self._log_weights_aux(self._flatten(x), n_samples)[0]

    # ------------------------------------------------------------------
    # bounds (same reducer family as objectives/estimators.py)
    # ------------------------------------------------------------------

    @staticmethod
    def _iwae(log_w):
        m = tf.stop_gradient(tf.reduce_max(log_w, axis=0, keepdims=True))
        return tf.reduce_mean(
            tf.math.log(tf.reduce_mean(tf.exp(log_w - m), axis=0)) + m[0])

    @staticmethod
    def _miwae(log_w, k2: int):
        k = log_w.shape[0]
        g = tf.reshape(log_w, (k2, k // k2) + tuple(log_w.shape[1:]))
        m = tf.stop_gradient(tf.reduce_max(g, axis=1, keepdims=True))
        return tf.reduce_mean(
            tf.math.log(tf.reduce_mean(tf.exp(g - m), axis=1)) + m[:, 0])

    def _bound(self, name, x, k, **over):
        x = self._flatten(x)
        log_w, aux = self._log_weights_aux(x, k)
        if name == "VAE":
            return tf.reduce_mean(log_w)
        if name == "IWAE":
            return self._iwae(log_w)
        if name == "L_power_p":
            p = over.get("p", self.p)
            return self._iwae(p * log_w) / p
        if name == "L_median":
            # interpolating median over the k axis (jnp.median semantics)
            s = tf.sort(log_w, axis=0)
            lo, hi = (k - 1) // 2, k // 2
            return tf.reduce_mean((s[lo] + s[hi]) / 2.0)
        if name == "CIWAE":
            b = over.get("beta", self.beta)
            return b * tf.reduce_mean(log_w) + (1 - b) * self._iwae(log_w)
        if name == "L_alpha":
            a = over.get("alpha", self.alpha)
            return ((1 - a) * tf.reduce_mean(aux["log_px_given_h"])
                    + a * tf.reduce_mean(log_w))
        if name == "MIWAE":
            return self._miwae(log_w, over.get("k2", self.k2))
        if name == "VAE_V1":
            if len(self.enc) > 1:
                raise ValueError(
                    "VAE_V1's analytic KL is defined for single-stochastic-"
                    "layer models only (flexible_IWAE.py:433); this model "
                    f"has {len(self.enc)} stochastic layers")
            mu, std = aux["q_last"]
            kl = tf.reduce_mean(tf.reduce_sum(
                -0.5 * (1 + 2 * tf.math.log(std) - mu ** 2 - std ** 2), -1))
            return tf.reduce_mean(aux["log_px_given_h"]) - kl
        raise NotImplementedError(
            f"objective {name!r} is not implemented in the tf2 backend")

    def get_L(self, x, k: int = 5000):
        return self._bound("VAE", x, k)

    def get_L_k(self, x, k: int):
        return self._bound("IWAE", x, k)

    def get_L_V1(self, x, n_samples: int):
        return self._bound("VAE_V1", x, n_samples)

    def get_L_alpha(self, x, n_samples: int, alpha: float):
        return self._bound("L_alpha", x, n_samples, alpha=alpha)

    def get_L_power_p(self, x, k: int, p: float):
        return self._bound("L_power_p", x, k, p=p)

    def get_L_median(self, x, k: int):
        return self._bound("L_median", x, k)

    def get_L_CIWAE(self, x, n_samples: int, beta: float):
        return self._bound("CIWAE", x, n_samples, beta=beta)

    def get_L_MIWAE(self, x, k1: int, k2: int):
        return self._bound("MIWAE", x, k1 * k2, k2=k2)

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------

    def compile(self, optimizer=None, learning_rate: float = 1e-3):
        self.optimizer = optimizer or tf.keras.optimizers.Adam(
            learning_rate=learning_rate, beta_1=0.9, beta_2=0.999,
            epsilon=1e-4)
        return self

    def set_learning_rate(self, lr: float):
        self.optimizer.learning_rate.assign(lr)

    def _estimator_value_and_grads(self, x, name: str, k: int, k2: int = 1):
        """DReG/STL/PIWAE gradients via surrogate scalars on a GradientTape
        (same derivation as torch_ref._estimator_value_and_grads). Returns
        ``(bound, variables, grads)`` as parallel lists (tf.Variable is not
        hashable in eager mode, so no dict keying)."""
        x = self._flatten(x)
        enc_v, rest_v = self._param_groups()
        varlist = enc_v + rest_v
        if name in ("DReG", "STL"):
            with tf.GradientTape(persistent=True) as tape:
                log_w, _ = self._log_weights_aux(x, k, stop_q_score=True)
                B = int(log_w.shape[1])
                w = tf.stop_gradient(tf.nn.softmax(log_w, axis=0))
                s_dec = tf.reduce_sum(w * log_w) / B
                s_enc = tf.reduce_sum(w ** 2 * log_w) / B
            bound = self._iwae(tf.stop_gradient(log_w))
            if name == "STL":
                grads = tape.gradient(s_dec, varlist)
            else:
                grads = (tape.gradient(s_enc, enc_v)
                         + tape.gradient(s_dec, rest_v))
            del tape
            return bound, varlist, grads
        if name == "PIWAE":
            with tf.GradientTape(persistent=True) as tape:
                log_w, _ = self._log_weights_aux(x, k)
                bound = self._iwae(log_w)
                miwae = self._miwae(log_w, k2)
            grads = tape.gradient(miwae, enc_v) + tape.gradient(bound, rest_v)
            del tape
            return bound, varlist, grads
        raise NotImplementedError(name)

    def train_step(self, x) -> Dict[str, float]:
        if self.optimizer is None:
            raise RuntimeError("call .compile() first")
        if self.loss_function in ("DReG", "STL", "PIWAE"):
            bound, varlist, grads = self._estimator_value_and_grads(
                x, self.loss_function, self.k, k2=self.k2)
            self.optimizer.apply_gradients(
                [(-g, v) for g, v in zip(grads, varlist) if g is not None])
            self.epoch += 1
            return {self.loss_function: float(-bound)}
        varlist = self.variables()
        with tf.GradientTape() as tape:
            loss = -self._bound(self.loss_function, x, self.k)
        grads = tape.gradient(loss, varlist)
        self.optimizer.apply_gradients(
            [(g, v) for g, v in zip(grads, varlist) if g is not None])
        self.epoch += 1
        return {self.loss_function: float(loss)}

    # fit() is the shared eager loop on the base facade
    # (api.FlexibleModel.fit); train_step accepts numpy via _flatten.

    # ------------------------------------------------------------------
    # evaluation surface (parity with flexible_IWAE.py:249-302, 466-526)
    # ------------------------------------------------------------------

    def _generate_from_top(self, h_top):
        h = h_top
        for i in range(self.L - 1):
            mu, std = self._block(self.dec[i], h)
            h = mu + std * tf.random.normal(tf.shape(mu))
        return self._decode_probs(h)

    def reconstructed_x_probs(self, x):
        h, _, _ = self._encode(self._flatten(x), 1)
        return self._generate_from_top(h[-1])

    def generate(self, n: int):
        h_top = tf.random.normal((1, n, self.n_latent_encoder[-1]))
        return self._generate_from_top(h_top)[0]

    def get_reconstruction_loss(self, x):
        x = self._flatten(x)
        probs = self.reconstructed_x_probs(x)
        lp = tf.reduce_sum(
            x * tf.math.log(probs) + (1 - x) * tf.math.log1p(-probs), -1)
        return -tf.reduce_mean(lp)

    def get_E_qhIx_log_pxIh(self, x, n_samples: int):
        _, aux = self._log_weights_aux(self._flatten(x), n_samples)
        return tf.reduce_mean(aux["log_px_given_h"])

    def get_Dkl_qhIx_ph(self, x, k: int):
        lw, aux = self._log_weights_aux(self._flatten(x), k)
        return tf.reduce_mean(aux["log_px_given_h"]) - tf.reduce_mean(lw)

    def get_Dkl_qhIx_phIx(self, x, k: int):
        return -(self._bound("VAE", x, k) + self.get_NLL(x))

    def get_NLL(self, x, k: int = 5000, chunk: int = 250):
        """Streaming large-k NLL, online logsumexp in O(chunk) memory."""
        from iwae_replication_project_tpu.evaluation.metrics import (
            largest_divisor_leq)
        chunk = largest_divisor_leq(k, chunk)
        x = self._flatten(x)
        n = int(x.shape[0])
        m = tf.fill((n,), -np.inf)
        s = tf.zeros((n,))
        for _ in range(k // chunk):
            lw, _ = self._log_weights_aux(x, chunk)
            cm = tf.maximum(m, tf.reduce_max(lw, axis=0))
            s = s * tf.exp(m - cm) + tf.reduce_sum(tf.exp(lw - cm), axis=0)
            m = cm
        return -tf.reduce_mean(tf.math.log(s / k) + m)

    def get_levels_of_units_activity(self, x, n_samples: int, chunk: int = 10):
        x = self._flatten(x)
        n = int(x.shape[0])
        sums = [tf.zeros((n, d)) for d in self.n_latent_encoder]
        done = 0
        while done < n_samples:
            c = min(chunk, n_samples - done)
            h, _, _ = self._encode(x, c)
            for j, hj in enumerate(h):
                sums[j] = sums[j] + tf.reduce_sum(hj, axis=0)
            done += c
        means = [s / n_samples for s in sums]
        variances = [tf.math.reduce_variance(mn, axis=0) for mn in means]
        eig = [self.get_eigenvalues_PCA(mn) for mn in means]
        return variances, eig

    def get_eigenvalues_PCA(self, data):
        data = tf.convert_to_tensor(np.asarray(data), tf.float32)
        centered = data - tf.reduce_mean(data, axis=0)
        cov = tf.linalg.matmul(centered, centered, transpose_a=True) \
            / float(data.shape[0])
        return tf.linalg.eigvalsh(cov)

    def get_active_units(self, variances, eigen_values, threshold: float = 0.01):
        masks = [tf.cast(v > threshold, tf.float32) for v in variances]
        n_active = [int(tf.reduce_sum(mk)) for mk in masks]
        n_pca = [int(tf.reduce_sum(tf.cast(e > threshold, tf.int32)))
                 for e in eigen_values]
        return masks, n_active, n_pca

    def _masked_log_weights(self, x, masks, k: int):
        return self._log_weights_aux(x, k, masks=masks)[0]

    def get_NLL_without_inactive_units(self, x, threshold: float = 0.01,
                                       n_samples: int = 5000,
                                       activity_samples: int = 1000,
                                       chunk: int = 250):
        from iwae_replication_project_tpu.evaluation.metrics import (
            largest_divisor_leq)
        x = self._flatten(x)
        variances, eig = self.get_levels_of_units_activity(x, activity_samples)
        masks, _, _ = self.get_active_units(variances, eig, threshold)
        chunk = largest_divisor_leq(n_samples, chunk)
        n = int(x.shape[0])
        m = tf.fill((n,), -np.inf)
        s = tf.zeros((n,))
        for _ in range(n_samples // chunk):
            lw = self._masked_log_weights(x, masks, chunk)
            cm = tf.maximum(m, tf.reduce_max(lw, axis=0))
            s = s * tf.exp(m - cm) + tf.reduce_sum(tf.exp(lw - cm), axis=0)
            m = cm
        return -tf.reduce_mean(tf.math.log(s / n_samples) + m)

    def get_training_statistics(self, x, k: int, batch_size: int = 100,
                                nll_k: int = 5000, nll_chunk: int = 250,
                                activity_samples: int = 1000,
                                activity_threshold: float = 0.01,
                                include_pruned_nll: bool = True):
        """Full eval driver, same schema as the JAX/torch paths
        (flexible_IWAE.py:496-526)."""
        from iwae_replication_project_tpu.evaluation.metrics import (
            largest_divisor_leq)
        x = self._flatten(x)
        n = int(x.shape[0])
        batch_size = largest_divisor_leq(n, batch_size)
        nll_chunk = largest_divisor_leq(nll_k, nll_chunk)
        n_batches = n // batch_size

        acc = {"VAE": 0.0, "IWAE": 0.0, "NLL": 0.0,
               "E_q(h|x)[log(p(x|h))]": 0.0, "D_kl(q(h|x),p(h))": 0.0,
               "D_kl(q(h|x),p(h|x))": 0.0, "reconstruction_loss": 0.0,
               "nll_chunk": float(nll_chunk),
               "eval_batch": float(batch_size)}
        for i in range(n_batches):
            xb = x[i * batch_size:(i + 1) * batch_size]
            lw, aux = self._log_weights_aux(xb, k)
            vae = float(tf.reduce_mean(lw))
            recon_term = float(tf.reduce_mean(aux["log_px_given_h"]))
            nll = float(self.get_NLL(xb, k=nll_k, chunk=nll_chunk))
            acc["VAE"] += vae / n_batches
            acc["IWAE"] += float(self._iwae(lw)) / n_batches
            acc["NLL"] += nll / n_batches
            acc["E_q(h|x)[log(p(x|h))]"] += recon_term / n_batches
            acc["D_kl(q(h|x),p(h))"] += (recon_term - vae) / n_batches
            acc["D_kl(q(h|x),p(h|x))"] += (-nll - vae) / n_batches
            acc["reconstruction_loss"] += float(
                self.get_reconstruction_loss(xb)) / n_batches

        variances, eig = self.get_levels_of_units_activity(x, activity_samples)
        masks, n_active, n_pca = self.get_active_units(variances, eig,
                                                       activity_threshold)
        res2 = {"active_units": masks, "number_of_active_units": n_active,
                "number_of_PCA_active_units": n_pca, "variances": variances}
        if include_pruned_nll:
            acc["LL_pruned"] = float(self.get_NLL_without_inactive_units(
                x[:batch_size], activity_threshold, nll_k, activity_samples,
                nll_chunk))
        return acc, res2

    # tensorboard_log() is shared on the base facade (api.FlexibleModel).

    @staticmethod
    def _flatten(x):
        x = tf.convert_to_tensor(np.asarray(x, np.float32))
        return tf.reshape(x, (x.shape[0], -1))
