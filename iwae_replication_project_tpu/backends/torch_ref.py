"""Eager CPU oracle backend (torch) with the same semantics as the JAX path.

Role (an independent THIRD implementation — backends/tf2_ref.py restores the
reference's own eager-TF2 execution style, cf. flexible_IWAE.py:220's
commented-out @tf.function; this torch oracle shares no framework with either
the JAX path or the TF2 path, which is what makes its parity checks
meaningful):

1. an independent implementation for cross-backend parity tests — same
   architecture, same clamps (prob clamp 1e-6/1e-7, std floor 1e-6), same
   Adam(eps=1e-4) — any systematic bug in the JAX path shows up as a
   divergence here;
2. the measured CPU-eager baseline for bench.py's ``vs_baseline`` speedup
   (BASELINE.md: no published throughput; the >=10x target is against a fresh
   eager-CPU run).

Per-op autograd, dynamic dispatch, no fusion — deliberately the execution
model the reference used.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import torch

from iwae_replication_project_tpu.api import FlexibleModel

_PCLAMP_SCALE = 1.0 - 1e-6
_PCLAMP_SHIFT = 1e-7
_STD_FLOOR = 1e-6


class _StochasticBlock(torch.nn.Module):
    def __init__(self, in_dim: int, hidden: int, latent: int):
        super().__init__()
        self.l1 = torch.nn.Linear(in_dim, hidden)
        self.l2 = torch.nn.Linear(hidden, hidden)
        self.mu = torch.nn.Linear(hidden, latent)
        self.lstd = torch.nn.Linear(hidden, latent)

    def forward(self, x):
        y = torch.tanh(self.l1(x))
        y = torch.tanh(self.l2(y))
        return self.mu(y), torch.exp(self.lstd(y)) + _STD_FLOOR


def _normal_log_prob(x, mu, std):
    z = (x - mu) / std
    return -0.5 * z * z - torch.log(std) - 0.5 * float(np.log(2 * np.pi))


class TorchFlexibleModel(FlexibleModel):
    def __init__(self, *args, mesh=None, mesh_sp: int = 1, compute_dtype=None,
                 likelihood: str = "clamp", **kwargs):
        # accept (and ignore) the jax-backend execution kwargs so callers can
        # flip backend= without changing anything else; unknown kwargs raise
        super().__init__(*args, **kwargs)
        torch.manual_seed(self.seed)
        L = len(self.n_hidden_encoder)
        self.L = L
        enc, in_dim = [], self.n_latent_decoder[-1]
        for i in range(L):
            enc.append(_StochasticBlock(in_dim, self.n_hidden_encoder[i],
                                        self.n_latent_encoder[i]))
            in_dim = self.n_latent_encoder[i]
        self.enc = torch.nn.ModuleList(enc)
        dec, in_dim = [], self.n_latent_encoder[-1]
        for i in range(L - 1):
            dec.append(_StochasticBlock(in_dim, self.n_hidden_decoder[i],
                                        self.n_latent_decoder[i]))
            in_dim = self.n_latent_decoder[i]
        self.dec = torch.nn.ModuleList(dec)
        out_dim = self.n_latent_decoder[-1]
        self.out = torch.nn.Sequential(
            torch.nn.Linear(in_dim, self.n_hidden_decoder[-1]), torch.nn.Tanh(),
            torch.nn.Linear(self.n_hidden_decoder[-1], self.n_hidden_decoder[-1]),
            torch.nn.Tanh(),
            torch.nn.Linear(self.n_hidden_decoder[-1], out_dim))
        if self._output_bias is not None:
            with torch.no_grad():
                self.out[-1].bias.copy_(torch.from_numpy(
                    np.asarray(self._output_bias, np.float32)))
        self.optimizer: Optional[torch.optim.Optimizer] = None

    # ------------------------------------------------------------------

    def compile(self, optimizer=None, learning_rate: float = 1e-3):
        params = list(self.enc.parameters()) + list(self.dec.parameters()) \
            + list(self.out.parameters())
        self.optimizer = optimizer or torch.optim.Adam(
            params, lr=learning_rate, betas=(0.9, 0.999), eps=1e-4)
        return self

    def set_learning_rate(self, lr: float):
        for g in self.optimizer.param_groups:
            g["lr"] = lr

    def _encode(self, x, k: int, stop_q_score: bool = False, h_fixed=None,
                masks=None):
        """Encoder pass. `stop_q_score` detaches the density parameters inside
        log q while keeping the pathwise sample dependence (the score-term
        removal of STL/DReG). `h_fixed` replays given latent values through the
        reparameterization (eps recovered with detached moments) so gradients
        can be compared against another backend's draw-for-draw. `masks`
        zeroes inactive latent coords after sampling, densities evaluated at
        the masked values (flexible_IWAE.py:466-494 semantics,
        = evaluation/activity.py).
        """
        sg = (lambda t: t.detach()) if stop_q_score else (lambda t: t)

        def draw(mu, std, i, shape):
            if h_fixed is None:
                return mu + std * torch.randn(shape)
            given = torch.as_tensor(np.array(h_fixed[i], dtype=np.float32))
            if tuple(given.shape) != tuple(shape):
                raise ValueError(
                    f"h_fixed[{i}] has shape {tuple(given.shape)}, expected "
                    f"{tuple(shape)} — k / latent sizes of the replayed draws "
                    f"must match this model")
            eps = ((given - mu) / std).detach()
            return mu + std * eps

        mu, std = self.enc[0](x)
        h1 = draw(mu, std, 0, (k,) + mu.shape)
        if masks is not None:
            h1 = h1 * masks[0]
        log_q = _normal_log_prob(h1, sg(mu), sg(std)).sum(-1)
        h = [h1]
        q_last = (mu, std)
        for i in range(1, self.L):
            mu, std = self.enc[i](h[-1])
            hi = draw(mu, std, i, mu.shape)
            if masks is not None:
                hi = hi * masks[i]
            log_q = log_q + _normal_log_prob(hi, sg(mu), sg(std)).sum(-1)
            h.append(hi)
            q_last = (mu, std)
        return h, log_q, q_last

    def _decode_probs(self, h1):
        probs = torch.sigmoid(self.out(h1))
        return probs * _PCLAMP_SCALE + _PCLAMP_SHIFT

    def _log_weights_aux(self, x, k: int, stop_q_score: bool = False,
                         h_fixed=None, masks=None):
        h, log_q, q_last = self._encode(x, k, stop_q_score=stop_q_score,
                                        h_fixed=h_fixed, masks=masks)
        probs = self._decode_probs(h[0])
        log_pxIh = (x * torch.log(probs) + (1 - x) * torch.log1p(-probs)).sum(-1)
        log_ph = (-0.5 * h[-1] ** 2 - 0.5 * float(np.log(2 * np.pi))).sum(-1)
        for i in range(self.L - 1):
            mu, std = self.dec[i](h[self.L - 1 - i])
            log_ph = log_ph + _normal_log_prob(h[self.L - 2 - i], mu, std).sum(-1)
        return log_ph + log_pxIh - log_q, {"log_px_given_h": log_pxIh,
                                           "q_last": q_last, "h": h}

    def get_log_weights(self, x, n_samples: int):
        return self._log_weights_aux(self._flatten(x), n_samples)[0]

    @staticmethod
    def _iwae(log_w):
        m = log_w.max(dim=0, keepdim=True).values.detach()
        return (torch.log(torch.exp(log_w - m).mean(0)) + m[0]).mean()

    @staticmethod
    def _miwae(log_w, k2: int):
        """Mean of k2 independent IWAE(k//k2) bounds, group-major reshape."""
        g = log_w.reshape(k2, log_w.shape[0] // k2, *log_w.shape[1:])
        m = g.max(dim=1, keepdim=True).values.detach()
        return (torch.log(torch.exp(g - m).mean(1)) + m[:, 0]).mean()

    def _bound(self, name, x, k, **over):
        x = self._flatten(x)
        log_w, aux = self._log_weights_aux(x, k)
        if name == "VAE":
            return log_w.mean()
        if name == "IWAE":
            return self._iwae(log_w)
        if name == "L_power_p":
            p = over.get("p", self.p)
            return self._iwae(p * log_w) / p
        if name == "L_median":
            return log_w.median(dim=0).values.mean()
        if name == "CIWAE":
            b = over.get("beta", self.beta)
            return b * log_w.mean() + (1 - b) * self._iwae(log_w)
        if name == "L_alpha":
            a = over.get("alpha", self.alpha)
            return (1 - a) * aux["log_px_given_h"].mean() + a * log_w.mean()
        if name == "MIWAE":
            return self._miwae(log_w, over.get("k2", self.k2))
        if name == "VAE_V1":
            if len(self.enc) > 1:
                raise ValueError(
                    "VAE_V1's analytic KL is defined for single-stochastic-"
                    "layer models only (flexible_IWAE.py:433); this model "
                    f"has {len(self.enc)} stochastic layers")
            mu, std = aux["q_last"]
            kl = (-0.5 * (1 + 2 * torch.log(std) - mu ** 2 - std ** 2)).sum(-1).mean()
            return aux["log_px_given_h"].mean() - kl
        raise NotImplementedError(
            f"objective {name!r} is not implemented in the torch oracle backend")

    # ------------------------------------------------------------------
    # modified-gradient estimators (DReG / STL / PIWAE)
    #
    # Independent oracle for objectives/gradients.py:64-109: where the JAX
    # path hand-rolls VJP cotangents on the [k, B] log-weight tensor, this
    # backend derives the same gradients from torch *autograd* on surrogate
    # scalars (Roeder et al. 2017; Tucker et al. 2018; Rainforth et al. 2018
    # — PAPERS.md), so a subtle cotangent bug cannot hide in both.
    # ------------------------------------------------------------------

    def _param_groups(self):
        enc = list(self.enc.parameters())
        rest = list(self.dec.parameters()) + list(self.out.parameters())
        return enc, rest

    def _estimator_value_and_grads(self, x, name: str, k: int, k2: int = 1,
                                   h_fixed=None):
        """``(bound, {param: grad})`` for DReG/STL/PIWAE.

        * STL: autograd of the IWAE bound on the score-stopped graph —
          surrogate sum_i sg(w~_i) log w_i / B.
        * DReG: encoder surrogate uses sg(w~_i^2), decoder sg(w~_i), both on
          the score-stopped graph.
        * PIWAE: decoder from the full-k IWAE bound, encoder from the
          MIWAE(k1, k2) bound, one shared (standard, score-carrying) graph.
        """
        x = self._flatten(x)
        enc_p, rest_p = self._param_groups()
        grads: Dict = {}
        if name in ("DReG", "STL"):
            log_w, _ = self._log_weights_aux(x, k, stop_q_score=True,
                                             h_fixed=h_fixed)
            B = log_w.shape[1]
            w = torch.softmax(log_w, dim=0).detach()
            bound = self._iwae(log_w).detach()
            s_dec = (w * log_w).sum() / B
            if name == "STL":
                g = torch.autograd.grad(s_dec, enc_p + rest_p)
                grads.update(zip(enc_p + rest_p, g))
            else:
                s_enc = (w.pow(2) * log_w).sum() / B
                g_enc = torch.autograd.grad(s_enc, enc_p, retain_graph=True)
                g_dec = torch.autograd.grad(s_dec, rest_p)
                grads.update(zip(enc_p, g_enc))
                grads.update(zip(rest_p, g_dec))
        elif name == "PIWAE":
            log_w, _ = self._log_weights_aux(x, k, h_fixed=h_fixed)
            bound = self._iwae(log_w)
            g_dec = torch.autograd.grad(bound, rest_p, retain_graph=True)
            g_enc = torch.autograd.grad(self._miwae(log_w, k2), enc_p)
            grads.update(zip(enc_p, g_enc))
            grads.update(zip(rest_p, g_dec))
            bound = bound.detach()
        else:
            raise NotImplementedError(name)
        return bound, grads

    def _iter_linear_tree(self):
        """Yield ``(torch.nn.Linear, jax-tree-path)`` pairs — the single
        source of truth for the torch-module <-> JAX-pytree correspondence
        (drives both load_jax_params and the gradient export)."""
        for group, blocks in (("enc", self.enc), ("dec", self.dec)):
            for i, blk in enumerate(blocks):
                for nm in ("l1", "l2", "mu", "lstd"):
                    yield getattr(blk, nm), (group, i, nm)
        for idx, nm in ((0, "l1"), (2, "l2"), (4, "out")):
            yield self.out[idx], ("out", nm)

    def estimator_gradients_as_jax_tree(self, x, name: str, k: int,
                                        k2: int = 1, h_fixed=None):
        """``(bound, grad-pytree)`` in the JAX param layout (``w`` transposed
        back to ``[in, out]``) — the cross-backend gradient-parity hook.
        `h_fixed` should be the latents from the JAX forward (aux["h"]) so
        both backends differentiate the same realized reparameterization."""
        bound, grads = self._estimator_value_and_grads(x, name, k, k2=k2,
                                                       h_fixed=h_fixed)
        return float(bound), self._jax_tree(
            lambda lin: {"w": np.asarray(grads[lin.weight].detach()).T.copy(),
                         "b": np.asarray(grads[lin.bias].detach()).copy()})

    def _jax_tree(self, leaf_fn):
        """Pytree in the models/iwae.init_params layout from one ``{"w","b"}``
        leaf per Linear (``w`` already transposed to ``[in, out]`` by
        `leaf_fn`)."""
        from iwae_replication_project_tpu.api import assemble_jax_tree
        return assemble_jax_tree((path, leaf_fn(lin))
                                 for lin, path in self._iter_linear_tree())

    def _weights_pytree(self):
        return self._jax_tree(
            lambda lin: {"w": np.asarray(lin.weight.detach()).T.copy(),
                         "b": np.asarray(lin.bias.detach()).copy()})

    def _set_weights_pytree(self, tree):
        self.load_jax_params(tree)

    def _eval_bound(self, name, x, k, **over):
        """Public bound getters are evaluation surface — no autograd graph
        (and no `float(requires_grad tensor)` warnings downstream)."""
        with torch.no_grad():
            return self._bound(name, x, k, **over)

    def get_L(self, x, k: int = 5000):
        return self._eval_bound("VAE", x, k)

    def get_L_k(self, x, k: int):
        return self._eval_bound("IWAE", x, k)

    def get_L_V1(self, x, n_samples: int):
        return self._eval_bound("VAE_V1", x, n_samples)

    def get_L_alpha(self, x, n_samples: int, alpha: float):
        return self._eval_bound("L_alpha", x, n_samples, alpha=alpha)

    def get_L_power_p(self, x, k: int, p: float):
        return self._eval_bound("L_power_p", x, k, p=p)

    def get_L_median(self, x, k: int):
        return self._eval_bound("L_median", x, k)

    def get_L_CIWAE(self, x, n_samples: int, beta: float):
        return self._eval_bound("CIWAE", x, n_samples, beta=beta)

    def get_L_MIWAE(self, x, k1: int, k2: int):
        return self._eval_bound("MIWAE", x, k1 * k2, k2=k2)

    def train_step(self, x) -> Dict[str, float]:
        if self.optimizer is None:
            raise RuntimeError("call .compile() first")
        if self.loss_function in ("DReG", "STL", "PIWAE"):
            bound, grads = self._estimator_value_and_grads(
                x, self.loss_function, self.k, k2=self.k2)
            self.optimizer.zero_grad()
            for p, g in grads.items():
                p.grad = -g  # ascend the bound
            self.optimizer.step()
            self.epoch += 1
            return {self.loss_function: float(-bound)}
        loss = -self._bound(self.loss_function, x, self.k)
        self.optimizer.zero_grad()
        loss.backward()
        self.optimizer.step()
        self.epoch += 1
        return {self.loss_function: float(loss.detach())}

    # fit() is the shared eager loop on the base facade
    # (api.FlexibleModel.fit); train_step accepts numpy via _flatten.

    # ------------------------------------------------------------------
    # evaluation surface (parity with flexible_IWAE.py:249-302, 466-526)
    # ------------------------------------------------------------------

    def _generate_from_top(self, h_top):
        """Ancestral sampling from the deepest latent (flexible_IWAE.py:107-118)."""
        h = h_top
        for i in range(self.L - 1):
            mu, std = self.dec[i](h)
            h = mu + std * torch.randn(mu.shape)
        return self._decode_probs(h)

    def reconstructed_x_probs(self, x):
        """1-sample encode + ancestral decode (flexible_IWAE.py:249-254)."""
        with torch.no_grad():
            h, _, _ = self._encode(self._flatten(x), 1)
            return self._generate_from_top(h[-1])

    def generate(self, n: int):
        """Prior samples -> pixel probs ``[n, x_dim]``."""
        with torch.no_grad():
            h_top = torch.randn(1, n, self.n_latent_encoder[-1])
            return self._generate_from_top(h_top)[0]

    def get_reconstruction_loss(self, x):
        """Pixel BCE of the reconstruction (flexible_IWAE.py:256-262)."""
        x = self._flatten(x)
        with torch.no_grad():
            probs = self.reconstructed_x_probs(x)
            lp = (x * torch.log(probs) + (1 - x) * torch.log1p(-probs)).sum(-1)
            return -lp.mean()

    def get_E_qhIx_log_pxIh(self, x, n_samples: int):
        with torch.no_grad():
            _, aux = self._log_weights_aux(self._flatten(x), n_samples)
            return aux["log_px_given_h"].mean()

    def get_Dkl_qhIx_ph(self, x, k: int):
        """E_q[log p(x|h)] - L, one pass (flexible_IWAE.py:414-415)."""
        with torch.no_grad():
            lw, aux = self._log_weights_aux(self._flatten(x), k)
            return aux["log_px_given_h"].mean() - lw.mean()

    def get_Dkl_qhIx_phIx(self, x, k: int):
        """L_5000 - L (flexible_IWAE.py:411-412)."""
        with torch.no_grad():
            return -(self._bound("VAE", x, k) + self.get_NLL(x))

    def get_levels_of_units_activity(self, x, n_samples: int, chunk: int = 10):
        """MC posterior means -> per-unit variances + PCA eigenvalues
        (flexible_IWAE.py:264-291), chunked like the reference's 1000 passes."""
        x = self._flatten(x)
        with torch.no_grad():
            sums = [torch.zeros(x.shape[0], d) for d in self.n_latent_encoder]
            done = 0
            while done < n_samples:
                c = min(chunk, n_samples - done)
                h, _, _ = self._encode(x, c)
                for j, hj in enumerate(h):
                    sums[j] += hj.sum(0)
                done += c
            means = [s / n_samples for s in sums]
            variances = [m.var(dim=0, unbiased=False) for m in means]
            eig = [self.get_eigenvalues_PCA(m) for m in means]
            return variances, eig

    def get_eigenvalues_PCA(self, data):
        data = torch.as_tensor(np.asarray(data), dtype=torch.float32)
        centered = data - data.mean(0)
        cov = centered.T @ centered / data.shape[0]
        return torch.linalg.eigvalsh(cov)

    def get_active_units(self, variances, eigen_values, threshold: float = 0.01):
        masks = [(v > threshold).float() for v in variances]
        n_active = [int(m.sum()) for m in masks]
        n_pca = [int((e > threshold).sum()) for e in eigen_values]
        return masks, n_active, n_pca

    def _masked_log_weights(self, x, masks, k: int):
        return self._log_weights_aux(x, k, masks=masks)[0]

    def get_NLL_without_inactive_units(self, x, threshold: float = 0.01,
                                       n_samples: int = 5000,
                                       activity_samples: int = 1000,
                                       chunk: int = 250):
        x = self._flatten(x)
        variances, eig = self.get_levels_of_units_activity(x, activity_samples)
        masks, _, _ = self.get_active_units(variances, eig, threshold)
        chunk = min(chunk, n_samples)
        with torch.no_grad():
            m = torch.full((x.shape[0],), -float("inf"))
            s = torch.zeros(x.shape[0])
            done = 0
            while done < n_samples:
                c = min(chunk, n_samples - done)
                lw = self._masked_log_weights(x, masks, c)
                cm = torch.maximum(m, lw.max(0).values)
                s = s * torch.exp(m - cm) + torch.exp(lw - cm).sum(0)
                m = cm
                done += c
            return -(torch.log(s / n_samples) + m).mean()

    def get_training_statistics(self, x, k: int, batch_size: int = 100,
                                nll_k: int = 5000, nll_chunk: int = 250,
                                activity_samples: int = 1000,
                                activity_threshold: float = 0.01,
                                include_pruned_nll: bool = True):
        """Full eval driver, same schema as the JAX path / the reference
        (flexible_IWAE.py:496-526). One log-weights pass feeds the per-batch
        scalars (the reference re-encodes ~7x)."""
        from iwae_replication_project_tpu.evaluation.metrics import (
            largest_divisor_leq)

        x = self._flatten(x)
        n = x.shape[0]
        batch_size = largest_divisor_leq(n, batch_size)
        nll_chunk = largest_divisor_leq(nll_k, nll_chunk)
        n_batches = n // batch_size

        acc = {"VAE": 0.0, "IWAE": 0.0, "NLL": 0.0,
               "E_q(h|x)[log(p(x|h))]": 0.0, "D_kl(q(h|x),p(h))": 0.0,
               "D_kl(q(h|x),p(h|x))": 0.0, "reconstruction_loss": 0.0,
               "nll_chunk": float(nll_chunk),
               "eval_batch": float(batch_size)}  # eval-RNG version stamp
        with torch.no_grad():
            for i in range(n_batches):
                xb = x[i * batch_size:(i + 1) * batch_size]
                lw, aux = self._log_weights_aux(xb, k)
                vae = float(lw.mean())
                recon_term = float(aux["log_px_given_h"].mean())
                nll = float(self.get_NLL(xb, k=nll_k, chunk=nll_chunk))
                acc["VAE"] += vae / n_batches
                acc["IWAE"] += float(self._iwae(lw)) / n_batches
                acc["NLL"] += nll / n_batches
                acc["E_q(h|x)[log(p(x|h))]"] += recon_term / n_batches
                acc["D_kl(q(h|x),p(h))"] += (recon_term - vae) / n_batches
                acc["D_kl(q(h|x),p(h|x))"] += (-nll - vae) / n_batches
                acc["reconstruction_loss"] += float(
                    self.get_reconstruction_loss(xb)) / n_batches

        variances, eig = self.get_levels_of_units_activity(x, activity_samples)
        masks, n_active, n_pca = self.get_active_units(variances, eig,
                                                       activity_threshold)
        res2 = {"active_units": masks, "number_of_active_units": n_active,
                "number_of_PCA_active_units": n_pca, "variances": variances}
        if include_pruned_nll:
            acc["LL_pruned"] = float(self.get_NLL_without_inactive_units(
                x[:batch_size], activity_threshold, nll_k, activity_samples,
                nll_chunk))
        return acc, res2

    def load_jax_params(self, params) -> "TorchFlexibleModel":
        """Copy a JAX param pytree (models/iwae.init_params layout) into this
        oracle — weight-tied cross-backend parity testing. JAX kernels are
        ``[in, out]``; torch Linear stores ``[out, in]``."""
        for linear, path in self._iter_linear_tree():
            d = params
            for pkey in path:
                d = d[pkey]
            with torch.no_grad():
                linear.weight.copy_(torch.from_numpy(
                    np.ascontiguousarray(np.asarray(d["w"]).T)))
                linear.bias.copy_(torch.from_numpy(np.asarray(d["b"]).copy()))
        return self

    def get_NLL(self, x, k: int = 5000, chunk: int = 250):
        """Streaming large-k NLL (no_grad, chunked like the JAX path). A chunk
        that does not divide k is clamped to the largest divisor, matching the
        JAX facade."""
        from iwae_replication_project_tpu.evaluation.metrics import (
            largest_divisor_leq)
        chunk = largest_divisor_leq(k, chunk)
        x = self._flatten(x)
        with torch.no_grad():
            m = torch.full((x.shape[0],), -float("inf"))
            s = torch.zeros(x.shape[0])
            for _ in range(k // chunk):
                lw, _ = self._log_weights_aux(x, chunk)
                cm = torch.maximum(m, lw.max(0).values)
                s = s * torch.exp(m - cm) + torch.exp(lw - cm).sum(0)
                m = cm
            return -(torch.log(s / k) + m).mean()

    @staticmethod
    def _flatten(x):
        if isinstance(x, np.ndarray):
            x = torch.from_numpy(np.asarray(x, np.float32))
        x = x.float()
        return x.reshape(x.shape[0], -1)
