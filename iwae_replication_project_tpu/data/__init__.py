from iwae_replication_project_tpu.data.loaders import (
    DATASETS,
    Dataset,
    digits_labels,
    load_dataset,
    output_bias_from_pixel_means,
)
from iwae_replication_project_tpu.data.pipeline import (
    epoch_batches,
    Binarization,
)

__all__ = [
    "DATASETS",
    "Dataset",
    "digits_labels",
    "load_dataset",
    "output_bias_from_pixel_means",
    "epoch_batches",
    "Binarization",
]
