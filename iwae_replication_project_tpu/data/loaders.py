"""Dataset layer: MNIST / fixed-binarization MNIST / Fashion-MNIST / Omniglot.

Replaces the reference's mixture of `tfds.load`, `keras.datasets`, and
`scipy.io.loadmat("chardata.mat")` (experiment_example.py:25-31;
flexible_IWAE.py:147-175) with offline-first loaders: every dataset resolves
from a local `data_dir` (standard idx-ubyte / .npz / .amat / chardata.mat
formats), and a deterministic synthetic fallback exists for hermetic tests and
benchmarks (this build environment has no network egress).

Design fixes over the reference, per SURVEY.md §1 'structural quirk':

* the output-layer bias is computed HERE from training pixel means and passed
  into the model as a value — no dataset I/O inside model constructors;
* for fixed-binarization MNIST the reference deliberately uses *raw* MNIST
  means for the bias (flexible_IWAE.py:150-155); `output_bias` reproduces that
  policy via the `bias_means` field so NLL parity is preserved.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
import zlib
from typing import Optional, Tuple

import numpy as np

X_DIM = 28 * 28


@dataclasses.dataclass
class Dataset:
    """Host-side dataset: float32 arrays in [0, 1], shape [N, 784]."""

    name: str
    x_train: np.ndarray
    x_test: np.ndarray
    #: pixel means used for the decoder output-bias init. May come from a
    #: DIFFERENT source than x_train: the reference initializes the fixed-bin
    #: model with raw-MNIST means (flexible_IWAE.py:150-155).
    bias_means: np.ndarray
    #: "none" (already binary / leave as-is) or "stochastic" (re-binarize per
    #: batch — the Burda protocol the PDF p.13 flags as the discrepancy).
    binarization: str = "none"
    #: True when the named dataset was NOT found on disk and deterministic
    #: synthetic blobs were substituted — downstream results are not
    #: comparable to any published number.
    synthetic: bool = False
    #: where `bias_means` came from: "raw" = raw grayscale means (the
    #: reference's fixed-binarization policy, flexible_IWAE.py:150-155),
    #: "train" = means of x_train itself (the default for every other
    #: dataset, and the fallback when raw files are absent).
    bias_source: str = "train"

    @property
    def output_bias(self) -> np.ndarray:
        return output_bias_from_pixel_means(self.bias_means)


def output_bias_from_pixel_means(means: np.ndarray) -> np.ndarray:
    """logit of the clipped mean pixel value — the decoder's output-bias init
    (formula of flexible_IWAE.py:174)."""
    clipped = np.clip(means, 0.001, 0.999)
    return (-np.log(1.0 / clipped - 1.0)).astype(np.float32)


# ---------------------------------------------------------------------------
# Raw-format readers (all offline)
# ---------------------------------------------------------------------------

def _read_idx_images(path: str) -> np.ndarray:
    """MNIST/Fashion idx3-ubyte (optionally .gz) -> [N, 784] float32 in [0,1]."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad idx magic {magic}")
        buf = f.read(n * rows * cols)
    arr = np.frombuffer(buf, dtype=np.uint8).reshape(n, rows * cols)
    return arr.astype(np.float32) / 255.0


def _warn_loud(msg: str) -> None:
    """Banner on stderr + plain line on stdout — the same double-channel the
    synthetic-data fallback uses, so the warning survives both log captures."""
    import sys
    banner = "=" * 78
    print(f"{banner}\nWARNING: {msg}\n{banner}", file=sys.stderr, flush=True)
    print(f"WARNING: {msg}", flush=True)


def _find(data_dir: str, candidates) -> Optional[str]:
    for c in candidates:
        p = os.path.join(data_dir, c)
        if os.path.exists(p):
            return p
    return None


def _load_idx_pair(data_dir: str, train_names, test_names) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    tr = _find(data_dir, train_names)
    te = _find(data_dir, test_names)
    if tr is None or te is None:
        return None
    return _read_idx_images(tr), _read_idx_images(te)


def _load_npz(data_dir: str, names) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    p = _find(data_dir, names)
    if p is None:
        return None
    with np.load(p) as z:
        xtr = z["x_train"].reshape(-1, X_DIM).astype(np.float32)
        xte = z["x_test"].reshape(-1, X_DIM).astype(np.float32)
    if xtr.max() > 1.0:
        xtr, xte = xtr / 255.0, xte / 255.0
    return xtr, xte


def _load_amat(data_dir: str, train_names, test_names) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Larochelle-format binarized-MNIST .amat text files."""
    tr = _find(data_dir, train_names)
    te = _find(data_dir, test_names)
    if tr is None or te is None:
        return None
    return (np.loadtxt(tr, dtype=np.float32), np.loadtxt(te, dtype=np.float32))


def _load_omniglot_mat(data_dir: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Burda-split Omniglot `chardata.mat` (flexible_IWAE.py:164-165 uses the
    same file; parsed here with scipy if present, else a minimal .mat reader
    is out of scope -> require scipy)."""
    p = _find(data_dir, ["chardata.mat"])
    if p is None:
        return None
    import scipy.io as sio  # scipy ships in the image with jax

    d = sio.loadmat(p)
    xtr = d["data"].T.reshape(-1, X_DIM).astype(np.float32)
    xte = d["testdata"].T.reshape(-1, X_DIM).astype(np.float32)
    return xtr, xte


def _synthetic(name: str, n_train: int = 1024, n_test: int = 256,
               seed: int = 0, binary: bool = True
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic digit-like blobs: mixture of per-class pixel-probability
    templates. Keeps tests/benches hermetic and shape-true.

    ``binary=True`` samples pixels to {0,1} (fixed-binarization stand-in);
    ``binary=False`` returns the grayscale probabilities themselves, so
    datasets whose protocol is per-epoch stochastic binarization feed the
    re-binarization path values genuinely in (0,1) — with binary inputs,
    ``bernoulli(p)`` is the identity and the stochastic path would be
    exercised in name only."""
    rs = np.random.RandomState(seed + (zlib.crc32(name.encode()) % 1000))
    n_classes = 10
    yy, xx = np.mgrid[0:28, 0:28] / 27.0
    templates = []
    for c in range(n_classes):
        cx, cy = rs.uniform(0.25, 0.75, 2)
        r1, r2 = rs.uniform(0.05, 0.2, 2)
        blob = np.exp(-(((xx - cx) ** 2) / (2 * r1 ** 2) + ((yy - cy) ** 2) / (2 * r2 ** 2)))
        ring = np.exp(-((np.sqrt((xx - cx) ** 2 + (yy - cy) ** 2) - 0.25) ** 2) / 0.004)
        templates.append(np.clip(0.85 * blob + 0.6 * ring, 0.01, 0.95).ravel())
    templates = np.stack(templates)

    def sample(n, seed2):
        rs2 = np.random.RandomState(seed2)
        cls = rs2.randint(0, n_classes, n)
        probs = templates[cls]
        if not binary:
            return probs.astype(np.float32)
        return (rs2.uniform(size=probs.shape) < probs).astype(np.float32)

    return sample(n_train, seed + 1), sample(n_test, seed + 2)


# ---------------------------------------------------------------------------
# Public registry
# ---------------------------------------------------------------------------

DATASETS = ("binarized_mnist", "mnist", "fashion_mnist", "omniglot", "digits",
            "digits_gray")


#: train/test split point of the 1797 sklearn digits — shared by the image
#: arrays and digits_labels so the two can never drift apart
_DIGITS_N_TRAIN = 1500


def _digits_gray_arrays() -> Tuple[np.ndarray, np.ndarray]:
    """sklearn's bundled UCI optdigits as 28x28 grayscale intensities in
    [0, 1]: nearest-neighbor upsample 8x8 -> 32x32, center-crop to 28x28
    (the same geometry prep `digits` uses before its fixed draw)."""
    from sklearn.datasets import load_digits as _sk_load_digits

    d = _sk_load_digits()
    gray = d.images.astype(np.float32) / 16.0  # [1797, 8, 8] in [0, 1]
    up = np.repeat(np.repeat(gray, 4, axis=1), 4, axis=2)  # [N, 32, 32]
    up = up[:, 2:30, 2:30].reshape(-1, X_DIM)  # center-crop -> [N, 784]
    return up[:_DIGITS_N_TRAIN], up[_DIGITS_N_TRAIN:]


def _load_sklearn_digits(seed: int = 0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """REAL handwritten-digit data that ships inside scikit-learn (UCI
    optdigits, 1797 8x8 grayscale images) — the only real image dataset
    available in this zero-egress environment.

    Prepared to mirror the fixed-binarization MNIST protocol (PDF §3.1):
    grayscale prep (:func:`_digits_gray_arrays`), then ONE deterministic
    Bernoulli binarization (Larochelle-style fixed draw). Returns
    ``(x_train_bin, x_test_bin, raw_train_means)`` — the raw grayscale means
    feed the bias init, reproducing the reference's raw-means-for-fixed-bin
    policy (flexible_IWAE.py:150-155).
    """
    gray_train, gray_test = _digits_gray_arrays()
    up = np.concatenate([gray_train, gray_test])
    rs = np.random.RandomState(seed)
    binary = (rs.uniform(size=up.shape) < up).astype(np.float32)
    n_train = len(gray_train)
    return binary[:n_train], binary[n_train:], gray_train.mean(axis=0)

def digits_labels() -> Tuple[np.ndarray, np.ndarray]:
    """Class labels aligned with the `digits`/`digits_gray` train/test split
    (same first-1500/rest ordering as :func:`_digits_gray_arrays`) — for the
    latent-space figures (utils/viz.latent_scatter; the reference report's
    qualitative latent visualizations, PDF pp.16-17)."""
    from sklearn.datasets import load_digits as _sk_load_digits

    y = _sk_load_digits().target.astype(np.int64)
    return y[:_DIGITS_N_TRAIN], y[_DIGITS_N_TRAIN:]


_MNIST_TRAIN = ["train-images-idx3-ubyte", "train-images-idx3-ubyte.gz"]
_MNIST_TEST = ["t10k-images-idx3-ubyte", "t10k-images-idx3-ubyte.gz"]


def load_dataset(name: str, data_dir: str = "data", allow_synthetic: bool = True,
                 synthetic_sizes: Tuple[int, int] = (1024, 256)) -> Dataset:
    """Resolve `name` from local files in `data_dir`, else synthetic fallback.

    Binarization policy mirrors the reference experiments (PDF §3.1):
    fixed-bin MNIST ships binary; "mnist"/"fashion_mnist"/"omniglot" use
    per-batch stochastic binarization of the grayscale intensities.
    """
    name = name.lower()
    if name not in DATASETS:
        raise ValueError(f"unknown dataset {name!r}; choose from {DATASETS}")

    pair = None
    bias_means = None
    if name == "binarized_mnist":
        pair = (_load_amat(data_dir,
                           ["binarized_mnist_train.amat", "binarized_mnist-train.amat"],
                           ["binarized_mnist_test.amat", "binarized_mnist-test.amat"])
                or _load_npz(data_dir, ["binarized_mnist.npz"]))
        # bias uses RAW mnist means when available (flexible_IWAE.py:150-155)
        raw = (_load_idx_pair(os.path.join(data_dir, "mnist"), _MNIST_TRAIN, _MNIST_TEST)
               or _load_idx_pair(data_dir, _MNIST_TRAIN, _MNIST_TEST)
               or _load_npz(data_dir, ["mnist.npz"])
               or _load_npz(os.path.join(data_dir, "mnist"), ["mnist.npz"]))
        if raw is not None:
            bias_means = raw[0].mean(axis=0)
        binarization = "none"
    elif name in ("mnist", "fashion_mnist"):
        sub = os.path.join(data_dir, name)
        pair = (_load_idx_pair(sub, _MNIST_TRAIN, _MNIST_TEST)
                or _load_npz(data_dir, [f"{name}.npz"]))
        # root-level idx files are accepted for plain MNIST only — fashion
        # shares the idx filenames, so a root fallback would silently load the
        # wrong dataset
        if pair is None and name == "mnist":
            pair = _load_idx_pair(data_dir, _MNIST_TRAIN, _MNIST_TEST)
        binarization = "stochastic"
    elif name == "omniglot":
        pair = _load_omniglot_mat(data_dir) or _load_npz(data_dir, ["omniglot.npz"])
        binarization = "stochastic"
    elif name == "digits":  # bundled with scikit-learn, needs no data_dir
        xtr, xte, raw_means = _load_sklearn_digits()
        pair = (xtr, xte)
        bias_means = raw_means
        binarization = "none"
    else:  # digits_gray: the same real images under the PDF Table 2 protocol
        # (grayscale intensities kept; per-epoch stochastic re-binarization
        # on device, like the reference's "mnist"/"omniglot" datasets —
        # flexible_IWAE.py:147-175). Bias comes from the grayscale train
        # means, which for this dataset ARE the raw means.
        pair = _digits_gray_arrays()
        binarization = "stochastic"

    # The fixed-binarization bias policy is a known tenths-of-nats NLL lever
    # (flexible_IWAE.py:150-155): silently substituting binarized-train means
    # would make a replication attempt quietly diverge from the reference.
    if name == "binarized_mnist" and pair is not None and bias_means is None:
        _warn_loud(
            f"dataset 'binarized_mnist' loaded from {data_dir!r} WITHOUT raw "
            f"MNIST files alongside — the decoder output bias will fall back "
            f"to binarized-train pixel means instead of the reference's "
            f"raw-MNIST means (flexible_IWAE.py:150-155). NLL may differ from "
            f"published numbers by tenths of nats. Place raw idx files "
            f"({_MNIST_TRAIN[0]}[.gz] / {_MNIST_TEST[0]}[.gz]) or mnist.npz "
            f"in {data_dir!r} (or its mnist/ subdir) to restore the policy.")

    synthetic = False
    if pair is None:
        if not allow_synthetic:
            raise FileNotFoundError(
                f"dataset {name!r} not found under {data_dir!r} and synthetic "
                f"fallback disabled")
        synthetic = True
        # any bias means gathered from real raw files must not leak into the
        # synthetic run: initializing the decoder bias to real-MNIST pixel
        # means while training on blobs would both skew the fake run and let
        # metrics certify `raw_means_bias` on data the policy never saw
        bias_means = None
        _warn_loud(
            f"dataset {name!r} NOT FOUND under {data_dir!r} — substituting "
            f"SYNTHETIC blobs. Results are NOT comparable to published "
            f"numbers. Place real files in {data_dir!r} (see data/loaders.py "
            f"docstring / scripts/prepare_data.py) or pass "
            f"allow_synthetic=False to fail instead.")
        # stochastic-binarization datasets get grayscale synthetic values so
        # the per-epoch re-binarization path sees real (0,1) probabilities
        pair = _synthetic(name, *synthetic_sizes,
                          binary=binarization != "stochastic")

    x_train, x_test = pair
    bias_source = "raw"
    if bias_means is None:
        bias_means = x_train.mean(axis=0)
        bias_source = "train"
    return Dataset(name=name, x_train=x_train, x_test=x_test,
                   bias_means=bias_means, binarization=binarization,
                   synthetic=synthetic, bias_source=bias_source)
