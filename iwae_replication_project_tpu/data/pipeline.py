"""Host-side batching pipeline: shuffling, binarization policies, DP-sharding.

The reference trains with `keras.Model.fit(x_train, batch_size=100)` on the
full in-memory tensor (experiment_example.py:82) and binarizes once at load.
Here an epoch is a deterministic generator of device-ready batches:

* **shuffle**: a fresh permutation per epoch from a numpy RNG seeded by
  (seed, epoch) — reproducible and resumable;
* **binarization** (`Binarization`): "none" keeps the loaded values (the
  fixed-binarization protocol); "stochastic" redraws pixels ~ Bernoulli(x)
  every time a batch is served (the Burda protocol — the discrepancy the PDF
  flags on p.13, supported here as a first-class policy);
* **drop-remainder** static batch shapes so jit never re-traces.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


class Binarization:
    NONE = "none"
    STOCHASTIC = "stochastic"


def epoch_batches(x: np.ndarray, batch_size: int, epoch: int, seed: int = 0,
                  binarization: str = Binarization.NONE,
                  shuffle: bool = True) -> Iterator[np.ndarray]:
    """Yield ``[batch_size, 784]`` float32 batches for one pass over `x`."""
    n = x.shape[0] - (x.shape[0] % batch_size)
    rs = np.random.RandomState((seed * 100003 + epoch) % (2 ** 31))
    idx = rs.permutation(x.shape[0])[:n] if shuffle else np.arange(n)
    for start in range(0, n, batch_size):
        batch = x[idx[start:start + batch_size]]
        if binarization == Binarization.STOCHASTIC:
            batch = (rs.uniform(size=batch.shape) < batch).astype(np.float32)
        else:
            batch = batch.astype(np.float32, copy=False)
        yield batch
