from iwae_replication_project_tpu.evaluation.metrics import (
    batch_metrics,
    streaming_log_px,
    streaming_nll,
    reconstruction_loss,
    training_statistics,
)
from iwae_replication_project_tpu.evaluation.activity import (
    posterior_mean_activity,
    pca_eigenvalues,
    active_units,
    nll_without_inactive_units,
)

__all__ = [
    "batch_metrics",
    "streaming_log_px",
    "streaming_nll",
    "reconstruction_loss",
    "training_statistics",
    "posterior_mean_activity",
    "pca_eigenvalues",
    "active_units",
    "nll_without_inactive_units",
]
