"""Latent-unit activity diagnostics (Burda §C; flexible_IWAE.py:264-302,466-494).

A unit is *active* if the across-data variance of its posterior mean exceeds a
threshold (0.01). The reference estimates posterior means with 1000 separate
full-test-set eager encoder passes (flexible_IWAE.py:270-273); here the same
estimator runs as a `lax.scan` over sample-chunks of a single jitted program —
the k fan-out axis does the sampling, an online sum does the averaging, so
memory is O(chunk * B * d) and the MXU sees large batched matmuls.
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.models.mlp import stochastic_block_apply
from iwae_replication_project_tpu.ops import distributions as dist
from iwae_replication_project_tpu.ops.logsumexp import (
    online_logsumexp_finalize,
    online_logsumexp_init,
    online_logsumexp_update,
)


@partial(jax.jit, static_argnames=("cfg", "n_samples", "chunk"))
def posterior_mean_activity(params, cfg: model.ModelConfig, key: jax.Array,
                            x: jax.Array, n_samples: int = 1000,
                            chunk: int = 10):
    """MC posterior means E_q[h_i | x] -> per-unit variances and PCA eigenvalues.

    Returns ``(variances, eigenvalues)``, tuples over stochastic layers with
    entries of shape ``[n_latent_enc[i]]`` — the inputs to :func:`active_units`.
    """
    if n_samples % chunk != 0:
        # largest divisor of n_samples not exceeding the requested chunk
        chunk = max(d for d in range(1, min(chunk, n_samples) + 1)
                    if n_samples % d == 0)

    def body(sums, i):
        h, _, _ = model.encode(params, cfg, jax.random.fold_in(key, i), x, chunk)
        return tuple(s + jnp.sum(hi, axis=0) for s, hi in zip(sums, h)), None

    init = tuple(jnp.zeros((x.shape[0], d)) for d in cfg.n_latent_enc)
    sums, _ = lax.scan(body, init, jnp.arange(n_samples // chunk))
    means = tuple(s / n_samples for s in sums)

    variances = tuple(jnp.var(m, axis=0) for m in means)
    eigenvalues = tuple(pca_eigenvalues(m) for m in means)
    return variances, eigenvalues


def pca_eigenvalues(data: jax.Array) -> jax.Array:
    """Eigenvalues of the empirical covariance of ``[B, d]`` data
    (flexible_IWAE.py:284-291)."""
    centered = data - jnp.mean(data, axis=0)
    cov = (centered.T @ centered) / data.shape[0]
    return jnp.linalg.eigvalsh(cov)


def active_units(variances, eigenvalues, threshold: float = 0.01
                 ) -> Tuple[Tuple[jax.Array, ...], List[int], List[int]]:
    """0/1 masks per layer + raw and PCA active-unit counts
    (flexible_IWAE.py:294-302)."""
    masks = tuple((v > threshold).astype(jnp.float32) for v in variances)
    n_active = [int(jnp.sum(m)) for m in masks]
    n_active_pca = [int(jnp.sum(e > threshold)) for e in eigenvalues]
    return masks, n_active, n_active_pca


@partial(jax.jit, static_argnames=("cfg", "k"))
def _masked_log_weights(params, cfg: model.ModelConfig, key: jax.Array,
                        x: jax.Array, masks, k: int) -> jax.Array:
    """Log-weights with inactive latent coordinates zeroed after sampling,
    densities evaluated at the masked values (flexible_IWAE.py:466-494)."""
    keys = jax.random.split(key, cfg.n_stochastic)
    mu, std = stochastic_block_apply(params["enc"][0], x, cfg.std_floor,
                                     cfg.matmul_dtype)
    h1 = dist.normal_sample(keys[0], mu, std, sample_shape=(k,)) * masks[0]
    log_q = jnp.sum(dist.normal_log_prob(h1, mu, std), axis=-1)
    h = [h1]
    for i in range(1, cfg.n_stochastic):
        mu, std = stochastic_block_apply(params["enc"][i], h[-1], cfg.std_floor,
                                         cfg.matmul_dtype)
        hi = dist.normal_sample(keys[i], mu, std) * masks[i]
        log_q = log_q + jnp.sum(dist.normal_log_prob(hi, mu, std), axis=-1)
        h.append(hi)
    h = tuple(h)
    return (model.log_prior(params, cfg, h)
            + model.log_px_given_h(params, cfg, x, h[0]) - log_q)


@partial(jax.jit, static_argnames=("cfg", "k", "chunk"))
def nll_without_inactive_units(params, cfg: model.ModelConfig, key: jax.Array,
                               x: jax.Array, masks, k: int = 5000,
                               chunk: int = 250) -> jax.Array:
    """-L_k with pruned latents — the 'cost of pruning' diagnostic (PDF §4.2.1),
    streamed in k-chunks like the unpruned NLL. One XLA program (a `lax.scan`
    over chunks) rather than a host loop of per-chunk dispatches; the per-chunk
    RNG folds are unchanged. A chunk that does not divide k is clamped to the
    largest divisor (a silent k//chunk==0 would finalize an empty carry into
    NaN)."""
    from iwae_replication_project_tpu.evaluation.metrics import (
        largest_divisor_leq)
    chunk = largest_divisor_leq(k, chunk)

    def body(state, i):
        lw = _masked_log_weights(params, cfg, jax.random.fold_in(key, i), x,
                                 masks, chunk)
        return online_logsumexp_update(state, lw, axis=0), None

    init = online_logsumexp_init((x.shape[0],))
    state, _ = lax.scan(body, init, jnp.arange(k // chunk))
    return -jnp.mean(online_logsumexp_finalize(state, mean=True))
