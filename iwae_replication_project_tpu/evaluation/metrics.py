"""Evaluation metrics — all from ONE log-weights pass per batch.

The reference's ``get_training_statistics`` re-encodes the same batch ~7 times
(one model pass per metric, flexible_IWAE.py:512-519). Every scalar in that
suite is a deterministic function of the ``[k, B]`` log-weights and the
``[k, B]`` reconstruction term, so here a single pass feeds them all:

* VAE bound        = mean(log w)
* IWAE bound       = mean_B logmeanexp_k(log w)
* E_q[log p(x|h)]  = mean(log p(x|h))                    (flexible_IWAE.py:304-325)
* D_KL(q||p(h))    = E_q[log p(x|h)] - L_VAE             (:414-415)
* D_KL(q||p(h|x))  = L_5000 - L_VAE                      (:411-412)
* NLL              = -IWAE bound at k=5000               (:463-464)

The k=5000 NLL runs as a `lax.scan` over k-chunks with the online-logsumexp
carry (O(chunk) memory — the reference materializes [5000, B, 784] eagerly).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import estimators as est
from iwae_replication_project_tpu.ops import distributions as dist
from iwae_replication_project_tpu.ops.logsumexp import (
    online_logsumexp_finalize,
    online_logsumexp_init,
    online_logsumexp_update,
)


# canonical home: utils/flops.py (ops/hot_loop needs it too and cannot
# import evaluation/); re-exported here because this was its historical home
# and parallel/eval imports it from this module
from iwae_replication_project_tpu.utils.flops import largest_divisor_leq


@partial(jax.jit, static_argnames=("cfg", "k"))
def batch_metrics(params, cfg: model.ModelConfig, key: jax.Array, x: jax.Array,
                  k: int) -> Dict[str, jax.Array]:
    """The single-pass metric bundle (everything except the k=5000 quantities)."""
    log_w, aux = model.log_weights_and_aux(params, cfg, key, x, k)
    vae = est.vae_bound(log_w)
    iwae = est.iwae_bound(log_w)
    recon_term = jnp.mean(aux["log_px_given_h"])
    return {
        "VAE": vae,
        "IWAE": iwae,
        "E_q(h|x)[log(p(x|h))]": recon_term,
        "D_kl(q(h|x),p(h))": recon_term - vae,
    }


@partial(jax.jit, static_argnames=("cfg", "k", "chunk"))
def streaming_log_px(params, cfg: model.ModelConfig, key: jax.Array, x: jax.Array,
                     k: int = 5000, chunk: int = 250) -> jax.Array:
    """Per-example IWAE-k log-likelihood estimate ``[B]``, O(chunk) memory.

    Each scan iteration draws `chunk` fresh importance samples (independent key
    per chunk) and folds their partial logsumexp into the online carry.
    """
    if k % chunk != 0:
        raise ValueError(f"chunk={chunk} must divide k={k}")

    def body(state, i):
        lw = model.log_weights(params, cfg, jax.random.fold_in(key, i), x, chunk)
        return online_logsumexp_update(state, lw, axis=0), None

    init = online_logsumexp_init((x.shape[0],))
    state, _ = lax.scan(body, init, jnp.arange(k // chunk))
    return online_logsumexp_finalize(state, mean=True)


def streaming_nll(params, cfg: model.ModelConfig, key: jax.Array, x: jax.Array,
                  k: int = 5000, chunk: int = 250) -> jax.Array:
    """scalar NLL = -mean_B log p̂(x) (flexible_IWAE.py:463-464 semantics)."""
    return -jnp.mean(streaming_log_px(params, cfg, key, x, k=k, chunk=chunk))


@partial(jax.jit, static_argnames=("cfg",))
def reconstruction_loss(params, cfg: model.ModelConfig, key: jax.Array,
                        x: jax.Array) -> jax.Array:
    """Pixel BCE of the 1-sample ancestral reconstruction
    (flexible_IWAE.py:249-262): -mean_B sum_pix log p(x | recon probs)."""
    probs = model.reconstruct_probs(params, cfg, key, x)
    lp = dist.bernoulli_log_prob(x[None], probs)
    return -jnp.mean(jnp.sum(lp, axis=-1))


SCALAR_NAMES = ("VAE", "IWAE", "NLL", "E_q(h|x)[log(p(x|h))]",
                "D_kl(q(h|x),p(h))", "D_kl(q(h|x),p(h|x))",
                "reconstruction_loss")


@partial(jax.jit, static_argnames=("cfg", "k", "nll_k", "nll_chunk"))
def dataset_scalars(params, cfg: model.ModelConfig, key: jax.Array,
                    batches: jax.Array, k: int, nll_k: int,
                    nll_chunk: int) -> jax.Array:
    """All 7 reference eval scalars over ``[n_batches, B, d]`` batches in ONE
    XLA program — a `lax.scan` over batches wrapping the per-batch kernels.

    One dispatch + one host fetch for the whole test set. This matters beyond
    aesthetics: every separate dispatch through a remote-device transport costs
    ~10-15 ms regardless of the work inside (measured; see RESULTS.md), so the
    old per-batch loop (~10 dispatches + syncs per batch) was transport-bound
    at <1% of the device's capability. Returns the 7-vector in
    :data:`SCALAR_NAMES` order, averaged over batches.

    RNG structure per batch is identical to calling the per-batch kernels in a
    host loop (fold_in(key, batch_index) then a 3-way split), so the scalars
    match the pre-fusion driver to accumulation-order rounding.
    """
    def body(carry, inp):
        i, xb = inp
        bkey = jax.random.fold_in(key, i)
        k1, k2, k3 = jax.random.split(bkey, 3)
        m = batch_metrics(params, cfg, k1, xb, k)
        nll = -jnp.mean(streaming_log_px(params, cfg, k2, xb,
                                         k=nll_k, chunk=nll_chunk))
        rl = reconstruction_loss(params, cfg, k3, xb)
        vals = jnp.stack([
            m["VAE"], m["IWAE"], nll, m["E_q(h|x)[log(p(x|h))]"],
            m["D_kl(q(h|x),p(h))"],
            # L_5000 - L_VAE, cf. flexible_IWAE.py:411-412
            -nll - m["VAE"], rl,
        ])
        return carry + vals, None

    n_batches = batches.shape[0]
    tot, _ = lax.scan(body, jnp.zeros(len(SCALAR_NAMES)),
                      (jnp.arange(n_batches), batches))
    return tot / n_batches


def training_statistics(params, cfg: model.ModelConfig, key: jax.Array,
                        x_test: jax.Array, k: int, batch_size: int = 100,
                        nll_k: int = 5000, nll_chunk: int = 250,
                        activity_samples: int = 1000,
                        activity_threshold: float = 0.01,
                        include_pruned_nll: bool = True
                        ) -> Tuple[Dict[str, float], Dict[str, object]]:
    """The full eval driver (parity with flexible_IWAE.py:496-526).

    Returns ``(res, res2)``: `res` maps the 7 scalar names (reference schema,
    so downstream logging is drop-in) plus ``LL_pruned``; `res2` holds the
    active-unit structures. The whole suite is 3 device dispatches: the fused
    batch-scan (:func:`dataset_scalars`), the activity estimator, and the
    pruned NLL — the reference re-encodes per metric per batch
    (flexible_IWAE.py:512-519).
    """
    import iwae_replication_project_tpu.evaluation.activity as au

    n = x_test.shape[0]
    # adapt the requested sizes so the driver works for any test-set length /
    # NLL sample count (the reference hard-assumes 10 | n)
    batch_size = largest_divisor_leq(n, batch_size)
    nll_chunk = largest_divisor_leq(nll_k, nll_chunk)
    n_batches = n // batch_size
    batches = x_test.reshape(n_batches, batch_size, -1)

    # the per-stage eval program goes through the AOT executable registry
    # (utils/compile_cache.py): compiled once per (model config, eval spec,
    # shape) signature, reused across the 8 stages, and accounted in the
    # warm-path cache_stats() the driver stamps per stage
    from iwae_replication_project_tpu.utils.compile_cache import aot_call
    scalars = np.asarray(aot_call(
        "dataset_scalars", dataset_scalars, (params,),
        kwargs=dict(key=key, batches=batches),
        static_kwargs=dict(cfg=cfg, k=k, nll_k=nll_k, nll_chunk=nll_chunk),
        build_key=(cfg, k, nll_k, nll_chunk)))
    acc = {name: float(v) for name, v in zip(SCALAR_NAMES, scalars)}
    # the chunk and batch actually used version the eval RNG stream (both may
    # be clamped below the configured ask) — stamp them at the source so
    # every caller logs the true values
    acc["nll_chunk"] = float(nll_chunk)
    acc["eval_batch"] = float(batch_size)
    # which hot-loop path the chunked NLL scorer (the eval suite's dominant
    # pass) selects for THIS row's shape — recomputed per config, never read
    # from trace-order state (ops/hot_loop.PATH_CODES)
    from iwae_replication_project_tpu.ops.hot_loop import path_code_for_model
    acc["kernel_path"] = path_code_for_model(cfg, nll_chunk, batch_size,
                                             on_tpu=model._on_tpu())

    res2: Dict[str, object] = {}
    k_au, k_pruned = jax.random.split(jax.random.fold_in(key, n_batches))
    variances, eigvals = au.posterior_mean_activity(params, cfg, k_au,
                                                   x_test.reshape(n, -1),
                                                   n_samples=activity_samples)
    masks, n_active, n_active_pca = au.active_units(variances, eigvals,
                                                    threshold=activity_threshold)
    res2["active_units"] = masks
    res2["number_of_active_units"] = n_active
    res2["number_of_PCA_active_units"] = n_active_pca
    res2["variances"] = variances

    if include_pruned_nll:
        acc["LL_pruned"] = float(au.nll_without_inactive_units(
            params, cfg, k_pruned, batches[0], masks, k=nll_k, chunk=nll_chunk))
    return acc, res2
