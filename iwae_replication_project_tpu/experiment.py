"""The experiment driver: the reference's *intended* outer loop, made robust.

Reproduces the behavior reconstructed in SURVEY.md §3.5 (the committed script,
experiment_example.py:75-97, is Colab-truncated and does not run): for each of
8 stages, set the Burda LR, train 3^(i-1) passes, run the full eval suite, log
scalars, checkpoint. Differences by design:

* checkpoint = params + optimizer state + RNG + stage (Orbax), with
  resume-from-latest — the reference saves weights only and cannot resume;
* eval metrics stream from single-pass kernels (evaluation/metrics.py);
* execution is jit + optional (dp, sp) mesh sharding, selected by config.

Preemption grace (``cfg.preemption_grace``, on by default): SIGTERM/SIGINT
is absorbed into a flag (utils/faults.PreemptionGuard), the in-flight pass
finishes, a mid-stage checkpoint is force-saved, and the run raises
:class:`TrainingPreempted` (``main`` exits with the distinct
:data:`PREEMPTED_EXIT_CODE` = 75, EX_TEMPFAIL — "come back with the same
command"). The whole-epoch scan carries the RNG key, so the resumed run is
bitwise identical to an uninterrupted one (pinned by tests and the chaos
smoke) — including when the newest checkpoint was truncated by the kill,
because restore falls back to the newest intact retained step
(utils/checkpoint.py) and the deterministic replay redoes the difference.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Optional

import jax

from iwae_replication_project_tpu.data import load_dataset, epoch_batches
from iwae_replication_project_tpu.evaluation import metrics as ev
from iwae_replication_project_tpu.parallel.multihost import fetch
from iwae_replication_project_tpu.training import (
    burda_stages,
    create_train_state,
    make_adam,
)
from iwae_replication_project_tpu.training.train_step import set_learning_rate
from iwae_replication_project_tpu.utils.checkpoint import restore_latest, save_checkpoint
from iwae_replication_project_tpu.utils.compile_cache import (
    cache_stats,
    donation_allowed,
    mesh_fingerprint,
    setup_persistent_cache,
    stats_delta,
    warm_callable,
)
from iwae_replication_project_tpu.telemetry.registry import get_registry
from iwae_replication_project_tpu.telemetry.spans import span
from iwae_replication_project_tpu.utils.config import ExperimentConfig
from iwae_replication_project_tpu.utils.faults import (
    SITE_TRAIN_PASS,
    PreemptionGuard,
    fault_point,
)
from iwae_replication_project_tpu.utils.logging import MetricsLogger

#: passes fused into one dispatch for the long Burda stages; 27 = 3^3 divides
#: every stage length >= 27 of the 3^(i-1) schedule, so stages 4-8 run
#: entirely in blocks and only stages 1-3 (1+3+9 passes) dispatch per pass
PASS_BLOCK = 27

#: the distinct exit status of a gracefully preempted run (os.EX_TEMPFAIL:
#: "temporary failure — try again", which is exactly the contract: re-run
#: the same command and resume continues bitwise where the save left off)
PREEMPTED_EXIT_CODE = 75


class TrainingPreempted(RuntimeError):
    """A SIGTERM/SIGINT was absorbed, the pass finished, and a mid-stage
    checkpoint is durably saved; ``main`` maps this to
    :data:`PREEMPTED_EXIT_CODE`."""

    def __init__(self, stage: int, passes_done: int, step: int):
        super().__init__(
            f"preempted at stage {stage}, pass {passes_done} (step {step}); "
            f"mid-stage checkpoint saved — resume with the same command")
        self.stage = stage
        self.passes_done = passes_done
        self.step = step


def run_experiment(cfg: ExperimentConfig, max_batches_per_pass: Optional[int] = None,
                   eval_subset: Optional[int] = None):
    """Run the staged experiment; returns ``(state, results_history)``.

    `max_batches_per_pass` / `eval_subset` exist for smoke tests and CI — the
    full run is 3280 passes (PDF §3.4).
    """
    if cfg.backend in ("torch", "tf2"):
        if cfg.multihost:
            raise ValueError(
                "--multihost requires backend='jax' (the eager torch/tf2 "
                "backends are single-process oracles)")
        return _run_experiment_eager(cfg, max_batches_per_pass, eval_subset)
    if cfg.backend != "jax":
        # anything else: let the facade produce the canonical error
        from iwae_replication_project_tpu.api import FlexibleModel
        FlexibleModel([1], [1], [1], [1], backend=cfg.backend)
        raise AssertionError("unreachable")

    # warm path: persistent XLA compilation cache under the checkpoint root
    # (the one directory that survives a preemption), so a resumed run —
    # or the next stage of this one — pays zero recompiles. Config/env
    # override or disable it; utils/compile_cache.py is the single owner of
    # the jax.config wiring (a lint-guard test keeps it that way).
    setup_persistent_cache(cfg.compile_cache_dir, base_dir=cfg.checkpoint_dir)

    is_primary = True
    if cfg.multihost:
        # join the jax.distributed cluster BEFORE the first device
        # computation (jax.distributed refuses once a backend exists);
        # afterwards jax.devices() spans every process, so the mesh below
        # does too. Only the primary process writes artifacts — except
        # checkpoints, which Orbax coordinates across hosts itself.
        from iwae_replication_project_tpu.parallel import multihost
        multihost.initialize(coordinator_address=cfg.coordinator,
                             num_processes=cfg.num_processes,
                             process_id=cfg.process_id)
        info = multihost.process_info()
        print(f"multihost: {info}")
        is_primary = info["process_index"] == 0

    ds = load_dataset(cfg.dataset, data_dir=cfg.data_dir,
                      allow_synthetic=cfg.allow_synthetic)
    model_cfg = cfg.model_config()
    opt = make_adam(eps=cfg.adam_eps)

    state = create_train_state(jax.random.PRNGKey(cfg.seed), model_cfg,
                               output_bias=ds.output_bias, optimizer=opt)

    n_train = len(ds.x_train)
    if max_batches_per_pass is not None:
        n_train = min(n_train, max_batches_per_pass * cfg.batch_size)
    x_train_dev = jax.numpy.asarray(ds.x_train[:n_train].reshape(n_train, -1))

    mesh = None
    if cfg.multihost or cfg.mesh_dp is not None or cfg.mesh_sp > 1:
        # under --multihost the mesh is mandatory (mesh_dp=None spans all
        # global devices) — otherwise each process would silently train its
        # own duplicate single-device copy
        from iwae_replication_project_tpu.parallel import make_mesh
        from iwae_replication_project_tpu.parallel.dp import replicate
        mesh = make_mesh(dp=cfg.mesh_dp, sp=cfg.mesh_sp)
        state = replicate(mesh, state)
        x_train_dev = replicate(mesh, x_train_dev)

    # train functions are built per active objective (objective switching,
    # PDF Table 10, changes the spec mid-run) and cached. Either way a data
    # pass is ONE compiled dispatch (whole-epoch lax.scan — training/epoch.py
    # single-device, parallel/dp.py under the mesh), and the long late stages
    # batch PASS_BLOCK passes per dispatch: at small-dataset scale a pass is
    # ~5 ms of device work vs ~10-15 ms of per-dispatch transport, so stage 8
    # (3^7 = 2187 passes) would otherwise spend ~30 s on dispatch alone.
    #
    # Each function is AOT-compiled once per (program, arg-signature) via the
    # module-level executable registry (utils/compile_cache.py): the compiled
    # executable survives across stages and across run_experiment calls in
    # this process, and the state buffers are donated to each dispatch
    # (cfg.donate_buffers) — the old state is dead once the new one returns,
    # so XLA updates params/Adam moments in place instead of holding both.
    _fn_cache = {}
    stoch_bin = ds.binarization == "stochastic"
    # the donation-vs-cache hazard (jaxlib-0.4.x XLA:CPU corrupts memory
    # when donated programs are deserialized from the persistent cache) is
    # decided by the executable store — the ONE owner of executable
    # lifetime and cache wiring; the driver only states its request
    donate = donation_allowed(cfg.donate_buffers)
    mesh_key = mesh_fingerprint(mesh)
    # the DiagnosticsConfig gate (telemetry/diagnostics.py): a jit static
    # AND part of the AOT build key — on/off are distinct compiled programs
    diag_cfg = cfg.diagnostics_config()

    def epoch_fn_for(active_spec, epochs_per_call=1):
        cache_key = (active_spec, epochs_per_call)
        if cache_key in _fn_cache:
            return _fn_cache[cache_key]
        if mesh is not None:
            from iwae_replication_project_tpu.parallel.dp import make_parallel_epoch_fn
            fn = make_parallel_epoch_fn(
                active_spec, model_cfg, mesh, n_train, cfg.batch_size,
                stochastic_binarization=stoch_bin,
                optimizer=opt, donate=donate,
                epochs_per_call=epochs_per_call, diagnostics=diag_cfg)
        else:
            from iwae_replication_project_tpu.training.epoch import make_epoch_fn
            fn = make_epoch_fn(
                active_spec, model_cfg, n_train, cfg.batch_size,
                stochastic_binarization=stoch_bin,
                optimizer=opt, donate=donate,
                epochs_per_call=epochs_per_call, diagnostics=diag_cfg)
        fn = warm_callable(
            "parallel_epoch" if mesh is not None else "epoch", fn,
            build_key=(active_spec, model_cfg, epochs_per_call, n_train,
                       cfg.batch_size, stoch_bin, donate,
                       cfg.adam_eps, mesh_key, diag_cfg))
        _fn_cache[cache_key] = fn
        return fn

    ckpt_dir = os.path.join(cfg.checkpoint_dir, cfg.run_name())
    start_stage = 1
    start_offset = 0  # passes already done within start_stage (mid-stage resume)
    if cfg.resume:
        restored = restore_latest(ckpt_dir, state,
                                  expect_config_json=cfg.to_json())
        if restored is not None:
            _, state, ckpt_stage, passes_done = restored
            stage_lengths = {s: n for s, _, n in
                             burda_stages(cfg.n_stages, cfg.passes_scale)}
            if passes_done is not None and \
                    passes_done < stage_lengths.get(ckpt_stage, 0):
                start_stage, start_offset = ckpt_stage, passes_done
                if is_primary:
                    print(f"resumed from mid-stage checkpoint; continuing at "
                          f"stage {start_stage}, pass {start_offset + 1}")
            else:
                start_stage = ckpt_stage + 1
                if is_primary:
                    print(f"resumed from checkpoint; continuing at stage "
                          f"{start_stage}")
        else:
            # run_name() embeds a hash of the science fields, so checkpoints
            # written under an older naming scheme (or an edited config) are
            # invisible to resume. Surface near-miss directories loudly rather
            # than silently restarting from scratch (ADVICE r2).
            prefix = f"{cfg.loss_function}-{len(cfg.n_hidden_encoder)}L-k_{cfg.k}-"
            if os.path.isdir(cfg.checkpoint_dir):
                stale = [d for d in os.listdir(cfg.checkpoint_dir)
                         if d.startswith(prefix) and d != cfg.run_name()]
                if stale:
                    shown = ", ".join(stale[:3]) + (", ..." if len(stale) > 3
                                                    else "")
                    print(f"note: no checkpoint under {ckpt_dir}, but "
                          f"{len(stale)} same-prefix run dir(s) exist "
                          f"({shown}): they belong to a different config "
                          f"hash / naming scheme and will NOT be resumed")

    # lazy: a resumed-already-complete run must not touch the run directory
    # at all (no fresh tfevents file, no figure/pkl rewrites)
    logger = None
    telem_logger = None
    eval_key = jax.random.PRNGKey(cfg.seed + 10_000)
    x_test = ds.x_test[:eval_subset] if eval_subset else ds.x_test
    y_test = None
    if cfg.save_figures and cfg.dataset in ("digits", "digits_gray"):
        # labeled dataset -> also the latent-space view per stage
        # (reference report pp.16-17)
        from iwae_replication_project_tpu.data import digits_labels
        y_test = digits_labels()[1][:len(x_test)]
    results_history = []

    # preemption grace: SIGTERM/SIGINT -> flag; the pass boundaries below
    # check it, force-save a mid-stage checkpoint, and raise
    # TrainingPreempted. Inert off the main thread, and off entirely via
    # --no-preemption-grace (guard=None restores the die-immediately
    # behavior). The finally releases the signal handlers however the stage
    # loop exits.
    guard = PreemptionGuard().__enter__() if cfg.preemption_grace else None
    try:
        for stage, lr, passes in burda_stages(cfg.n_stages, cfg.passes_scale):
            if stage < start_stage:
                continue
            if logger is None and is_primary:
                logger = MetricsLogger(cfg.log_dir, run_name=cfg.run_name())
            state = set_learning_rate(state, lr)
            active_spec = cfg.objective_spec(stage)
            if is_primary:
                print(f"stage {stage}: lr={lr:.2e}, {passes} passes, "
                      f"objective {active_spec.name} k={active_spec.k}")
            offset = start_offset if stage == start_stage else 0
            done = offset          # passes completed within this stage
            since_save = 0         # passes since the last intra-stage checkpoint
            ckpt_s = 0.0           # seconds inside mid-stage checkpoint saves
            stage_stats0 = cache_stats()

            def maybe_save_mid_stage():
                # save at dispatch boundaries once >= checkpoint_every_passes
                # passes have accumulated — but never for the final boundary,
                # which the end-of-stage save below covers. The save (incl. its
                # pipeline-draining fetch) is timed separately so
                # stage_train_seconds / derived steps-per-sec stay comparable
                # across --checkpoint-every-passes cadences (ADVICE r5).
                nonlocal since_save, ckpt_s
                if cfg.checkpoint_every_passes \
                        and since_save >= cfg.checkpoint_every_passes \
                        and done < passes:
                    t_ck = time.perf_counter()
                    save_checkpoint(ckpt_dir, int(fetch(state.step)), state, stage,
                                    config_json=cfg.to_json(),
                                    keep=cfg.checkpoint_keep, passes_done=done)
                    ckpt_s += time.perf_counter() - t_ck
                    since_save = 0

            def pass_boundary():
                # one call per dispatch boundary: the chaos hook (a sigterm
                # action here is absorbed by the guard synchronously), then
                # preemption grace — force-save the CURRENT mid-stage state
                # and stop — then the ordinary cadence save. Grace runs
                # before maybe_save_mid_stage so the two never write the
                # same step twice (Orbax refuses duplicate steps).
                fault_point(SITE_TRAIN_PASS, stage=stage, done=done)
                if guard is not None and guard.requested and done < passes:
                    # mid-stage only: a signal on the FINAL pass boundary
                    # instead lets the stage finish its eval + end-of-stage
                    # save (bounded work) and raises there — otherwise the
                    # resume would classify the stage complete and its
                    # metrics row / artifacts would exist in neither run
                    step_now = int(fetch(state.step))
                    save_checkpoint(ckpt_dir, step_now, state, stage,
                                    config_json=cfg.to_json(),
                                    keep=cfg.checkpoint_keep,
                                    passes_done=done)
                    if is_primary:
                        print(f"preemption grace: signal {guard.signum} "
                              f"absorbed; mid-stage checkpoint saved at "
                              f"stage {stage}, pass {done} (step {step_now})")
                    raise TrainingPreempted(stage, done, step_now)
                maybe_save_mid_stage()

            t_train = time.perf_counter()
            remaining = passes - offset
            last_diag = None  # device scalars from the newest epoch dispatch
            with span("train/stage"):
                if remaining >= PASS_BLOCK and max_batches_per_pass is None:
                    block_fn = epoch_fn_for(active_spec, PASS_BLOCK)
                    for _ in range(remaining // PASS_BLOCK):
                        state, out = block_fn(state, x_train_dev)
                        if diag_cfg is not None:
                            _, last_diag = out
                        done += PASS_BLOCK
                        since_save += PASS_BLOCK
                        pass_boundary()
                    remaining = remaining % PASS_BLOCK
                epoch_fn = epoch_fn_for(active_spec)
                for _ in range(remaining):
                    state, out = epoch_fn(state, x_train_dev)
                    if diag_cfg is not None:
                        _, last_diag = out
                    done += 1
                    since_save += 1
                    pass_boundary()
            # fetch forces completion of the async dispatches (np.asarray under
            # the hood — block_until_ready only reports enqueue on remote
            # transports), so the stage timings are honest train/eval splits
            step_n = int(fetch(state.step))
            train_s = time.perf_counter() - t_train

            t_eval = time.perf_counter()
            with span("eval/statistics"):
                if mesh is not None:
                    from iwae_replication_project_tpu.parallel.eval import (
                        parallel_training_statistics)
                    res, res2 = parallel_training_statistics(
                        state.params, model_cfg, mesh,
                        jax.random.fold_in(eval_key, stage),
                        jax.numpy.asarray(x_test.reshape(len(x_test), -1)),
                        cfg.eval_k,
                        batch_size=min(cfg.eval_batch_size, len(x_test)),
                        nll_k=cfg.nll_k, nll_chunk=cfg.nll_chunk,
                        activity_samples=cfg.activity_samples)
                else:
                    res, res2 = ev.training_statistics(
                        state.params, model_cfg,
                        jax.random.fold_in(eval_key, stage),
                        jax.numpy.asarray(x_test.reshape(len(x_test), -1)),
                        cfg.eval_k,
                        batch_size=min(cfg.eval_batch_size, len(x_test)),
                        nll_k=cfg.nll_k, nll_chunk=cfg.nll_chunk,
                        activity_samples=cfg.activity_samples)
            # estimator diagnostics (telemetry/diagnostics.py): the weight-space
            # suite as one extra device program per eval, plus the train-side
            # grad-SNR scalars the newest epoch dispatch carried — fetched here,
            # with everything else, never per step. Multihost runs skip the eval
            # program (params are not single-process-addressable; the replicated
            # grad-SNR scalars still flow).
            if diag_cfg is not None:
                diag_vals = {}
                if not cfg.multihost:
                    from iwae_replication_project_tpu.telemetry.diagnostics import (
                        estimator_diagnostics)
                    from iwae_replication_project_tpu.utils.compile_cache import (
                        aot_call)
                    n_eval = len(x_test)
                    ebs = ev.largest_divisor_leq(
                        n_eval, min(cfg.eval_batch_size, n_eval))
                    ebatches = jax.numpy.asarray(
                        x_test.reshape(n_eval // ebs, ebs, -1))
                    with span("eval/diagnostics"):
                        diag_vals.update(fetch(aot_call(
                            "estimator_diagnostics", estimator_diagnostics,
                            (state.params,),
                            kwargs=dict(key=jax.random.fold_in(eval_key,
                                                               30_000 + stage),
                                        batches=ebatches),
                            static_kwargs=dict(cfg=model_cfg, k=cfg.eval_k,
                                               diag=diag_cfg),
                            build_key=(model_cfg, cfg.eval_k, diag_cfg))))
                if last_diag is not None:
                    diag_vals.update(fetch(last_diag))
                res.update({k: float(v) for k, v in diag_vals.items()})
                reg = get_registry()
                for k, v in diag_vals.items():
                    reg.gauge(k).set(float(v))
            res["learning_rate"] = lr
            res["stage"] = stage
            # make fake-data runs unmistakable in every artifact (metrics.jsonl,
            # results.pkl, stdout), and record which bias policy the decoder was
            # initialized under (raw-means = the reference's fixed-bin policy)
            res["synthetic_data"] = bool(ds.synthetic)
            res["raw_means_bias"] = ds.bias_source == "raw"
            res["bfloat16"] = cfg.compute_dtype == "bfloat16"
            # wall-clock per stage (train = the passes, with mid-stage checkpoint
            # saves broken out into stage_checkpoint_seconds so steps/s stays
            # comparable across --checkpoint-every-passes cadences; eval = the
            # full statistics suite), for capacity planning. After a mid-stage
            # resume the timer only saw `passes - offset` passes —
            # stage_passes_timed records that so steps/s derived from these
            # fields stays honest (scripts/dress_rehearsal.py uses it).
            res["stage_train_seconds"] = round(train_s - ckpt_s, 3)
            res["stage_checkpoint_seconds"] = round(ckpt_s, 3)
            # the cadence the row was produced under (0 = end-of-stage saves
            # only), so rows from different --checkpoint-every-passes settings
            # are identifiable when comparing derived steps/s (ADVICE r5)
            res["checkpoint_every_passes"] = float(
                cfg.checkpoint_every_passes or 0)
            res["stage_passes_timed"] = float(passes - offset)
            res["stage_eval_seconds"] = round(time.perf_counter() - t_eval, 3)
            # warm-path accounting for THIS stage (utils/compile_cache.py): how
            # many programs the AOT registry reused vs newly compiled, and the
            # XLA compile seconds paid. A warm start (persistent cache populated)
            # shows compile_cache_misses == 0 from stage 1 onward.
            d_stats = stats_delta(stage_stats0)
            res["aot_hits"] = float(d_stats["aot_hits"])
            res["aot_misses"] = float(d_stats["aot_misses"])
            res["aot_compile_seconds"] = round(d_stats["aot_compile_seconds"], 3)
            res["compile_cache_misses"] = float(d_stats["persistent_cache_misses"])
            res["compile_cache_hits"] = float(d_stats["persistent_cache_hits"])
            res["compile_seconds"] = round(d_stats["backend_compile_seconds"], 3)
            # `res` already carries "nll_chunk" — the EFFECTIVE chunk the eval
            # driver used (clamped per device under sp) — as the eval-RNG version
            if is_primary:
                print({k: round(v, 4) for k, v in res.items()
                       if isinstance(v, float)})
            results_history.append((res, {
                "number_of_active_units": res2["number_of_active_units"],
                "number_of_PCA_active_units": res2["number_of_PCA_active_units"]}))
            if logger is not None:  # primary process only under --multihost
                # registry export (span timings, diagnostic gauges, aot counters)
                # lands in its own runs/<run>/telemetry/ stream: metrics.jsonl
                # keeps one row per stage — the schema every downstream consumer
                # (plot scripts, replication driver, tests) keys on — and the
                # telemetry stream shows up in TensorBoard as a <run>/telemetry
                # subrun next to it
                if diag_cfg is not None:
                    if telem_logger is None:
                        telem_logger = MetricsLogger(logger.dir,
                                                     run_name="telemetry")
                    telem_logger.log_registry(get_registry(), step=step_n)
                logger.log(res, step=step_n)
                if cfg.save_figures:
                    from iwae_replication_project_tpu.utils.viz import (
                        save_stage_figures)
                    save_stage_figures(state.params, model_cfg,
                                       jax.random.fold_in(eval_key, 10_000 + stage),
                                       x_test, logger.dir, stage)
                    if y_test is not None:
                        from iwae_replication_project_tpu.utils.viz import (
                            latent_scatter)
                        latent_scatter(
                            state.params, model_cfg,
                            jax.random.fold_in(eval_key, 20_000 + stage),
                            x_test, os.path.join(logger.dir, "figures",
                                                 f"stage_{stage:02d}_latent.png"),
                            labels=y_test)
                with open(os.path.join(logger.dir, "results.pkl"), "wb") as f:
                    pickle.dump(results_history, f)

            # every process participates: Orbax coordinates multi-host saves
            save_checkpoint(ckpt_dir, step_n, state, stage,
                            config_json=cfg.to_json(), keep=cfg.checkpoint_keep)
            if guard is not None and guard.requested:
                # the signal landed during this stage's tail (final pass
                # boundary, eval, or artifact writes): the stage is now
                # complete AND durably saved — stop here, resume continues
                # at the next stage
                if is_primary:
                    print(f"preemption grace: signal {guard.signum} "
                          f"absorbed; stage {stage} completed and saved "
                          f"(step {step_n})")
                raise TrainingPreempted(stage, passes, step_n)

    finally:
        if guard is not None:
            guard.__exit__(None, None, None)
    if telem_logger is not None:
        telem_logger.close()
    if logger is not None:
        logger.close()
    return state, results_history


def _run_experiment_eager(cfg: ExperimentConfig,
                          max_batches_per_pass: Optional[int] = None,
                          eval_subset: Optional[int] = None):
    """The staged experiment on an eager facade backend ("torch" — the CPU
    oracle — or "tf2" — the reference's own execution style), with the FULL
    evaluation suite (training statistics incl. activity + pruned NLL —
    parity with flexible_IWAE.py:496-526). No checkpoint/resume (the
    reference's eager path had none either)."""
    from iwae_replication_project_tpu.api import FlexibleModel

    ds = load_dataset(cfg.dataset, data_dir=cfg.data_dir,
                      allow_synthetic=cfg.allow_synthetic)
    mdl = FlexibleModel(list(cfg.n_hidden_encoder), list(cfg.n_hidden_decoder),
                        list(cfg.n_latent_encoder), list(cfg.n_latent_decoder),
                        dataset_bias=None, pixel_means=ds.bias_means,
                        loss_function=cfg.loss_function, k=cfg.k, p=cfg.p,
                        alpha=cfg.alpha, beta=cfg.beta, k2=cfg.k2,
                        backend=cfg.backend, seed=cfg.seed).compile()
    logger = MetricsLogger(cfg.log_dir,
                           run_name=f"{cfg.run_name()}-{cfg.backend}")
    x_test = ds.x_test[:eval_subset] if eval_subset else ds.x_test
    results_history = []
    step_count = 0
    for stage, lr, passes in burda_stages(cfg.n_stages, cfg.passes_scale):
        mdl.set_learning_rate(lr)
        for _ in range(passes):
            for bi, batch in enumerate(epoch_batches(
                    ds.x_train, cfg.batch_size, epoch=step_count, seed=cfg.seed,
                    binarization=ds.binarization)):
                if max_batches_per_pass is not None and bi >= max_batches_per_pass:
                    break
                mdl.train_step(batch)
                step_count += 1
        res, res2 = mdl.get_training_statistics(
            x_test, cfg.eval_k,
            batch_size=min(cfg.eval_batch_size, len(x_test)),
            nll_k=cfg.nll_k, nll_chunk=cfg.nll_chunk,
            activity_samples=cfg.activity_samples)
        res["learning_rate"] = lr
        res["stage"] = stage
        res["synthetic_data"] = bool(ds.synthetic)
        res["raw_means_bias"] = ds.bias_source == "raw"
        # the eager oracles accept-and-ignore compute_dtype (f32 math)
        res["bfloat16"] = False
        print({k: round(v, 4) for k, v in res.items() if isinstance(v, float)})
        logger.log(res, step=step_count)
        results_history.append((res, {
            "number_of_active_units": res2["number_of_active_units"],
            "number_of_PCA_active_units": res2["number_of_PCA_active_units"]}))
    logger.close()
    return mdl, results_history


def main(argv=None):
    from iwae_replication_project_tpu.utils.config import config_from_args
    cfg = config_from_args(argv)
    try:
        run_experiment(cfg)
    except TrainingPreempted as e:
        # the distinct preemption exit: schedulers (and humans) distinguish
        # "resume me" from a crash, and the saved mid-stage checkpoint makes
        # re-running the same command continue bitwise
        print(f"exiting {PREEMPTED_EXIT_CODE} (preempted): {e}")
        raise SystemExit(PREEMPTED_EXIT_CODE) from None


if __name__ == "__main__":
    main()
