from iwae_replication_project_tpu.models.iwae import (
    ModelConfig,
    init_params,
    encode,
    decode_probs,
    log_weights,
    log_weights_and_aux,
    generate_x,
    reconstruct_probs,
)

__all__ = [
    "ModelConfig",
    "init_params",
    "encode",
    "decode_probs",
    "log_weights",
    "log_weights_and_aux",
    "generate_x",
    "reconstruct_probs",
]
