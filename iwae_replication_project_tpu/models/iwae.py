"""The IWAE model family: multi-layer stochastic encoder/decoder, purely functional.

Capability parity with the reference's ``Encoder``/``Decoder``/``Flexible_Model``
model core (flexible_IWAE.py:22-175, 327-351), re-designed for TPU:

* parameters are plain pytrees; every entry point is a pure function of
  ``(params, cfg, key, ...)`` — jit/grad/shard_map compose directly;
* the k-sample axis is a leading array axis (``[k, B, d]``), so all dense math
  is one large MXU matmul per layer, not per-sample work;
* RNG is explicit: one key per stochastic draw via `jax.random.split`,
  reproducing the independence structure of TFP's implicit sampling;
* the dataset-dependent output bias is *passed in* as a value
  (cf. the reference's network I/O inside the constructor at
  flexible_IWAE.py:147-175 — lifted into the data layer here).

Shapes follow the reference's convention: ``h[i]`` has shape
``[k, B, n_latent_enc[i]]``, log-densities reduce to ``[k, B]``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from iwae_replication_project_tpu.models import mlp
from iwae_replication_project_tpu.ops import distributions as dist

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Static architecture hyperparameters (hashable -> usable as a jit static).

    Mirrors the ctor lists of the reference (flexible_IWAE.py:178-202):
    ``n_hidden_enc[i]``/``n_latent_enc[i]`` size encoder stochastic layer i;
    the decoder lists run top-down (layer 0 maps the deepest latent toward x)
    and ``n_latent_dec[-1]`` must equal ``x_dim``.
    """

    n_hidden_enc: Tuple[int, ...]
    n_latent_enc: Tuple[int, ...]
    n_hidden_dec: Tuple[int, ...]
    n_latent_dec: Tuple[int, ...]
    x_dim: int = 784
    std_floor: float = dist.STD_FLOOR
    # "clamp": sigmoid + reference prob clamp (bit-parity with flexible_IWAE.py:102);
    # "logits": exact x*l - softplus(l) Bernoulli (faster, tighter).
    likelihood: str = "clamp"
    # None | "bfloat16" — matmul operand dtype; accumulation stays float32.
    compute_dtype: Optional[str] = None
    # Route log p(x|h) through the blocked hot-loop dispatcher: the whole
    # decoder output block (3 matmuls + tanh + Bernoulli + pixel reduction)
    # fused over (k, batch) tiles so neither the [k, B, hid] hiddens nor the
    # [k, B, x_dim] logits hit HBM; per-shape fallback to a remat'd blocked
    # scan or the unfused composition. Requires likelihood="logits".
    # (ops/hot_loop.py; ops/fused_likelihood.py is the k-only predecessor)
    fused_likelihood: bool = False
    # Trace-time pin of the hot-loop implementation ("pallas" |
    # "blocked_scan" | "reference"; None = the dispatcher's auto selection).
    # The serving engines resolve the probe-gated selection OUTSIDE the
    # trace — once per (op, bucket, k), ops/hot_loop.serving_select_path —
    # and bake the outcome here, so the traced program is deterministic,
    # the AOT registry keys on it (cfg rides every build key), and the
    # per-row kernel_path stamps recompute it exactly. hot_loop_tile pins
    # the pallas (tk, tb) tile alongside (gate/autotuner-validated; the
    # trace then skips re-selection and re-probing entirely).
    hot_loop_path: Optional[str] = None
    hot_loop_tile: Optional[Tuple[int, int]] = None

    def __post_init__(self):
        L = self.n_stochastic
        if not (len(self.n_latent_enc) == L and len(self.n_hidden_dec) == L
                and len(self.n_latent_dec) == L):
            raise ValueError("encoder/decoder size lists must have equal length")
        if self.n_latent_dec[-1] != self.x_dim:
            raise ValueError(f"n_latent_dec[-1]={self.n_latent_dec[-1]} must equal x_dim={self.x_dim}")
        if self.likelihood not in ("clamp", "logits"):
            raise ValueError(f"unknown likelihood {self.likelihood!r}")
        if self.fused_likelihood and self.likelihood != "logits":
            raise ValueError("fused_likelihood requires likelihood='logits'")
        if self.hot_loop_path is not None:
            if self.hot_loop_path not in ("pallas", "blocked_scan",
                                          "reference"):
                raise ValueError(f"unknown hot_loop_path "
                                 f"{self.hot_loop_path!r}")
            if not self.fused_likelihood:
                raise ValueError("hot_loop_path is a pin on the fused "
                                 "dispatcher; it requires "
                                 "fused_likelihood=True")
        if self.hot_loop_tile is not None:
            if self.hot_loop_path != "pallas":
                raise ValueError("hot_loop_tile requires "
                                 "hot_loop_path='pallas'")
            t = tuple(self.hot_loop_tile)
            if len(t) != 2 or any(int(v) < 1 for v in t):
                raise ValueError(f"hot_loop_tile must be two positive ints, "
                                 f"got {self.hot_loop_tile!r}")
            # normalize to a hashable tuple of ints (hashability is what
            # lets the config ride jit statics and AOT build keys)
            object.__setattr__(self, "hot_loop_tile",
                               (int(t[0]), int(t[1])))

    @property
    def n_stochastic(self) -> int:
        return len(self.n_hidden_enc)

    @property
    def matmul_dtype(self):
        return jnp.bfloat16 if self.compute_dtype == "bfloat16" else None

    @staticmethod
    def two_layer(**kw) -> "ModelConfig":
        """The flagship architecture of experiment_example.py:48-51."""
        defaults = dict(n_hidden_enc=(200, 100), n_latent_enc=(100, 50),
                        n_hidden_dec=(100, 200), n_latent_dec=(100, 784))
        defaults.update(kw)
        return ModelConfig(**defaults)

    @staticmethod
    def one_layer(**kw) -> "ModelConfig":
        """The 1-stochastic-layer architecture of Burda Table 1 / PDF §3.3."""
        defaults = dict(n_hidden_enc=(200,), n_latent_enc=(50,),
                        n_hidden_dec=(200,), n_latent_dec=(784,))
        defaults.update(kw)
        return ModelConfig(**defaults)


def init_params(key: jax.Array, cfg: ModelConfig,
                output_bias: Optional[jax.Array] = None) -> Params:
    """Build the parameter pytree. `output_bias` is the logit-of-pixel-mean
    vector computed by the data layer (see data.bias; formula of
    flexible_IWAE.py:174)."""
    L = cfg.n_stochastic
    keys = jax.random.split(key, 2 * L)
    enc = []
    in_dim = cfg.x_dim
    for i in range(L):
        enc.append(mlp.stochastic_block_init(keys[i], in_dim, cfg.n_hidden_enc[i],
                                             cfg.n_latent_enc[i]))
        in_dim = cfg.n_latent_enc[i]

    dec = []
    in_dim = cfg.n_latent_enc[-1]
    for i in range(L - 1):
        dec.append(mlp.stochastic_block_init(keys[L + i], in_dim, cfg.n_hidden_dec[i],
                                             cfg.n_latent_dec[i]))
        in_dim = cfg.n_latent_dec[i]
    out = mlp.output_block_init(keys[2 * L - 1], in_dim, cfg.n_hidden_dec[-1],
                                cfg.x_dim, out_bias=output_bias)
    return {"enc": tuple(enc), "dec": tuple(dec), "out": out}


# ---------------------------------------------------------------------------
# Encoder
# ---------------------------------------------------------------------------

def encode(params: Params, cfg: ModelConfig, key: jax.Array, x: jax.Array, k: int,
           stop_q_score: bool = False):
    """Run the inference chain q(h|x) with a k-sample fan-out at the first layer.

    Returns ``(h, log_q, q_last)`` where ``h`` is a tuple of ``[k, B, d_i]``
    samples, ``log_q`` is ``[k, B]`` (sum over layers and latent dims), and
    ``q_last`` is the (mu, std) of the final conditional — the analytic-ELBO
    oracle needs it (cf. flexible_IWAE.py:75,443,457).

    `stop_q_score=True` stops gradients through the *density parameters* inside
    ``log q`` while keeping the pathwise dependence through the samples — the
    score-term removal that DReG / sticking-the-landing estimators require
    (Tucker et al. 2018, PAPERS.md).
    """
    dt = cfg.matmul_dtype
    sg = jax.lax.stop_gradient if stop_q_score else (lambda t: t)
    keys = jax.random.split(key, cfg.n_stochastic)
    mu, std = mlp.stochastic_block_apply(params["enc"][0], x, cfg.std_floor, dt)
    h1 = dist.normal_sample(keys[0], mu, std, sample_shape=(k,))
    log_q = jnp.sum(dist.normal_log_prob(h1, sg(mu), sg(std)), axis=-1)
    h = [h1]
    q_last = (mu, std)
    for i in range(1, cfg.n_stochastic):
        mu, std = mlp.stochastic_block_apply(params["enc"][i], h[-1], cfg.std_floor, dt)
        hi = dist.normal_sample(keys[i], mu, std)
        log_q = log_q + jnp.sum(dist.normal_log_prob(hi, sg(mu), sg(std)), axis=-1)
        h.append(hi)
        q_last = (mu, std)
    return tuple(h), log_q, q_last


# ---------------------------------------------------------------------------
# Decoder
# ---------------------------------------------------------------------------

def decode_logits(params: Params, cfg: ModelConfig, h1: jax.Array) -> jax.Array:
    """Pixel logits from the bottom latent, ``[k, B, x_dim]``."""
    return mlp.output_block_apply(params["out"], h1, cfg.matmul_dtype)


def decode_probs(params: Params, cfg: ModelConfig, h1: jax.Array) -> jax.Array:
    """Clamped pixel probabilities (reference parity, flexible_IWAE.py:101-102)."""
    return dist.clamp_probs(jax.nn.sigmoid(decode_logits(params, cfg, h1)))


def log_px_given_h(params: Params, cfg: ModelConfig, x: jax.Array,
                   h1: jax.Array) -> jax.Array:
    """``log p(x|h)`` summed over pixels -> ``[k, B]`` (flexible_IWAE.py:123-129)."""
    if "out_q" in params:
        # the int8 precision policy (ISSUE 16): the serving engine replaced
        # the fp32 output block with its weight-only-quantized twin
        # (hot_loop.quantize_out_block) at load, so the scoring path reads
        # int8 weights + per-channel fp32 scales. Only the serving score
        # program builds such a tree; train/eval params always carry "out".
        from iwae_replication_project_tpu.ops import hot_loop
        return hot_loop.decoder_score_int8(params["out_q"], x, h1)
    if cfg.fused_likelihood:
        # the hot-loop dispatcher (ops/hot_loop.py): the FULL output block
        # (three matmuls + tanh + Bernoulli + pixel reduction) blocked over
        # (k, batch) tiles — Pallas where a tile fits scoped VMEM (probe-
        # gated), a remat'd blocked scan for oversized working sets, and
        # the unfused XLA composition otherwise. Selection is trace-time
        # static and recorded on the telemetry registry (kernel_path).
        from iwae_replication_project_tpu.ops import hot_loop
        return hot_loop.decoder_score(params["out"], x, h1,
                                      compute_dtype=cfg.matmul_dtype,
                                      on_tpu=_on_tpu(),
                                      force_path=cfg.hot_loop_path,
                                      force_tile=cfg.hot_loop_tile)
    logits = decode_logits(params, cfg, h1)
    if cfg.likelihood == "clamp":
        probs = dist.clamp_probs(jax.nn.sigmoid(logits))
        lp = dist.bernoulli_log_prob(x, probs)
    else:
        lp = dist.bernoulli_log_prob_from_logits(x, logits)
    return jnp.sum(lp, axis=-1)


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def log_prior(params: Params, cfg: ModelConfig, h: Tuple[jax.Array, ...]) -> jax.Array:
    """``log p(h)``: standard-Normal on the deepest latent plus the decoder's
    conditional chain down to h1 -> ``[k, B]`` (flexible_IWAE.py:134-142)."""
    L = cfg.n_stochastic
    log_p = jnp.sum(dist.standard_normal_log_prob(h[-1]), axis=-1)
    for i in range(L - 1):
        mu, std = mlp.stochastic_block_apply(params["dec"][i], h[L - 1 - i],
                                             cfg.std_floor, cfg.matmul_dtype)
        log_p = log_p + jnp.sum(dist.normal_log_prob(h[L - 2 - i], mu, std), axis=-1)
    return log_p


def generate_x(params: Params, cfg: ModelConfig, key: jax.Array,
               h_top: jax.Array) -> jax.Array:
    """Ancestral sampling from the deepest latent down, returning pixel probs
    (flexible_IWAE.py:107-118)."""
    L = cfg.n_stochastic
    keys = jax.random.split(key, max(L - 1, 1))
    h = h_top
    for i in range(L - 1):
        mu, std = mlp.stochastic_block_apply(params["dec"][i], h, cfg.std_floor,
                                             cfg.matmul_dtype)
        h = dist.normal_sample(keys[i], mu, std)
    return decode_probs(params, cfg, h)


# ---------------------------------------------------------------------------
# Log-weights — the framework's spine
# ---------------------------------------------------------------------------

def log_weights_and_aux(params: Params, cfg: ModelConfig, key: jax.Array,
                        x: jax.Array, k: int, stop_q_score: bool = False):
    """One encoder+decoder pass -> ``[k, B]`` log importance weights plus every
    intermediate any metric needs (the reference recomputes this pass up to 7x
    per eval batch, flexible_IWAE.py:512-519 — here it is computed once).

    ``log w = (log p(h) + log p(x|h)) - log q(h|x)`` (flexible_IWAE.py:343-349).
    """
    h, log_q, q_last = encode(params, cfg, key, x, k, stop_q_score=stop_q_score)
    log_pxh_cond = log_px_given_h(params, cfg, x, h[0])
    log_ph = log_prior(params, cfg, h)
    log_w = log_ph + log_pxh_cond - log_q
    aux = {
        "h": h,
        "log_q": log_q,
        "log_px_given_h": log_pxh_cond,
        "log_prior": log_ph,
        "q_last": q_last,
    }
    return log_w, aux


def log_weights(params: Params, cfg: ModelConfig, key: jax.Array, x: jax.Array,
                k: int, stop_q_score: bool = False) -> jax.Array:
    return log_weights_and_aux(params, cfg, key, x, k, stop_q_score=stop_q_score)[0]


def reconstruct_probs(params: Params, cfg: ModelConfig, key: jax.Array,
                      x: jax.Array) -> jax.Array:
    """Encode with one sample, ancestral-decode — ``[1, B, x_dim]`` pixel probs
    (flexible_IWAE.py:249-254)."""
    k_enc, k_dec = jax.random.split(key)
    h, _, _ = encode(params, cfg, k_enc, x, 1)
    return generate_x(params, cfg, k_dec, h[-1])
