"""Dense / stochastic-block primitives as param pytrees + pure apply functions.

TPU-first notes: the k-sample fan-out lives in the *leading* axes of the
activations (``[k, B, d]``), so every dense layer is one big ``[k*B, d] @ [d, h]``
matmul that XLA tiles straight onto the MXU — no Python loop over samples, no
vmap overhead. An optional ``compute_dtype`` (bfloat16) casts matmul operands
while keeping distribution parameters in float32.

Reference behavior being matched (not copied): a stochastic block is
2x tanh-Dense followed by parallel mu / exp-activated std heads with a 1e-6 std
floor (flexible_IWAE.py:22-38); Dense init is Keras' default glorot-uniform with
zero bias.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def dense_init(key: jax.Array, in_dim: int, out_dim: int,
               bias: Optional[jax.Array] = None) -> Params:
    """Glorot-uniform kernel, zero (or given) bias — Keras Dense defaults."""
    limit = jnp.sqrt(6.0 / (in_dim + out_dim))
    w = jax.random.uniform(key, (in_dim, out_dim), jnp.float32, -limit, limit)
    b = jnp.zeros((out_dim,), jnp.float32) if bias is None else jnp.asarray(bias, jnp.float32)
    return {"w": w, "b": b}


def dense_apply(p: Params, x: jax.Array, compute_dtype=None) -> jax.Array:
    if compute_dtype is not None:
        y = jnp.dot(x.astype(compute_dtype), p["w"].astype(compute_dtype),
                    preferred_element_type=jnp.float32)
    else:
        y = jnp.dot(x, p["w"])
    return y + p["b"]


def stochastic_block_init(key: jax.Array, in_dim: int, hidden: int, latent: int) -> Params:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "l1": dense_init(k1, in_dim, hidden),
        "l2": dense_init(k2, hidden, hidden),
        "mu": dense_init(k3, hidden, latent),
        "lstd": dense_init(k4, hidden, latent),
    }


def stochastic_block_apply(p: Params, x: jax.Array, std_floor: float = 1e-6,
                           compute_dtype=None):
    """Returns ``(mu, std)`` of the conditional Gaussian given `x`.

    std = exp(head) + floor, matching flexible_IWAE.py:29,37.
    """
    y = jnp.tanh(dense_apply(p["l1"], x, compute_dtype))
    y = jnp.tanh(dense_apply(p["l2"], y, compute_dtype))
    mu = dense_apply(p["mu"], y, compute_dtype).astype(jnp.float32)
    std = jnp.exp(dense_apply(p["lstd"], y, compute_dtype).astype(jnp.float32)) + std_floor
    return mu, std


def output_block_init(key: jax.Array, in_dim: int, hidden: int, out_dim: int,
                      out_bias: Optional[jax.Array] = None) -> Params:
    """Final deterministic decoder head: 2x tanh-Dense + logit layer.

    The reference's head ends in ``Dense(784, sigmoid, bias_initializer=...)``
    (flexible_IWAE.py:92-94); here the layer produces *logits* and the sigmoid /
    clamp happen at the use site, so the exact Bernoulli-from-logits form stays
    available for the fast path.
    """
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "l1": dense_init(k1, in_dim, hidden),
        "l2": dense_init(k2, hidden, hidden),
        "out": dense_init(k3, hidden, out_dim, bias=out_bias),
    }


def output_block_apply(p: Params, x: jax.Array, compute_dtype=None) -> jax.Array:
    """Returns pixel *logits* of shape ``[..., out_dim]``."""
    y = jnp.tanh(dense_apply(p["l1"], x, compute_dtype))
    y = jnp.tanh(dense_apply(p["l2"], y, compute_dtype))
    return dense_apply(p["out"], y, compute_dtype).astype(jnp.float32)
