from iwae_replication_project_tpu.objectives.estimators import (
    ObjectiveSpec,
    OBJECTIVE_NAMES,
    vae_bound,
    iwae_bound,
    miwae_bound,
    ciwae_bound,
    power_bound,
    median_bound,
    alpha_bound,
    vae_v1_bound,
    bound_from_log_weights,
    objective_bound,
)
from iwae_replication_project_tpu.objectives.gradients import (
    objective_value_and_grad,
)

__all__ = [
    "ObjectiveSpec",
    "OBJECTIVE_NAMES",
    "vae_bound",
    "iwae_bound",
    "miwae_bound",
    "ciwae_bound",
    "power_bound",
    "median_bound",
    "alpha_bound",
    "vae_v1_bound",
    "bound_from_log_weights",
    "objective_bound",
    "objective_value_and_grad",
]
