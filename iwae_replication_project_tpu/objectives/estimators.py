"""Variational-bound estimators: pure reductions of the ``[k, B]`` log-weights.

Capability parity with the reference's seven dispatch branches
(flexible_IWAE.py:228-241, 354-460) plus the report-only / paper extensions the
baseline configs require (MIWAE; PIWAE/DReG/STL live in
:mod:`objectives.gradients` since they change the *gradient*, not the bound):

===========  ==================================================================
name         bound
===========  ==================================================================
VAE          ``mean(log w)``                               (flexible_IWAE.py:429)
IWAE         ``mean_B logmeanexp_k(log w)``                (:363-370)
VAE_V1       analytic-KL ELBO (single stochastic layer)    (:434-460)
L_alpha      ``(1-a) E_q[log p(x|h)] + a L_VAE``           (:386-402)
L_power_p    ``mean_B (1/p) logmeanexp_k(p log w)``        (:405-409)
L_median     ``mean_B median_k(log w)``                    (:373-379)
CIWAE        ``b L_VAE + (1-b) L_IWAE``                    (:382-383)
MIWAE        mean of k2 independent k1-sample IWAE bounds  (PDF §2.4, Table 9)
===========  ==================================================================

All reducers operate on a leading k axis and are trivially differentiable; jit
fuses them into the producing pass.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from iwae_replication_project_tpu.ops import distributions as dist
from iwae_replication_project_tpu.ops.logsumexp import logmeanexp

#: every objective name accepted by the framework's dispatchers.
OBJECTIVE_NAMES = ("VAE", "IWAE", "VAE_V1", "L_alpha", "L_power_p", "L_median",
                   "CIWAE", "MIWAE", "PIWAE", "DReG", "STL")


@dataclasses.dataclass(frozen=True)
class ObjectiveSpec:
    """An objective name plus its hyperparameters (hashable -> jit static).

    Defaults match the reference ctor (flexible_IWAE.py:180). For MIWAE/PIWAE,
    ``k`` is interpreted as ``k1 * k2`` with ``k2`` outer averages of
    ``k1``-sample bounds (PDF §2.4); for every other objective ``k1``/``k2``
    are ignored.
    """

    name: str = "VAE"
    k: int = 50
    p: float = 1.0
    alpha: float = 1.0
    beta: float = 0.5
    k2: int = 1  # MIWAE/PIWAE outer-average count; k1 = k // k2

    def __post_init__(self):
        if self.name not in OBJECTIVE_NAMES:
            raise ValueError(f"unknown objective {self.name!r}; choose from {OBJECTIVE_NAMES}")
        if self.name in ("MIWAE", "PIWAE") and self.k % self.k2 != 0:
            raise ValueError(f"MIWAE/PIWAE need k2 | k, got k={self.k}, k2={self.k2}")


# --------------------------------------------------------------------------
# Pure reducers of [k, B] log-weights
# --------------------------------------------------------------------------

def vae_bound(log_w: jnp.ndarray) -> jnp.ndarray:
    """k-sample MC estimate of the ELBO: mean over samples and batch."""
    return jnp.mean(log_w)


def iwae_per_example(log_w: jnp.ndarray) -> jnp.ndarray:
    """``[B]`` per-example k-sample bound: ``logmeanexp_k(log w)``.

    The shared reduction tail of the hot loop (ops/hot_loop.py produces the
    ``[k, B]`` log-weights; this is the ``ops.logsumexp`` step that closes
    it): training's :func:`iwae_bound` means it over the batch, the k=5000
    eval scorer streams it through the online-logsumexp carry, and the
    serving ``score`` op returns it per request — one reduction definition
    for all three workloads.
    """
    return logmeanexp(log_w, axis=0)


def iwae_bound(log_w: jnp.ndarray) -> jnp.ndarray:
    """L_k = mean_B[ log mean_k exp(log w) ], max-stabilized."""
    return jnp.mean(iwae_per_example(log_w))


def miwae_bound(log_w: jnp.ndarray, k2: int) -> jnp.ndarray:
    """L^MIWAE_{k1,k2}: average of k2 independent k1-sample IWAE bounds.

    Edge cases are free identity oracles: k2==k -> VAE, k2==1 -> IWAE
    (PDF Table 9 caption).
    """
    k = log_w.shape[0]
    grouped = log_w.reshape(k2, k // k2, *log_w.shape[1:])
    return jnp.mean(logmeanexp(grouped, axis=1))


def ciwae_bound(log_w: jnp.ndarray, beta: float) -> jnp.ndarray:
    """Convex combination beta*VAE + (1-beta)*IWAE (Rainforth et al.)."""
    return beta * vae_bound(log_w) + (1.0 - beta) * iwae_bound(log_w)


def power_bound(log_w: jnp.ndarray, p: float) -> jnp.ndarray:
    """L_power_p = mean_B[ (1/p) log mean_k exp(p log w) ]; p=1 -> IWAE."""
    return jnp.mean(logmeanexp(p * log_w, axis=0) / p)


def median_bound(log_w: jnp.ndarray) -> jnp.ndarray:
    """mean_B[ median_k log w ].

    `jnp.median` linearly interpolates, which at the 50th percentile equals the
    reference's 'midpoint' interpolation (flexible_IWAE.py:377). The gradient
    flows through the middle order statistic(s) only (PDF p.6 fn.3 caveat).
    """
    return jnp.mean(jnp.median(log_w, axis=0))


def alpha_bound(log_w: jnp.ndarray, log_px_given_h: jnp.ndarray,
                alpha: float) -> jnp.ndarray:
    """L_alpha = (1-alpha) E_q[log p(x|h)] + alpha L_VAE (flexible_IWAE.py:386-402).

    `log_px_given_h` is the ``[k, B]`` reconstruction term from the same pass.
    """
    return (1.0 - alpha) * jnp.mean(log_px_given_h) + alpha * vae_bound(log_w)


def vae_v1_bound(log_px_given_h: jnp.ndarray, q_mu: jnp.ndarray,
                 q_std: jnp.ndarray) -> jnp.ndarray:
    """Analytic-KL ELBO for a single stochastic layer (flexible_IWAE.py:434-460).

    ``E_q[log p(x|h)] - mean_B sum_d KL(q(h|x) || N(0,1))`` — the MC-vs-analytic
    consistency oracle the reference ships as its only built-in test.

    Defined for SINGLE-stochastic-layer models only (the reference's comment
    at flexible_IWAE.py:433): with L>=2 the last conditional's KL against a
    standard Normal is not the model's KL term, so the "analytic" value would
    be wrong by construction. A multi-layer encoder is detected by the sample
    axis on ``q_mu`` (layer-1 params are [B, d]; deeper layers' depend on the
    k sampled ancestors -> [k, B, d]) and rejected.
    """
    if q_mu.ndim != 2:
        raise ValueError(
            "VAE_V1's analytic KL is defined for single-stochastic-layer "
            "models only (flexible_IWAE.py:433); this encoder has L >= 2 — "
            "use VAE (the MC estimator) instead")
    recon = jnp.mean(log_px_given_h)
    kl = jnp.mean(jnp.sum(dist.normal_kl_standard(q_mu, q_std), axis=-1))
    return recon - kl


# --------------------------------------------------------------------------
# Dispatch
# --------------------------------------------------------------------------

def bound_from_log_weights(spec: ObjectiveSpec, log_w: jnp.ndarray,
                           aux: dict | None = None) -> jnp.ndarray:
    """Evaluate `spec`'s bound. `aux` (from models.log_weights_and_aux) is
    required for L_alpha and VAE_V1 only.

    PIWAE/DReG/STL *evaluate* as IWAE (they alter gradients, not the bound).
    """
    name = spec.name
    if name == "VAE":
        return vae_bound(log_w)
    if name in ("IWAE", "PIWAE", "DReG", "STL"):
        return iwae_bound(log_w)
    if name == "MIWAE":
        return miwae_bound(log_w, spec.k2)
    if name == "CIWAE":
        return ciwae_bound(log_w, spec.beta)
    if name == "L_power_p":
        return power_bound(log_w, spec.p)
    if name == "L_median":
        return median_bound(log_w)
    if name == "L_alpha":
        if aux is None:
            raise ValueError("L_alpha needs aux['log_px_given_h']")
        return alpha_bound(log_w, aux["log_px_given_h"], spec.alpha)
    if name == "VAE_V1":
        if aux is None:
            raise ValueError("VAE_V1 needs aux['log_px_given_h'] and aux['q_last']")
        q_mu, q_std = aux["q_last"]
        return vae_v1_bound(aux["log_px_given_h"], q_mu, q_std)
    raise ValueError(f"unknown objective {name!r}")


def objective_bound(spec: ObjectiveSpec, params, cfg, key, x) -> jnp.ndarray:
    """Convenience: one model pass + the bound."""
    from iwae_replication_project_tpu.models import iwae as model

    log_w, aux = model.log_weights_and_aux(params, cfg, key, x, spec.k)
    return bound_from_log_weights(spec, log_w, aux)
