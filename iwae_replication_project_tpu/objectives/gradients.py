"""Gradient estimators, including the ones that differ from naive autodiff.

Standard objectives (VAE/IWAE/MIWAE/CIWAE/L_*) are just
``value_and_grad(-bound)``. Three estimators from the baseline's extended
configs (BASELINE.json configs 4-5; papers in PAPERS.md) prescribe *different
gradients for the same IWAE-family bound*:

* **STL** (sticking the landing, Roeder et al. 2017): drop the score term of
  ``log q`` — pathwise-only encoder gradient with cotangent ``w~`` (the
  normalized importance weights).
* **DReG** (doubly-reparameterized, Tucker et al. 2018): encoder cotangent
  ``w~^2`` on the score-stopped graph; decoder keeps the standard ``w~``.
* **PIWAE** (Rainforth et al. 2018): decoder trained on the full
  ``k``-sample IWAE bound, encoder on the MIWAE(k1, k2) bound.

All three are realized as explicit VJP cotangents on the ``[k, B]`` log-weight
tensor: one forward pass, the reducer's analytic derivative as cotangent, and
(where encoder/decoder disagree) per-subtree selection of two backward passes.
This keeps the estimator code independent of the network, exactly like the
bound reducers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import estimators as est


def _normalized_weights(log_w: jax.Array) -> jax.Array:
    """``w~ = softmax_k(log w)``, stop-gradded — the self-normalized weights."""
    return jax.lax.stop_gradient(jax.nn.softmax(log_w, axis=0))


def _select(tree_a, tree_b, take_enc_from_a: bool):
    """Take the encoder subtree from one grad pytree and the rest from the other."""
    enc_src, rest_src = (tree_a, tree_b) if take_enc_from_a else (tree_b, tree_a)
    out = dict(rest_src)
    out["enc"] = enc_src["enc"]
    return out


def objective_value_and_grad(spec: est.ObjectiveSpec, params, cfg, key, x
                             ) -> Tuple[jax.Array, dict]:
    """``(bound, d bound / d params)`` for any objective, special-casing the
    modified-gradient estimators. Train steps negate for descent."""
    name = spec.name
    if name in ("DReG", "STL"):
        return _dreg_stl_value_and_grad(spec, params, cfg, key, x, dreg=name == "DReG")
    if name == "PIWAE":
        return _piwae_value_and_grad(spec, params, cfg, key, x)

    def bound_fn(p):
        log_w, aux = model.log_weights_and_aux(p, cfg, key, x, spec.k)
        return est.bound_from_log_weights(spec, log_w, aux)

    return jax.value_and_grad(bound_fn)(params)


def _dreg_stl_value_and_grad(spec, params, cfg, key, x, dreg: bool):
    """One score-stopped forward; cotangent w~ (STL) or per-part w~/w~^2 (DReG).

    The IWAE bound's derivative wrt log w_i is ``w~_i / B``; DReG replaces the
    encoder's with ``w~_i^2 / B`` on the score-stopped graph.
    """
    B = x.shape[0]

    def log_w_fn(p):
        return model.log_weights(p, cfg, key, x, spec.k, stop_q_score=True)

    log_w, vjp = jax.vjp(log_w_fn, params)
    w_tilde = _normalized_weights(log_w)
    bound = est.iwae_bound(log_w)

    if not dreg:
        (grads,) = vjp(w_tilde / B)
        return bound, grads

    (g_enc,) = vjp(jnp.square(w_tilde) / B)
    (g_dec,) = vjp(w_tilde / B)
    return bound, _select(g_enc, g_dec, take_enc_from_a=True)


def _piwae_value_and_grad(spec, params, cfg, key, x):
    """Encoder grad from MIWAE(k1,k2), decoder grad from IWAE(k): one forward,
    two analytic cotangents on the shared log-weight graph."""
    B = x.shape[0]

    def log_w_fn(p):
        return model.log_weights(p, cfg, key, x, spec.k)

    log_w, vjp = jax.vjp(log_w_fn, params)
    bound = est.iwae_bound(log_w)

    # d IWAE / d log_w = softmax over the full k axis, / B.
    ct_dec = _normalized_weights(log_w) / B
    # d MIWAE / d log_w = softmax within each k1-group, / (k2 * B).
    k2 = spec.k2
    grouped = log_w.reshape(k2, spec.k // k2, *log_w.shape[1:])
    ct_enc = (jax.lax.stop_gradient(jax.nn.softmax(grouped, axis=1))
              .reshape(log_w.shape) / (k2 * B))

    (g_dec,) = vjp(ct_dec)
    (g_enc,) = vjp(ct_enc)
    return bound, _select(g_enc, g_dec, take_enc_from_a=True)
