"""Numerics layer: distributions and stable log-space reductions.

Replaces the reference's TFP dependency (flexible_IWAE.py:3,17) with two
closed-form log-probs and hand-rolled, streaming-capable logsumexp reductions.
"""

from iwae_replication_project_tpu.ops.distributions import (
    normal_log_prob,
    normal_sample,
    normal_kl_standard,
    bernoulli_log_prob,
    clamp_probs,
    PROB_CLAMP_SCALE,
    PROB_CLAMP_SHIFT,
    STD_FLOOR,
)
from iwae_replication_project_tpu.ops.logsumexp import (
    logmeanexp,
    logsumexp,
    online_logsumexp_init,
    online_logsumexp_update,
    online_logsumexp_finalize,
)

__all__ = [
    "normal_log_prob",
    "normal_sample",
    "normal_kl_standard",
    "bernoulli_log_prob",
    "clamp_probs",
    "PROB_CLAMP_SCALE",
    "PROB_CLAMP_SHIFT",
    "STD_FLOOR",
    "logmeanexp",
    "logsumexp",
    "online_logsumexp_init",
    "online_logsumexp_update",
    "online_logsumexp_finalize",
]
