"""Measured tile/remat autotuner for the hot loop (ROADMAP item 3).

The hand-picked ``(tk, tb)`` tiles in ops/hot_loop.py were chosen from VMEM
arithmetic, never searched: the estimate proves a tile *fits*, not that it
is *fast*, and the gap between those two is most of the ~7x headroom the
r05 train MFU (0.136 vs the bf16 roofline) leaves on the table. This module
searches the space the dispatcher actually selects from and persists what
it MEASURES:

* **search space** per kind —

  - ``fwd`` / ``bwd``: pallas ``(tk, tb)`` out-tiles of the blocked kernel
    (tk over sublane multiples, tb over the full batch + 128-lane
    multiples, filtered by ``tile_admissible`` + ``fits_vmem_block`` under
    the live ``_vmem_budget()``), plus — for ``fwd`` — the blocked-scan
    remat slabs and the reference composition, so the measured winner can
    overrule the pallas-first heuristic where XLA genuinely wins;
  - ``scan``: the remat slab height ``block_k`` of the blocked-scan
    fallback (the hand pick targets ~32 MiB of slab activations; the
    search measures the divisor ladder of k);
  - ``serving_row``: the row-vmapped serving composition at one
    (k, bucket) — per-row ``(tk, 1)`` pallas tiles, per-row scan slabs,
    and the reference path, exactly the menu
    ``hot_loop.serving_select_path`` chooses from.

* **ranking** — candidates are ordered by a static roofline prior
  (trace-only, analysis/audit/cost.py: ``max(flops/peak, bytes/bw)`` on
  the resolved chip) and decided by **measured wall time**: one probe
  compile per candidate (a compile failure discards the candidate, never
  crashes the search), one warm run, then best-of-``reps`` timed runs.
  Pallas candidates are only measured where they can run natively
  (``on_tpu``); interpret-mode timings would rank the interpreter, not the
  kernel, so off-TPU searches honestly exclude them.

* **persistence** — winners land in a versioned JSON cache *beside* the
  persistent XLA compilation cache (utils/compile_cache.resolve_cache_dir;
  override with ``IWAE_AUTOTUNE_CACHE``, memory-only when no cache dir is
  configured), keyed per (kind, shape, compute dtype, chip generation,
  VMEM budget). Tuning cost is paid once per fleet: a warm cache makes
  ``tune()`` a pure lookup — zero probe compiles, zero timed runs — and
  every replica's trace-time selection reads the same winners. A version
  bump, a budget change, or another chip generation simply misses (the
  hand-picked heuristics still stand); a *corrupt* cache warns loudly and
  falls back to the hand-picked tiles.

Consumers: ``hot_loop.kernel_usable_block`` (tile override),
``hot_loop._scan_block_k`` (remat override), ``hot_loop.select_path`` /
``serving_select_path`` (measured path choice). All consultation is
fail-soft — no cache, no behavior change.

CLI: ``iwae-autotune`` pre-tunes a bucket ladder offline (the fleet-warmup
companion to ``iwae-serve``'s AOT warmup); see ``main()``.

Telemetry (PR-4 registry): ``autotune/searches``, ``autotune/tune_cache_
hits``, ``autotune/probe_compiles``, ``autotune/probe_failures``,
``autotune/lookup_hits``, ``autotune/lookup_misses``, ``autotune/cache_
corrupt``, ``autotune/version_mismatch``; spans ``span/autotune/search``
and ``span/autotune/measure``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

#: bump when the record schema, the candidate space, or the measurement
#: methodology changes incompatibly: old winners must invalidate wholesale
#: (a tile measured under another methodology is not comparable)
AUTOTUNE_VERSION = 1

#: the winner-cache file, living beside the persistent XLA cache
CACHE_FILENAME = "autotune_cache.json"

KINDS = ("fwd", "bwd", "scan", "serving_row", "serving_int8")

#: tk candidates (sublane multiples) and tb candidates (lane multiples)
#: for the kernel tile search — superset of the hand-picked TILE_K=8 /
#: full-batch choices, bounded so a search stays tens of candidates
TK_CANDIDATES = (8, 16, 24, 32)
TB_PARTIAL_CANDIDATES = (128, 256, 384, 512)


# ---------------------------------------------------------------------------
# keys, store, persistence
# ---------------------------------------------------------------------------

def chip_kind() -> str:
    """Cache-key identity of the local accelerator generation (a winner
    measured on one chip must never rank candidates on another)."""
    try:
        import jax
        dev = jax.devices()[0]
        return str(getattr(dev, "device_kind", dev.platform))
    except Exception:
        return "unknown"


def _budget() -> int:
    from iwae_replication_project_tpu.ops.fused_likelihood import _vmem_budget
    return _vmem_budget()


def entry_key(kind: str, k: int, b: int, h1_dim: int, hid: int,
              n_pixels: int, compute_dtype, *, chip: Optional[str] = None,
              vmem_budget: Optional[int] = None) -> str:
    """The JSON-cache key: kind + shape + compute dtype + chip generation +
    VMEM budget. Everything that changes which candidate WOULD win must be
    in here — the satellite tests pin that budget/chip/version drift each
    invalidate independently."""
    if kind not in KINDS:
        raise ValueError(f"unknown autotune kind {kind!r}; choose {KINDS}")
    cd = "f32" if compute_dtype in (None, "None", "float32") \
        else str(compute_dtype)
    chip = chip if chip is not None else chip_kind()
    budget = vmem_budget if vmem_budget is not None else _budget()
    return (f"{kind}|k={int(k)}|b={int(b)}|h1={int(h1_dim)}|hid={int(hid)}"
            f"|d={int(n_pixels)}|dt={cd}|chip={chip}|vmem={int(budget)}")


def cache_path(explicit: Optional[str] = None) -> Optional[str]:
    """Where the winner cache lives: explicit > ``IWAE_AUTOTUNE_CACHE`` env
    > ``<persistent-XLA-cache-dir>/autotune_cache.json`` > None (memory-
    only — tuning still works, winners just die with the process)."""
    if explicit is not None:
        return explicit
    env = os.environ.get("IWAE_AUTOTUNE_CACHE")
    if env:
        return None if env.strip().lower() in ("off", "none", "0") else env
    from iwae_replication_project_tpu.utils.compile_cache import (
        resolve_cache_dir)
    base = resolve_cache_dir()
    return os.path.join(base, CACHE_FILENAME) if base else None


def _count(name: str, n: float = 1) -> None:
    from iwae_replication_project_tpu.telemetry.registry import get_registry
    get_registry().counter(f"autotune/{name}").inc(n)


#: process-level store: {"path": resolved path, "entries": {key: record}}
_store: Dict[str, Any] = {"path": None, "entries": None}


def _load_entries(path: Optional[str]) -> Dict[str, dict]:
    """Read + validate the winner file. A missing file or a version
    mismatch is an ordinary (silent-ish) miss; a CORRUPT file is loud —
    the operator must know their paid-for tuning evaporated — and falls
    back to the hand-picked tiles (an empty store)."""
    if path is None or not os.path.exists(path):
        return {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or "entries" not in doc \
                or not isinstance(doc["entries"], dict):
            raise ValueError("not an autotune cache document")
    except Exception as e:
        import warnings
        _count("cache_corrupt")
        warnings.warn(
            f"autotune cache {path!r} is corrupt ({type(e).__name__}: "
            f"{str(e)[:200]}); falling back to the hand-picked tiles — "
            f"re-run iwae-autotune to rebuild it", RuntimeWarning,
            stacklevel=3)
        return {}
    if doc.get("version") != AUTOTUNE_VERSION:
        _count("version_mismatch")
        return {}
    return dict(doc["entries"])


def get_store(path: Optional[str] = None) -> Dict[str, dict]:
    """The loaded winner entries (lazily read once per resolved path)."""
    p = cache_path(path)
    if _store["entries"] is None or _store["path"] != p:
        _store["entries"] = _load_entries(p)
        _store["path"] = p
    return _store["entries"]


def reload_store() -> None:
    """Drop the in-memory store so the next lookup re-reads disk (tests,
    and operators who re-tuned in another process)."""
    _store["entries"] = None
    _store["path"] = None


def _save_store(path: str, entries: Dict[str, dict]) -> None:
    """Atomic write (tmp + rename): a reader never sees a torn file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    doc = {"version": AUTOTUNE_VERSION, "entries": entries}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def winner_for(kind: str, k: int, b: int, h1_dim: int, hid: int,
               n_pixels: int, compute_dtype,
               path: Optional[str] = None) -> Optional[dict]:
    """The persisted winner record for this exact (kind, shape, dtype,
    chip, budget), or None — hot_loop's trace-time consultation point."""
    entries = get_store(path)
    if not entries:
        return None
    rec = entries.get(entry_key(kind, k, b, h1_dim, hid, n_pixels,
                                compute_dtype))
    _count("lookup_hits" if rec is not None else "lookup_misses")
    return rec


# ---------------------------------------------------------------------------
# candidates
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Candidate:
    """One point of the search space: a path plus its tuning parameter."""

    path: str                              # pallas | blocked_scan | reference
    tile: Optional[Tuple[int, int]] = None  # pallas only
    block_k: Optional[int] = None           # blocked_scan only
    estimated_ms: Optional[float] = None    # the static roofline prior

    def label(self) -> str:
        if self.path == "pallas":
            return f"pallas{self.tile}"
        if self.path == "blocked_scan":
            return f"blocked_scan(bk={self.block_k})"
        if self.path == "int8":
            return "int8(weight-only)"
        return "reference"


def _pallas_tiles(k: int, b: int, h1_dim: int, hid: int, n_pixels: int,
                  grad: bool) -> List[Tuple[int, int]]:
    from iwae_replication_project_tpu.ops.hot_loop import (
        fits_vmem_block,
        tile_admissible,
    )

    tks = sorted({t for t in TK_CANDIDATES if t <= max(k, 8)} | {min(8, k)})
    tbs = [b] + [t for t in TB_PARTIAL_CANDIDATES if t < b]
    out = []
    for tk in tks:
        for tb in tbs:
            if tile_admissible(tk, tb, k, b) and \
                    fits_vmem_block(tk, tb, h1_dim, hid, n_pixels,
                                    grad=grad):
                out.append((tk, tb))
    return out


def _scan_blocks(k: int) -> List[int]:
    from iwae_replication_project_tpu.utils.flops import largest_divisor_leq
    targets = {1, max(1, k // 8), max(1, k // 4), max(1, k // 2), k}
    return sorted({largest_divisor_leq(k, t) for t in targets})


def candidates_for(kind: str, k: int, b: int, h1_dim: int, hid: int,
                   n_pixels: int, *,
                   include_pallas: Optional[bool] = None) -> List[Candidate]:
    """Enumerate the admissible search space for one (kind, shape).

    `include_pallas` defaults to "is there a TPU" — pallas candidates are
    only worth MEASURING where the kernel runs natively (interpret-mode
    wall time ranks the interpreter, not the kernel). Forcing it True is
    for tests with injected measure functions.
    """
    if include_pallas is None:
        try:
            import jax
            include_pallas = any(d.platform == "tpu" for d in jax.devices())
        except Exception:
            include_pallas = False
    out: List[Candidate] = []
    if kind in ("fwd", "bwd"):
        if include_pallas:
            out += [Candidate("pallas", tile=t)
                    for t in _pallas_tiles(k, b, h1_dim, hid, n_pixels,
                                           grad=(kind == "bwd"))]
        if kind == "fwd":
            out += [Candidate("blocked_scan", block_k=bk)
                    for bk in _scan_blocks(k)]
            out.append(Candidate("reference"))
    elif kind == "scan":
        out += [Candidate("blocked_scan", block_k=bk)
                for bk in _scan_blocks(k)]
    elif kind == "serving_row":
        if include_pallas:
            out += [Candidate("pallas", tile=(tk, 1))
                    for (tk, _) in _pallas_tiles(k, 1, h1_dim, hid,
                                                 n_pixels, grad=False)]
        out += [Candidate("blocked_scan", block_k=bk)
                for bk in _scan_blocks(k)]
        out.append(Candidate("reference"))
    elif kind == "serving_int8":
        # the precision-admission race (ISSUE 16): the weight-only int8
        # row program vs the exact fp32 reference, both plain XLA, so the
        # verdict is measurable on any backend. The winner's path ("int8"
        # or "reference") IS hot_loop.serving_int8_admit's verdict.
        out.append(Candidate("int8"))
        out.append(Candidate("reference"))
    else:
        raise ValueError(f"unknown autotune kind {kind!r}; choose {KINDS}")
    return out


# ---------------------------------------------------------------------------
# candidate programs + measurement
# ---------------------------------------------------------------------------

def _operands(kind: str, k: int, b: int, h1_dim: int, hid: int,
              n_pixels: int, seed: int = 0):
    """Seeded random operands at the real shape (measured time must include
    real data movement, not zeros XLA might constant-fold)."""
    import jax.numpy as jnp

    rs = np.random.RandomState(seed)
    f32 = np.float32
    args = [jnp.asarray(rs.randn(k, b, h1_dim).astype(f32) * 0.5),
            jnp.asarray(rs.randn(h1_dim, hid).astype(f32) * 0.2),
            jnp.asarray(rs.randn(hid).astype(f32) * 0.1),
            jnp.asarray(rs.randn(hid, hid).astype(f32) * 0.2),
            jnp.asarray(rs.randn(hid).astype(f32) * 0.1),
            jnp.asarray(rs.randn(hid, n_pixels).astype(f32) * 0.2),
            jnp.asarray(rs.randn(n_pixels).astype(f32) * 0.1),
            jnp.asarray((rs.rand(b, n_pixels) > 0.5).astype(f32))]
    if kind in ("serving_row", "serving_int8"):
        # the row-vmapped composition: per-row [k, 1, .] latents and
        # [1, d] targets, vmapped over the b request rows
        args[0] = jnp.moveaxis(args[0], 1, 0)[:, :, None, :]  # [b, k, 1, h1]
        args[-1] = args[-1][:, None, :]                       # [b, 1, d]
    if kind == "serving_int8":
        # quantize OUTSIDE the measured program (production quantizes once
        # at engine load, so the timed program must read the int8 weights
        # from HBM, not quantize fp32 ones in-trace): the shared operand
        # tuple carries both weight forms — the fp32 block for the
        # reference leg, the quantized pytree for the int8 leg
        from iwae_replication_project_tpu.ops.hot_loop import (
            quantize_out_block)
        args.append(quantize_out_block(
            {"l1": {"w": args[1], "b": args[2]},
             "l2": {"w": args[3], "b": args[4]},
             "out": {"w": args[5], "b": args[6]}}))
    return tuple(args)


def _candidate_fn(kind: str, cand: Candidate, k: int, on_tpu: bool,
                  compute_dtype) -> Callable:
    """The jittable program of one candidate — the same implementations
    decoder_score dispatches to, at the same composition shape."""
    from iwae_replication_project_tpu.ops import hot_loop as hl

    cd = compute_dtype if compute_dtype not in ("None", "f32") else None

    if kind == "serving_int8":
        def per_row_q(h1, w1, b1, w2, b2, w3, b3, x, out_q):
            if cand.path == "int8":
                return hl.decoder_score_int8(out_q, x, h1)
            return hl._reference_impl(h1, w1, b1, w2, b2, w3, b3, x, cd)

        import jax
        return jax.vmap(per_row_q, in_axes=(0, None, None, None, None,
                                            None, None, 0, None))

    if kind == "serving_row":
        def per_row(h1, w1, b1, w2, b2, w3, b3, x):
            if cand.path == "pallas":
                return hl._fused_block_ll(h1, w1, b1, w2, b2, w3, b3, x,
                                          cand.tile[0], cand.tile[1],
                                          not on_tpu, cd)
            if cand.path == "blocked_scan":
                return hl._blocked_scan_impl(h1, w1, b1, w2, b2, w3, b3, x,
                                             block_k=cand.block_k,
                                             compute_dtype=cd)
            return hl._reference_impl(h1, w1, b1, w2, b2, w3, b3, x, cd)

        import jax
        return jax.vmap(per_row,
                        in_axes=(0, None, None, None, None, None, None, 0))

    def fwd(h1, w1, b1, w2, b2, w3, b3, x):
        if cand.path == "pallas":
            return hl._fused_block_ll(h1, w1, b1, w2, b2, w3, b3, x,
                                      cand.tile[0], cand.tile[1],
                                      not on_tpu, cd)
        if cand.path == "blocked_scan":
            return hl._blocked_scan_impl(h1, w1, b1, w2, b2, w3, b3, x,
                                         block_k=cand.block_k,
                                         compute_dtype=cd)
        return hl._reference_impl(h1, w1, b1, w2, b2, w3, b3, x, cd)

    if kind == "bwd":
        import jax

        def bwd(h1, w1, b1, w2, b2, w3, b3, x):
            def loss(*ps):
                return fwd(*ps, x).sum()
            return jax.grad(loss, argnums=(0, 1, 2, 3, 4, 5, 6))(
                h1, w1, b1, w2, b2, w3, b3)
        return bwd
    return fwd


def _static_prior_ms(fn: Callable, args: tuple) -> Optional[float]:
    """Trace-only roofline estimate (analysis/audit/cost.py) used to ORDER
    the search: ``max(flops/peak, fused_bytes/bandwidth)`` on the resolved
    chip. Strictly fail-soft — a prior the analyzer cannot produce leaves
    the candidate unordered (measured time still decides)."""
    try:
        import jax

        from iwae_replication_project_tpu.analysis.audit.cost import (
            CostAnalyzer, resolve_chip)
        from iwae_replication_project_tpu.utils.flops import (
            peak_flops_for_kind, peak_hbm_bytes_for_kind)

        closed = jax.make_jaxpr(fn)(*args)
        rec, _ = CostAnalyzer().analyze_jaxpr("autotune_candidate", closed)
        chip, _src = resolve_chip(None)
        peak, _ = peak_flops_for_kind(chip)
        bw, _ = peak_hbm_bytes_for_kind(chip)
        if not peak or not bw or not rec.flops:
            return None
        return 1e3 * max(rec.flops / peak, rec.bytes_accessed_fused / bw)
    except Exception:
        return None


def _measure_candidate(fn: Callable, args: tuple, reps: int
                       ) -> Optional[float]:
    """Probe-compile + best-of-`reps` wall ms; None when the candidate
    fails to compile (discarded, search continues)."""
    import jax

    jitted = jax.jit(fn)
    try:
        _count("probe_compiles")
        compiled = jitted.lower(*args).compile()
    except Exception:
        _count("probe_failures")
        return None
    # measuring completion wall time is this module's entire job: the
    # explicit block_until_ready syncs below are the measurement itself
    out = compiled(*args)
    jax.block_until_ready(out)
    walls = []
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(compiled(*args))
        walls.append(time.perf_counter() - t0)
    return 1e3 * min(walls)


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------

def tune(kind: str, k: int, b: int, h1_dim: int, hid: int, n_pixels: int, *,
         compute_dtype=None, reps: int = 3,
         include_pallas: Optional[bool] = None,
         measure: Optional[Callable] = None,
         path: Optional[str] = None, force: bool = False,
         save: bool = True) -> dict:
    """Search one (kind, shape) and persist the measured winner.

    A winner already cached for this exact key returns immediately
    (``result["cache"] == "hit"``) with ZERO probe compiles and zero timed
    runs — the once-per-fleet contract. `measure` injects the measurement
    function for tests ``(fn, args, reps) -> ms | None``; `force` re-tunes
    over an existing entry; `save=False` keeps the winner in-memory only.
    Returns the winner record (also what :func:`winner_for` will serve).
    """
    from iwae_replication_project_tpu.telemetry.spans import span

    key = entry_key(kind, k, b, h1_dim, hid, n_pixels, compute_dtype)
    entries = get_store(path)
    if not force and key in entries:
        _count("tune_cache_hits")
        return dict(entries[key], cache="hit")

    try:
        import jax
        on_tpu = any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        on_tpu = False
    measure = measure or _measure_candidate
    _count("searches")
    with span("autotune/search"):
        cands = candidates_for(kind, k, b, h1_dim, hid, n_pixels,
                               include_pallas=include_pallas)
        if not cands:
            raise ValueError(
                f"no admissible candidates for {kind} at k={k} b={b} "
                f"(pallas excluded off-TPU and no fallback in this kind)")
        args = _operands(kind, k, b, h1_dim, hid, n_pixels)
        for c in cands:
            c.estimated_ms = _static_prior_ms(
                _candidate_fn(kind, c, k, on_tpu, compute_dtype), args)
        # prior-ordered search (unpriored candidates keep their position
        # at the tail); measurement decides
        cands.sort(key=lambda c: (c.estimated_ms is None,
                                  c.estimated_ms or 0.0))
        measured = []
        with span("autotune/measure"):
            for c in cands:
                ms = measure(_candidate_fn(kind, c, k, on_tpu,
                                           compute_dtype), args, reps)
                if ms is not None:
                    measured.append((ms, c))
    if not measured:
        raise RuntimeError(
            f"autotune: every candidate failed to compile for {kind} at "
            f"k={k} b={b} h1={h1_dim} hid={hid} d={n_pixels}")
    best_ms, best = min(measured, key=lambda mc: mc[0])
    record = {
        "path": best.path,
        "tile": list(best.tile) if best.tile else None,
        "block_k": best.block_k,
        "measured_ms": round(best_ms, 4),
        "estimated_ms": (round(best.estimated_ms, 4)
                         if best.estimated_ms is not None else None),
        "candidates": len(cands),
        "measured_candidates": len(measured),
        "chip": chip_kind(),
        "vmem_budget": _budget(),
        "all_measured": [
            {"candidate": c.label(), "measured_ms": round(ms, 4),
             "estimated_ms": (round(c.estimated_ms, 4)
                              if c.estimated_ms is not None else None)}
            for ms, c in sorted(measured, key=lambda mc: mc[0])],
    }
    entries[key] = record
    p = cache_path(path)
    if save and p is not None:
        _save_store(p, entries)
    return dict(record, cache="tuned")


def dims_for_model(cfg) -> Tuple[int, int, int]:
    """``(h1_dim, hid, n_pixels)`` of a model's decoder output block —
    the same duck-typed derivation hot_loop.path_code_for_model uses."""
    L = len(cfg.n_hidden_enc)
    h1_dim = cfg.n_latent_dec[-2] if L >= 2 else cfg.n_latent_enc[-1]
    return h1_dim, cfg.n_hidden_dec[-1], cfg.x_dim


def tune_ladder(cfg, ks, buckets, *, train_batch: Optional[int] = None,
                kinds=("serving_row",), compute_dtype=None, reps: int = 3,
                include_pallas: Optional[bool] = None,
                path: Optional[str] = None, force: bool = False) -> List[dict]:
    """Pre-tune a serving bucket ladder (and optionally the train shapes)
    offline — the ``iwae-autotune`` CLI's engine. ``serving_row`` tunes the
    (k, bucket) grid; ``fwd``/``bwd``/``scan`` tune at (k, train_batch).
    Returns one summary row per tuned shape."""
    h1_dim, hid, n_pixels = dims_for_model(cfg)
    cd = None if compute_dtype in (None, "None") else compute_dtype
    rows = []
    for kind in kinds:
        if kind == "serving_row":
            shapes = [(k, bucket) for k in ks for bucket in buckets]
        else:
            if train_batch is None:
                raise ValueError(f"kind {kind!r} needs train_batch")
            shapes = [(k, train_batch) for k in ks]
        for k, b in shapes:
            t0 = time.perf_counter()
            rec = tune(kind, k, b, h1_dim, hid, n_pixels, compute_dtype=cd,
                       reps=reps, include_pallas=include_pallas, path=path,
                       force=force)
            rows.append({"kind": kind, "k": k, "b": b,
                         "wall_seconds": round(time.perf_counter() - t0, 3),
                         **rec})
    return rows


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    """``iwae-autotune``: pre-tune a bucket ladder offline, once per fleet.

    Winners persist beside the persistent XLA cache, so every replica that
    shares the cache directory (the fleet deployment shape) reads the same
    measured tiles at trace time with zero search cost.
    """
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="iwae-autotune", description=main.__doc__.splitlines()[0])
    ap.add_argument("--k", type=str, default="50",
                    help="comma-separated k values to tune (default: 50, "
                         "the paper's training k)")
    ap.add_argument("--buckets", type=str, default="1,2,4,8,16,32,64",
                    help="serving bucket ladder rungs (serving_row kind)")
    ap.add_argument("--kinds", type=str, default="serving_row",
                    help=f"comma-separated kinds from {KINDS} (train kinds "
                         f"fwd/bwd/scan tune at --train-batch)")
    ap.add_argument("--train-batch", dest="train_batch", type=int,
                    default=100,
                    help="batch for the fwd/bwd/scan kinds (default: the "
                         "paper config's 100)")
    ap.add_argument("--compute-dtype", dest="compute_dtype", type=str,
                    default=None, choices=["bfloat16", "float32"],
                    help="matmul operand dtype to tune for (default f32)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed runs per candidate (best-of)")
    ap.add_argument("--cache", type=str, default=None,
                    help="winner-cache path override (default: beside the "
                         "persistent XLA cache; IWAE_AUTOTUNE_CACHE env)")
    ap.add_argument("--force", action="store_true",
                    help="re-tune over existing cache entries")
    ap.add_argument("--include-pallas", dest="include_pallas",
                    action="store_true", default=None,
                    help="measure pallas candidates even off-TPU "
                         "(interpret mode — test/debug only, the timings "
                         "rank the interpreter)")
    args = ap.parse_args(argv)

    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline like every entry point — and the probe compiles
    # of the search itself should hit the persistent cache on a re-run
    setup_persistent_cache(base_dir=os.getcwd())

    from iwae_replication_project_tpu.models import ModelConfig

    cfg = ModelConfig.two_layer(likelihood="logits")
    ks = [int(v) for v in args.k.split(",") if v.strip()]
    buckets = [int(v) for v in args.buckets.split(",") if v.strip()]
    kinds = tuple(v.strip() for v in args.kinds.split(",") if v.strip())
    cd = None if args.compute_dtype in (None, "float32") else \
        args.compute_dtype
    t0 = time.perf_counter()
    rows = tune_ladder(cfg, ks, buckets, train_batch=args.train_batch,
                       kinds=kinds, compute_dtype=cd, reps=args.reps,
                       include_pallas=args.include_pallas, path=args.cache,
                       force=args.force)
    for row in rows:
        print(json.dumps(row))
    summary = {
        "metric": "iwae-autotune: measured tile/remat winners",
        "shapes_tuned": len(rows),
        "tuned": sum(1 for r in rows if r.get("cache") == "tuned"),
        "cache_hits": sum(1 for r in rows if r.get("cache") == "hit"),
        "cache_path": cache_path(args.cache),
        "chip": chip_kind(),
        "version": AUTOTUNE_VERSION,
        "wall_seconds": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(summary))
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
