"""Closed-form distribution numerics (no TFP dependency).

The reference relies on ``tfd.Normal``/``tfd.Bernoulli`` (flexible_IWAE.py:37,103).
Only two log-densities are ever needed, so they are implemented directly as pure
functions that XLA can fuse into the surrounding matmuls. Numerical-parity
constants from the reference:

* std floor ``1e-6`` added to the exp-activated scale head (flexible_IWAE.py:37)
* pixel-probability clamp ``p * (1 - 1e-6) + 1e-7`` (flexible_IWAE.py:102,126)
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

# Reference parity constants (flexible_IWAE.py:37,102).
STD_FLOOR = 1e-6
PROB_CLAMP_SCALE = 1.0 - 1e-6
PROB_CLAMP_SHIFT = 1e-7

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


def normal_sample(key: jax.Array, mu: jax.Array, std: jax.Array,
                  sample_shape: tuple = ()) -> jax.Array:
    """Reparameterized draw ``mu + std * eps`` (pathwise estimator, PDF p.5).

    `sample_shape` is prepended, matching ``tfd.Normal.sample(n)`` semantics used
    for the k-sample fan-out at flexible_IWAE.py:59.
    """
    shape = sample_shape + jnp.broadcast_shapes(jnp.shape(mu), jnp.shape(std))
    eps = jax.random.normal(key, shape, dtype=jnp.result_type(jnp.asarray(mu).dtype,
                                                              jnp.asarray(std).dtype))
    return mu + std * eps


def normal_log_prob(x: jax.Array, mu: jax.Array, std: jax.Array) -> jax.Array:
    """Elementwise diagonal-Normal log density."""
    z = (x - mu) / std
    return -0.5 * z * z - jnp.log(std) - _HALF_LOG_2PI


def standard_normal_log_prob(x: jax.Array) -> jax.Array:
    """log N(x; 0, 1) — the top-of-chain prior (flexible_IWAE.py:135-136)."""
    return -0.5 * x * x - _HALF_LOG_2PI


def normal_kl_standard(mu: jax.Array, std: jax.Array) -> jax.Array:
    """Closed-form KL(N(mu, std) || N(0, 1)), elementwise.

    The analytic oracle used by the reference's ``get_L_V1`` cross-check
    (flexible_IWAE.py:457): ``-0.5 * (1 + 2 log std - mu^2 - std^2)``.
    """
    return -0.5 * (1.0 + 2.0 * jnp.log(std) - mu * mu - std * std)


def clamp_probs(probs: jax.Array) -> jax.Array:
    """Reference pixel-probability clamp keeping Bernoulli log-probs finite."""
    return probs * PROB_CLAMP_SCALE + PROB_CLAMP_SHIFT


def bernoulli_log_prob(x: jax.Array, probs: jax.Array) -> jax.Array:
    """Elementwise Bernoulli log pmf with {0,1} or relaxed x in [0,1].

    ``x log p + (1-x) log(1-p)`` — the same expression TFP evaluates for float
    targets, which the reference applies to stochastically-binarized pixels too.
    Callers clamp `probs` first (see :func:`clamp_probs`).
    """
    return x * jnp.log(probs) + (1.0 - x) * jnp.log1p(-probs)


def bernoulli_log_prob_from_logits(x: jax.Array, logits: jax.Array) -> jax.Array:
    """Numerically-exact Bernoulli log pmf from logits.

    ``x*l - softplus(l)`` — avoids the sigmoid→log round-trip entirely. Used by
    the fast path; the clamped-probs form above exists for bitwise parity with
    the reference's sigmoid-output head (flexible_IWAE.py:94,102).
    """
    return x * logits - jax.nn.softplus(logits)
