"""Pallas TPU kernel: fused decoder-head matmul + Bernoulli log-likelihood.

The largest tensor in the whole model is the decoder's pixel-logit block
``[k, B, 784]`` (e.g. k=50, B=100 -> ~15.7 MB in f32). The reference
materializes it (twice: once as probs, once re-computed for the likelihood,
flexible_IWAE.py:101,125); even the fused XLA path spills it to HBM between the
matmul and the loglik reduction when fusion heuristics split them. This kernel
computes

    out[k, b] = sum_d [ x[b,d] * logits[k,b,d] - softplus(logits[k,b,d]) ]
    logits    = h1 @ W + bias

tile-by-tile entirely in VMEM: the logits tile never touches HBM. The matmul
rides the MXU; the loglik + masked pixel reduction ride the VPU; HBM traffic
drops from O(k*B*784) to O(k*B*H + B*784).

Tiling: the grid runs over the K (importance-sample) axis in slabs of
``TILE_K`` rows; K is zero-padded up to a multiple of TILE_K and the pixel axis
up to the next multiple of the 128-lane tile (784 -> 896). Trailing block dims equal the full array dims, which
satisfies the TPU (8, 128) tiling rules for any batch size. VMEM per step at
the flagship shape (K-slab 8, B=100, H=200): ~4.6 MB.

Uses the exact Bernoulli-from-logits form (ops.distributions.
bernoulli_log_prob_from_logits), i.e. the ``likelihood="logits"`` model mode.
Backward is a custom VJP with tile-local recompute (flash-attention-style):
``d logits = g * (x - sigmoid(logits))`` is rebuilt per slab, so the backward
never materializes the full logits tensor either; dW/db accumulate across the
sequential grid.

Falls back to interpret mode off-TPU (tests pin down parity with the unfused
XLA composition).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_K = 8    # K-slab height (sublane-aligned; Pallas requires multiples of 8)

#: usable scoped-VMEM budget (bytes) by device-generation substring of
#: ``jax.Device.device_kind``. 13M was measured on v5e (16M scoped-vmem limit;
#: batch 300 compiles at ~12.3M est, batch 400 fails at ~16.2M). Every current
#: TPU generation documents ~16 MB VMEM/core, so the same conservative margin
#: is the default; a generation measured to differ gets its own row. Shapes the
#: estimate mispredicts are caught by the probe-compile in `kernel_usable` —
#: a wrong row here costs a fallback, never a crash.
VMEM_BUDGETS = {"default": 13 * 1024 * 1024}


def _vmem_budget() -> int:
    """Scoped-VMEM budget for the local device generation.

    Override with ``IWAE_FUSED_VMEM_BUDGET`` (bytes) — also the lever for
    forcing the fallback path in tests."""
    import os
    env = os.environ.get("IWAE_FUSED_VMEM_BUDGET")
    if env:
        return int(env)
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # uninitialized backend etc. — be conservative
        return VMEM_BUDGETS["default"]
    for sub, budget in VMEM_BUDGETS.items():
        if sub != "default" and sub in kind:
            return budget
    return VMEM_BUDGETS["default"]


def fits_vmem(k: int, b: int, hdim: int, n_pixels: int,
              grad: bool = False, itemsize: int = 4) -> bool:
    """Whether the kernel's per-program VMEM working set fits at TILE_K.

    The K-slab cannot shrink below 8 (TPU sublane rule), so oversized shapes
    cannot be tiled smaller — they must fall back to the unfused XLA
    composition instead of failing to compile. Two gates use this (both via
    :func:`kernel_usable`, which adds a probe-compile safety net):

    * models/iwae.log_px_given_h checks the forward estimate and skips the
      kernel entirely when it cannot fit;
    * _fused_bwd checks the larger `grad=True` estimate (recomputed logits
      + x/g rows + dlogits slabs; batch 200 was observed to exceed scoped
      vmem at 17.7M) and swaps in the XLA backward while keeping the fused
      forward.

    `itemsize` is the *operand* element width in bytes and scales only the
    streamed input blocks (h/w/bias/x/g); the logits/dlogits tiles and the
    dh/dW/db accumulators are f32 regardless (the kernel computes with
    ``preferred_element_type=jnp.float32``), so those terms stay at 4 bytes.
    At itemsize=4 both formulas reduce exactly to the v5e-calibrated
    estimate. NOTE: today every caller passes f32 — ``mlp.dense_apply`` pins
    its output to f32 even under ``compute_dtype=bfloat16`` — so the
    itemsize<4 path is future-proofing for a bf16-operand kernel variant.
    """
    p_pad = _pixel_pad(n_pixels)
    tk = min(TILE_K, k)
    if grad:
        # f32: logits + dlogits + g_rows tiles, dh out, dW/db accumulators,
        # and the g cotangent block (always f32 — the kernel's out dtype,
        # matching _probe_compiles' arg construction)
        est = 4 * (3 * tk * b * p_pad + tk * b * hdim + hdim * p_pad + p_pad
                   + tk * b)
        # operand blocks: h, w, x
        est += itemsize * (tk * b * hdim + hdim * p_pad + b * p_pad)
    else:
        # f32: logits tile + out rows; operands: h, w, x
        est = 4 * (tk * b * p_pad + tk * b)
        est += itemsize * (tk * b * hdim + hdim * p_pad + b * p_pad)
    return est <= _vmem_budget()


_probe_cache: dict = {}


def kernel_usable(k: int, b: int, hdim: int, n_pixels: int, *,
                  grad: bool = False, interpret: bool = False,
                  dtype=jnp.float32) -> bool:
    """The production gate: analytic estimate + one probe compile per shape.

    The estimate is calibrated on v5e; on other generations it may mispredict
    in either direction. Saying "doesn't fit" when it would only costs the
    fused kernel's speedup; saying "fits" for a shape that fails to compile
    used to crash the enclosing jit. So the first time a shape passes the
    estimate, the kernel is AOT-compiled standalone (abstract args, no device
    data); a compile failure logs once and permanently falls back to the
    unfused composition for that shape. Interpret mode (CPU tests) has no
    scoped-VMEM limit — the estimate alone decides.

    `dtype` is the dtype of the streamed operands (``y``/w/bias/x — the probe
    compiles exactly that variant, and the cache keys on it).
    """
    from iwae_replication_project_tpu.utils.dtypes import byte_width

    dtype = jnp.dtype(dtype)
    if not fits_vmem(k, b, hdim, n_pixels, grad=grad,
                     itemsize=byte_width(dtype)):
        return False
    if interpret:
        return True
    # the effective budget is part of the key: a mid-process change to
    # IWAE_FUSED_VMEM_BUDGET must invalidate earlier probe verdicts, not
    # silently keep the decision made under the old budget (ADVICE r5)
    key = (k, b, hdim, n_pixels, grad, dtype.name, _vmem_budget())
    hit = _probe_cache.get(key)
    if hit is None:
        hit = _probe_compiles(k, b, hdim, n_pixels, grad, dtype)
        _probe_cache[key] = hit
    return hit


def _probe_compiles(k: int, b: int, hdim: int, n_pixels: int,
                    grad: bool, dtype) -> bool:
    import warnings
    s = jax.ShapeDtypeStruct
    args = (s((k, b, hdim), dtype), s((hdim, n_pixels), dtype),
            s((n_pixels,), dtype), s((b, n_pixels), dtype))
    if grad:
        fn = functools.partial(_bwd_pallas, interpret=False)
        # the cotangent arrives in f32 (the kernel's out dtype)
        args = args + (s((k, b), jnp.float32),)
    else:
        fn = functools.partial(_fwd_pallas, interpret=False)
    try:
        jax.jit(fn).lower(*args).compile()
        return True
    except Exception as e:  # scoped-vmem overflow, Mosaic layout limits, ...
        warnings.warn(
            f"fused-likelihood kernel failed to compile for shape "
            f"k={k} b={b} h={hdim} d={n_pixels} grad={grad} on "
            f"{jax.devices()[0].device_kind!r}; using the unfused XLA "
            f"composition for this shape ({type(e).__name__}: {str(e)[:200]})",
            RuntimeWarning, stacklevel=3)
        return False


def _pixel_pad(n_pixels: int) -> int:
    """Pixel axis padded up to the 128-lane TPU tile (784 -> 896)."""
    return ((n_pixels + 127) // 128) * 128


def _pad_axis(arr: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    size = arr.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return jnp.pad(arr, widths)


def _prep(h1, w, bias, x):
    k = h1.shape[0]
    tile_k = min(TILE_K, k)
    h1_p = _pad_axis(h1, 0, tile_k)
    p_pad = _pixel_pad(w.shape[-1])
    return h1_p, _pad_axis(w, 1, p_pad), _pad_axis(bias, 0, p_pad)[None], \
        _pad_axis(x, 1, p_pad), tile_k, p_pad


def _fwd_kernel(h_ref, w_ref, b_ref, x_ref, out_ref, *, n_pixels: int,
                p_pad: int):
    tk, b, hdim = h_ref.shape
    h2d = h_ref[:].reshape(tk * b, hdim)
    logits = jnp.dot(h2d, w_ref[:], preferred_element_type=jnp.float32)
    logits = logits + b_ref[:]
    x_rows = jnp.broadcast_to(x_ref[:][None], (tk, b, p_pad)).reshape(tk * b, p_pad)
    ll = x_rows * logits - jax.nn.softplus(logits)
    mask = lax.broadcasted_iota(jnp.int32, (1, p_pad), 1) < n_pixels
    out_ref[:] = jnp.sum(jnp.where(mask, ll, 0.0), axis=-1).reshape(tk, b)


def _fwd_pallas(h1, w, bias, x, *, interpret: bool) -> jnp.ndarray:
    k, b, hdim = h1.shape
    n_pixels = w.shape[-1]
    h1_p, w_p, bias_p, x_p, tile_k, p_pad = _prep(h1, w, bias, x)
    kp = h1_p.shape[0]
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, n_pixels=n_pixels, p_pad=p_pad),
        out_shape=jax.ShapeDtypeStruct((kp, b), jnp.float32),
        grid=(kp // tile_k,),
        in_specs=[
            pl.BlockSpec((tile_k, b, hdim), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hdim, p_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, p_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, p_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_k, b), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(h1_p, w_p, bias_p, x_p)
    return out[:k]


def _bwd_kernel(h_ref, w_ref, b_ref, x_ref, g_ref,
                dh_ref, dw_ref, db_ref, *, n_pixels: int, p_pad: int):
    """Slab-local recompute backward. Padded-K rows carry zero cotangent, so
    their recomputed dlogits vanish and the dW/db accumulation stays exact."""
    i = pl.program_id(0)
    tk, b, hdim = h_ref.shape
    h2d = h_ref[:].reshape(tk * b, hdim)
    logits = jnp.dot(h2d, w_ref[:], preferred_element_type=jnp.float32) + b_ref[:]
    x_rows = jnp.broadcast_to(x_ref[:][None], (tk, b, p_pad)).reshape(tk * b, p_pad)
    mask = lax.broadcasted_iota(jnp.int32, (1, p_pad), 1) < n_pixels
    # broadcast-then-collapse instead of reshape-to-[N,1] (Mosaic layout limit)
    g_rows = jnp.broadcast_to(g_ref[:][:, :, None],
                              (tk, b, p_pad)).reshape(tk * b, p_pad)
    dlogits = jnp.where(mask, g_rows * (x_rows - jax.nn.sigmoid(logits)), 0.0)
    dh_ref[:] = jnp.dot(dlogits, w_ref[:].T,
                        preferred_element_type=jnp.float32).reshape(tk, b, hdim)

    @pl.when(i == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    dw_ref[:] += jnp.dot(h2d.T, dlogits, preferred_element_type=jnp.float32)
    db_ref[:] += jnp.sum(dlogits, axis=0, keepdims=True)


def _bwd_pallas(h1, w, bias, x, g, *, interpret: bool):
    k, b, hdim = h1.shape
    n_pixels = w.shape[-1]
    h1_p, w_p, bias_p, x_p, tile_k, p_pad = _prep(h1, w, bias, x)
    kp = h1_p.shape[0]
    g_p = _pad_axis(g, 0, tile_k)
    dh, dw_p, db_p = pl.pallas_call(
        functools.partial(_bwd_kernel, n_pixels=n_pixels, p_pad=p_pad),
        out_shape=(
            jax.ShapeDtypeStruct((kp, b, hdim), jnp.float32),
            jax.ShapeDtypeStruct((hdim, p_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, p_pad), jnp.float32),
        ),
        grid=(kp // tile_k,),
        in_specs=[
            pl.BlockSpec((tile_k, b, hdim), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hdim, p_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, p_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, p_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_k, b), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((tile_k, b, hdim), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hdim, p_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, p_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(h1_p, w_p, bias_p, x_p, g_p)
    return dh[:k], dw_p[:, :n_pixels], db_p[0, :n_pixels]


def _reference_impl(h1, w, bias, x):
    """Unfused XLA composition — the fallback and the parity oracle."""
    logits = jnp.einsum("kbh,hd->kbd", h1, w) + bias
    ll = x[None] * logits - jax.nn.softplus(logits)
    return jnp.sum(ll, axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_bernoulli_ll(h1, w, bias, x, interpret: bool = False):
    """``log p(x | h1)`` summed over pixels, ``[k, B]``, logits never in HBM.

    Args: ``h1 [k,B,H]`` post-tanh decoder activations; ``w [H,D]``,
    ``bias [D]`` the decoder output layer; ``x [B,D]`` binary targets.
    `interpret` runs the kernel in interpreter mode (CPU tests).
    """
    return _fused_fwd(h1, w, bias, x, interpret)[0]


def _fused_fwd(h1, w, bias, x, interpret):
    out = _fwd_pallas(h1, w, bias, x, interpret=interpret)
    return out, (h1, w, bias, x)


def _bwd_reference(h1, w, bias, x, g):
    """Unfused XLA backward (same math as _bwd_kernel, materialized)."""
    logits = jnp.einsum("kbh,hd->kbd", h1, w) + bias
    dlogits = g[..., None] * (x[None] - jax.nn.sigmoid(logits))
    dh = jnp.einsum("kbd,hd->kbh", dlogits, w)
    dw = jnp.einsum("kbh,kbd->hd", h1, dlogits)
    db = jnp.sum(dlogits, axis=(0, 1))
    return dh, dw, db


def _fused_bwd(interpret, res, g):
    h1, w, bias, x = res
    k, b, hdim = h1.shape
    if kernel_usable(k, b, hdim, w.shape[-1], grad=True, interpret=interpret,
                     dtype=h1.dtype):
        dh, dw, db = _bwd_pallas(h1, w, bias, x, g, interpret=interpret)
    else:
        # backward working set over scoped-vmem budget (e.g. batch >= ~150):
        # keep the fused forward, let XLA schedule the backward
        dh, dw, db = _bwd_reference(h1, w, bias, x, g)
    return dh, dw, db, None  # no gradient for the binary targets


fused_bernoulli_ll.defvjp(_fused_fwd, _fused_bwd)
