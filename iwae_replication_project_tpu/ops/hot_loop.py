"""The K-sample hot loop, blocked over (k, batch) tiles.

ROADMAP item 4 / BENCH_r05: the whole training workload is the ``[k, B]``
log-weight inner loop, and the flagship train MFU sits at ~0.136 — an order
of magnitude under the bf16 roofline. The per-step hot path is

    encoder matmuls -> reparameterized K-sampling -> scoring
        (log p(x|h) + log p(h) - log q(h|x)) -> logsumexp reduction

and its FLOPs/bytes are dominated by the decoder *output block*: for the
2-layer flagship, ``h1 @ W1 -> tanh -> @ W2 -> tanh -> @ W3 -> Bernoulli``
is ~77% of all k-scaled matmul MACs and >90% of the activation bytes (the
``[k, B, 200]`` hiddens and the ``[k, B, 784]`` logits). The predecessor
kernel (ops/fused_likelihood.py) fused only the FINAL matmul of that block;
this module extends the fused region to the whole block and tiles it over
BOTH the k and batch axes, so shapes the k-only kernel had to reject (eval
batches >= ~300) stay fused.

Three selectable implementations of the same math, chosen per shape at trace
time by :func:`select_path` (``kernel_usable``-style: analytic VMEM estimate
under ops.fused_likelihood._vmem_budget, then one probe compile per shape):

* ``pallas``      — the blocked TPU kernel below: per (k-tile, batch-tile),
  all three matmuls ride the MXU with the intermediates living only in VMEM;
  the backward is a tile-local-recompute custom VJP (flash-attention-style)
  that rebuilds y1/y2/logits per tile and accumulates dW/db across the
  sequential grid. The backward tile is chosen independently of the forward
  (its working set is ~1.6x larger), and falls back to the XLA backward on
  its own when no tile fits. ``interpret=True`` runs the same kernel on CPU
  for the parity tests and the smoke gate.
* ``blocked_scan`` — the hand-blocked fallback wherever Pallas is
  unavailable: a ``lax.scan`` over k-slabs of the identical per-slab math
  under ``jax.checkpoint``, so the forward materializes only one slab of
  logits at a time and the backward *recomputes* per slab instead of saving
  the full ``[k, B, 784]`` tensor — the same remat/layout policy as the
  kernel, expressed in XLA.
* ``reference``   — the straight XLA composition (also the parity oracle).

Selection is recorded through the PR-4 telemetry registry: a ``kernel_path``
gauge (see :data:`PATH_CODES`), per-path counters ``kernel_path/<path>``,
and ``span/kernel/select/<path>`` spans timing the probe work — so bench and
serving rows can stamp which path actually ran.

Env levers (all read at trace/selection time):

* ``IWAE_HOT_LOOP_PATH`` — force ``pallas`` / ``blocked_scan`` /
  ``reference`` (default ``auto``);
* ``IWAE_HOT_LOOP_SCAN_BYTES`` — working-set threshold above which ``auto``
  prefers the blocked scan over the materializing reference composition when
  the kernel is unavailable (default 256 MiB off-TPU, disabled on TPU where
  HBM absorbs the reference path at r05 behavior);
* ``IWAE_FUSED_VMEM_BUDGET`` — shared with ops.fused_likelihood: the
  scoped-VMEM budget the tile estimates are held to.

Two later layers compose with the selection machinery here:

* **the serving gate** (:func:`serving_select_path`) — the serving programs
  (serving/programs.py) are row-vmapped per-request compositions, so their
  kernel shape is ``(k, 1)`` per row with the bucket as the vmap axis. The
  engine resolves the gate OUTSIDE the trace, once per (op, bucket, k),
  probe-compiling the actual row-vmapped kernel, and bakes the outcome into
  the dispatch config (``ModelConfig.hot_loop_path``/``hot_loop_tile``) so
  the traced program is deterministic and falls back to the reference
  (previously pinned) path whenever the probe rejects the shape;
* **the measured autotuner** (ops/autotune.py) — persisted per
  (shape, compute dtype, chip generation, VMEM budget) winners, consulted
  by :func:`kernel_usable_block` (tile override), :func:`_scan_block_k`
  (remat slab override), and :func:`serving_select_path` (path + tile).
  Consultation is passive and fail-soft: no winner cache, or a corrupt
  one, selects exactly what the hand-picked heuristics select today.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from iwae_replication_project_tpu.ops.fused_likelihood import (
    TILE_K,
    _pad_axis,
    _pixel_pad,
    _vmem_budget,
)
from iwae_replication_project_tpu.utils.flops import largest_divisor_leq

#: selection outcome -> the value of the ``kernel_path`` telemetry gauge
#: (numeric so the gauge exports through JSONL/TB/Prometheus like any scalar).
#: ``int8`` is the weight-only-quantized serving path (ISSUE 16): not a
#: selectable train path — only :func:`serving_int8_admit` routes to it.
PATH_CODES = {"reference": 0, "blocked_scan": 1, "pallas": 2, "int8": 3}

#: default auto-threshold (bytes) for preferring the blocked scan over the
#: materializing reference path off-TPU: the reference working set is
#: ~k*B*(2*hid + pixels) floats (two hiddens + logits); past 256 MiB the
#: one-shot composition starts to dominate host RSS on CPU eval chunks
_SCAN_BYTES_DEFAULT = 256 * 1024 * 1024

def _scan_threshold(on_tpu: bool) -> float:
    env = os.environ.get("IWAE_HOT_LOOP_SCAN_BYTES")
    if env:
        return float(env)
    return float("inf") if on_tpu else float(_SCAN_BYTES_DEFAULT)


# --------------------------------------------------------------------------
# Telemetry: which path ran (PR-4 registry)
# --------------------------------------------------------------------------

def _record_path(path: str) -> None:
    from iwae_replication_project_tpu.telemetry.registry import get_registry
    reg = get_registry()
    reg.counter(f"kernel_path/{path}").inc()
    reg.gauge("kernel_path").set(float(PATH_CODES[path]))


def selected_path_code() -> float:
    """Last selection recorded on the default registry (the gauge value).

    Last-write-wins across every shape the process traces — fine for a live
    gauge, WRONG for stamping rows (a jit-cache hit traces nothing, so the
    gauge may describe some other program). Rows stamp
    :func:`path_code_for_model` instead, which recomputes the deterministic
    selection for the row's own shape.
    """
    from iwae_replication_project_tpu.telemetry.registry import get_registry
    return get_registry().gauge("kernel_path").value


def path_code_for_model(cfg, k: int, batch: int, *, on_tpu: bool) -> float:
    """The PATH_CODES code :func:`decoder_score` selects for one model shape.

    Selection is a pure function of (shape, env, VMEM budget) with probe
    results cached, so recomputing it here matches what a trace of the same
    shape bakes in — without depending on trace ORDER the way the
    ``kernel_path`` gauge does (a jit-cache-hit dispatch traces nothing and
    would otherwise stamp whichever unrelated program traced last). A config
    carrying a ``hot_loop_path`` pin (the serving engines' dispatch configs)
    stamps the pin — that IS what the trace bakes in.
    `cfg` is duck-typed on the ModelConfig fields (ops/ must not import
    models/).
    """
    if not getattr(cfg, "fused_likelihood", False):
        return float(PATH_CODES["reference"])
    L = len(cfg.n_hidden_enc)
    h1_dim = cfg.n_latent_dec[-2] if L >= 2 else cfg.n_latent_enc[-1]
    cd = cfg.matmul_dtype
    path, _ = select_path(k, batch, h1_dim, cfg.n_hidden_dec[-1], cfg.x_dim,
                          on_tpu=on_tpu,
                          compute_dtype=None if cd is None
                          else jnp.dtype(cd).name,
                          force=getattr(cfg, "hot_loop_path", None),
                          force_tile=getattr(cfg, "hot_loop_tile", None))
    return float(PATH_CODES[path])


def path_counters() -> dict:
    """``{path: times selected}`` — bench/serving stamp this into their rows."""
    from iwae_replication_project_tpu.telemetry.registry import get_registry
    snap = get_registry().snapshot()["counters"]
    return {name.split("/", 1)[1]: int(v) for name, v in snap.items()
            if name.startswith("kernel_path/")}


# --------------------------------------------------------------------------
# VMEM accounting + tile selection
# --------------------------------------------------------------------------

def fits_vmem_block(tk: int, tb: int, h1_dim: int, hid: int, n_pixels: int,
                    grad: bool = False) -> bool:
    """Whether one (tk, tb) program of the 3-matmul kernel fits scoped VMEM.

    Counts the peak-live f32 tiles (operands stream in f32 today — see the
    itemsize note in ops.fused_likelihood.fits_vmem): the h tile, the y1/y2
    hiddens, the logits tile plus the broadcast x rows, and — under
    ``grad`` — the dlogits/dy tiles, the dh output, and the full dW/db
    accumulators. Deliberately conservative; :func:`kernel_usable_block`
    adds the probe-compile safety net for shapes this formula mispredicts.
    """
    p_pad = _pixel_pad(n_pixels)
    rows = tk * tb
    weights = h1_dim * hid + hid * hid + hid * p_pad + 2 * hid + p_pad
    if grad:
        # live tiles: h, dh (2*h1) + y1, y2, dy1, dy2 (4*hid)
        #             + logits, dlogits, x_rows (3*p_pad) + g
        est = 4 * (rows * (2 * h1_dim + 4 * hid + 3 * p_pad + 1)
                   + 2 * weights + tb * p_pad)
    else:
        # live tiles: h + y1, y2 + logits, x_rows + out
        est = 4 * (rows * (h1_dim + 2 * hid + 2 * p_pad + 1)
                   + weights + tb * p_pad)
    return est <= _vmem_budget()


def select_block(k: int, b: int, h1_dim: int, hid: int, n_pixels: int,
                 grad: bool = False) -> Optional[Tuple[int, int]]:
    """Largest (tk, tb) tile whose working set fits, or None.

    tk is the sublane dim of the ``[k, B]`` out tile -> multiples of 8 (or
    all of k when k < 8). tb is its LANE dim -> either the full batch (any
    size, Mosaic's full-dim exemption) or a multiple of 128; candidates run
    largest-first so the grid stays as coarse as the budget allows.
    """
    tk = min(TILE_K, k)
    # tb is the LANE dim of the [k, B] out/g tiles: a partial batch tile
    # must be a multiple of 128; the full batch may be any size (Mosaic's
    # full-dim exemption — the same rule the k-only predecessor leaned on).
    # The full batch (zero padding) goes first; partial tiles rank by TOTAL
    # padded rows, then by coarseness — a 384 tile that pads b=420 to 768
    # must lose to a 256 tile padding to 512 (padded rows are computed and
    # thrown away), not win on raw tile size.
    partial = sorted((m for m in (512, 384, 256, 128) if m < b),
                     key=lambda m: (b + (-b) % m, -m))
    for tb in [b] + partial:
        if fits_vmem_block(tk, tb, h1_dim, hid, n_pixels, grad=grad):
            return tk, tb
    return None


def tile_admissible(tk: int, tb: int, k: int, b: int) -> bool:
    """Mosaic-shape admissibility of a candidate ``(tk, tb)`` out-tile:
    tk is the sublane dim (multiples of 8, or all of k when k < 8), tb the
    lane dim (a multiple of 128, or >= the full batch — after padding a
    tb >= b tile IS the full dim, Mosaic's full-dim exemption). The one
    rule shared by the hand-picked heuristic, the autotuner's candidate
    generator, and the winner-cache validation below — a persisted tile
    from another version can never smuggle an un-tileable shape in."""
    if tk < 1 or tb < 1 or tk > max(k, 8):
        return False
    if tk % 8 != 0 and tk != k:
        return False
    if tb % 128 != 0 and tb < b:
        return False
    return True


def _autotune_winner(kind: str, k: int, b: int, h1_dim: int, hid: int,
                     n_pixels: int, compute_dtype) -> Optional[dict]:
    """Measured winner for this shape from the persistent autotune cache
    (ops/autotune.py), or None. Strictly fail-soft: selection must keep
    working — on the hand-picked heuristics — when the cache is absent,
    corrupt (autotune warns loudly itself), or the module cannot load."""
    try:
        from iwae_replication_project_tpu.ops import autotune
        return autotune.winner_for(kind, k, b, h1_dim, hid, n_pixels,
                                   compute_dtype)
    except Exception:
        return None


_probe_cache: dict = {}


def kernel_usable_block(k: int, b: int, h1_dim: int, hid: int, n_pixels: int,
                        *, grad: bool = False, interpret: bool = False,
                        compute_dtype=None) -> Optional[Tuple[int, int]]:
    """The production gate: tile estimate + one probe compile per shape.

    Returns the chosen (tk, tb) when the kernel is usable, else None. Same
    contract as ops.fused_likelihood.kernel_usable: a shape that passes the
    estimate but fails to compile (another chip generation, a Mosaic layout
    limit) warns once and permanently selects the fallback — never crashes
    the enclosing jit. Interpret mode (CPU tests) has no scoped-VMEM limit,
    so the estimate alone decides. The probe cache is keyed on the effective
    budget so a mid-process ``IWAE_FUSED_VMEM_BUDGET`` change re-probes.

    A measured autotune winner (ops/autotune.py) overrides the hand-picked
    tile when one is persisted for this exact shape/dtype/chip/budget — but
    only after re-validating admissibility and the live VMEM estimate, so a
    stale cache can at worst cost a fallback, never an oversized compile.
    """
    block = None
    win = _autotune_winner("bwd" if grad else "fwd", k, b, h1_dim, hid,
                           n_pixels, compute_dtype)
    if win is not None and win.get("path") == "pallas" and win.get("tile"):
        tk, tb = (int(v) for v in win["tile"])
        if tile_admissible(tk, tb, k, b) and \
                fits_vmem_block(tk, tb, h1_dim, hid, n_pixels, grad=grad):
            block = (tk, tb)
    if block is None:
        block = select_block(k, b, h1_dim, hid, n_pixels, grad=grad)
    if block is None:
        return None
    if interpret:
        return block
    key = (k, b, h1_dim, hid, n_pixels, grad, str(compute_dtype), block,
           _vmem_budget())
    hit = _probe_cache.get(key)
    if hit is None:
        hit = _probe_compiles(k, b, h1_dim, hid, n_pixels, grad,
                              compute_dtype, block)
        _probe_cache[key] = hit
    return block if hit else None


def _probe_compiles(k, b, h1_dim, hid, n_pixels, grad, compute_dtype,
                    block) -> bool:
    import warnings
    s = jax.ShapeDtypeStruct
    f32 = jnp.float32
    args = (s((k, b, h1_dim), f32), s((h1_dim, hid), f32), s((hid,), f32),
            s((hid, hid), f32), s((hid,), f32), s((hid, n_pixels), f32),
            s((n_pixels,), f32), s((b, n_pixels), f32))
    tk, tb = block
    if grad:
        fn = functools.partial(_bwd_pallas, tk=tk, tb=tb, interpret=False,
                               compute_dtype=compute_dtype)
        args = args + (s((k, b), f32),)
    else:
        fn = functools.partial(_fwd_pallas, tk=tk, tb=tb, interpret=False,
                               compute_dtype=compute_dtype)
    try:
        jax.jit(fn).lower(*args).compile()
        return True
    except Exception as e:  # scoped-vmem overflow, Mosaic layout limits, ...
        warnings.warn(
            f"hot-loop kernel failed to compile for shape k={k} b={b} "
            f"h1={h1_dim} hid={hid} d={n_pixels} grad={grad} tile={block} "
            f"on {jax.devices()[0].device_kind!r}; selecting the fallback "
            f"path for this shape ({type(e).__name__}: {str(e)[:200]})",
            RuntimeWarning, stacklevel=3)
        return False


# --------------------------------------------------------------------------
# The serving gate: the row-vmapped composition (ROADMAP item 3)
# --------------------------------------------------------------------------

def _probe_compiles_vmapped(k, rows, h1_dim, hid, n_pixels, compute_dtype,
                            block) -> bool:
    """One probe compile of the ROW-VMAPPED forward kernel — the actual
    Mosaic composition the serving programs dispatch (`vmap` lifts the
    request axis into the pallas grid), which the unbatched probe in
    :func:`_probe_compiles` cannot vouch for."""
    import warnings
    s = jax.ShapeDtypeStruct
    f32 = jnp.float32
    tk, tb = block
    fn = functools.partial(_fwd_pallas, tk=tk, tb=tb, interpret=False,
                           compute_dtype=compute_dtype)
    vf = jax.vmap(fn, in_axes=(0, None, None, None, None, None, None, 0))
    args = (s((rows, k, 1, h1_dim), f32), s((h1_dim, hid), f32),
            s((hid,), f32), s((hid, hid), f32), s((hid,), f32),
            s((hid, n_pixels), f32), s((n_pixels,), f32),
            s((rows, 1, n_pixels), f32))
    try:
        jax.jit(vf).lower(*args).compile()
        return True
    except Exception as e:  # Mosaic batching limits, scoped-vmem overflow...
        warnings.warn(
            f"row-vmapped hot-loop kernel failed to compile for serving "
            f"shape k={k} rows={rows} h1={h1_dim} hid={hid} d={n_pixels} "
            f"tile={block} on {jax.devices()[0].device_kind!r}; serving "
            f"keeps the reference path for this bucket "
            f"({type(e).__name__}: {str(e)[:200]})",
            RuntimeWarning, stacklevel=3)
        return False


def serving_kernel_usable(k: int, rows: int, h1_dim: int, hid: int,
                          n_pixels: int, *, interpret: bool = False,
                          compute_dtype=None,
                          tile: Optional[Tuple[int, int]] = None
                          ) -> Optional[Tuple[int, int]]:
    """Probe gate for the serving composition: per-row ``(tk, 1)`` tiles,
    vmapped over `rows` requests. Same estimate-then-probe contract as
    :func:`kernel_usable_block` (probe cached per shape + budget; a compile
    failure warns once and permanently selects the fallback), with the
    probe compiling the *vmapped* kernel. `tile` proposes a (tk, 1) tile
    (an autotune winner); inadmissible proposals fall back to the default
    K-slab."""
    tk = None
    if tile is not None:
        t0, t1 = (int(v) for v in tile)
        if t1 == 1 and tile_admissible(t0, 1, k, 1):
            tk = t0
    if tk is None:
        tk = min(TILE_K, k)
    block = (tk, 1)
    if not fits_vmem_block(tk, 1, h1_dim, hid, n_pixels, grad=False):
        return None
    if interpret:
        return block
    key = ("serving", k, rows, h1_dim, hid, n_pixels, str(compute_dtype),
           block, _vmem_budget())
    hit = _probe_cache.get(key)
    if hit is None:
        hit = _probe_compiles_vmapped(k, rows, h1_dim, hid, n_pixels,
                                      compute_dtype, block)
        _probe_cache[key] = hit
    return block if hit else None


def serving_select_path(k: int, rows: int, h1_dim: int, hid: int,
                        n_pixels: int, *, on_tpu: bool, compute_dtype=None,
                        force: Optional[str] = None
                        ) -> Tuple[str, Optional[Tuple[int, int]]]:
    """``(path, tile_or_None)`` for the row-vmapped serving composition at
    one (bucket=`rows`, `k`).

    The serving engines call this OUTSIDE the trace — once per
    (op, bucket, k), results cached engine-side — and bake the outcome into
    the dispatch config (``ModelConfig.hot_loop_path``/``hot_loop_tile``),
    so program identity is deterministic, the AOT registry keys on it, and
    row stamps recompute it exactly. Order mirrors :func:`select_path`:
    force/env > persisted serving autotune winner > probe-gated pallas
    (TPU) > scan threshold over the whole-bucket working set > reference —
    where "reference" IS the previously pinned unfused program (the
    automatic-fallback contract: an ineligible shape serves exactly what
    PR 6 served).
    """
    from iwae_replication_project_tpu.telemetry.spans import span

    forced = (force or os.environ.get("IWAE_HOT_LOOP_PATH", "auto")).lower()
    if forced not in ("auto", "pallas", "blocked_scan", "reference"):
        source = "force argument" if force else "IWAE_HOT_LOOP_PATH"
        raise ValueError(
            f"{source}={forced!r}: expected auto | pallas | "
            f"blocked_scan | reference")
    if forced == "auto":
        win = _autotune_winner("serving_row", k, rows, h1_dim, hid,
                               n_pixels, compute_dtype)
        if win is not None:
            path = win.get("path")
            if path == "pallas" and on_tpu:
                # on_tpu guard mirrors select_path's auto rule: a pallas
                # winner (however it got into the cache) must never route
                # CPU production through the interpreter — off-TPU it
                # falls through to the hand-picked order below
                block = serving_kernel_usable(
                    k, rows, h1_dim, hid, n_pixels, interpret=False,
                    compute_dtype=compute_dtype, tile=win.get("tile"))
                if block is not None:
                    return "pallas", block
                # the winner no longer fits/compiles (budget or chip
                # drift): fall through to the hand-picked auto order
            elif path in ("blocked_scan", "reference"):
                return path, None
    if forced == "pallas" or (forced == "auto" and on_tpu):
        with span("kernel/select/serving"):
            block = serving_kernel_usable(k, rows, h1_dim, hid, n_pixels,
                                          interpret=not on_tpu,
                                          compute_dtype=compute_dtype)
        if block is not None:
            return "pallas", block
        if forced == "pallas":
            import warnings
            warnings.warn(
                f"serving hot-loop path forced to pallas but no tile fits "
                f"k={k} rows={rows} h1={h1_dim} hid={hid} d={n_pixels}; "
                f"using blocked_scan", RuntimeWarning, stacklevel=2)
            return "blocked_scan", None
    if forced == "blocked_scan":
        return "blocked_scan", None
    if forced == "reference":
        return "reference", None
    workset = 4.0 * k * rows * (2 * hid + n_pixels)
    if workset > _scan_threshold(on_tpu):
        return "blocked_scan", None
    return "reference", None


def serving_dispatch_config(cfg, k: int, rows: int, *, on_tpu: bool,
                            force: Optional[str] = None) -> tuple:
    """``(dispatch cfg, path, tile)``: resolve :func:`serving_select_path`
    for one model at one (k, rows) and bake the outcome into the config's
    ``hot_loop_path``/``hot_loop_tile`` pins — the ONE resolve-then-bake
    sequence shared by the fast serving engine, the sharded scorer, and
    the bench's direct-program legs, so the three can never drift. Every
    ineligible model (``likelihood != "logits"``), explicit reference
    force, and probe rejection returns `cfg` unchanged: the automatic
    fallback IS the previously pinned program. `cfg` is duck-typed on the
    ModelConfig fields (ops/ must not import models/); the pinned fields
    must exist on it (they do on ModelConfig)."""
    import dataclasses

    if getattr(cfg, "likelihood", None) != "logits" or force == "reference":
        return cfg, "reference", None
    from iwae_replication_project_tpu.ops.autotune import dims_for_model
    h1_dim, hid, n_pixels = dims_for_model(cfg)
    cd = cfg.matmul_dtype
    path, tile = serving_select_path(
        k, rows, h1_dim, hid, n_pixels, on_tpu=on_tpu,
        compute_dtype=None if cd is None else jnp.dtype(cd).name,
        force=force)
    if path == "reference":
        return cfg, "reference", None
    return dataclasses.replace(cfg, likelihood="logits",
                               fused_likelihood=True, hot_loop_path=path,
                               hot_loop_tile=tile), path, tile


# --------------------------------------------------------------------------
# The int8 weight-only serving path (ISSUE 16)
# --------------------------------------------------------------------------
#
# The ``int8`` precision policy quantizes the decoder output block's matmul
# WEIGHTS symmetric-per-output-channel at engine load: weights become int8
# with one fp32 scale per output channel, biases and activations stay fp32,
# and every matmul accumulates in fp32. The per-channel scale commutes with
# the row-times-matrix product (each output channel j is
# ``sum_i x[i] * w[i, j]``, uniformly scaled by ``scale[j]``), so dequantizing
# AFTER the matmul is exact up to the rounding already spent at quantization
# time. iwae-cost's roofline says the serving decoder is memory-bound at
# small buckets, so quartering weight bytes is the latency lever; whether it
# is an actual win on the running chip is decided by measurement — the
# ``serving_int8`` autotune kind via :func:`serving_int8_admit` — never
# assumed. Numerical acceptance is the statistical-parity contract
# (telemetry/parity.py), NOT bitwise parity: the quantized program is a
# different (lossy) function of the weights by construction.

def quantize_out_block(out_params) -> dict:
    """Weight-only symmetric per-output-channel int8 quantization of the
    decoder output block (``l1``/``l2``/``out`` dense layers).

    Each layer ``{"w": [in, out] f32, "b": [out] f32}`` becomes
    ``{"w_q": [in, out] int8, "scale": [out] f32, "b": [out] f32}`` with
    ``scale[j] = max(|w[:, j]|) / 127`` (an all-zero channel gets scale 1.0
    so the divide stays finite — its quantized column is exactly zero
    anyway) and ``w_q = clip(round(w / scale), -127, 127)``. Runs once at
    engine load, outside any trace.
    """
    def one(layer):
        w = jnp.asarray(layer["w"], jnp.float32)
        amax = jnp.max(jnp.abs(w), axis=0)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        w_q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        return {"w_q": w_q, "scale": scale,
                "b": jnp.asarray(layer["b"], jnp.float32)}

    return {name: one(out_params[name]) for name in ("l1", "l2", "out")}


def _dense_wq(x, layer):
    """Dense apply against one quantized layer: fp32 activations against the
    int8 weights with fp32 accumulation, per-output-channel dequant AFTER
    the matmul (exact — the scale is constant along the contraction)."""
    y = jnp.dot(x, layer["w_q"].astype(jnp.float32),
                preferred_element_type=jnp.float32)
    return y * layer["scale"] + layer["b"]


def decoder_score_int8(out_q, x, h1) -> jnp.ndarray:
    """``log p(x | h1)`` summed over pixels -> ``[k, B]`` through the
    quantized output block — the int8 twin of :func:`_reference_impl`
    (same composition, same logits-form Bernoulli reduction, fp32
    everywhere except the weight storage). `out_q` is the pytree
    :func:`quantize_out_block` built; `x` is ``[B, D]``, `h1` ``[k, B, H1]``.
    """
    _record_path("int8")
    y1 = jnp.tanh(_dense_wq(h1, out_q["l1"]))
    y2 = jnp.tanh(_dense_wq(y1, out_q["l2"]))
    logits = _dense_wq(y2, out_q["out"])
    ll = x[None] * logits - jax.nn.softplus(logits)
    return jnp.sum(ll, axis=-1)


_int8_admit_cache: dict = {}


def serving_int8_admit(k: int, rows: int, h1_dim: int, hid: int,
                       n_pixels: int, *, on_tpu: bool) -> Tuple[bool, str]:
    """``(admitted, reason)`` — may the int8-quantized program serve this
    (bucket=`rows`, `k`) shape?

    The measured-win contract of the tentpole: int8 ships only where the
    ``serving_int8`` autotune kind (ops/autotune.py) measured the quantized
    row program faster than the exact fp32 reference on THIS chip; anything
    else — measured slower, measurement failed, or no measurement possible
    (off-TPU with no persisted winner) — keeps the exact fp32 program, and
    the reason string says why (engines surface it in telemetry).
    ``IWAE_SERVING_INT8`` overrides: ``force`` admits unconditionally (how
    CPU CI exercises the quantized path), ``off`` rejects unconditionally,
    ``auto``/unset measures; any other value raises — the same
    loud-unknown-env contract as ``IWAE_HOT_LOOP_PATH``. Decisions are
    cached per (shape, env) for the engine's resolve-once discipline.
    """
    env = os.environ.get("IWAE_SERVING_INT8", "auto").lower()
    if env not in ("auto", "force", "off"):
        raise ValueError(f"IWAE_SERVING_INT8={env!r}: expected "
                         f"auto | force | off")
    if env == "force":
        return True, "forced via IWAE_SERVING_INT8=force"
    if env == "off":
        return False, "disabled via IWAE_SERVING_INT8=off"
    key = (k, rows, h1_dim, hid, n_pixels, on_tpu)
    hit = _int8_admit_cache.get(key)
    if hit is not None:
        return hit
    win = _autotune_winner("serving_int8", k, rows, h1_dim, hid, n_pixels,
                           None)
    if win is None and on_tpu:
        # no persisted verdict: measure now, once, fail-soft (a failed
        # search must degrade to the exact fp32 program, never crash
        # engine construction)
        try:
            from iwae_replication_project_tpu.ops import autotune
            win = autotune.tune("serving_int8", k, rows, h1_dim, hid,
                                n_pixels)
        except Exception:
            win = None
    if win is None:
        verdict = (False,
                   "autotune measurement failed; serving the exact fp32 "
                   "program" if on_tpu else
                   "no measured winner and not on TPU; int8 admission "
                   "requires a measured win (set IWAE_SERVING_INT8=force "
                   "to override)")
    elif win.get("path") == "int8":
        verdict = (True, f"measured faster than the fp32 reference "
                         f"({win.get('measured_ms')} ms)")
    else:
        verdict = (False, "measured slower than the fp32 reference at "
                          "this shape")
    _int8_admit_cache[key] = verdict
    return verdict


# --------------------------------------------------------------------------
# The blocked Pallas kernels
# --------------------------------------------------------------------------

def _maybe_cast(a, compute_dtype):
    return a if compute_dtype is None else a.astype(compute_dtype)


def _fwd_kernel(h_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref, x_ref,
                out_ref, *, n_pixels: int, p_pad: int, compute_dtype):
    tk, tb, h1_dim = h_ref.shape
    hid = w1_ref.shape[1]
    cast = functools.partial(_maybe_cast, compute_dtype=compute_dtype)
    h2d = h_ref[:].reshape(tk * tb, h1_dim)
    y1 = jnp.tanh(jnp.dot(cast(h2d), cast(w1_ref[:]),
                          preferred_element_type=jnp.float32) + b1_ref[:])
    y2 = jnp.tanh(jnp.dot(cast(y1), cast(w2_ref[:]),
                          preferred_element_type=jnp.float32) + b2_ref[:])
    logits = jnp.dot(cast(y2), cast(w3_ref[:]),
                     preferred_element_type=jnp.float32) + b3_ref[:]
    x_rows = jnp.broadcast_to(x_ref[:][None],
                              (tk, tb, p_pad)).reshape(tk * tb, p_pad)
    ll = x_rows * logits - jax.nn.softplus(logits)
    mask = lax.broadcasted_iota(jnp.int32, (1, p_pad), 1) < n_pixels
    out_ref[:] = jnp.sum(jnp.where(mask, ll, 0.0), axis=-1).reshape(tk, tb)


def _prep(h1, w3, b3, x, tk, tb):
    """Pad (k, batch, pixels) up to the tile grid; weights w1/w2/b1/b2 need
    no padding (their dims are full block dims)."""
    p_pad = _pixel_pad(w3.shape[-1])
    h1_p = _pad_axis(_pad_axis(h1, 0, tk), 1, tb)
    return (h1_p, _pad_axis(w3, 1, p_pad), _pad_axis(b3, 0, p_pad)[None],
            _pad_axis(_pad_axis(x, 0, tb), 1, p_pad), p_pad)


def _fwd_pallas(h1, w1, b1, w2, b2, w3, b3, x, *, tk: int, tb: int,
                interpret: bool, compute_dtype=None) -> jnp.ndarray:
    k, b, h1_dim = h1.shape
    hid = w1.shape[1]
    n_pixels = w3.shape[-1]
    h1_p, w3_p, b3_p, x_p, p_pad = _prep(h1, w3, b3, x, tk, tb)
    kp, bp = h1_p.shape[0], h1_p.shape[1]
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, n_pixels=n_pixels, p_pad=p_pad,
                          compute_dtype=compute_dtype),
        out_shape=jax.ShapeDtypeStruct((kp, bp), jnp.float32),
        grid=(kp // tk, bp // tb),
        in_specs=[
            pl.BlockSpec((tk, tb, h1_dim), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h1_dim, hid), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hid), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hid, hid), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hid), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hid, p_pad), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, p_pad), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, p_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tk, tb), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(h1_p, w1, b1[None], w2, b2[None], w3_p, b3_p, x_p)
    return out[:k, :b]


def _bwd_kernel(h_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref, x_ref,
                g_ref, dh_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, dw3_ref,
                db3_ref, *, n_pixels: int, p_pad: int, compute_dtype):
    """Tile-local recompute backward. Padded k/batch rows carry zero
    cotangent (g is zero-padded), so their dlogits vanish and every dW/db
    accumulation stays exact; padded pixels are masked out of dlogits."""
    i, j = pl.program_id(0), pl.program_id(1)
    tk, tb, h1_dim = h_ref.shape
    cast = functools.partial(_maybe_cast, compute_dtype=compute_dtype)
    h2d = h_ref[:].reshape(tk * tb, h1_dim)
    y1 = jnp.tanh(jnp.dot(cast(h2d), cast(w1_ref[:]),
                          preferred_element_type=jnp.float32) + b1_ref[:])
    y2 = jnp.tanh(jnp.dot(cast(y1), cast(w2_ref[:]),
                          preferred_element_type=jnp.float32) + b2_ref[:])
    logits = jnp.dot(cast(y2), cast(w3_ref[:]),
                     preferred_element_type=jnp.float32) + b3_ref[:]
    x_rows = jnp.broadcast_to(x_ref[:][None],
                              (tk, tb, p_pad)).reshape(tk * tb, p_pad)
    mask = lax.broadcasted_iota(jnp.int32, (1, p_pad), 1) < n_pixels
    # broadcast-then-collapse instead of reshape-to-[N,1] (Mosaic layout limit)
    g_rows = jnp.broadcast_to(g_ref[:][:, :, None],
                              (tk, tb, p_pad)).reshape(tk * tb, p_pad)
    dlogits = jnp.where(mask, g_rows * (x_rows - jax.nn.sigmoid(logits)), 0.0)
    dy2 = jnp.dot(cast(dlogits), cast(w3_ref[:]).T,
                  preferred_element_type=jnp.float32) * (1.0 - y2 * y2)
    dy1 = jnp.dot(cast(dy2), cast(w2_ref[:]).T,
                  preferred_element_type=jnp.float32) * (1.0 - y1 * y1)
    dh_ref[:] = jnp.dot(cast(dy1), cast(w1_ref[:]).T,
                        preferred_element_type=jnp.float32
                        ).reshape(tk, tb, h1_dim)

    @pl.when((i == 0) & (j == 0))
    def _():
        dw1_ref[:] = jnp.zeros_like(dw1_ref)
        db1_ref[:] = jnp.zeros_like(db1_ref)
        dw2_ref[:] = jnp.zeros_like(dw2_ref)
        db2_ref[:] = jnp.zeros_like(db2_ref)
        dw3_ref[:] = jnp.zeros_like(dw3_ref)
        db3_ref[:] = jnp.zeros_like(db3_ref)

    dw3_ref[:] += jnp.dot(cast(y2).T, cast(dlogits),
                          preferred_element_type=jnp.float32)
    db3_ref[:] += jnp.sum(dlogits, axis=0, keepdims=True)
    dw2_ref[:] += jnp.dot(cast(y1).T, cast(dy2),
                          preferred_element_type=jnp.float32)
    db2_ref[:] += jnp.sum(dy2, axis=0, keepdims=True)
    dw1_ref[:] += jnp.dot(cast(h2d).T, cast(dy1),
                          preferred_element_type=jnp.float32)
    db1_ref[:] += jnp.sum(dy1, axis=0, keepdims=True)


def _bwd_pallas(h1, w1, b1, w2, b2, w3, b3, x, g, *, tk: int, tb: int,
                interpret: bool, compute_dtype=None):
    k, b, h1_dim = h1.shape
    hid = w1.shape[1]
    n_pixels = w3.shape[-1]
    h1_p, w3_p, b3_p, x_p, p_pad = _prep(h1, w3, b3, x, tk, tb)
    kp, bp = h1_p.shape[0], h1_p.shape[1]
    g_p = _pad_axis(_pad_axis(g, 0, tk), 1, tb)
    wspec = lambda d0, d1: pl.BlockSpec((d0, d1), lambda i, j: (0, 0),
                                        memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, n_pixels=n_pixels, p_pad=p_pad,
                          compute_dtype=compute_dtype),
        out_shape=(
            jax.ShapeDtypeStruct((kp, bp, h1_dim), jnp.float32),
            jax.ShapeDtypeStruct((h1_dim, hid), jnp.float32),
            jax.ShapeDtypeStruct((1, hid), jnp.float32),
            jax.ShapeDtypeStruct((hid, hid), jnp.float32),
            jax.ShapeDtypeStruct((1, hid), jnp.float32),
            jax.ShapeDtypeStruct((hid, p_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, p_pad), jnp.float32),
        ),
        grid=(kp // tk, bp // tb),
        in_specs=[
            pl.BlockSpec((tk, tb, h1_dim), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            wspec(h1_dim, hid), wspec(1, hid), wspec(hid, hid), wspec(1, hid),
            wspec(hid, p_pad), wspec(1, p_pad),
            pl.BlockSpec((tb, p_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tk, tb), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((tk, tb, h1_dim), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            wspec(h1_dim, hid), wspec(1, hid), wspec(hid, hid), wspec(1, hid),
            wspec(hid, p_pad), wspec(1, p_pad),
        ),
        interpret=interpret,
    )(h1_p, w1, b1[None], w2, b2[None], w3_p, b3_p, x_p, g_p)
    dh, dw1, db1, dw2, db2, dw3, db3 = outs
    return (dh[:k, :b], dw1, db1[0], dw2, db2[0],
            dw3[:, :n_pixels], db3[0, :n_pixels])


# --------------------------------------------------------------------------
# Reference composition + blocked-scan fallback (identical math)
# --------------------------------------------------------------------------

def _dense(x, w, b, compute_dtype):
    """mlp.dense_apply's exact op sequence (re-stated locally: ops/ must not
    import models/) — bf16 operand casts with f32 accumulation when asked."""
    if compute_dtype is not None:
        y = jnp.dot(x.astype(compute_dtype), w.astype(compute_dtype),
                    preferred_element_type=jnp.float32)
    else:
        y = jnp.dot(x, w)
    return y + b


def _reference_impl(h1, w1, b1, w2, b2, w3, b3, x, compute_dtype=None):
    """Unfused XLA composition — the fallback tail and the parity oracle.

    Op-for-op the same sequence as models.mlp.output_block_apply followed by
    the logits-form Bernoulli reduction, so selecting ``reference`` is
    bitwise-identical to the pre-hot-loop unfused path.
    """
    y1 = jnp.tanh(_dense(h1, w1, b1, compute_dtype))
    y2 = jnp.tanh(_dense(y1, w2, b2, compute_dtype))
    logits = _dense(y2, w3, b3, compute_dtype).astype(jnp.float32)
    ll = x[None] * logits - jax.nn.softplus(logits)
    return jnp.sum(ll, axis=-1)


def _blocked_scan_impl(h1, w1, b1, w2, b2, w3, b3, x, *, block_k: int,
                       compute_dtype=None):
    """Hand-blocked scan over k-slabs with per-slab remat.

    Each slab runs the identical per-row math as :func:`_reference_impl`
    under ``jax.checkpoint``: the forward holds one ``[bk, B, 784]`` logits
    slab at a time and the backward recomputes it, mirroring the kernel's
    tile-local-recompute policy in plain XLA. Per-row results are the same
    dot products over the same operands, so slab blocking changes memory,
    not values.
    """
    k = h1.shape[0]
    bk = largest_divisor_leq(k, max(block_k, 1))

    @jax.checkpoint
    def slab(h_slab):
        return _reference_impl(h_slab, w1, b1, w2, b2, w3, b3, x,
                               compute_dtype)

    if bk == k:
        return slab(h1)
    out = lax.map(slab, h1.reshape(k // bk, bk, *h1.shape[1:]))
    return out.reshape(k, h1.shape[1])


def _scan_block_k(k: int, b: int, hid: int, n_pixels: int,
                  h1_dim: int = 0, compute_dtype=None) -> int:
    """Slab height targeting ~32 MiB of slab activations: big enough to keep
    the matmuls efficient, small enough that remat actually bounds memory.
    A persisted autotune winner for the scan kind (a measured remat point,
    ops/autotune.py) overrides the hand-picked target when present."""
    win = _autotune_winner("scan", k, b, h1_dim, hid, n_pixels,
                           compute_dtype)
    if win is not None and win.get("block_k"):
        bk = int(win["block_k"])
        if 1 <= bk <= k:
            return largest_divisor_leq(k, bk)
    per_k = b * (2 * hid + n_pixels) * 4
    return max(1, min(k, (32 * 1024 * 1024) // max(per_k, 1)))


# --------------------------------------------------------------------------
# Custom VJP over the pallas forward (backward tile chosen independently)
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11))
def _fused_block_ll(h1, w1, b1, w2, b2, w3, b3, x, tk, tb, interpret,
                    compute_dtype):
    return _fused_fwd(h1, w1, b1, w2, b2, w3, b3, x, tk, tb, interpret,
                      compute_dtype)[0]


def _fused_fwd(h1, w1, b1, w2, b2, w3, b3, x, tk, tb, interpret,
               compute_dtype):
    out = _fwd_pallas(h1, w1, b1, w2, b2, w3, b3, x, tk=tk, tb=tb,
                      interpret=interpret, compute_dtype=compute_dtype)
    return out, (h1, w1, b1, w2, b2, w3, b3, x)


def _bwd_reference(h1, w1, b1, w2, b2, w3, b3, x, g, compute_dtype):
    """XLA backward of the same composition (the over-budget fallback)."""
    def f(h1_, w1_, b1_, w2_, b2_, w3_, b3_):
        return _reference_impl(h1_, w1_, b1_, w2_, b2_, w3_, b3_, x,
                               compute_dtype)

    _, vjp = jax.vjp(f, h1, w1, b1, w2, b2, w3, b3)
    return vjp(g)


def _fused_bwd(tk, tb, interpret, compute_dtype, res, g):
    h1, w1, b1, w2, b2, w3, b3, x = res
    k, b, h1_dim = h1.shape
    block = kernel_usable_block(k, b, h1_dim, w1.shape[1], w3.shape[-1],
                                grad=True, interpret=interpret,
                                compute_dtype=compute_dtype)
    if block is not None:
        grads = _bwd_pallas(h1, w1, b1, w2, b2, w3, b3, x, g,
                            tk=block[0], tb=block[1], interpret=interpret,
                            compute_dtype=compute_dtype)
    else:
        # backward working set over the scoped-VMEM budget: keep the fused
        # forward, let XLA schedule the backward (materializes logits once)
        grads = _bwd_reference(h1, w1, b1, w2, b2, w3, b3, x, g,
                               compute_dtype)
    return grads + (None,)  # no gradient for the binary targets


_fused_block_ll.defvjp(_fused_fwd, _fused_bwd)


# --------------------------------------------------------------------------
# Selection + the public entry point
# --------------------------------------------------------------------------

def select_path(k: int, b: int, h1_dim: int, hid: int, n_pixels: int, *,
                on_tpu: bool, compute_dtype=None,
                force: Optional[str] = None,
                force_tile: Optional[Tuple[int, int]] = None
                ) -> Tuple[str, Optional[Tuple[int, int]]]:
    """``(path, pallas_block_or_None)`` for one hot-loop shape.

    Order: explicit `force` (callers that must trace ONE specific path —
    the program auditor enumerates all three without mutating the process
    env; the serving engines bake their probe-gated outcome in through the
    dispatch config) > env override > a persisted autotune winner for this
    shape (measured path choice, ops/autotune.py) > Pallas (probe-gated;
    interpret mode only when forced, so CPU production never pays the
    interpreter) > blocked scan when the materialized working set crosses
    the threshold > reference. Runs at trace time only — the choice is
    baked into the compiled program, so it can never cause a mid-run
    recompile. `force_tile` (only with ``force="pallas"``) pins the tile
    too, skipping re-selection and re-probing: the caller — the serving
    gate, whose probe covers the *vmapped* composition the inner probe
    cannot see — has already validated it.
    """
    from iwae_replication_project_tpu.telemetry.spans import span

    forced = (force or os.environ.get("IWAE_HOT_LOOP_PATH", "auto")).lower()
    if forced not in ("auto", "pallas", "blocked_scan", "reference"):
        source = "force argument" if force else "IWAE_HOT_LOOP_PATH"
        raise ValueError(
            f"{source}={forced!r}: expected auto | pallas | "
            f"blocked_scan | reference")
    if forced == "pallas" and force_tile is not None:
        tk, tb = (int(v) for v in force_tile)
        if not tile_admissible(tk, tb, k, b):
            raise ValueError(f"forced tile {(tk, tb)} is not admissible for "
                             f"shape k={k} b={b}")
        return "pallas", (tk, tb)
    if forced == "auto":
        # a measured winner decides the path outright (it was ranked by
        # wall time against the very alternatives below); pallas winners
        # still pass the probe gate via their tile in kernel_usable_block
        win = _autotune_winner("fwd", k, b, h1_dim, hid, n_pixels,
                               compute_dtype)
        if win is not None and win.get("path") in ("blocked_scan",
                                                   "reference"):
            return win["path"], None
    if forced == "pallas" or (forced == "auto" and on_tpu):
        with span("kernel/select/pallas"):
            block = kernel_usable_block(k, b, h1_dim, hid, n_pixels,
                                        grad=False, interpret=not on_tpu,
                                        compute_dtype=compute_dtype)
        if block is not None:
            return "pallas", block
        if forced == "pallas":
            import warnings
            warnings.warn(
                f"IWAE_HOT_LOOP_PATH=pallas but no tile fits shape "
                f"k={k} b={b} h1={h1_dim} hid={hid} d={n_pixels}; "
                f"using blocked_scan", RuntimeWarning, stacklevel=2)
            return "blocked_scan", None
    if forced == "blocked_scan":
        return "blocked_scan", None
    if forced == "reference":
        return "reference", None
    workset = 4.0 * k * b * (2 * hid + n_pixels)
    if workset > _scan_threshold(on_tpu):
        return "blocked_scan", None
    return "reference", None


def decoder_score(out_params, x, h1, *, compute_dtype=None,
                  on_tpu: bool = False,
                  force_path: Optional[str] = None,
                  force_tile: Optional[Tuple[int, int]] = None
                  ) -> jnp.ndarray:
    """``log p(x | h1)`` summed over pixels -> ``[k, B]``, hot-loop-blocked.

    `out_params` is the models.mlp output block pytree (``l1``/``l2``/``out``
    dense layers); `x` is ``[B, D]`` binary targets, `h1` the ``[k, B, H1]``
    bottom latent. The decoder intermediates (two ``[k, B, hid]`` hiddens
    and the ``[k, B, D]`` logits) never materialize at full k on the pallas
    and blocked-scan paths. Selection happens here, at trace time, and is
    recorded on the telemetry registry. `force_path` pins one implementation
    regardless of env/shape (the program auditor traces every path this way;
    the serving engines pin their probe-gated outcome through the dispatch
    config); `force_tile` additionally pins the pallas tile (the serving
    gate / autotuner already validated it — no re-probe inside the trace).
    Production train/eval callers leave both None.
    """
    w1, b1 = out_params["l1"]["w"], out_params["l1"]["b"]
    w2, b2 = out_params["l2"]["w"], out_params["l2"]["b"]
    w3, b3 = out_params["out"]["w"], out_params["out"]["b"]
    k, b, h1_dim = h1.shape
    hid = w1.shape[1]
    n_pixels = w3.shape[-1]
    cd = None if compute_dtype is None else jnp.dtype(compute_dtype).name
    path, block = select_path(k, b, h1_dim, hid, n_pixels, on_tpu=on_tpu,
                              compute_dtype=cd, force=force_path,
                              force_tile=force_tile)
    _record_path(path)
    if path == "pallas":
        return _fused_block_ll(h1, w1, b1, w2, b2, w3, b3, x,
                               block[0], block[1], not on_tpu, cd)
    if path == "blocked_scan":
        return _blocked_scan_impl(h1, w1, b1, w2, b2, w3, b3, x,
                                  block_k=_scan_block_k(k, b, hid, n_pixels,
                                                        h1_dim, cd),
                                  compute_dtype=cd)
    return _reference_impl(h1, w1, b1, w2, b2, w3, b3, x, cd)
