"""Stable log-space reductions, including a streaming (online) logsumexp.

The reference hand-rolls max-subtracted logmeanexp (flexible_IWAE.py:363-370) and
materializes full ``[k, B, 784]`` tensors even at k=5000 evaluation
(flexible_IWAE.py:463). Here the same reduction is also available as an *online*
recurrence (running max + rescaled sum — the online-softmax/ring-attention
trick), so large-k evaluation runs as a ``lax.scan`` over k-chunks with O(chunk)
memory, and as a *distributed* reduction over a sharded k axis (pmax + psum).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


def logsumexp(log_w: jax.Array, axis: int = 0) -> jax.Array:
    """Max-subtracted logsumexp along `axis`."""
    m = lax.stop_gradient(jnp.max(log_w, axis=axis, keepdims=True))
    m = jnp.where(jnp.isfinite(m), m, 0.0)  # all -inf column -> return -inf, not nan
    out = jnp.log(jnp.sum(jnp.exp(log_w - m), axis=axis)) + jnp.squeeze(m, axis=axis)
    return out


def logmeanexp(log_w: jax.Array, axis: int = 0) -> jax.Array:
    """``log mean exp`` along `axis` — the IWAE bound core (flexible_IWAE.py:368-369)."""
    n = log_w.shape[axis]
    return logsumexp(log_w, axis=axis) - jnp.log(float(n))


class OnlineLSE(NamedTuple):
    """Carry for the streaming logsumexp recurrence.

    `m` is the running max, `s` the sum of ``exp(x - m)`` seen so far, `n` the
    element count. Merging two states is associative, so the same update works
    for a `lax.scan` over chunks and for a tree/ring reduction over devices.
    """

    m: jax.Array
    s: jax.Array
    n: jax.Array


def online_logsumexp_init(shape, dtype=jnp.float32) -> OnlineLSE:
    return OnlineLSE(
        m=jnp.full(shape, -jnp.inf, dtype=dtype),
        s=jnp.zeros(shape, dtype=dtype),
        n=jnp.zeros((), dtype=jnp.int32),
    )


def online_logsumexp_update(state: OnlineLSE, log_w: jax.Array, axis: int = 0) -> OnlineLSE:
    """Fold a chunk of log-weights (reduced along `axis`) into the state."""
    chunk_m = jnp.max(log_w, axis=axis)
    new_m = jnp.maximum(state.m, chunk_m)
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    scaled_old = state.s * jnp.exp(state.m - safe_m)
    chunk_s = jnp.sum(jnp.exp(log_w - jnp.expand_dims(safe_m, axis)), axis=axis)
    return OnlineLSE(m=new_m, s=scaled_old + chunk_s,
                     n=state.n + jnp.int32(log_w.shape[axis]))


def online_logsumexp_merge(a: OnlineLSE, b: OnlineLSE) -> OnlineLSE:
    """Associative merge of two partial states (device-level reduction)."""
    new_m = jnp.maximum(a.m, b.m)
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    return OnlineLSE(
        m=new_m,
        s=a.s * jnp.exp(a.m - safe_m) + b.s * jnp.exp(b.m - safe_m),
        n=a.n + b.n,
    )


def online_logsumexp_finalize(state: OnlineLSE, mean: bool = False) -> jax.Array:
    safe_m = jnp.where(jnp.isfinite(state.m), state.m, 0.0)
    out = jnp.log(state.s) + safe_m
    if mean:
        out = out - jnp.log(state.n.astype(out.dtype))
    return out


class OnlineLSEVar(NamedTuple):
    """Augmented streaming-logsumexp carry: first AND second weight moments.

    Extends :class:`OnlineLSE` with ``s2 = sum(exp(2*(x - m)))`` — the
    second moment of the weights under the same running max — which is what
    a per-row standard-error / effective-sample-size estimate needs without
    ever materializing the weights:

    * ``ESS = s^2 / s2`` (Kong's effective sample size, in [1, n]);
    * ``SE[log p_hat] ~= sqrt((s2/s^2 - 1/n) * n/(n-1))`` (delta method on
      ``log mean(w)``; both ratios are scale-free, so the running max
      cancels exactly).

    The ``(m, s)`` arithmetic is kept expression-identical to
    :class:`OnlineLSE`'s update/merge, so a consumer that needs bitwise
    parity with the plain carry (the adaptive scorer's early-stopped-prefix
    contract) gets it by construction. Merging is associative, so the same
    state works for a scan over chunks and a psum over devices.
    """

    m: jax.Array
    s: jax.Array
    s2: jax.Array
    n: jax.Array


def online_lse_var_init(shape, dtype=jnp.float32) -> OnlineLSEVar:
    return OnlineLSEVar(
        m=jnp.full(shape, -jnp.inf, dtype=dtype),
        s=jnp.zeros(shape, dtype=dtype),
        s2=jnp.zeros(shape, dtype=dtype),
        n=jnp.zeros((), dtype=jnp.int32),
    )


def online_lse_var_update(state: OnlineLSEVar, log_w: jax.Array,
                          axis: int = 0) -> OnlineLSEVar:
    """Fold a chunk of log-weights into the augmented state. ``(m, s)``
    follow :func:`online_logsumexp_update` bit-for-bit; ``s2`` rescales by
    ``exp(2*(m_old - m_new))`` (squared-weight units)."""
    chunk_m = jnp.max(log_w, axis=axis)
    new_m = jnp.maximum(state.m, chunk_m)
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    scaled_old = state.s * jnp.exp(state.m - safe_m)
    chunk_s = jnp.sum(jnp.exp(log_w - jnp.expand_dims(safe_m, axis)), axis=axis)
    scaled_old2 = state.s2 * jnp.exp(2.0 * (state.m - safe_m))
    chunk_s2 = jnp.sum(jnp.exp(2.0 * (log_w - jnp.expand_dims(safe_m, axis))),
                       axis=axis)
    return OnlineLSEVar(m=new_m, s=scaled_old + chunk_s,
                        s2=scaled_old2 + chunk_s2,
                        n=state.n + jnp.int32(log_w.shape[axis]))


def online_lse_var_merge(a: OnlineLSEVar, b: OnlineLSEVar) -> OnlineLSEVar:
    """Associative merge of two augmented partial states."""
    new_m = jnp.maximum(a.m, b.m)
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    return OnlineLSEVar(
        m=new_m,
        s=a.s * jnp.exp(a.m - safe_m) + b.s * jnp.exp(b.m - safe_m),
        s2=a.s2 * jnp.exp(2.0 * (a.m - safe_m))
        + b.s2 * jnp.exp(2.0 * (b.m - safe_m)),
        n=a.n + b.n,
    )


def lse_var_stats(s: jax.Array, s2: jax.Array, n) -> tuple:
    """``(ess, se)`` from merged augmented-carry sums (scale-free: callers
    pass the max-subtracted ``s``/``s2`` directly; the running max cancels).

    ``ess = s^2/s2`` (1 when one weight dominates, n for uniform weights);
    ``se`` is the delta-method standard error of ``log mean(w)`` with the
    n/(n-1) small-sample correction. An all-``-inf`` row (``s == 0``) gets
    ``ess = 0`` and ``se = +inf`` — defined, never NaN, and never falsely
    converged.
    """
    n_f = jnp.asarray(n, s.dtype)
    safe_s = jnp.where(s > 0, s, 1.0)
    safe_s2 = jnp.where(s2 > 0, s2, 1.0)
    ess = jnp.where(s > 0, safe_s * safe_s / safe_s2, 0.0)
    bessel = n_f / jnp.maximum(n_f - 1.0, 1.0)
    var = jnp.maximum(safe_s2 / (safe_s * safe_s) - 1.0 / jnp.maximum(n_f, 1.0),
                      0.0) * bessel
    se = jnp.where(s > 0, jnp.sqrt(var), jnp.inf)
    return ess, se


def streaming_logmeanexp(log_w_fn, k: int, chunk: int, shape, dtype=jnp.float32) -> jax.Array:
    """``logmeanexp`` over k samples produced chunk-at-a-time by `log_w_fn(i)`.

    `log_w_fn(chunk_index)` must return a ``[chunk, *shape]`` block of
    log-weights. Memory is O(chunk), not O(k) — this is how k=5000 NLL
    evaluation (flexible_IWAE.py:463) fits on-chip.
    """
    if k % chunk != 0:
        raise ValueError(f"k={k} must be divisible by chunk={chunk}")
    n_chunks = k // chunk

    def body(state, i):
        return online_logsumexp_update(state, log_w_fn(i), axis=0), None

    init = online_logsumexp_init(shape, dtype)
    state, _ = lax.scan(body, init, jnp.arange(n_chunks))
    return online_logsumexp_finalize(state, mean=True)
