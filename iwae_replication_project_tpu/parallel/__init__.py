from iwae_replication_project_tpu.parallel.mesh import make_mesh, MeshAxes
from iwae_replication_project_tpu.parallel.dp import (
    make_parallel_epoch_fn,
    make_parallel_train_step,
    make_parallel_value_and_grad,
    shard_batch,
    distributed_logmeanexp,
)
from iwae_replication_project_tpu.parallel.auto import make_pjit_train_step
from iwae_replication_project_tpu.parallel import multihost

__all__ = [
    "make_mesh",
    "MeshAxes",
    "make_parallel_epoch_fn",
    "make_parallel_train_step",
    "make_parallel_value_and_grad",
    "shard_batch",
    "distributed_logmeanexp",
    "make_pjit_train_step",
    "multihost",
]
