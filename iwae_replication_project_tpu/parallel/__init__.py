from iwae_replication_project_tpu.parallel.mesh import make_mesh, MeshAxes
from iwae_replication_project_tpu.parallel.dp import (
    make_parallel_train_step,
    shard_batch,
    distributed_logmeanexp,
)
from iwae_replication_project_tpu.parallel.auto import make_pjit_train_step

__all__ = [
    "make_mesh",
    "MeshAxes",
    "make_parallel_train_step",
    "shard_batch",
    "distributed_logmeanexp",
    "make_pjit_train_step",
]
