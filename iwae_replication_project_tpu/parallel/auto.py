"""Compiler-sharded (pjit) training path.

The idiomatic modern alternative to the explicit shard_map step in
parallel.dp: annotate the batch ``P('dp')``, leave parameters replicated (these
MLPs are far below the size where tensor parallelism pays), and let XLA's SPMD
partitioner insert the gradient all-reduce. Useful both as a cross-check of the
explicit path (tests assert they match) and as the zero-boilerplate default.
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.parallel.mesh import AXES
from iwae_replication_project_tpu.training.train_step import make_train_step_fn


def make_pjit_train_step(spec: ObjectiveSpec, cfg: model.ModelConfig, mesh,
                         optimizer: optax.GradientTransformation | None = None,
                         donate: bool = True):
    """jit with in/out shardings: state replicated, batch sharded over dp.

    Returns ``(step, place_state, place_batch)`` — the placement helpers pin
    inputs to the mesh so XLA partitions instead of transferring.
    """
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(AXES.dp))
    step = jax.jit(make_train_step_fn(spec, cfg, optimizer),
                   in_shardings=(repl, batch_sh), out_shardings=(repl, repl),
                   donate_argnums=(0,) if donate else ())

    def place_state(state):
        return jax.device_put(state, repl)

    def place_batch(batch):
        return jax.device_put(batch, batch_sh)

    return step, place_state, place_batch
