"""Explicitly-sharded training: shard_map over a (dp, sp) mesh.

The train step runs SPMD: each device sees a ``[B/dp]`` batch shard and draws
``k/sp`` of the importance samples. Cross-device coupling is exactly two
collectives, both riding ICI:

* the **global logmeanexp** over the sharded k axis (`pmax` + `psum` over
  ``sp``) — the distributed form of the online-softmax recurrence in
  ops.logsumexp, and this framework's analog of ring-attention's streaming
  normalization;
* the **gradient reduction** (`psum` over ``sp``, `pmean` over ``dp``).

JAX differentiates the collectives, so one `jax.grad` of the collective-coupled
local bound yields the correct global gradient contributions.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import (
    ObjectiveSpec,
    estimators as est,
    objective_value_and_grad,
)
from iwae_replication_project_tpu.parallel.mesh import AXES
from iwae_replication_project_tpu.training.train_step import TrainState, make_adam

#: objectives whose bound decomposes over a sharded k axis via a global
#: logmeanexp / mean. L_median needs a global median (not shardable this way);
#: the gradient-estimator family would need globally-normalized cotangents.
SP_SHARDABLE = ("IWAE", "VAE", "CIWAE", "L_power_p", "MIWAE")


def distributed_logmeanexp(log_w_local: jax.Array, axis_name: str, k_global: int,
                           scale: float = 1.0) -> jax.Array:
    """``log mean exp(scale * log_w)`` over a k axis sharded on `axis_name`.

    Max-stabilized with a `pmax` of the per-shard max, then one `psum` of the
    rescaled partial sums — O(B) bytes over ICI regardless of k.
    """
    z = scale * log_w_local
    m = lax.stop_gradient(jnp.max(z, axis=0))
    m = lax.pmax(m, axis_name)
    s = lax.psum(jnp.sum(jnp.exp(z - m), axis=0), axis_name)
    return jnp.log(s) + m - jnp.log(float(k_global))


def _sharded_bound(spec: ObjectiveSpec, log_w_local: jax.Array, aux: dict,
                   k_global: int) -> jax.Array:
    """Per-device bound over (local batch, local k shard) with sp collectives."""
    name = spec.name
    if name == "VAE":
        # mean over global k: local sum / global k, psum'd
        return jnp.mean(lax.psum(jnp.sum(log_w_local, axis=0), AXES.sp) / k_global)
    if name == "IWAE":
        return jnp.mean(distributed_logmeanexp(log_w_local, AXES.sp, k_global))
    if name == "CIWAE":
        vae = jnp.mean(lax.psum(jnp.sum(log_w_local, axis=0), AXES.sp) / k_global)
        iwae = jnp.mean(distributed_logmeanexp(log_w_local, AXES.sp, k_global))
        return spec.beta * vae + (1.0 - spec.beta) * iwae
    if name == "L_power_p":
        z = distributed_logmeanexp(spec.p * log_w_local, AXES.sp, k_global)
        return jnp.mean(z / spec.p)
    if name == "MIWAE":
        # each device holds (k2/sp) whole k1-sample groups (sp | k2 checked at build)
        from iwae_replication_project_tpu.ops.logsumexp import logmeanexp
        grouped = log_w_local.reshape(-1, spec.k // spec.k2, *log_w_local.shape[1:])
        return jnp.mean(lax.pmean(jnp.mean(logmeanexp(grouped, axis=1), axis=0), AXES.sp))
    raise ValueError(f"objective {name!r} is not sample-parallel shardable; "
                     f"use sp=1 (supported: {SP_SHARDABLE})")


def shard_batch(mesh, batch: jax.Array) -> jax.Array:
    """Place a host batch with the leading axis sharded over dp, replicated over sp."""
    return jax.device_put(batch, NamedSharding(mesh, P(AXES.dp)))


def replicate(mesh, tree):
    return jax.device_put(tree, NamedSharding(mesh, P()))


def make_parallel_train_step(spec: ObjectiveSpec, cfg: model.ModelConfig, mesh,
                             optimizer: optax.GradientTransformation | None = None,
                             donate: bool = True):
    """Build the SPMD train step: ``(state, sharded_batch) -> (state, metrics)``.

    `state` is replicated; the batch is sharded ``P('dp')``. Each device folds
    its (dp, sp) coordinates into the RNG so sample draws are independent
    across both the batch shards and the k shards.
    """
    opt = optimizer if optimizer is not None else make_adam()
    n_sp = mesh.shape[AXES.sp]
    if n_sp > 1 and spec.name not in SP_SHARDABLE:
        raise ValueError(f"objective {spec.name!r} does not support sp>1")
    if spec.k % n_sp != 0:
        raise ValueError(f"sp={n_sp} must divide k={spec.k}")
    if spec.name == "MIWAE" and n_sp > 1 and spec.k2 % n_sp != 0:
        raise ValueError(f"MIWAE with sp={n_sp} needs sp | k2={spec.k2}")
    k_local = spec.k // n_sp

    def local_loss(params, key, x_local):
        log_w, aux = model.log_weights_and_aux(params, cfg, key, x_local, k_local)
        if n_sp == 1:
            return est.bound_from_log_weights(spec, log_w, aux)
        return _sharded_bound(spec, log_w, aux, spec.k)

    def spmd_step(state: TrainState, x_local):
        key, subkey = jax.random.split(state.key)
        # independent noise per (dp, sp) coordinate
        subkey = jax.random.fold_in(subkey, lax.axis_index(AXES.dp))
        subkey = jax.random.fold_in(subkey, lax.axis_index(AXES.sp))
        if n_sp == 1 and spec.name in ("DReG", "STL", "PIWAE"):
            # modified-gradient estimators: their custom VJP-cotangent path
            bound, grads = objective_value_and_grad(spec, state.params, cfg,
                                                    subkey, x_local)
        else:
            bound, grads = jax.value_and_grad(local_loss)(state.params, subkey, x_local)
        # sum sample-shard contributions, average batch shards
        grads = jax.tree.map(lambda g: lax.pmean(lax.psum(g, AXES.sp), AXES.dp), grads)
        bound = lax.pmean(bound, AXES.dp)
        neg_grads = jax.tree.map(jnp.negative, grads)
        updates, opt_state = opt.update(neg_grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": -bound, spec.name: -bound}
        return TrainState(params, opt_state, key, state.step + 1), metrics

    sharded = shard_map(
        spmd_step, mesh=mesh,
        in_specs=(P(), P(AXES.dp)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())
