"""Explicitly-sharded training: shard_map over a (dp, sp) mesh.

The train step runs SPMD: each device sees a ``[B/dp]`` batch shard and draws
``k/sp`` of the importance samples. Cross-device coupling is exactly two
collectives, both riding ICI:

* the **global logmeanexp** over the sharded k axis (`pmax` + `psum` over
  ``sp``) — the distributed form of the online-softmax recurrence in
  ops.logsumexp, and this framework's analog of ring-attention's streaming
  normalization;
* the **gradient reduction** (`psum` over ``sp``, `pmean` over ``dp``).

JAX differentiates the collectives, so one `jax.grad` of the collective-coupled
local bound yields the correct global gradient contributions.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import (
    ObjectiveSpec,
    estimators as est,
    objective_value_and_grad,
)
from iwae_replication_project_tpu.parallel.mesh import AXES, shard_map
from iwae_replication_project_tpu.training.train_step import TrainState, make_adam

#: every objective supports sp (k-axis) sharding. Most decompose via a global
#: logmeanexp / mean with O(B) collectives; L_median all_gathers the sharded k
#: axis (O(k*B) over ICI — the only estimator needing the full weight vector);
#: DReG/STL/PIWAE use globally-normalized softmax cotangents (one psum of the
#: per-shard denominators, _make_sharded_gradient_estimator).
SP_SHARDABLE = ("IWAE", "VAE", "VAE_V1", "L_alpha", "CIWAE", "L_power_p",
                "L_median", "MIWAE", "PIWAE", "DReG", "STL")


def distributed_logmeanexp(log_w_local: jax.Array, axis_name: str, k_global: int,
                           scale: float = 1.0) -> jax.Array:
    """``log mean exp(scale * log_w)`` over a k axis sharded on `axis_name`.

    Max-stabilized with a `pmax` of the per-shard max, then one `psum` of the
    rescaled partial sums — O(B) bytes over ICI regardless of k.
    """
    z = scale * log_w_local
    m = lax.stop_gradient(jnp.max(z, axis=0))
    m = lax.pmax(m, axis_name)
    s = lax.psum(jnp.sum(jnp.exp(z - m), axis=0), axis_name)
    return jnp.log(s) + m - jnp.log(float(k_global))


def _sharded_bound(spec: ObjectiveSpec, log_w_local: jax.Array, aux: dict,
                   k_global: int) -> jax.Array:
    """Per-device bound over (local batch, local k shard) with sp collectives."""
    name = spec.name
    if name == "VAE":
        # mean over global k: local sum / global k, psum'd
        return jnp.mean(lax.psum(jnp.sum(log_w_local, axis=0), AXES.sp) / k_global)
    if name == "IWAE":
        return jnp.mean(distributed_logmeanexp(log_w_local, AXES.sp, k_global))
    if name == "CIWAE":
        vae = jnp.mean(lax.psum(jnp.sum(log_w_local, axis=0), AXES.sp) / k_global)
        iwae = jnp.mean(distributed_logmeanexp(log_w_local, AXES.sp, k_global))
        return spec.beta * vae + (1.0 - spec.beta) * iwae
    if name == "L_power_p":
        z = distributed_logmeanexp(spec.p * log_w_local, AXES.sp, k_global)
        return jnp.mean(z / spec.p)
    if name == "MIWAE":
        # each device holds (k2/sp) whole k1-sample groups (sp | k2 checked at build)
        from iwae_replication_project_tpu.ops.logsumexp import logmeanexp
        grouped = log_w_local.reshape(-1, spec.k // spec.k2, *log_w_local.shape[1:])
        return jnp.mean(lax.pmean(jnp.mean(logmeanexp(grouped, axis=1), axis=0), AXES.sp))
    if name == "L_median":
        # the one estimator that needs the full per-example weight vector: one
        # all_gather over sp (shard order matches a single-device concat)
        lw_full = lax.all_gather(log_w_local, AXES.sp, axis=0, tiled=True)
        return est.median_bound(lw_full)
    if name == "L_alpha":
        recon = jnp.mean(
            lax.psum(jnp.sum(aux["log_px_given_h"], axis=0), AXES.sp) / k_global)
        vae = jnp.mean(lax.psum(jnp.sum(log_w_local, axis=0), AXES.sp) / k_global)
        return (1.0 - spec.alpha) * recon + spec.alpha * vae
    if name == "VAE_V1":
        # analytic KL is k-independent ([B, d] for the single-layer model this
        # oracle is defined on — multi-layer is rejected like est.vae_v1_bound);
        # only the recon MC average couples over sp
        q_mu, q_std = aux["q_last"]
        if q_mu.ndim != 2:
            raise ValueError(
                "VAE_V1 is single-stochastic-layer only (flexible_IWAE.py:433)")
        recon = jnp.mean(
            lax.psum(jnp.sum(aux["log_px_given_h"], axis=0), AXES.sp) / k_global)
        from iwae_replication_project_tpu.ops import distributions as dist
        kl = jnp.mean(jnp.sum(dist.normal_kl_standard(q_mu, q_std), axis=-1))
        return recon - kl
    raise ValueError(f"objective {name!r} is not sample-parallel shardable; "
                     f"use sp=1 (supported: {SP_SHARDABLE})")


def shard_batch(mesh, batch: jax.Array) -> jax.Array:
    """Place a host batch with the leading axis sharded over dp, replicated over sp."""
    n_dp = mesh.shape[AXES.dp]
    if batch.shape[0] % n_dp != 0:
        raise ValueError(
            f"batch size {batch.shape[0]} must be divisible by dp={n_dp}")
    return jax.device_put(batch, NamedSharding(mesh, P(AXES.dp)))


def replicate(mesh, tree):
    return jax.device_put(tree, NamedSharding(mesh, P()))


def _validate_sharding(spec: ObjectiveSpec, mesh, batch_size: int | None) -> Tuple[int, int]:
    """Build-time divisibility/support checks; returns ``(n_sp, k_local)``."""
    n_sp = mesh.shape[AXES.sp]
    n_dp = mesh.shape[AXES.dp]
    if n_sp > 1 and spec.name not in SP_SHARDABLE:
        raise ValueError(f"objective {spec.name!r} does not support sp>1")
    if spec.k % n_sp != 0:
        raise ValueError(f"sp={n_sp} must divide k={spec.k}")
    if spec.name in ("MIWAE", "PIWAE") and n_sp > 1 and spec.k2 % n_sp != 0:
        raise ValueError(f"{spec.name} with sp={n_sp} needs sp | k2={spec.k2}")
    if batch_size is not None and batch_size % n_dp != 0:
        raise ValueError(
            f"batch_size={batch_size} must be divisible by dp={n_dp}")
    return n_sp, spec.k // n_sp


def _make_sharded_gradient_estimator(spec: ObjectiveSpec, cfg: model.ModelConfig,
                                     n_sp: int, k_local: int):
    """DReG / STL / PIWAE with the k axis sharded over sp.

    These estimators prescribe explicit VJP cotangents built from the
    *globally* self-normalized weights ``w~ = softmax_k(log w)`` (see
    objectives/gradients.py for the single-device math). Under sp sharding the
    normalization needs exactly two collectives — a pmax of the per-shard
    maxima and a psum of the per-shard exp-sums — after which each device
    applies its local cotangent slice and the partial grads sum over sp.
    Returns ``(bound, grads)`` with grads ALREADY psum'd over sp (true
    partials, no transpose factor: the collectives sit on the stop_grad side).
    """
    from iwae_replication_project_tpu.objectives.gradients import _select

    k_global = spec.k

    def vg(params, subkey, x_local):
        B = x_local.shape[0]
        stop_q = spec.name in ("DReG", "STL")

        def log_w_fn(p):
            return model.log_weights(p, cfg, subkey, x_local, k_local,
                                     stop_q_score=stop_q)

        log_w, vjp = jax.vjp(log_w_fn, params)
        lw_sg = lax.stop_gradient(log_w)
        m = lax.pmax(jnp.max(lw_sg, axis=0), AXES.sp)
        e = jnp.exp(lw_sg - m)
        denom = lax.psum(jnp.sum(e, axis=0), AXES.sp)
        w_tilde = e / denom  # [k_local, B], globally normalized
        bound = jnp.mean(jnp.log(denom) + m - jnp.log(float(k_global)))

        if spec.name == "STL":
            (grads,) = vjp(w_tilde / B)
        elif spec.name == "DReG":
            (g_enc,) = vjp(jnp.square(w_tilde) / B)
            (g_dec,) = vjp(w_tilde / B)
            grads = _select(g_enc, g_dec, take_enc_from_a=True)
        else:  # PIWAE: decoder on IWAE(k), encoder on MIWAE(k1, k2)
            k2_local = spec.k2 // n_sp  # sp | k2 validated at build
            grouped = lw_sg.reshape(k2_local, k_local // k2_local,
                                    *lw_sg.shape[1:])
            ct_enc = (jax.nn.softmax(grouped, axis=1)
                      .reshape(lw_sg.shape) / (spec.k2 * B))
            (g_dec,) = vjp(w_tilde / B)
            (g_enc,) = vjp(ct_enc)
            grads = _select(g_enc, g_dec, take_enc_from_a=True)

        grads = jax.tree.map(lambda g: lax.psum(g, AXES.sp), grads)
        return bound, grads

    return vg


def _make_local_value_and_grad(spec: ObjectiveSpec, cfg: model.ModelConfig,
                               n_sp: int, k_local: int):
    """The per-device (bound, grads) computation, *including* the collectives.

    `subkey` must already be folded per-(dp, sp) coordinate. Outputs are
    replicated: grads are psum'd over sp (sample-shard contributions) and
    pmean'd over dp (batch-shard average); the bound is pmean'd over dp.
    """

    def local_loss(params, key, x_local):
        log_w, aux = model.log_weights_and_aux(params, cfg, key, x_local, k_local)
        if n_sp == 1:
            return est.bound_from_log_weights(spec, log_w, aux)
        return _sharded_bound(spec, log_w, aux, spec.k)

    sharded_estimator = (_make_sharded_gradient_estimator(spec, cfg, n_sp, k_local)
                         if spec.name in ("DReG", "STL", "PIWAE") and n_sp > 1
                         else None)

    def value_and_grad(params, subkey, x_local):
        if spec.name in ("DReG", "STL", "PIWAE"):
            if n_sp == 1:
                # modified-gradient estimators: their custom VJP-cotangent path
                bound, grads = objective_value_and_grad(spec, params, cfg,
                                                        subkey, x_local)
            else:
                # sharded cotangents; grads arrive already psum'd over sp
                bound, grads = sharded_estimator(params, subkey, x_local)
        else:
            bound, grads = jax.value_and_grad(local_loss)(params, subkey, x_local)
            # Under shard_map, transpose(psum) = psum: differentiating the
            # sp-coupled loss (whose value psums/all_gathers over sp) hands
            # every device a cotangent that is already summed over sp, i.e.
            # each local grad is n_sp x its true partial. pmean over sp (NOT
            # psum) therefore recovers the exact sum of partials. Verified
            # numerically against a matched-RNG single-device reference in
            # tests/test_parallel.py.
        grads = jax.tree.map(lambda g: lax.pmean(g, AXES.sp), grads)
        # dp is uncoupled in-value: plain batch-shard average
        grads = jax.tree.map(lambda g: lax.pmean(g, AXES.dp), grads)
        bound = lax.pmean(bound, AXES.dp)
        return bound, grads

    return value_and_grad


def _fold_axis_coords(key: jax.Array) -> jax.Array:
    """Independent noise per (dp, sp) mesh coordinate."""
    key = jax.random.fold_in(key, lax.axis_index(AXES.dp))
    return jax.random.fold_in(key, lax.axis_index(AXES.sp))


def make_parallel_value_and_grad(spec: ObjectiveSpec, cfg: model.ModelConfig,
                                 mesh, batch_size: int | None = None):
    """``(params, key, sharded_batch) -> (bound, grads)``, both replicated.

    The exact collective composition the train step uses, exposed standalone so
    tests can assert numeric equivalence against a single-device reference that
    folds the same (dp, sp) indices into the same key (tests/test_parallel.py).
    """
    n_sp, k_local = _validate_sharding(spec, mesh, batch_size)
    vg = _make_local_value_and_grad(spec, cfg, n_sp, k_local)

    def spmd_vg(params, key, x_local):
        return vg(params, _fold_axis_coords(key), x_local)

    return jax.jit(shard_map(
        spmd_vg, mesh=mesh,
        in_specs=(P(), P(), P(AXES.dp)),
        out_specs=(P(), P()),
        check_vma=False,
    ))


def make_parallel_epoch_fn(spec: ObjectiveSpec, cfg: model.ModelConfig, mesh,
                           n_train: int, batch_size: int,
                           stochastic_binarization: bool = False,
                           optimizer: optax.GradientTransformation | None = None,
                           shuffle: bool = True, donate: bool = True,
                           epochs_per_call: int = 1,
                           diagnostics=None):
    """Whole-epoch training under the mesh: ONE dispatch per data pass.

    The single-device path already runs each epoch as one `lax.scan`
    (training/epoch.py) because per-step Python dispatch dominates at this
    model scale; this is the same design *inside* shard_map, so multi-chip
    training keeps that property instead of regressing to per-batch dispatch.

    `x_train` is replicated (MNIST-scale data is far below HBM limits; a
    replicated store makes the reference's *global* shuffle semantics exact —
    every device computes the same permutation from the same key and gathers
    its own batch slice locally, no collectives in the data path). Stochastic
    binarization is keyed per (batch, dp) but NOT per sp, so all k-shards of a
    sample see the same binarized pixels, exactly like the host pipeline.

    Returns ``epoch(state, x_train_replicated) -> (state, per-batch losses)``.
    ``epochs_per_call > 1`` scans that many consecutive epochs inside the one
    dispatch (losses concatenated), exactly like training/epoch.py.

    `diagnostics` (a telemetry DiagnosticsConfig) mirrors the single-device
    contract: the second return value becomes ``(losses, grad-SNR scalars)``.
    The grads `vg` yields are already globally reduced (psum over sp, pmean
    over dp), so the windowed moment accumulators are replicated and the SNR
    scalars come out identical on every device — out_specs P().
    """
    from iwae_replication_project_tpu.telemetry.diagnostics import (
        grad_accum_init, grad_accum_update, grad_snr_summary)

    opt = optimizer if optimizer is not None else make_adam()
    n_sp, k_local = _validate_sharding(spec, mesh, batch_size)
    n_dp = mesh.shape[AXES.dp]
    n_batches = n_train // batch_size
    if n_batches == 0:
        raise ValueError(f"batch_size={batch_size} exceeds n_train={n_train}")
    if epochs_per_call < 1:
        raise ValueError(f"epochs_per_call={epochs_per_call} must be >= 1")
    diag_on = diagnostics is not None and diagnostics.enabled
    window = min(diagnostics.snr_window, n_batches) if diag_on else 0
    b_local = batch_size // n_dp
    vg = _make_local_value_and_grad(spec, cfg, n_sp, k_local)

    def epoch_local(state: TrainState, x_train):
        key_next, k_batch, k_perm, k_bin = jax.random.split(state.key, 4)
        if shuffle:
            perm = jax.random.permutation(k_perm, n_train)
        else:
            perm = jnp.arange(n_train)
        idx = perm[: n_batches * batch_size].reshape(n_batches, batch_size)
        i_dp = lax.axis_index(AXES.dp)

        def step(st, batch_idx, i):
            local_idx = lax.dynamic_slice(batch_idx, (i_dp * b_local,), (b_local,))
            batch = x_train[local_idx]
            if stochastic_binarization:
                bin_key = jax.random.fold_in(jax.random.fold_in(k_bin, i), i_dp)
                batch = jax.random.bernoulli(bin_key, batch).astype(jnp.float32)
            bkey = _fold_axis_coords(jax.random.fold_in(k_batch, i))
            bound, grads = vg(st.params, bkey, batch)
            neg = jax.tree.map(jnp.negative, grads)
            updates, opt_state = opt.update(neg, st.opt_state, st.params)
            params = optax.apply_updates(st.params, updates)
            return (TrainState(params, opt_state, st.key, st.step + 1),
                    -bound, grads)

        if not diag_on:
            def body(st, xs):
                st, loss, _ = step(st, *xs)
                return st, loss

            state, losses = lax.scan(body, state, (idx, jnp.arange(n_batches)))
            return state._replace(key=key_next), losses

        def body(carry, xs):
            st, acc = carry
            st, loss, grads = step(st, *xs)
            include = (xs[1] >= n_batches - window).astype(jnp.float32)
            return (st, grad_accum_update(acc, grads, include)), loss

        (state, (s1, s2)), losses = lax.scan(
            body, (state, grad_accum_init(state.params)),
            (idx, jnp.arange(n_batches)))
        return (state._replace(key=key_next),
                (losses, grad_snr_summary(s1, s2, window)))

    if epochs_per_call == 1:
        local_fn = epoch_local
    else:
        def local_fn(state, x_train):
            state, out = lax.scan(
                lambda st, _: epoch_local(st, x_train), state,
                None, length=epochs_per_call)
            if not diag_on:
                return state, out.reshape(-1)
            losses, diag = out
            return state, (losses.reshape(-1),
                           jax.tree.map(lambda a: a[-1], diag))

    # stable program name -> attributable persistent-cache entries / traces
    local_fn.__name__ = local_fn.__qualname__ = (
        f"parallel_epoch_block{epochs_per_call}_{spec.name}_k{spec.k}"
        if epochs_per_call > 1 else f"parallel_epoch_{spec.name}_k{spec.k}")
    sharded = shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    from iwae_replication_project_tpu.telemetry.spans import spanned
    return spanned(jax.jit(sharded, donate_argnums=(0,) if donate else ()),
                   "train/parallel_epoch")


def make_parallel_train_step(spec: ObjectiveSpec, cfg: model.ModelConfig, mesh,
                             optimizer: optax.GradientTransformation | None = None,
                             donate: bool = True, batch_size: int | None = None):
    """Build the SPMD train step: ``(state, sharded_batch) -> (state, metrics)``.

    `state` is replicated; the batch is sharded ``P('dp')``. Each device folds
    its (dp, sp) coordinates into the RNG so sample draws are independent
    across both the batch shards and the k shards. Pass `batch_size` to
    fail fast at build time on indivisible batch sharding.
    """
    opt = optimizer if optimizer is not None else make_adam()
    n_sp, k_local = _validate_sharding(spec, mesh, batch_size)
    vg = _make_local_value_and_grad(spec, cfg, n_sp, k_local)

    def spmd_step(state: TrainState, x_local):
        key, subkey = jax.random.split(state.key)
        bound, grads = vg(state.params, _fold_axis_coords(subkey), x_local)
        neg_grads = jax.tree.map(jnp.negative, grads)
        updates, opt_state = opt.update(neg_grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": -bound, spec.name: -bound}
        return TrainState(params, opt_state, key, state.step + 1), metrics

    sharded = shard_map(
        spmd_step, mesh=mesh,
        in_specs=(P(), P(AXES.dp)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())
