"""Mesh-sharded evaluation: the k=5000 NLL and the full statistics suite.

The reference's evaluation is its memory/compute hot spot — `get_NLL` draws
k=5000 samples per test point as one eager ``[5000, B, 784]`` tensor
(flexible_IWAE.py:463,515) and the activity suite runs 1000 full-test-set
encoder passes (:270-273). The single-device path already streams these
(evaluation/metrics.py); here the same reductions are *distributed* over the
``(dp, sp)`` mesh:

* test batches shard over ``dp``;
* the k sample axis shards over ``sp`` — each device streams ``k/sp`` samples
  through the online-logsumexp carry, and the carries merge across ``sp`` with
  one ``pmax`` + one ``psum`` (O(B) bytes over ICI, the associative-merge form
  of ops.logsumexp.online_logsumexp_merge);
* the activity estimator shards its ``n_samples`` Monte-Carlo passes over ALL
  devices (dp*sp), psum-ing the posterior-mean sums.

Output schema matches evaluation.metrics.training_statistics, which matches
the reference (flexible_IWAE.py:496-526).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from iwae_replication_project_tpu.evaluation import activity as au
from iwae_replication_project_tpu.evaluation.metrics import (
    SCALAR_NAMES,
    largest_divisor_leq,
)
from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.ops import distributions as dist
from iwae_replication_project_tpu.ops.logsumexp import (
    lse_var_stats,
    online_logsumexp_init,
    online_logsumexp_update,
    online_lse_var_init,
    online_lse_var_update,
)
from iwae_replication_project_tpu.parallel.dp import (
    _fold_axis_coords,
    distributed_logmeanexp,
)
from iwae_replication_project_tpu.parallel.mesh import AXES, shard_map


def _merge_lse_over_sp(state):
    """Cross-device form of online_logsumexp_merge: one pmax + one psum."""
    m_g = lax.pmax(state.m, AXES.sp)
    safe = jnp.where(jnp.isfinite(m_g), m_g, 0.0)
    s_g = lax.psum(state.s * jnp.exp(state.m - safe), AXES.sp)
    return m_g, safe, s_g


def _merge_lse_var_over_sp(state):
    """Cross-device merge of the AUGMENTED carry (ops.logsumexp.OnlineLSEVar):
    one pmax + one psum. ``s`` uses the exact :func:`_merge_lse_over_sp`
    expression (the adaptive scorer's bitwise fixed-k-prefix contract rides
    on it); ``s2`` rescales by the squared max shift. The two sums ride one
    stacked psum so the per-round collective cost of the adaptive
    convergence check stays one pmax + one psum, like the plain merge."""
    m_g = lax.pmax(state.m, AXES.sp)
    safe = jnp.where(jnp.isfinite(m_g), m_g, 0.0)
    scale = jnp.exp(state.m - safe)
    both = lax.psum(jnp.stack([state.s * scale, state.s2 * scale * scale]),
                    AXES.sp)
    return m_g, safe, both[0], both[1]


# --- shared per-device bodies -------------------------------------------------
# One source of truth for the local math: the standalone per-batch factories
# below AND the fused whole-dataset scan both call these, so the two eval
# paths cannot drift apart. Every body expects `key` already folded per
# (dp, sp) coordinate via _fold_axis_coords, except _local_recon_loss which
# folds dp itself (its sp members intentionally compute identical values).

def _local_streaming_log_px(params, cfg, key, x_local, k_local: int,
                            chunk: int, k_global: int):
    """``[B_local]`` log p̂(x): scan k_local/chunk fresh-sample blocks through
    the online-logsumexp carry, then merge carries across sp."""
    def body(state, i):
        lw = model.log_weights(params, cfg, jax.random.fold_in(key, i),
                               x_local, chunk)
        return online_logsumexp_update(state, lw, axis=0), None

    init = online_logsumexp_init((x_local.shape[0],))
    state, _ = lax.scan(body, init, jnp.arange(k_local // chunk))
    _, safe, s_g = _merge_lse_over_sp(state)
    return jnp.log(s_g) + safe - jnp.log(float(k_global))


def _local_row_streaming_log_px(params, cfg, base_key, seeds_local, x_local,
                                k_dyn, k_chunk: int, n_sp: int):
    """Per-device body of the *serving-grade* sharded scorer: ``[B_local]``
    partial log p̂(x) with per-ROW RNG and a *dynamic* k.

    The per-batch sibling above (:func:`_local_streaming_log_px`) fans one
    key into the whole ``[chunk, B]`` tensor — fine offline, fatal for a
    micro-batching engine (a row's value would depend on its batch peers).
    Here each row's sample block ``g`` draws from
    ``fold_in(fold_in(base_key, seed_row), g)`` where ``g`` is the *global*
    block index — so the sampled weights are bitwise independent of batch
    coalescing, of how many blocks a dispatch spans, and of which sp device
    streams which blocks. ``k_dyn`` is a traced int32 scalar: the loop runs
    ``ceil(ceil(k/k_chunk)/sp)`` blocks per device (a dynamic
    ``fori_loop``), and samples at global index >= k — the ragged final
    block, and whole blocks on idle devices when sp does not divide the
    block count — are masked to ``-inf`` (an exact zero contribution to the
    online carry). Callers finish with :func:`_merge_lse_over_sp` and
    normalize by ``log k``.
    """
    sp_idx = lax.axis_index(AXES.sp)
    n_blocks = lax.div(k_dyn + (k_chunk - 1), k_chunk)
    blocks_per_dev = lax.div(n_blocks + (n_sp - 1), n_sp)

    def row_block(seed, xr, g):
        key = jax.random.fold_in(jax.random.fold_in(base_key, seed), g)
        return model.log_weights(params, cfg, key, xr[None], k_chunk)[:, 0]

    def body(i, state):
        g = sp_idx * blocks_per_dev + i
        lw = jax.vmap(lambda s, xr: row_block(s, xr, g))(
            seeds_local, x_local)                        # [B_local, k_chunk]
        sample_idx = g * k_chunk + jnp.arange(k_chunk)
        lw = jnp.where(sample_idx[None, :] < k_dyn, lw, -jnp.inf)
        return online_logsumexp_update(state, lw, axis=1)

    init = online_logsumexp_init((x_local.shape[0],))
    return lax.fori_loop(0, blocks_per_dev, body, init)


def _local_row_adaptive_log_px(params, cfg, base_key, seeds_local, x_local,
                               k_cap, target_se, ess_floor,
                               k_chunk: int, n_sp: int):
    """Per-device body of the accuracy-targeted adaptive scorer:
    ``[B_local, 3]`` rows of ``(log p_hat, achieved_se, k_used)``.

    Two phases, one sample stream (block ``g`` of a row always draws from
    ``fold_in(fold_in(base_key, seed_row), g)`` — the PR-9 stream):

    **Phase 1 — decide k_used.** Devices walk the stream round-robin
    (round ``r`` covers global blocks ``r*sp + sp_idx``), folding blocks
    into the augmented carry (ops.logsumexp.OnlineLSEVar). After each round
    the per-device carries merge across sp (one pmax + one stacked psum)
    and every row's running ESS / delta-method SE is checked against the
    target; a row converges at the first round whose PREFIX of the stream
    meets it, freezing ``k_used`` at that prefix length. The loop exits
    when every row has converged or the cap is reached (rows that never
    converge get ``k_used = k_cap``). ``k_used`` is therefore a pure
    function of (weights, payload, seed, target, caps) plus the program
    constants (k_chunk, sp) — the stopping grid is quantized to
    ``sp * k_chunk`` samples per round; it cannot depend on routing,
    coalescing, batch peers (per-row RNG), or on whether the row would
    have kept going.

    **Phase 2 — recompute the answer at k_used, on the fixed-k schedule.**
    The returned bits must equal a fixed-k call at ``k = k_used``
    (early-stopped prefix == fixed-k prefix, test-pinned), and the fixed
    path assigns block ranges ``[sp_idx*bpd, (sp_idx+1)*bpd)`` with
    ``bpd = ceil(ceil(k/k_chunk)/sp)`` — a *k-dependent* layout phase 1's
    round-robin walk cannot reproduce. So the answer is recomputed over
    the ``k_used``-prefix with exactly the fixed-k per-device schedule
    (per-row ``bpd``, identical masking and carry arithmetic), making the
    equality hold by construction. The cost is bounded by one extra pass
    over the kept prefix — for easy rows still a fraction of the fixed
    k_cap cost (bench.py --adaptive-k quantifies both passes honestly).
    """
    sp_idx = lax.axis_index(AXES.sp)
    n_rows = x_local.shape[0]

    def row_block(seed, xr, g):
        key = jax.random.fold_in(jax.random.fold_in(base_key, seed), g)
        return model.log_weights(params, cfg, key, xr[None], k_chunk)[:, 0]

    # -- phase 1: round-robin stream until every row's prefix meets target --
    n_blocks_cap = lax.div(k_cap + (k_chunk - 1), k_chunk)
    rounds_cap = lax.div(n_blocks_cap + (n_sp - 1), n_sp)
    round_samples = n_sp * k_chunk

    def p1_cond(carry):
        _, converged, _, r = carry
        return jnp.logical_and(r < rounds_cap,
                               jnp.logical_not(jnp.all(converged)))

    def p1_body(carry):
        st, converged, k_used, r = carry
        g = r * n_sp + sp_idx
        lw = jax.vmap(lambda s, xr: row_block(s, xr, g))(
            seeds_local, x_local)                        # [B_local, k_chunk]
        sample_idx = g * k_chunk + jnp.arange(k_chunk)
        lw = jnp.where(sample_idx[None, :] < k_cap, lw, -jnp.inf)
        st = online_lse_var_update(st, lw, axis=1)
        _, _, s_g, s2_g = _merge_lse_var_over_sp(st)
        n_drawn = jnp.minimum((r + 1) * round_samples, k_cap)
        ess, se = lse_var_stats(s_g, s2_g, n_drawn)
        ok = jnp.logical_or(
            jnp.logical_and(target_se > 0, se <= target_se),
            jnp.logical_and(ess_floor > 0, ess >= ess_floor))
        k_used = jnp.where(jnp.logical_and(ok, jnp.logical_not(converged)),
                           n_drawn, k_used)
        return st, jnp.logical_or(converged, ok), k_used, r + 1

    init = (online_lse_var_init((n_rows,)),
            jnp.zeros((n_rows,), bool),
            jnp.broadcast_to(k_cap, (n_rows,)),
            jnp.int32(0))
    _, _, k_used, _ = lax.while_loop(p1_cond, p1_body, init)

    # -- phase 2: fixed-k schedule over each row's k_used-prefix -----------
    n_blocks_row = lax.div(k_used + (k_chunk - 1), k_chunk)       # [B_local]
    bpd_row = lax.div(n_blocks_row + (n_sp - 1), n_sp)            # [B_local]

    def p2_body(i, st):
        g_row = sp_idx * bpd_row + i                              # [B_local]
        lw = jax.vmap(lambda s, xr, g: row_block(s, xr, g))(
            seeds_local, x_local, g_row)                 # [B_local, k_chunk]
        sample_idx = g_row[:, None] * k_chunk + jnp.arange(k_chunk)[None, :]
        # beyond a row's own bpd the block index would wrap into another
        # device's range: mask the whole block (exact identity update)
        valid = jnp.logical_and(sample_idx < k_used[:, None],
                                (i < bpd_row)[:, None])
        lw = jnp.where(valid, lw, -jnp.inf)
        return online_lse_var_update(st, lw, axis=1)

    st2 = lax.fori_loop(0, jnp.max(bpd_row), p2_body,
                        online_lse_var_init((n_rows,)))
    # final merge: (m, s) through the exact fixed-path expression (the
    # bitwise contract), s2 as its own psum beside it
    m_g, safe, s_g = _merge_lse_over_sp(st2)
    s2_g = lax.psum(st2.s2 * jnp.exp(2.0 * (st2.m - safe)), AXES.sp)
    log_px = jnp.log(s_g) + safe - jnp.log(k_used.astype(jnp.float32))
    _, se = lse_var_stats(s_g, s2_g, k_used)
    return jnp.stack([log_px, se, k_used.astype(jnp.float32)], axis=1)


def sharded_score_adaptive_offline(params, cfg, mesh, base_key, seeds, x, *,
                                   k_cap: int, target_se: float = 0.0,
                                   ess_floor: float = 0.0,
                                   k_chunk: int = 250):
    """Offline entry to THE adaptive serving score program: ``[B, 3]`` rows
    of ``(log p_hat, achieved_se, k_used)`` — the adaptive sibling of
    :func:`sharded_score_offline`, calling the exact jitted program the
    serving engine dispatches (serving/programs.make_sharded_score_adaptive)
    so offline sweeps and online ``score_adaptive`` requests at the same
    (mesh, k_chunk, seed, target) are bitwise identical by construction.

    ``target_se`` / ``ess_floor`` <= 0 disable that criterion (both ride as
    dynamic scalars; a disabled pair degenerates to fixed ``k = k_cap``
    scoring with SE reporting).
    """
    from iwae_replication_project_tpu.serving.programs import (
        make_sharded_score_adaptive)

    seeds = jnp.asarray(seeds, jnp.int32)
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    n_dp = mesh.shape[AXES.dp]
    pad = (-n) % n_dp
    if pad:
        seeds = jnp.pad(seeds, (0, pad))
        x = jnp.pad(x, ((0, pad), (0, 0)))
    fn = make_sharded_score_adaptive(cfg, mesh, k_chunk)
    out = fn(params, base_key, seeds, x, jnp.int32(k_cap),
             jnp.float32(target_se), jnp.float32(ess_floor))
    return out[:n]


def sharded_score_offline(params, cfg, mesh, base_key, seeds, x, k: int,
                          k_chunk: int = 250):
    """Offline entry to THE sharded serving score program: ``[B]`` per-row
    log p̂(x) with batch over dp, k blocks over sp.

    This calls the exact jitted program the mesh-backed serving engine
    dispatches (serving/programs.make_sharded_score_rows), so an offline
    paper-grade NLL sweep and an online ``score`` request at the same
    (mesh, k_chunk, seed) are bitwise identical *by construction* — the
    parity pin bench.py --large-k and scripts/large_k_smoke.py assert.

    A batch not divisible by dp is zero-padded up to the next dp multiple
    and sliced after — exactly the serving engine's bucket move, and
    exactly as invisible: per-row RNG makes every real row's value
    independent of the padding rows around it.
    """
    from iwae_replication_project_tpu.serving.programs import (
        make_sharded_score_rows)

    seeds = jnp.asarray(seeds, jnp.int32)
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    n_dp = mesh.shape[AXES.dp]
    pad = (-n) % n_dp
    if pad:
        seeds = jnp.pad(seeds, (0, pad))
        x = jnp.pad(x, ((0, pad), (0, 0)))
    fn = make_sharded_score_rows(cfg, mesh, k_chunk)
    out = fn(params, base_key, seeds, x, jnp.int32(k))
    return out[:n]


def _local_batch_metrics(params, cfg, key, x_local, k_local: int,
                         k_global: int):
    """Single-pass metric bundle on the local shard; scalars are means over
    the local batch shard (callers pmean over dp)."""
    log_w, aux = model.log_weights_and_aux(params, cfg, key, x_local, k_local)
    vae = jnp.mean(lax.psum(jnp.sum(log_w, axis=0), AXES.sp) / k_global)
    iwae = jnp.mean(distributed_logmeanexp(log_w, AXES.sp, k_global))
    recon = jnp.mean(
        lax.psum(jnp.sum(aux["log_px_given_h"], axis=0), AXES.sp) / k_global)
    return {
        "VAE": vae,
        "IWAE": iwae,
        "E_q(h|x)[log(p(x|h))]": recon,
        "D_kl(q(h|x),p(h))": recon - vae,
    }


def _local_recon_loss(params, cfg, key, x_local):
    """dp-local 1-sample reconstruction BCE (flexible_IWAE.py:249-262)."""
    key = jax.random.fold_in(key, lax.axis_index(AXES.dp))
    probs = model.reconstruct_probs(params, cfg, key, x_local)
    lp = dist.bernoulli_log_prob(x_local[None], probs)
    return -jnp.mean(jnp.sum(lp, axis=-1))


def _validate_eval_k(name: str, k: int, n_sp: int) -> int:
    if k % n_sp != 0:
        raise ValueError(f"sp={n_sp} must divide {name}={k}")
    return k // n_sp


@functools.lru_cache(maxsize=32)
def make_parallel_streaming_log_px(cfg: model.ModelConfig, mesh, k: int = 5000,
                                   chunk: int = 250):
    """``(params, key, x) -> [B] log p̂(x)`` with batch over dp, k over sp.

    Each device scans ``k/sp`` fresh importance samples in `chunk`-sized
    blocks through the online-logsumexp carry; the per-device carries merge
    across sp at the end. Per-device RNG folds (chunk index, dp, sp) so all
    ``k`` global samples are independent.
    """
    k_local = _validate_eval_k("eval k", k, mesh.shape[AXES.sp])
    chunk = largest_divisor_leq(k_local, chunk)

    def local_fn(params, key, x_local):
        return _local_streaming_log_px(params, cfg, _fold_axis_coords(key),
                                       x_local, k_local, chunk, k)

    return jax.jit(shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(), P(AXES.dp)),
        out_specs=P(AXES.dp),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def make_parallel_batch_metrics(cfg: model.ModelConfig, mesh, k: int):
    """Sharded single-pass metric bundle (cf. evaluation.metrics.batch_metrics):
    batch over dp, the k fan-out over sp, scalars replicated."""
    k_local = _validate_eval_k("eval k", k, mesh.shape[AXES.sp])

    def local_fn(params, key, x_local):
        out = _local_batch_metrics(params, cfg, _fold_axis_coords(key),
                                   x_local, k_local, k)
        return {name: lax.pmean(v, AXES.dp) for name, v in out.items()}

    return jax.jit(shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(), P(AXES.dp)),
        out_specs=P(),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def make_parallel_reconstruction_loss(cfg: model.ModelConfig, mesh):
    """Sharded 1-sample reconstruction BCE (cf. flexible_IWAE.py:249-262):
    batch over dp; sp members compute identical shards (no k axis here)."""

    def local_fn(params, key, x_local):
        return lax.pmean(_local_recon_loss(params, cfg, key, x_local), AXES.dp)

    return jax.jit(shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(), P(AXES.dp)),
        out_specs=P(),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def make_parallel_posterior_means(cfg: model.ModelConfig, mesh,
                                  n_samples: int, chunk: int = 10):
    """MC posterior means E_q[h|x] with the sample count sharded over ALL
    devices (the reference's 1000 eager passes, flexible_IWAE.py:270-273).

    `x` is replicated (the activity suite needs cross-datapoint variances, so
    every device sees the full set); each of the dp*sp devices contributes
    ``n_samples / (dp*sp)`` samples via an on-device scan, then one psum.
    Returns per-layer means ``[B, d_i]`` (replicated).
    """
    n_dev = mesh.shape[AXES.dp] * mesh.shape[AXES.sp]
    if n_samples % n_dev != 0:
        raise ValueError(f"activity n_samples={n_samples} must be divisible "
                         f"by the device count {n_dev}")
    n_local = n_samples // n_dev
    chunk = largest_divisor_leq(n_local, chunk)

    def local_fn(params, key, x):
        key = _fold_axis_coords(key)

        def body(sums, i):
            h, _, _ = model.encode(params, cfg, jax.random.fold_in(key, i),
                                   x, chunk)
            return tuple(s + jnp.sum(hi, axis=0) for s, hi in zip(sums, h)), None

        init = tuple(jnp.zeros((x.shape[0], d)) for d in cfg.n_latent_enc)
        sums, _ = lax.scan(body, init, jnp.arange(n_local // chunk))
        sums = jax.tree.map(
            lambda s: lax.psum(lax.psum(s, AXES.sp), AXES.dp), sums)
        return tuple(s / n_samples for s in sums)

    return jax.jit(shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def make_parallel_pruned_nll(cfg: model.ModelConfig, mesh, k: int = 5000,
                             chunk: int = 250, n_layers: int = 1):
    """Masked-latent NLL (flexible_IWAE.py:466-494) with k sharded over sp;
    the (small, first-batch) `x` is replicated."""
    n_sp = mesh.shape[AXES.sp]
    if k % n_sp != 0:
        raise ValueError(f"sp={n_sp} must divide pruned-NLL k={k}")
    k_local = k // n_sp
    chunk = largest_divisor_leq(k_local, chunk)

    def local_fn(params, key, x, *masks):
        key = _fold_axis_coords(key)

        def body(state, i):
            lw = au._masked_log_weights(params, cfg, jax.random.fold_in(key, i),
                                        x, masks, chunk)
            return online_logsumexp_update(state, lw, axis=0), None

        init = online_logsumexp_init((x.shape[0],))
        state, _ = lax.scan(body, init, jnp.arange(k_local // chunk))
        _, safe, s_g = _merge_lse_over_sp(state)
        return -jnp.mean(jnp.log(s_g) + safe - jnp.log(float(k)))

    in_specs = (P(), P(), P()) + (P(),) * n_layers
    return jax.jit(shard_map(
        local_fn, mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def make_parallel_dataset_scalars(cfg: model.ModelConfig, mesh, k: int,
                                  nll_k: int, nll_chunk: int):
    """``(params, key, batches[n_batches, B, d]) -> 7-vector`` — the whole
    test-set scalar suite as ONE sharded XLA program.

    A `lax.scan` over batches wraps the same local computations as
    :func:`make_parallel_batch_metrics` / :func:`make_parallel_streaming_log_px`
    / :func:`make_parallel_reconstruction_loss`, with identical per-batch RNG
    folding — so the result matches the per-batch host loop to accumulation
    rounding, at one dispatch instead of ~3 per batch (each dispatch through a
    remote-device transport costs ~10-15 ms; see RESULTS.md). Batches shard
    over dp on their *second* axis; sample axes shard over sp. Output order is
    evaluation.metrics.SCALAR_NAMES.
    """
    n_sp = mesh.shape[AXES.sp]
    k_local = _validate_eval_k("eval k", k, n_sp)
    nll_k_local = _validate_eval_k("nll_k", nll_k, n_sp)
    nll_chunk = largest_divisor_leq(nll_k_local, nll_chunk)

    def local_fn(params, key, batches_local):
        def per_batch(carry, inp):
            i, xb = inp
            bkey = jax.random.fold_in(key, i)
            k1, k2, k3 = jax.random.split(bkey, 3)
            m = _local_batch_metrics(params, cfg, _fold_axis_coords(k1), xb,
                                     k_local, k)
            nll = -jnp.mean(_local_streaming_log_px(
                params, cfg, _fold_axis_coords(k2), xb,
                nll_k_local, nll_chunk, nll_k))
            rl = _local_recon_loss(params, cfg, k3, xb)
            vals = jnp.stack([m["VAE"], m["IWAE"], nll,
                              m["E_q(h|x)[log(p(x|h))]"],
                              m["D_kl(q(h|x),p(h))"], -nll - m["VAE"], rl])
            return carry + lax.pmean(vals, AXES.dp), None

        n_batches = batches_local.shape[0]
        tot, _ = lax.scan(per_batch, jnp.zeros(len(SCALAR_NAMES)),
                          (jnp.arange(n_batches), batches_local))
        return tot / n_batches

    return jax.jit(shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(), P(None, AXES.dp)),
        out_specs=P(),
        check_vma=False,
    ))


def parallel_training_statistics(params, cfg: model.ModelConfig, mesh,
                                 key: jax.Array, x_test: jax.Array, k: int,
                                 batch_size: int = 100, nll_k: int = 5000,
                                 nll_chunk: int = 250,
                                 activity_samples: int = 1000,
                                 activity_threshold: float = 0.01,
                                 include_pruned_nll: bool = True
                                 ) -> Tuple[Dict[str, float], Dict[str, object]]:
    """Mesh-sharded drop-in for evaluation.metrics.training_statistics.

    Same output schema (the reference's 7 scalars + LL_pruned and the
    active-unit structures). The whole scalar suite runs as one fused
    batch-scan program (batch over dp, sample axes over sp); activity and the
    pruned NLL are one dispatch each.
    """
    n_dp = mesh.shape[AXES.dp]
    n_sp = mesh.shape[AXES.sp]
    n = x_test.shape[0]
    if n % n_dp != 0:
        # dp needs equal batch shards; drop the ragged tail (≤ n_dp-1 points)
        n_use = (n // n_dp) * n_dp
        if jax.process_index() == 0:
            print(f"parallel eval: trimming test set {n} -> {n_use} "
                  f"for dp={n_dp} sharding")
        x_test = x_test[:n_use]
        n = n_use
    # batches must split over dp; after the trim n % n_dp == 0, so d = n_dp
    # always qualifies — the search floor keeps batch_size >= n_dp even when
    # the requested batch_size is smaller (ADVICE r2: empty-max crash).
    batch_size = max(d for d in range(1, min(max(batch_size, n_dp), n) + 1)
                     if n % d == 0 and d % n_dp == 0)
    if k % n_sp != 0:
        raise ValueError(f"eval k={k} must be divisible by sp={n_sp}")
    if nll_k % n_sp != 0:
        raise ValueError(f"nll_k={nll_k} must be divisible by sp={n_sp}")
    n_dev = n_dp * n_sp
    activity_samples = max(n_dev, (activity_samples // n_dev) * n_dev)

    scalars_fn = make_parallel_dataset_scalars(cfg, mesh, k, nll_k, nll_chunk)
    means_fn = make_parallel_posterior_means(cfg, mesh, activity_samples)

    n_batches = n // batch_size
    batches = x_test.reshape(n_batches, batch_size, -1)
    batches = jax.device_put(batches, NamedSharding(mesh, P(None, AXES.dp)))

    # conversions go through multihost.fetch: under a process-spanning mesh
    # the replicated outputs are not fully addressable and plain np.asarray
    # raises; in single-process runs fetch is equivalent to np.asarray
    from iwae_replication_project_tpu.parallel.multihost import fetch
    from iwae_replication_project_tpu.telemetry.spans import span

    with span("eval/scalars"):
        scalars = np.asarray(fetch(scalars_fn(params, key, batches)))  # iwaelint: disable=host-sync -- end of the fused eval suite: the ONE deliberate fetch that realizes all scalars at once
    acc = {name: float(v) for name, v in zip(SCALAR_NAMES, scalars)}
    # the per-DEVICE chunk actually used (clamped against nll_k/sp inside
    # make_parallel_dataset_scalars) — the eval-RNG version stamp
    acc["nll_chunk"] = float(largest_divisor_leq(nll_k // n_sp, nll_chunk))
    acc["eval_batch"] = float(batch_size)
    # which hot-loop path the chunked NLL scorer selects at the PER-DEVICE
    # shape of this row (chunk x local batch) — recomputed per config, never
    # read from trace-order state (ops/hot_loop.PATH_CODES)
    from iwae_replication_project_tpu.ops.hot_loop import path_code_for_model
    acc["kernel_path"] = path_code_for_model(
        cfg, int(acc["nll_chunk"]), batch_size // n_dp,
        on_tpu=model._on_tpu())

    res2: Dict[str, object] = {}
    k_au, k_pruned = jax.random.split(jax.random.fold_in(key, n_batches))
    with span("eval/activity"):
        means = fetch(means_fn(params, k_au,
                               jnp.asarray(x_test.reshape(n, -1))))
    variances = tuple(jnp.var(m, axis=0) for m in means)
    eigvals = tuple(au.pca_eigenvalues(m) for m in means)
    masks, n_active, n_active_pca = au.active_units(variances, eigvals,
                                                    threshold=activity_threshold)
    res2["active_units"] = masks
    res2["number_of_active_units"] = n_active
    res2["number_of_PCA_active_units"] = n_active_pca
    res2["variances"] = variances

    if include_pruned_nll:
        pruned_fn = make_parallel_pruned_nll(cfg, mesh, nll_k, nll_chunk,
                                             n_layers=cfg.n_stochastic)
        with span("eval/pruned_nll"):
            acc["LL_pruned"] = float(fetch(pruned_fn(params, k_pruned,
                                                     jnp.asarray(batches[0]),
                                                     *masks)))
    return acc, res2
