"""Device mesh construction for the framework's parallelism axes.

The reference has no parallelism at all (single eager device,
experiment_example.py:82); SURVEY.md §2.5 records the TPU-native equivalents
built here:

* ``dp`` — data parallelism: the batch axis is sharded across devices and
  gradients are mean-reduced over ICI (`psum`/`pmean`).
* ``sp`` — *sample* parallelism: the K importance-sample axis (the reference's
  scaling axis, k up to 5000 at eval) is sharded, with the IWAE logmeanexp
  computed as a distributed online reduction (`pmax` + `psum`) — the analog of
  sequence/context parallelism for this model family.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

try:  # jax >= 0.6 exports shard_map at top level with `check_vma`
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4/0.5: experimental home, flag named `check_rep`
    from jax.experimental.shard_map import shard_map as _shard_map

_CHECK_FLAG = "check_vma" \
    if "check_vma" in inspect.signature(_shard_map).parameters else "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable `jax.shard_map` (the repo's one import point).

    The replication/varying-manual-axes checker flag was renamed
    ``check_rep`` -> ``check_vma`` across JAX releases; callers use the
    modern spelling and this shim translates for whichever JAX is installed.
    """
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_FLAG: check_vma})


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    dp: str = "dp"
    sp: str = "sp"


AXES = MeshAxes()


def make_mesh(dp: Optional[int] = None, sp: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A ``(dp, sp)`` mesh. With `dp=None`, dp absorbs all remaining devices.

    ICI note: adjacent mesh positions map to ICI-adjacent devices on TPU, so
    the high-traffic axis (sp's logmeanexp reductions during eval; dp's gradient
    psum during training) stays on-torus.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if dp is None:
        if n % sp != 0:
            raise ValueError(f"sp={sp} must divide device count {n}")
        dp = n // sp
    if dp * sp > n:
        raise ValueError(f"mesh {dp}x{sp} needs {dp * sp} devices, have {n}")
    grid = np.asarray(devs[: dp * sp]).reshape(dp, sp)  # iwaelint: disable=host-sync -- np.asarray of jax.Device OBJECTS (mesh construction), no device buffer is transferred
    return Mesh(grid, (AXES.dp, AXES.sp))
