"""Device mesh construction for the framework's parallelism axes.

The reference has no parallelism at all (single eager device,
experiment_example.py:82); SURVEY.md §2.5 records the TPU-native equivalents
built here:

* ``dp`` — data parallelism: the batch axis is sharded across devices and
  gradients are mean-reduced over ICI (`psum`/`pmean`).
* ``sp`` — *sample* parallelism: the K importance-sample axis (the reference's
  scaling axis, k up to 5000 at eval) is sharded, with the IWAE logmeanexp
  computed as a distributed online reduction (`pmax` + `psum`) — the analog of
  sequence/context parallelism for this model family.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    dp: str = "dp"
    sp: str = "sp"


AXES = MeshAxes()


def make_mesh(dp: Optional[int] = None, sp: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A ``(dp, sp)`` mesh. With `dp=None`, dp absorbs all remaining devices.

    ICI note: adjacent mesh positions map to ICI-adjacent devices on TPU, so
    the high-traffic axis (sp's logmeanexp reductions during eval; dp's gradient
    psum during training) stays on-torus.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if dp is None:
        if n % sp != 0:
            raise ValueError(f"sp={sp} must divide device count {n}")
        dp = n // sp
    if dp * sp > n:
        raise ValueError(f"mesh {dp}x{sp} needs {dp * sp} devices, have {n}")
    grid = np.asarray(devs[: dp * sp]).reshape(dp, sp)
    return Mesh(grid, (AXES.dp, AXES.sp))
