"""Multi-host execution: ``jax.distributed`` + process-spanning (dp, sp) meshes.

The reference runs on one eager device (experiment_example.py:82) and has no
distributed communication backend (SURVEY.md §2.5); the TPU-native equivalent
is XLA collectives over a device mesh. Single-host multi-chip is
parallel/dp.py; THIS module is the multi-host layer on top, and it adds no new
compute code by design:

* :func:`initialize` forms the cluster via ``jax.distributed`` (GRPC
  coordinator — auto-detected on TPU pods/GKE, explicit ``host:port``
  elsewhere);
* once initialized, ``jax.devices()`` spans every process, so
  ``parallel.make_mesh`` returns a process-spanning ``Mesh`` and every
  existing shard_map program (``make_parallel_train_step``,
  ``make_parallel_epoch_fn``, ``parallel.eval``'s sharded suites) compiles
  over it **unchanged** — XLA routes the ``psum``/``pmax``/``all_gather``
  segments over ICI within a slice and DCN across hosts;
* data stays host-local: each process loads only its own batch rows and
  :func:`host_local_batch_to_global` assembles the global dp-sharded array
  the step functions expect — the multi-host analog of ``dp.shard_batch``.

Validated end-to-end by tests/test_multihost.py: two OS processes with 4
virtual CPU devices each form one 8-device (dp=4, sp=2) mesh, and the
framework's jitted training epoch and host-local-fed train step reproduce the
single-process results exactly.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from iwae_replication_project_tpu.parallel.mesh import AXES


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None, **kwargs) -> None:
    """Join (or form) the multi-process JAX cluster.

    On TPU pods / GKE all three arguments are auto-detected — call with no
    arguments. Elsewhere (CPU/GPU clusters, or local multi-process tests)
    pass the coordinator ``host:port`` plus this process's rank. Must run
    before the first backend use; after it returns, ``jax.devices()`` lists
    the devices of every process and ``parallel.make_mesh()`` spans them.
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)


def host_local_batch_to_global(batch, mesh, axis: str = AXES.dp) -> jax.Array:
    """Assemble per-process batch rows into one global dp-sharded array.

    ``batch`` holds ONLY this process's rows (its contiguous slice of the
    global batch, in mesh order along `axis`). The returned global array has
    leading dimension ``sum of all processes' rows`` and the sharding
    ``P(axis)`` that ``make_parallel_train_step`` expects — each host feeds
    its shard, no host ever materializes the full batch.
    """
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    return multihost_utils.host_local_array_to_global_array(
        np.asarray(batch), mesh, P(axis))  # iwaelint: disable=host-sync -- host->device feed path: the batch starts ON HOST by definition


def fetch(tree):
    """Local (host-addressable) numpy values of replicated outputs.

    In a multi-process job, ``np.asarray`` on a program output raises for
    arrays whose shards live on other hosts; for fully-replicated outputs
    (losses, metrics, the replicated TrainState) every host holds complete
    values, and this returns them. Works identically in single-process runs.
    """
    def leaf(a):
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            if not a.sharding.is_fully_replicated:
                raise ValueError(
                    "fetch() got a non-addressable array that is not fully "
                    f"replicated (sharding {a.sharding}); returning its local "
                    "shard would silently truncate the global value. "
                    "all_gather/psum it inside the program, or use "
                    "jax.experimental.multihost_utils.process_allgather.")
            return np.asarray(a.addressable_data(0))  # iwaelint: disable=host-sync -- fetch() IS the designated host boundary the drivers call
        return np.asarray(a) if isinstance(a, jax.Array) else a  # iwaelint: disable=host-sync -- fetch() IS the designated host boundary the drivers call

    return jax.tree.map(leaf, tree)


def process_info() -> dict:
    """This process's place in the cluster (for logging / data slicing)."""
    return {"process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "local_device_count": jax.local_device_count(),
            "global_device_count": jax.device_count()}
