"""Online inference serving: dynamic micro-batching over AOT warm paths.

``ServingEngine`` turns ragged online requests (``encode`` / ``decode`` /
``score``) into fixed-shape bucket dispatches through the compile-once AOT
executable registry. See engine.py for the request lifecycle and
ARCHITECTURE.md "Serving" for the subsystem map. The network-facing layer
— N engine replicas behind a TCP front end with routing, quotas, and
failure handling — lives in :mod:`.frontend` (``ServingTier`` /
``TierClient``). CLI: ``python -m iwae_replication_project_tpu.serving``
(or ``iwae-serve``; ``--replicas/--port`` runs the tier, ``--client``
drives one over TCP).
"""

from iwae_replication_project_tpu.serving.batcher import (
    EngineOverloaded,
    MicroBatcher,
    Request,
    RequestTimeout,
)
from iwae_replication_project_tpu.serving.buckets import (
    BucketLadder,
    KChunkMenu,
)
from iwae_replication_project_tpu.serving.engine import ServingEngine
from iwae_replication_project_tpu.serving.metrics import ServingMetrics
from iwae_replication_project_tpu.serving.sharded import ShardedScoreEngine

__all__ = ["ServingEngine", "ShardedScoreEngine", "BucketLadder",
           "KChunkMenu", "MicroBatcher", "Request", "ServingMetrics",
           "EngineOverloaded", "RequestTimeout"]
