import sys

from iwae_replication_project_tpu.serving.cli import main

if __name__ == "__main__":
    sys.exit(main())
