"""Dynamic micro-batcher: queue, coalesce, expire — no device code here.

Pure data-structure layer so every policy decision is unit-testable with an
injected fake clock (tests/test_serving.py): the engine owns the threads and
the device dispatch, this module owns WHEN a batch forms
(:class:`MicroBatcher`) and HOW MANY dispatched batches may be outstanding
at once (:class:`InflightWindow` — the bounded hand-off between the
dispatcher and completion stages of the pipelined engine).

Policy (per coalescing group — requests only batch with same-program peers,
i.e. identical ``(op, k)``):

* flush when a group reaches ``max_batch`` requests (full-batch flush), or
* when the group's oldest request has waited ``max_wait_us`` (latency bound:
  a lone request is dispatched after at most max_wait_us even at zero load);
* a request whose deadline passes while queued is completed with a
  :class:`RequestTimeout` error — never dispatched, never a crash;
* ``submit`` on a full queue raises :class:`EngineOverloaded` — bounded
  memory and an explicit shed signal instead of an OOM/latency collapse;
* the dispatcher stalls (stops coalescing new dispatches) once
  ``max_inflight`` batches are outstanding — backpressure that flows into
  the queue bound above, so overload turns into shed, not unbounded
  device/host memory.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np


class EngineOverloaded(RuntimeError):
    """The bounded request queue is full; the caller must back off/retry."""


class RequestTimeout(RuntimeError):
    """The request's deadline passed while it waited in the queue."""


def complete_future(fut: Future, result=None, exc=None) -> bool:
    """Complete a future, tolerating caller-side cancellation and duplicate
    completions: a client that cancelled its pending Future — or a reroute
    that already delivered it — must not be able to kill the completing
    thread with InvalidStateError (dispatcher, completion, router-callback,
    and remote-reader threads all outlive any one request by contract).
    Returns whether this call delivered the result. The ONE shared
    implementation for the engine, the replica router, and RemoteEngine."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
        return True
    except Exception:   # cancelled (or already completed): drop quietly
        return False


@dataclasses.dataclass
class Request:
    """One row of work: a single example plus its program selector.

    `seed` versions the request's private RNG stream inside the batched
    program (serving/programs.py folds it into the engine's base key), so a
    request's result is a pure function of (weights, payload, seed) — not of
    whichever batch it happened to be coalesced into.
    """

    op: str
    payload: np.ndarray            # [d] one row, engine-validated
    k: int
    seed: int
    t_enqueue: float
    deadline: Optional[float]      # absolute clock time; None = no timeout
    future: Future = dataclasses.field(default_factory=Future)
    #: stamped by the engine when the batch carrying this request is enqueued
    #: on the device — splits observed latency into queue-wait
    #: (t_dispatch - t_enqueue) and device-wait (completion - t_dispatch)
    t_dispatch: Optional[float] = None
    #: optional :class:`~..telemetry.tracing.TraceContext` the engine's
    #: pipeline-stage spans attach under (None = untraced; the engine's
    #: hot path then records nothing)
    trace: Optional[Any] = None
    #: adaptive accuracy target (``score_adaptive`` only; 0.0 = criterion
    #: disabled). For adaptive requests ``k`` above holds ``k_cap``. These
    #: join the coalescing group: every request in one dispatch shares ONE
    #: set of target scalars (they ride the program as dynamic replicated
    #: inputs), so only exact-target peers may batch together.
    target_se: float = 0.0
    ess_floor: float = 0.0

    @property
    def group(self) -> Tuple:
        """The coalescing key: only same-program, same-dynamic-scalar peers
        may share a dispatch. Non-adaptive requests keep the historical
        ``(op, k)`` key; adaptive requests extend it with their exact
        target pair."""
        if self.target_se == 0.0 and self.ess_floor == 0.0:
            return (self.op, self.k)
        return (self.op, self.k, self.target_se, self.ess_floor)


class MicroBatcher:
    """Bounded multi-group FIFO with max-batch / max-wait flush policy.

    Not thread-safe by itself — the engine serializes access under its own
    lock. `clock` is injectable (tests drive a fake monotonic clock).
    """

    def __init__(self, *, max_batch: int, max_wait_us: float,
                 queue_limit: int,
                 clock: Callable[[], float] = time.monotonic):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_us) / 1e6
        self.queue_limit = int(queue_limit)
        self.clock = clock
        self._groups: "OrderedDict[Tuple[str, int], Deque[Request]]" = \
            OrderedDict()
        self._pending = 0

    @property
    def pending(self) -> int:
        return self._pending

    def submit(self, req: Request) -> None:
        if self._pending >= self.queue_limit:
            raise EngineOverloaded(
                f"request queue full ({self.queue_limit} pending); "
                f"shedding — retry with backoff")
        self._groups.setdefault(req.group, deque()).append(req)
        self._pending += 1

    def poll(self, now: Optional[float] = None, force: bool = False
             ) -> Tuple[List[Request], List[List[Request]]]:
        """``(expired, batches)`` ready at time `now`.

        `expired` are requests whose deadline passed while queued (the caller
        completes them with :class:`RequestTimeout`); each inner list of
        `batches` is one coalesced dispatch of <= max_batch same-group
        requests. `force=True` flushes every non-empty group regardless of
        the wait policy (inline/blocking mode and engine shutdown).

        Expiry pops from each group's HEAD only: deadlines are assumed
        FIFO-monotone per group (the engine derives them as
        ``enqueue_time + timeout_s`` under a monotonic clock, so they are).
        A caller minting out-of-order deadlines degrades gracefully — a
        mid-queue short-deadline request is served late instead of expired —
        and in exchange poll() touches O(flushed + expired) requests, not
        O(pending), per wakeup.
        """
        now = self.clock() if now is None else now
        expired: List[Request] = []
        batches: List[List[Request]] = []
        for group in list(self._groups):
            q = self._groups[group]
            while q and q[0].deadline is not None and now >= q[0].deadline:
                expired.append(q.popleft())
                self._pending -= 1
            while len(q) >= self.max_batch:
                batches.append([q.popleft() for _ in range(self.max_batch)])
                self._pending -= self.max_batch
            if q and (force or now - q[0].t_enqueue >= self.max_wait_s):
                batch = list(q)
                q.clear()
                self._pending -= len(batch)
                batches.append(batch)
            if not q:
                del self._groups[group]
        return expired, batches

    def next_event(self, now: Optional[float] = None) -> Optional[float]:
        """Earliest future clock time at which :meth:`poll` could produce
        something new (a wait-flush or an expiry), or None when idle. The
        dispatcher thread sleeps until this instead of busy-polling. Only
        each group's head matters: FIFO order makes both the wait-flush
        trigger and (per the monotone-deadline contract above) the earliest
        expiry a property of ``q[0]``."""
        now = self.clock() if now is None else now
        t: Optional[float] = None
        for q in self._groups.values():
            if not q:
                continue
            cand = q[0].t_enqueue + self.max_wait_s
            if q[0].deadline is not None:
                cand = min(cand, q[0].deadline)
            t = cand if t is None else min(t, cand)
        return t


class InflightWindow:
    """Bounded FIFO hand-off between the dispatcher and completion stages.

    The dispatcher :meth:`acquire`s a slot BEFORE enqueueing a batch on the
    device, :meth:`commit`s the in-flight handle after (or :meth:`release`s
    the slot when the enqueue failed); the completion thread :meth:`pop`s
    handles in dispatch order, fetches and completes, then calls
    :meth:`done`. A slot is held from acquire until done, so at most
    ``limit`` batches ever sit between device enqueue and future
    completion: the backpressure bound that keeps device/host memory flat
    under overload (the stalled dispatcher stops draining the request
    queue, which then sheds at ``queue_limit``).

    Pure synchronization — no device code, no clock — so pipeline mechanics
    (saturation, drain, FIFO hand-off) are unit-testable without real device
    timing (tests/test_serving.py).
    """

    def __init__(self, limit: int,
                 on_change: Optional[Callable[[int], None]] = None):
        if limit < 1:
            raise ValueError(f"limit must be >= 1, got {limit}")
        self.limit = int(limit)
        self._cv = threading.Condition()
        self._q: Deque[Any] = deque()
        self._open = 0   # acquired and not yet done()
        # observer for the slot count (the engine's inflight gauge), invoked
        # UNDER the window lock so two threads' updates can never land out
        # of order (a stale write would misreport device occupancy)
        self._on_change = on_change

    def _changed(self) -> None:
        if self._on_change is not None:
            self._on_change(self._open)

    @property
    def inflight(self) -> int:
        """Batches currently holding a slot (acquired, not yet done)."""
        with self._cv:
            return self._open

    def acquire(self, abort: Optional[Callable[[], bool]] = None,
                poll_s: float = 0.05) -> bool:
        """Block until a slot frees, then take it. `abort` (polled) breaks
        the wait — the slot is STILL taken (transiently exceeding the
        limit) so a shutting-down dispatcher can never lose a batch it
        already popped from the request queue. Returns False iff the
        acquire was forced past the limit by `abort`."""
        with self._cv:
            while self._open >= self.limit:
                if abort is not None and abort():
                    self._open += 1
                    self._changed()
                    return False
                self._cv.wait(timeout=poll_s if abort is not None else None)
            self._open += 1
            self._changed()
            return True

    def commit(self, item: Any) -> None:
        """Hand an enqueued batch (under a held slot) to the completion
        stage."""
        with self._cv:
            self._q.append(item)
            self._cv.notify_all()

    def release(self) -> None:
        """Give back a held slot without committing (the enqueue failed —
        its futures were error-completed by the dispatcher)."""
        with self._cv:
            self._open -= 1
            self._changed()
            self._cv.notify_all()

    def pop(self, stop: Optional[Callable[[], bool]] = None,
            poll_s: float = 0.05) -> Optional[Any]:
        """Next batch in dispatch order; blocks while empty. Returns None
        once `stop` (polled) is true AND the window is empty — the
        completion thread's drain-then-exit contract. (An acquired-but-not-
        yet-committed batch is safe: its committer is the dispatcher, which
        is joined before the completion stage is stopped.)"""
        with self._cv:
            while not self._q:
                if stop is not None and stop():
                    return None
                self._cv.wait(timeout=poll_s if stop is not None else None)
            return self._q.popleft()

    def done(self) -> None:
        """Release the slot of a popped batch (after its futures completed)."""
        with self._cv:
            self._open -= 1
            self._changed()
            self._cv.notify_all()

    def wake(self) -> None:
        """Nudge blocked acquire/pop callers to re-check their abort/stop
        predicates now (shutdown fast path)."""
        with self._cv:
            self._cv.notify_all()
