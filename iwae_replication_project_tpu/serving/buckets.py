"""Shape-bucket ladder: the fixed menu of batch shapes serving may dispatch.

Every distinct batch size is a distinct XLA program, so serving ragged
request batches at their natural sizes would compile (and registry-key) an
executable per size seen — a compile storm under live traffic. Instead the
engine rounds every coalesced batch UP to a small power-of-two ladder
(1, 2, 4, ..., max_batch): at most ``log2(max_batch)+1`` executables per
(op, k, dtype) exist, all pre-compiled at warmup, and the padding rows are
sliced off before results leave the engine (the per-row RNG design in
serving/programs.py makes real-row values bitwise independent of padding —
pinned by tests/test_serving.py's parity test).

This module is also the serving stack's designated **payload host
boundary**: :func:`as_row` / :func:`as_rows` normalize caller-provided
request payloads (lists, arrays, any dtype) into the engine's float32 row
layout. Payloads start on host by definition, so the conversion lives here
— outside the host-sync-linted dispatch hot path (engine.py), where a bare
``np.asarray`` would be indistinguishable from an accidental device fetch.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np


def as_row(row, n_features: int, op: str) -> np.ndarray:
    """One request payload as a flat float32 ``[n_features]`` row.

    Raises ValueError when the payload's size does not match the op's
    feature contract (engine.row_dims).
    """
    row = np.asarray(row, np.float32).reshape(-1)
    if row.shape[0] != n_features:
        raise ValueError(f"{op} payload must have {n_features} features, "
                         f"got {row.shape[0]}")
    return row


def as_rows(x) -> Tuple[np.ndarray, bool]:
    """Caller payload as a float32 ``[n, d]`` matrix; second element flags
    whether the input was a single row (the blocking helpers un-batch the
    result for those)."""
    x = np.asarray(x, np.float32)
    single = x.ndim == 1
    rows = x[None] if single else x.reshape(x.shape[0], -1)
    return rows, single


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """An ascending tuple of permitted batch sizes (the largest is the
    engine's max coalesced batch)."""

    buckets: Tuple[int, ...]

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("ladder needs at least one bucket")
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets must be strictly ascending, got "
                             f"{self.buckets}")
        if self.buckets[0] < 1:
            raise ValueError("buckets must be >= 1")

    @staticmethod
    def powers_of_two(max_batch: int) -> "BucketLadder":
        """1, 2, 4, ... up to and including `max_batch` (appended as its own
        rung when it is not itself a power of two)."""
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        rungs = []
        b = 1
        while b < max_batch:
            rungs.append(b)
            b *= 2
        rungs.append(max_batch)
        return BucketLadder(tuple(rungs))

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n. Raises for n outside (0, max_batch]."""
        if n < 1:
            raise ValueError(f"batch size must be >= 1, got {n}")
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"batch size {n} exceeds the ladder's max bucket "
                         f"{self.max_batch}")

    def pad_rows(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        """`rows` ``[n, ...]`` zero-padded to ``[bucket, ...]`` (n <= bucket).

        Zero is a safe fill for every serving op: pixel payloads are {0,1}
        Bernoulli observations and latent payloads are unconstrained reals,
        so the padded rows compute ordinary finite garbage that the engine
        slices off — they can never NaN-poison a dispatch.
        """
        n = rows.shape[0]
        if n > bucket:
            raise ValueError(f"{n} rows do not fit bucket {bucket}")
        if n == bucket:
            return rows
        out = np.zeros((bucket,) + rows.shape[1:], dtype=rows.dtype)
        out[:n] = rows
        return out
