"""Shape-bucket ladder: the fixed menu of batch shapes serving may dispatch.

Every distinct batch size is a distinct XLA program, so serving ragged
request batches at their natural sizes would compile (and registry-key) an
executable per size seen — a compile storm under live traffic. Instead the
engine rounds every coalesced batch UP to a small power-of-two ladder
(1, 2, 4, ..., max_batch): at most ``log2(max_batch)+1`` executables per
(op, k, dtype) exist, all pre-compiled at warmup, and the padding rows are
sliced off before results leave the engine (the per-row RNG design in
serving/programs.py makes real-row values bitwise independent of padding —
pinned by tests/test_serving.py's parity test).

This module is also the serving stack's designated **payload host
boundary**: :func:`as_row` / :func:`as_rows` normalize caller-provided
request payloads (lists, arrays, any dtype) into the engine's float32 row
layout. Payloads start on host by definition, so the conversion lives here
— outside the host-sync-linted dispatch hot path (engine.py), where a bare
``np.asarray`` would be indistinguishable from an accidental device fetch.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

# the serving boundaries' shared validators live next to each other: model
# and k are defined below; the precision policy vocabulary is owned by
# utils.dtypes (the byte-width table that makes the policy billable) and
# re-exported here so every admission boundary imports one module
from iwae_replication_project_tpu.utils.dtypes import (  # noqa: F401
    PRECISIONS,
    validate_precision,
)


def as_row(row, n_features: int, op: str) -> np.ndarray:
    """One request payload as a flat float32 ``[n_features]`` row.

    Raises ValueError when the payload's size does not match the op's
    feature contract (engine.row_dims).
    """
    row = np.asarray(row, np.float32).reshape(-1)
    if row.shape[0] != n_features:
        raise ValueError(f"{op} payload must have {n_features} features, "
                         f"got {row.shape[0]}")
    return row


def as_rows(x) -> Tuple[np.ndarray, bool]:
    """Caller payload as a float32 ``[n, d]`` matrix; second element flags
    whether the input was a single row (the blocking helpers un-batch the
    result for those)."""
    x = np.asarray(x, np.float32)
    single = x.ndim == 1
    rows = x[None] if single else x.reshape(x.shape[0], -1)
    return rows, single


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """An ascending tuple of permitted batch sizes (the largest is the
    engine's max coalesced batch)."""

    buckets: Tuple[int, ...]

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("ladder needs at least one bucket")
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets must be strictly ascending, got "
                             f"{self.buckets}")
        if self.buckets[0] < 1:
            raise ValueError("buckets must be >= 1")

    @staticmethod
    def powers_of_two(max_batch: int) -> "BucketLadder":
        """1, 2, 4, ... up to and including `max_batch` (appended as its own
        rung when it is not itself a power of two)."""
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        rungs = []
        b = 1
        while b < max_batch:
            rungs.append(b)
            b *= 2
        rungs.append(max_batch)
        return BucketLadder(tuple(rungs))

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n. Raises for n outside (0, max_batch]."""
        if n < 1:
            raise ValueError(f"batch size must be >= 1, got {n}")
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"batch size {n} exceeds the ladder's max bucket "
                         f"{self.max_batch}")

    def pad_rows(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        """`rows` ``[n, ...]`` zero-padded to ``[bucket, ...]`` (n <= bucket).

        Zero is a safe fill for every serving op: pixel payloads are {0,1}
        Bernoulli observations and latent payloads are unconstrained reals,
        so the padded rows compute ordinary finite garbage that the engine
        slices off — they can never NaN-poison a dispatch.
        """
        n = rows.shape[0]
        if n > bucket:
            raise ValueError(f"{n} rows do not fit bucket {bucket}")
        if n == bucket:
            return rows
        out = np.zeros((bucket,) + rows.shape[1:], dtype=rows.dtype)
        out[:n] = rows
        return out


@dataclasses.dataclass(frozen=True)
class KChunkMenu:
    """The 2-D ``(batch_bucket, k)`` menu of the sharded large-k score path.

    The batch axis keeps the 1-D :class:`BucketLadder` quantization (one
    executable per rung). The k axis needs no quantization at all: the
    sharded score program (serving/programs.make_sharded_score_rows) takes
    ``k`` as a *dynamic* scalar input and streams it in fixed ``k_chunk``
    sample blocks — RNG is keyed per (request seed, global block index), and
    a ragged final block is masked to ``-inf`` — so ONE executable per batch
    bucket serves every ``k`` in ``[1, k_max]`` with zero recompiles.
    ``k_chunk`` is therefore a *sampling-contract* constant (it versions the
    RNG stream and the per-step working-set size), and ``k_max`` is the
    admission bound that turns an absurd ask into a typed ``bad_request``
    instead of an unbounded device occupation.
    """

    batch: BucketLadder
    k_chunk: int = 250
    k_max: int = 5000

    def __post_init__(self):
        if self.k_chunk < 1:
            raise ValueError(f"k_chunk must be >= 1, got {self.k_chunk}")
        if self.k_max < 1:
            raise ValueError(f"k_max must be >= 1, got {self.k_max}")

    def validate_k(self, k) -> int:
        """`k` as a validated int, or ValueError (the typed ``bad_request``
        for every serving boundary — engine submit, router, protocol)."""
        return validate_k(k, self.k_max)

    def n_chunks(self, k: int) -> int:
        """Sample blocks a k-request spans (the final one may be ragged)."""
        return -(-self.validate_k(k) // self.k_chunk)


def validate_model(model, served) -> str:
    """Shared unknown-model check: `model` must be a string naming one of
    the `served` models, else ValueError (the typed ``bad_request``).

    One implementation for every admission boundary — wire protocol,
    replica router, engine submit — so "unknown model" means the same thing
    everywhere: a request naming a model the fleet does not hold must
    surface as a typed ``bad_request`` at the first boundary it crosses,
    never be silently served by the wrong weights.
    """
    if not isinstance(model, str) or not model:
        raise ValueError(f"model must be a non-empty string, got "
                         f"{type(model).__name__}")
    if model not in served:
        raise ValueError(
            f"unknown model {model!r}; "
            + (f"this serving boundary holds {sorted(served)}" if served
               else "no named models are served here"))
    return model


def validate_adaptive_target(target_se, ess_floor, k_cap,
                             k_max: int) -> Tuple[float, float, int]:
    """Shared adaptive-target check for ``score_adaptive`` requests:
    ``(target_se, ess_floor, k_cap)`` normalized, or ValueError (the typed
    ``bad_request``).

    One implementation for every admission boundary — engine submit,
    replica router, wire protocol — so a malformed accuracy target means
    the same thing everywhere and surfaces as a typed ``bad_request``
    RESPONSE at the first boundary it crosses (the connection survives),
    never an internal error inside a replica.

    Rules: ``k_cap`` is a k (``validate_k`` against ``k_max``);
    ``target_se`` and ``ess_floor`` are finite positive reals when given
    (``None`` -> disabled, normalized to 0.0 — the dynamic-scalar encoding
    the program takes); at least one of the two criteria must be active
    (a target-less adaptive request is a fixed-k request wearing the wrong
    op); an ``ess_floor`` above ``k_cap`` can never be met (ESS <= n) and
    is rejected rather than silently truncated to the cap.
    """
    k_cap = validate_k(k_cap, k_max)

    def norm(name, v):
        if v is None:
            return 0.0
        if isinstance(v, bool) or not isinstance(v, (int, float, np.floating,
                                                     np.integer)):
            raise ValueError(f"{name} must be a number, got "
                             f"{type(v).__name__}")
        v = float(v)
        if not np.isfinite(v) or v <= 0.0:
            raise ValueError(f"{name} must be finite and > 0, got {v!r}")
        return v

    target_se = norm("target_se", target_se)
    ess_floor = norm("ess_floor", ess_floor)
    if target_se == 0.0 and ess_floor == 0.0:
        raise ValueError("an adaptive score request needs a target: give "
                         "target_se > 0 and/or ess_floor > 0 (use the plain "
                         "score op for fixed-k scoring)")
    if ess_floor > k_cap:
        raise ValueError(f"ess_floor={ess_floor:g} can never be reached "
                         f"under k_cap={k_cap} (ESS <= sample count)")
    return target_se, ess_floor, k_cap


def target_class(target_se: float, ess_floor: float) -> str:
    """The coarse target-class label an adaptive request's measured
    ``k_used`` is attributed under (router EWMA, profiler): the active
    criterion plus its decade, e.g. ``"se:e-2"`` or ``"ess:e+2"``. Decade
    quantization keeps the class set small under ragged target streams
    while still separating cheap asks from expensive ones — exact values
    stay in the request (and in the dispatch scalars); the class is an
    ACCOUNTING key only, never a program key.
    """
    import math
    if target_se > 0.0:
        return f"se:e{math.floor(math.log10(target_se)):+d}"
    return f"ess:e{math.floor(math.log10(max(ess_floor, 1.0))):+d}"


def validate_k(k, k_max: int) -> int:
    """Shared out-of-range-k check: an int in ``[1, k_max]`` or ValueError.

    One implementation for every admission boundary so the engine, the
    replica router, and the wire protocol cannot drift on what "bad k"
    means — an out-of-range k must surface as a typed ``bad_request`` at
    the first boundary it crosses, never as an internal error or a silent
    giant compile inside a replica.
    """
    if isinstance(k, bool) or not isinstance(k, (int, np.integer)):
        raise ValueError(f"k must be an integer, got {type(k).__name__}")
    k = int(k)
    if not 1 <= k <= k_max:
        raise ValueError(f"k={k} is out of range [1, {k_max}] for this "
                         f"serving path")
    return k
