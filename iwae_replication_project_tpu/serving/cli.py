"""``iwae-serve``: warm the bucket ladder, then serve.

Two modes after warmup:

* **synthetic load** (default): a Poisson-ish open-loop request stream of
  ragged batch sizes against the engine — the smoke/load profile, printing
  the metrics snapshot JSON (and stamping it as JSONL through
  utils/logging.MetricsLogger, same pipeline as the experiment driver);
* **interactive** (``--interactive``): JSON lines on stdin
  (``{"op": "score", "x": [[...pixels...]], "k": 50}``), one JSON result
  line per request on stdout — the request-loop profile.

Weights come from ``--checkpoint RUN_DIR`` (an experiment run directory) or
are freshly initialized from ``--preset NAME`` / the flagship default —
untrained, which is fine for load/latency work and makes the CLI runnable in
a zero-data container.

``--metrics-port PORT`` additionally serves the engine's telemetry registry
(counters, per-bucket latency histograms, serve/aot spans) as a Prometheus
text page at ``http://127.0.0.1:PORT/metrics`` for the lifetime of the
process (telemetry/exporters.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="iwae-serve",
        description="online IWAE inference: dynamic micro-batching engine "
                    "over AOT warm paths")
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--checkpoint", type=str, default=None,
                     help="experiment checkpoint run directory "
                          "(<checkpoint_dir>/<run_name>)")
    src.add_argument("--preset", type=str, default=None,
                     help="zoo preset naming the architecture (fresh, "
                          "untrained weights)")
    ap.add_argument("--k", type=int, default=None,
                    help="importance samples per score/encode request "
                         "(default: the preset/checkpoint config's k)")
    ap.add_argument("--ops", type=str, default="score,encode,decode",
                    help="comma-separated ops to warm and exercise")
    ap.add_argument("--max-batch", dest="max_batch", type=int, default=64)
    ap.add_argument("--max-wait-us", dest="max_wait_us", type=float,
                    default=2000.0)
    ap.add_argument("--queue-limit", dest="queue_limit", type=int,
                    default=1024)
    ap.add_argument("--max-inflight", dest="max_inflight", type=int,
                    default=2,
                    help="bounded window of dispatched-but-uncompleted "
                         "batches for the two-stage pipeline (dispatcher "
                         "enqueues async, a completion thread fetches); "
                         "0 = serial dispatch (the pre-pipeline baseline)")
    ap.add_argument("--timeout-s", dest="timeout_s", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--interactive", action="store_true",
                    help="serve JSON-lines requests from stdin instead of "
                         "synthetic load")
    ap.add_argument("--requests", type=int, default=200,
                    help="synthetic load: number of ragged request batches")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="synthetic load: offered batches/sec (0 = closed "
                         "loop, as fast as the engine completes)")
    ap.add_argument("--sizes", type=str, default="1,3,7,17",
                    help="synthetic load: cycle of ragged batch sizes")
    ap.add_argument("--log-dir", dest="log_dir", type=str, default=None,
                    help="also stamp the metrics snapshot as JSONL/TB under "
                         "this directory (utils/logging.MetricsLogger)")
    ap.add_argument("--metrics-port", dest="metrics_port", type=int,
                    default=None,
                    help="serve a Prometheus text snapshot of the engine's "
                         "metric registry (+ process spans) at "
                         "http://127.0.0.1:PORT/metrics (0 = pick an "
                         "ephemeral port, printed in the warmup line; "
                         "omit = off)")
    return ap


def _build_engine(args):
    from iwae_replication_project_tpu.serving.engine import ServingEngine

    if args.checkpoint:
        eng = ServingEngine(args.checkpoint, k=args.k,
                            max_batch=args.max_batch,
                            max_wait_us=args.max_wait_us,
                            queue_limit=args.queue_limit,
                            max_inflight=args.max_inflight,
                            timeout_s=args.timeout_s, seed=args.seed)
        return eng
    from iwae_replication_project_tpu import zoo
    from iwae_replication_project_tpu.utils.config import ExperimentConfig
    ecfg = zoo.get(args.preset) if args.preset else ExperimentConfig()
    return zoo.serving_engine(
        ecfg, k=args.k, max_batch=args.max_batch,
        max_wait_us=args.max_wait_us, queue_limit=args.queue_limit,
        max_inflight=args.max_inflight,
        timeout_s=args.timeout_s, seed=args.seed)


def _synthetic_load(eng, ops, args) -> dict:
    """Open-loop ragged request stream; returns the final snapshot."""
    import numpy as np

    from iwae_replication_project_tpu.serving.batcher import EngineOverloaded

    sizes = [int(s) for s in args.sizes.split(",") if s]
    rng = np.random.RandomState(args.seed)
    dims = eng.row_dims
    eng.start()
    futures = []
    t0 = time.perf_counter()
    for i in range(args.requests):
        op = ops[i % len(ops)]
        n = sizes[i % len(sizes)]
        batch = (rng.rand(n, dims[op]) > 0.5).astype(np.float32) \
            if op != "decode" else rng.randn(n, dims[op]).astype(np.float32)
        for row in batch:
            try:
                futures.append(eng.submit(op, row))
            except EngineOverloaded:
                pass  # counted by the engine as shed
        if args.rate > 0:
            time.sleep(rng.exponential(1.0 / args.rate))
    for f in futures:
        try:
            f.result()
        except Exception:
            pass  # timeouts/errors are counted in the snapshot
    wall = time.perf_counter() - t0
    eng.stop()
    snap = eng.metrics.snapshot()
    snap["wall_seconds"] = round(wall, 3)
    snap["throughput_rows_per_sec"] = round(
        snap["counters"]["completed"] / wall, 2) if wall else None
    return snap


def _interactive(eng, args) -> None:
    eng.start()
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            op = req.get("op", "score")
            x = req["x"]
            fn = {"score": eng.score, "encode": eng.encode,
                  "decode": eng.decode}[op]
            kw = {"k": req["k"]} if "k" in req and op != "decode" else {}
            out = fn(x, **kw)
            print(json.dumps({"op": op, "result": out.tolist()}), flush=True)
        except Exception as e:  # a bad request must not kill the loop
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}),
                  flush=True)
    eng.stop()


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)

    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm path: compiled serving programs persist across server restarts —
    # keyed under the checkpoint dir when serving one, else the cwd
    setup_persistent_cache(base_dir=args.checkpoint or os.getcwd())

    eng = _build_engine(args)
    ops = tuple(s for s in args.ops.split(",") if s)
    warm = eng.warmup(ops=ops)
    metrics_srv = None
    if args.metrics_port is not None:
        from iwae_replication_project_tpu.telemetry import (
            get_registry, start_metrics_server)
        # engine registry (counters, per-bucket latency, serve/* spans) plus
        # the process-default registry (aot/* dispatch spans)
        metrics_srv = start_metrics_server(
            (get_registry(), eng.metrics.registry), args.metrics_port)
    print(json.dumps({"warmup": warm,
                      "buckets": list(eng.ladder.buckets),
                      "k": eng.k,
                      "metrics_port": (metrics_srv.server_address[1]
                                       if metrics_srv else None)}),
          flush=True)

    if args.interactive:
        _interactive(eng, args)
        if metrics_srv is not None:
            metrics_srv.shutdown()
        return 0
    snap = _synthetic_load(eng, ops, args)
    print(json.dumps(snap), flush=True)
    if args.log_dir:
        from iwae_replication_project_tpu.utils.logging import MetricsLogger
        logger = MetricsLogger(args.log_dir, run_name="serving")
        logger.log(eng.metrics.flat(),
                   step=int(snap["counters"]["dispatches"]))
        logger.close()
    if metrics_srv is not None:
        metrics_srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
