"""``iwae-serve``: warm the bucket ladder, then serve.

One binary, three roles:

* **in-process engine** (default, no ``--replicas``/``--client``): the
  original single-engine modes below;
* **serving tier** (``--replicas N [--port P]``): N engine replicas over
  shared weights behind the TCP front end (serving/frontend/) — prints a
  ready line with the bound port, serves until stdin EOF (or SIGINT),
  then drains gracefully and prints the final router snapshot. With
  ``--models NAME1,NAME2,...`` the tier is **multi-model**: one (or
  ``--replicas``) model-labeled replica(s) per zoo preset behind the same
  endpoint, requests selecting their tenant via the protocol ``model``
  field, the shared executable store bounding device memory across the
  whole zoo (``--store-budget-mb``: LRU demotion to the persistent XLA
  cache, readmission without a fresh compile);
* **tier client** (``--client HOST:PORT``): drive a running tier over TCP
  — synthetic ragged load by default (same ``--requests``/``--sizes``
  knobs, payload dims discovered via the ``info`` op), or
  ``--interactive`` to forward JSON-lines requests from stdin.

In-process modes after warmup:

* **synthetic load** (default): a Poisson-ish open-loop request stream of
  ragged batch sizes against the engine — the smoke/load profile, printing
  the metrics snapshot JSON (and stamping it as JSONL through
  utils/logging.MetricsLogger, same pipeline as the experiment driver);
* **interactive** (``--interactive``): JSON lines on stdin
  (``{"op": "score", "x": [[...pixels...]], "k": 50}``), one JSON result
  line per request on stdout — the request-loop profile.

Weights come from ``--checkpoint RUN_DIR`` (an experiment run directory) or
are freshly initialized from ``--preset NAME`` / the flagship default —
untrained, which is fine for load/latency work and makes the CLI runnable in
a zero-data container.

``--metrics-port PORT`` additionally serves the engine's telemetry registry
(counters, per-bucket latency histograms, serve/aot spans) as a Prometheus
text page at ``http://127.0.0.1:PORT/metrics`` for the lifetime of the
process (telemetry/exporters.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="iwae-serve",
        description="online IWAE inference: dynamic micro-batching engine "
                    "over AOT warm paths")
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--checkpoint", type=str, default=None,
                     help="experiment checkpoint run directory "
                          "(<checkpoint_dir>/<run_name>)")
    src.add_argument("--preset", type=str, default=None,
                     help="zoo preset naming the architecture (fresh, "
                          "untrained weights)")
    src.add_argument("--models", type=str, default=None,
                     metavar="NAME1,NAME2,...",
                     help="multi-model tier: serve SEVERAL zoo presets "
                          "behind one endpoint (fresh weights per preset; "
                          "requests pick a tenant via the protocol 'model' "
                          "field, the first name is the default). Implies "
                          "the tier mode; --replicas N runs N replicas PER "
                          "model (default 1). The shared executable store "
                          "bounds device memory across all of them "
                          "(--store-budget-mb)")
    ap.add_argument("--store-budget-mb", dest="store_budget_mb", type=float,
                    default=None,
                    help="device-memory budget (MiB) for the process "
                         "executable store: past it, least-recently-used "
                         "executables are demoted to the persistent XLA "
                         "cache and readmitted on demand without a fresh "
                         "compile (default: unbounded; env "
                         "IWAE_STORE_BUDGET_BYTES)")
    ap.add_argument("--k", type=int, default=None,
                    help="importance samples per score/encode request "
                         "(default: the preset/checkpoint config's k)")
    ap.add_argument("--ops", type=str, default="score,encode,decode",
                    help="comma-separated ops to warm and exercise")
    ap.add_argument("--max-batch", dest="max_batch", type=int, default=64)
    ap.add_argument("--max-wait-us", dest="max_wait_us", type=float,
                    default=2000.0)
    ap.add_argument("--queue-limit", dest="queue_limit", type=int,
                    default=1024)
    ap.add_argument("--max-inflight", dest="max_inflight", type=int,
                    default=2,
                    help="bounded window of dispatched-but-uncompleted "
                         "batches for the two-stage pipeline (dispatcher "
                         "enqueues async, a completion thread fetches); "
                         "0 = serial dispatch (the pre-pipeline baseline)")
    ap.add_argument("--timeout-s", dest="timeout_s", type=float, default=2.0,
                    help="per-request queue deadline; <= 0 disables (what "
                         "deep closed-loop benches want)")
    ap.add_argument("--buckets", type=str, default=None,
                    help="comma-separated explicit bucket ladder (e.g. "
                         "'32' pins every dispatch to ONE padded shape, "
                         "making results bitwise independent of batch "
                         "composition — the fleet-parity configuration); "
                         "default: powers of two up to --max-batch")
    ap.add_argument("--pin-core", dest="pin_core", type=int, default=None,
                    help="pin this process to one CPU core before JAX "
                         "initializes (XLA sizes its intra-op pool from the "
                         "schedulable-CPU count, so a pinned replica "
                         "process models one device: disjoint compute, no "
                         "cross-replica contention)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kernel-path", dest="kernel_path", type=str,
                    default=None,
                    choices=["reference", "blocked_scan", "pallas"],
                    help="force the hot-loop path of every engine's score "
                         "programs (default: the probe-gated per-(op, "
                         "bucket, k) selection — ops/hot_loop."
                         "serving_select_path; 'reference' restores the "
                         "pre-ISSUE-12 serving pin)")
    ap.add_argument("--precision", type=str, default=None,
                    help="serving precision policy (fp32 | bf16 | int8): "
                         "one value applies to every engine; comma-"
                         "separated model=precision pairs (with --models) "
                         "configure per tenant, unmapped models serving "
                         "the historical fp32 path. bf16 scores with bf16 "
                         "operands / fp32 accumulation; int8 quantizes "
                         "decoder weights at load and ships only where the "
                         "autotuner measured it faster (exact fp32 "
                         "fallback otherwise). Unknown values are a typed "
                         "error here, never a silent fp32 fleet")
    tier = ap.add_argument_group("serving tier (serving/frontend/)")
    tier.add_argument("--replicas", type=int, default=0,
                      help="run the network tier with N engine replicas "
                           "over shared weights (0 = in-process engine "
                           "modes, the default)")
    tier.add_argument("--port", type=int, default=0,
                      help="tier TCP port (0 = ephemeral, printed in the "
                           "ready line)")
    tier.add_argument("--host", type=str, default="127.0.0.1")
    tier.add_argument("--max-outstanding", dest="max_outstanding", type=int,
                      default=4096,
                      help="tier-wide admission ceiling (outstanding "
                           "requests) before typed 'overloaded' rejections")
    tier.add_argument("--no-tracing", dest="tracing", action="store_false",
                      default=True,
                      help="disable end-to-end request tracing (on by "
                           "default: every request's hop/queue/dispatch "
                           "spans land in the tail-sampled flight "
                           "recorder, dumpable via the 'traces' wire op, "
                           "the /traces endpoint on --metrics-port, and "
                           "the iwae-trace CLI; results are bitwise "
                           "identical either way)")
    tier.add_argument("--sharded-replicas", dest="sharded_replicas",
                      type=int, default=0,
                      help="additionally run N mesh-backed large-k score "
                           "replicas (ShardedScoreEngine over the same "
                           "weights): the router sends score requests "
                           "above the k threshold to them, small-k "
                           "traffic keeps the fast single-device path")
    tier.add_argument("--k-chunk", dest="k_chunk", type=int, default=250,
                      help="sharded path: canonical sample-block size (it "
                           "versions the RNG stream; k streams over the "
                           "mesh sp axis in blocks of this size)")
    tier.add_argument("--k-max", dest="k_max", type=int, default=5000,
                      help="sharded path: per-request k admission bound "
                           "(typed bad_request past it)")
    tier.add_argument("--k-threshold", dest="k_threshold", type=int,
                      default=None,
                      help="route score requests with k above this to the "
                           "sharded replicas; it also becomes the fast "
                           "replicas' k_max, so the two classes tile "
                           "[1, --k-max] exactly (default: the engine "
                           "default bound, or --k-max/2 when --k-max is "
                           "at or below it)")
    tier.add_argument("--mesh-dp", dest="mesh_dp", type=int, default=1,
                      help="sharded replicas: data-parallel mesh axis "
                           "(batch rows shard over it)")
    tier.add_argument("--mesh-sp", dest="mesh_sp", type=int, default=None,
                      help="sharded replicas: sample-parallel mesh axis "
                           "(k blocks stream over it; default: all "
                           "remaining devices)")
    tier.add_argument("--quota-rate", dest="quota_rate", type=float,
                      default=None,
                      help="per-client token-bucket refill (rows/sec); "
                           "omit = quotas off")
    tier.add_argument("--quota-burst", dest="quota_burst", type=float,
                      default=None,
                      help="per-client bucket capacity in rows (default "
                           "10x rate when --quota-rate is set)")
    tier.add_argument("--client", type=str, default=None, metavar="HOST:PORT",
                      help="client mode: drive a running tier over TCP "
                           "(synthetic load, or --interactive to forward "
                           "stdin JSON lines)")
    tier.add_argument("--client-id", dest="client_id", type=str,
                      default=None,
                      help="client mode: the quota principal stamped on "
                           "requests")
    tier.add_argument("--model", type=str, default=None,
                      help="client mode: the tenant model stamped on every "
                           "request (a multi-model tier routes it to that "
                           "model's replicas; omit = the tier's default "
                           "model)")
    tier.add_argument("--retries", type=int, default=0,
                      help="client mode: RETRIES per request after the "
                           "first attempt (reconnect + typed retryable "
                           "errors with decorrelated-jitter backoff, "
                           "honoring the tier's retry_after_s hints; "
                           "0 = off, the raw one-shot client)")
    tier.add_argument("--hedge-after-s", dest="hedge_after_s", type=float,
                      default=None,
                      help="client mode: tail-latency hedge — re-send a "
                           "request unanswered after this many seconds on "
                           "a second connection, first response wins "
                           "(needs --retries >= 1)")
    tier.add_argument("--retry-deadline-s", dest="retry_deadline_s",
                      type=float, default=30.0,
                      help="client mode: overall wall budget per request "
                           "across retries and hedges")
    tier.add_argument("--k-sweep", dest="k_sweep", type=str, default=None,
                      metavar="K1,K2,...",
                      help="client mode: score-only load that cycles "
                           "per-request k through these values (e.g. "
                           "'50,500,5000') — the closed-loop driver for "
                           "the large-k path; reports per-k latency")
    tier.add_argument("--target-se", dest="target_se", type=float,
                      default=None,
                      help="client mode: drive score_adaptive instead of "
                           "score — per-row target standard error on "
                           "log p-hat(x); with --k-sweep the values become "
                           "sample CAPS and the sweep reports measured "
                           "k_used next to latency")
    tier.add_argument("--ess-floor", dest="ess_floor", type=float,
                      default=None,
                      help="client mode: adaptive ESS stopping floor "
                           "(combinable with --target-se; at least one "
                           "required for score_adaptive)")
    scale = ap.add_argument_group(
        "elastic fleet (serving/fleet/; needs --replicas)")
    scale.add_argument("--autoscale", action="store_true",
                       help="run the SLO-driven autoscaler: a control "
                            "thread reads the tier's burn-rate gauges and "
                            "scales the fast-replica count between "
                            "--autoscale-min and --autoscale-max (scale-up "
                            "joins warm via the persistent caches; "
                            "scale-down drains — no accepted request is "
                            "ever lost, results stay bitwise identical to "
                            "a fixed fleet)")
    scale.add_argument("--autoscale-min", dest="autoscale_min", type=int,
                       default=1,
                       help="lower replica bound (default 1)")
    scale.add_argument("--autoscale-max", dest="autoscale_max", type=int,
                       default=None,
                       help="upper replica bound (default: 2x --replicas)")
    scale.add_argument("--autoscale-up-burn", dest="autoscale_up_burn",
                       type=float, default=1.0,
                       help="fast-window worst burn rate at/above which "
                            "the fleet grows (default 1.0: the error "
                            "budget burns faster than it refills)")
    scale.add_argument("--autoscale-down-burn", dest="autoscale_down_burn",
                       type=float, default=0.25,
                       help="fast-window burn at/below which an idle fleet "
                            "shrinks; the gap up to --autoscale-up-burn is "
                            "the hysteresis band")
    scale.add_argument("--autoscale-up-cooldown-s",
                       dest="autoscale_up_cooldown_s", type=float,
                       default=30.0,
                       help="minimum seconds between scale-ups")
    scale.add_argument("--autoscale-down-cooldown-s",
                       dest="autoscale_down_cooldown_s", type=float,
                       default=120.0,
                       help="minimum seconds from the last scale event (in "
                            "either direction) to a scale-down")
    scale.add_argument("--autoscale-interval-s",
                       dest="autoscale_interval_s", type=float, default=1.0,
                       help="control-loop tick period")
    scale.add_argument("--autoscale-dry-run", dest="autoscale_dry_run",
                       action="store_true",
                       help="evaluate and log every scaling decision but "
                            "never actuate (rehearsal mode; the decision "
                            "log still lands in the shutdown snapshot)")
    ap.add_argument("--interactive", action="store_true",
                    help="serve JSON-lines requests from stdin instead of "
                         "synthetic load")
    ap.add_argument("--requests", type=int, default=200,
                    help="synthetic load: number of ragged request batches")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="synthetic load: offered batches/sec (0 = closed "
                         "loop, as fast as the engine completes)")
    ap.add_argument("--sizes", type=str, default="1,3,7,17",
                    help="synthetic load: cycle of ragged batch sizes")
    ap.add_argument("--log-dir", dest="log_dir", type=str, default=None,
                    help="also stamp the metrics snapshot as JSONL/TB under "
                         "this directory (utils/logging.MetricsLogger)")
    ap.add_argument("--metrics-port", dest="metrics_port", type=int,
                    default=None,
                    help="serve a Prometheus text snapshot of the engine's "
                         "metric registry (+ process spans) at "
                         "http://127.0.0.1:PORT/metrics (0 = pick an "
                         "ephemeral port, printed in the warmup line; "
                         "omit = off)")
    return ap


def _engine_knobs(args) -> dict:
    """The ServingEngine keyword set shared by every construction path."""
    from iwae_replication_project_tpu.serving.buckets import BucketLadder

    ladder = None
    if args.buckets:
        ladder = BucketLadder(tuple(
            int(s) for s in args.buckets.split(",") if s))
    return dict(
        max_batch=args.max_batch, max_wait_us=args.max_wait_us,
        queue_limit=args.queue_limit, max_inflight=args.max_inflight,
        timeout_s=(args.timeout_s if args.timeout_s > 0 else None),
        ladder=ladder, seed=args.seed, kernel_path=args.kernel_path)


def _parse_precision(spec):
    """``--precision``: one policy name applies fleet-wide; comma-separated
    ``model=precision`` pairs configure per model. Returns None, a str, or
    a ``{model: precision}`` dict. A typo'd policy is a typed SystemExit
    HERE, at the CLI boundary — it must never silently become fp32."""
    from iwae_replication_project_tpu.serving.buckets import (
        validate_precision)

    if spec is None:
        return None
    try:
        if "=" not in spec:
            return validate_precision(spec)
        out = {}
        for part in (s for s in spec.split(",") if s):
            model, eq, prec = part.partition("=")
            if not model or not eq:
                raise ValueError(f"bad --precision entry {part!r}; "
                                 f"expected model=precision")
            out[model] = validate_precision(prec)
        return out
    except ValueError as e:
        raise SystemExit(f"--precision: {e}")


def _single_engine_precision(args):
    """The one policy a single-weight-source mode serves. Pairs may only
    name the preset actually being served — extra keys are a typo, not a
    no-op."""
    prec = _parse_precision(args.precision)
    if isinstance(prec, dict):
        extra = sorted(set(prec) - ({args.preset} if args.preset else set()))
        if extra:
            raise SystemExit(f"--precision names models {extra} but this "
                             f"mode serves only "
                             f"{args.preset or 'the flagship default'} "
                             f"(per-model pairs need --models)")
        prec = prec.get(args.preset)
    return prec


def _build_engine(args):
    from iwae_replication_project_tpu.serving.engine import ServingEngine

    prec = _single_engine_precision(args)
    if args.checkpoint:
        return ServingEngine(args.checkpoint, k=args.k, precision=prec,
                             **_engine_knobs(args))
    from iwae_replication_project_tpu import zoo
    from iwae_replication_project_tpu.utils.config import ExperimentConfig
    ecfg = zoo.get(args.preset) if args.preset else ExperimentConfig()
    return zoo.serving_engine(ecfg, k=args.k, precision=prec,
                              **_engine_knobs(args))


def _k_split(args):
    """The mixed tier's (fast k_max, routing threshold): the two classes
    must tile ``[1, --k-max]`` — fast serves up to the threshold, sharded
    takes the rest — or the sharded replicas would be unreachable. With no
    explicit ``--k-threshold`` the split sits at the engine default bound
    (DEFAULT_K_MAX), falling back to half of ``--k-max`` when the whole
    range fits under it."""
    from iwae_replication_project_tpu.serving.engine import DEFAULT_K_MAX

    if args.sharded_replicas <= 0:
        return None, args.k_threshold
    t = args.k_threshold
    if t is None:
        t = DEFAULT_K_MAX if DEFAULT_K_MAX < args.k_max \
            else max(1, args.k_max // 2)
    if not 1 <= t < args.k_max:
        # threshold at/above k_max would make the sharded replicas
        # unreachable while claiming to serve large k — refuse loudly
        raise SystemExit(f"--k-threshold {t} must be in [1, --k-max "
                         f"{args.k_max}) when --sharded-replicas is set")
    return t, t


def _sharded_engines(args, sources):
    """``--sharded-replicas`` mesh engines per (model label, weight-source
    engine) — the ONE construction both the single-model and the
    ``--models`` paths share (mesh sizing, dp-aligned-ladder knob pops,
    ShardedScoreEngine plumbing must never diverge between them)."""
    import jax

    from iwae_replication_project_tpu.parallel.mesh import make_mesh
    from iwae_replication_project_tpu.serving.sharded import (
        ShardedScoreEngine)

    sp = args.mesh_sp if args.mesh_sp is not None \
        else max(1, jax.device_count() // args.mesh_dp)
    mesh = make_mesh(dp=args.mesh_dp, sp=sp)
    knobs = _engine_knobs(args)
    knobs.pop("ladder", None)   # the sharded ladder must be dp-aligned;
    knobs.pop("max_batch", None)  # let the engine derive it
    return [ShardedScoreEngine(
        params=first._params, model_config=first.cfg, k=first.k,
        mesh=mesh, k_chunk=args.k_chunk, k_max=args.k_max,
        max_batch=args.max_batch, model=label, **knobs)
        for label, first in sources
        for _ in range(args.sharded_replicas)]


def _build_replicas(args, n: int):
    """N fast engines (+ any ``--sharded-replicas`` mesh engines) over ONE
    set of weights: the first engine resolves the checkpoint/preset, the
    rest share its params and config — process-local replicas, exactly
    what the tier composes on a multi-device host with one engine (or one
    mesh slice) per replica. With ``--models``, the fleet is instead one
    (or N) model-labeled engine(s) per zoo preset — the multi-tenant
    construction (zoo.serving_engines) — each model getting its own
    sharded replicas over the same weights."""
    from iwae_replication_project_tpu.serving.engine import ServingEngine

    fast_k_max, _ = _k_split(args)
    if args.models:
        from iwae_replication_project_tpu import zoo
        names = [s for s in args.models.split(",") if s]
        engines = zoo.serving_engines(names, replicas_per_model=max(1, n),
                                      k=args.k,
                                      precisions=_parse_precision(
                                          args.precision),
                                      **_engine_knobs(args))
        if fast_k_max is not None:
            for e in engines:       # the k-split applies per fast replica
                e.k_max = max(fast_k_max, e.k)
        if args.sharded_replicas > 0:
            engines.extend(_sharded_engines(args, [
                (name, next(e for e in engines if e.model == name))
                for name in names]))
        return engines
    first = _build_engine(args)
    if fast_k_max is not None:
        # the fast bound IS the threshold (raised as well as capped, so an
        # explicit --k-threshold above the engine default leaves no k with
        # zero eligible replicas), but never below the engine's own
        # default k (a checkpoint trained above the split must still
        # serve its default requests)
        first.k_max = max(fast_k_max, first.k)
    engines = [first]
    for _ in range(1, n):
        engines.append(ServingEngine(
            params=first._params, model_config=first.cfg, k=first.k,
            k_max=first.k_max, precision=first.precision,
            **_engine_knobs(args)))
    if args.sharded_replicas > 0:
        engines.extend(_sharded_engines(args, [(None, first)]))
    return engines


def _tier_mode(args, ops) -> int:
    """``--replicas N``: run the network tier until stdin EOF/SIGINT."""
    from iwae_replication_project_tpu.serving.frontend import (
        QuotaPolicy, ServingTier)

    quota = None
    if args.quota_rate is not None:
        quota = QuotaPolicy(rate=args.quota_rate,
                            burst=(args.quota_burst
                                   if args.quota_burst is not None
                                   else 10.0 * args.quota_rate))
    _, threshold = _k_split(args)
    tier = ServingTier(_build_replicas(args, args.replicas), quota=quota,
                       max_outstanding=args.max_outstanding,
                       host=args.host, port=args.port,
                       large_k_threshold=threshold,
                       tracing=args.tracing)
    warm = tier.warmup(ops=ops)
    tier.start()
    fleet = None
    if args.autoscale:
        from iwae_replication_project_tpu.serving.engine import ServingEngine
        from iwae_replication_project_tpu.serving.fleet import (
            AutoscaleConfig, FleetManager)

        # the scale-up primitive: a NEW fast engine over the first fast
        # replica's shared params — with the persistent XLA/autotune
        # caches active its warmup deserializes instead of compiling, so
        # it joins warm (the 0-fresh-compiles contract the smoke pins)
        first = next(e for e in tier.router.engines
                     if not getattr(e, "sharded", False))

        def factory(first=first, knobs=_engine_knobs(args)):
            return ServingEngine(
                params=first._params, model_config=first.cfg, k=first.k,
                k_max=first.k_max, precision=first.precision,
                model=getattr(first, "model", None), **knobs)

        fleet = FleetManager(tier, factory, AutoscaleConfig(
            min_replicas=max(1, args.autoscale_min),
            max_replicas=(args.autoscale_max
                          if args.autoscale_max is not None
                          else max(2 * args.replicas, args.autoscale_min)),
            scale_up_burn=args.autoscale_up_burn,
            scale_down_burn=args.autoscale_down_burn,
            up_cooldown_s=args.autoscale_up_cooldown_s,
            down_cooldown_s=args.autoscale_down_cooldown_s,
            interval_s=args.autoscale_interval_s,
            dry_run=args.autoscale_dry_run,
            seed=args.seed)).start()
    metrics_srv = None
    if args.metrics_port is not None:
        from iwae_replication_project_tpu.telemetry import (
            get_registry, start_metrics_server)
        # process spans + the router's gauges/counters (incl. the slo/*
        # burn-rate gauges); per-replica engine histograms stay in the
        # shutdown snapshot (their unprefixed names would collide across
        # replicas on one exposition page). The tier's flight recorder
        # additionally serves /traces as Chrome trace-event JSON, the
        # replica engines' dispatch profilers serve /prof, and /healthz
        # reports tier liveness (503 once no healthy replica remains).
        def _tier_health():
            states = tier.router.replica_states()
            healthy = sum(1 for s in states if s["healthy"])
            return {"ok": healthy > 0, "replicas": len(states),
                    "healthy": healthy,
                    "outstanding": tier.router.outstanding}

        metrics_srv = start_metrics_server(
            (get_registry(), tier.registry), args.metrics_port,
            recorder=tier.recorder, profilers=tier.router.profilers(),
            health=_tier_health)
    info = tier.info()
    print(json.dumps({
        "tier": {"replicas": args.replicas,
                 "sharded_replicas": info["sharded_replicas"],
                 "large_k_threshold": info["large_k_threshold"],
                 "k_max": info["k_max"], "port": tier.port,
                 "host": args.host,
                 "models": sorted(info["models"]),
                 "default_model": info["default_model"],
                 "quota": info["quota"],
                 "autoscale": (dataclasses.asdict(fleet.config)
                               if fleet is not None else None)},
        "warmup": warm,
        "buckets": info["buckets"], "k": info["k"],
        "metrics_port": (metrics_srv.server_address[1]
                         if metrics_srv else None)}), flush=True)
    try:
        for _ in sys.stdin:     # lifetime control: serve until stdin EOF
            pass
    except KeyboardInterrupt:
        pass
    if fleet is not None:
        fleet.stop()            # the control thread first: no scale event
    tier.stop()                 # may race the tier drain
    if metrics_srv is not None:
        metrics_srv.shutdown()
    snap = tier.registry.snapshot()
    print(json.dumps({
        "router": {k: v for k, v in snap["counters"].items()
                   if k.startswith("router/")},
        "replicas": tier.router.replica_states(),
        "fleet": fleet.doc() if fleet is not None else None,
        "engines": [e.metrics.snapshot()["counters"]
                    for e in tier.router.engines]}), flush=True)
    return 0


def _client_interactive(cli) -> None:
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            op = req.get("op", "score")
            if op in ("submit_job", "job_status"):
                # bulk-lane job ops answer synchronously like control ops
                doc = cli._control(op, **{key: req[key] for key in req
                                          if key not in ("op", "id")})
                print(json.dumps({"id": req.get("id"), "ok": True,
                                  "result": doc}), flush=True)
                continue
            rid = cli.submit(op, req["x"], k=req.get("k"),
                             seed=req.get("seed"),
                             target_se=req.get("target_se"),
                             ess_floor=req.get("ess_floor"))
            resp = cli.drain([rid])[rid]
            # the caller correlates on ITS id, not the client's wire id
            resp["id"] = req.get("id")
            print(json.dumps(resp), flush=True)
        except Exception as e:  # a bad request must not kill the loop
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}),
                  flush=True)


def _client_k_sweep(cli, args) -> int:
    """``--client ... --k-sweep K1,K2,...``: closed-loop score load that
    cycles per-request k — the synthetic driver for the large-k sharded
    path over TCP. Blocking one-at-a-time requests so each k value gets an
    honest per-request latency sample; errors (e.g. a k above the tier's
    k_max, probing the typed bad_request path) are counted, not fatal."""
    import numpy as np

    from iwae_replication_project_tpu.serving.frontend.client import (
        TierError)

    info = cli.info()
    want_op = "score_adaptive" \
        if (args.target_se is not None or args.ess_floor is not None) \
        else "score"
    if want_op not in info["row_dims"]:
        print(json.dumps({"error": f"tier does not serve {want_op!r}"}),
              file=sys.stderr, flush=True)
        cli.close()
        return 2
    ks = [int(s) for s in args.k_sweep.split(",") if s]
    dim = info["row_dims"][want_op]
    # --target-se / --ess-floor switch the sweep to the adaptive op: the
    # swept values become sample CAPS, and measured k_used is reported
    # next to latency (the estimated-work signal the router balances on)
    adaptive = args.target_se is not None or args.ess_floor is not None
    rng = np.random.RandomState(args.seed)
    sizes = [int(s) for s in args.sizes.split(",") if s]
    walls: dict = {k: [] for k in ks}
    k_used: dict = {k: [] for k in ks}
    errors: dict = {}
    rows_ok = 0
    t0 = time.perf_counter()
    for i in range(args.requests):
        k = ks[i % len(ks)]
        n = sizes[i % len(sizes)]
        batch = (rng.rand(n, dim) > 0.5).astype(np.float32)
        t1 = time.perf_counter()
        try:
            if adaptive:
                out = cli.score_adaptive(batch.tolist(), k=k,
                                         model=args.model,
                                         target_se=args.target_se,
                                         ess_floor=args.ess_floor)
                k_used[k].extend(row[2] for row in out)
            else:
                out = cli.score(batch.tolist(), k=k, model=args.model)
            rows_ok += len(out)
            walls[k].append(time.perf_counter() - t1)
        except TierError as e:
            errors[e.code] = errors.get(e.code, 0) + 1
    wall = time.perf_counter() - t0
    cli.close()
    per_k = {
        str(k): {"requests": len(w),
                 "p50_s": round(float(np.percentile(w, 50)), 6) if w else None,
                 "p95_s": round(float(np.percentile(w, 95)), 6) if w else None}
        for k, w in walls.items()}
    if adaptive:
        for k, used in k_used.items():
            if used:
                per_k[str(k)]["k_used_mean"] = round(float(np.mean(used)), 1)
                per_k[str(k)]["k_used_max"] = int(max(used))
    print(json.dumps({"mode": "client-k-sweep", "target": args.client,
                      "op": "score_adaptive" if adaptive else "score",
                      "k_sweep": ks, "per_k": per_k, "ok_rows": rows_ok,
                      "errors": errors, "wall_seconds": round(wall, 3),
                      "info": {key: info[key] for key in
                               ("large_k_threshold", "k_max",
                                "sharded_replicas", "replicas")}}),
          flush=True)
    return 0


def _client_mode(args) -> int:
    """``--client HOST:PORT``: drive a running tier over TCP."""
    import numpy as np

    from iwae_replication_project_tpu.serving.frontend import (
        RetryPolicy, TierClient)

    retry = None
    if args.retries > 0:
        # the flag counts RETRIES; the policy counts total attempts
        retry = RetryPolicy(max_attempts=args.retries + 1,
                            deadline_s=args.retry_deadline_s,
                            hedge_after_s=args.hedge_after_s,
                            seed=args.seed)
    host, _, port = args.client.rpartition(":")
    cli = TierClient(host or "127.0.0.1", int(port),
                     client_id=args.client_id, retry=retry)
    if args.interactive:
        _client_interactive(cli)
        cli.close()
        return 0
    if args.k_sweep:
        return _client_k_sweep(cli, args)
    info = cli.info()
    ops = [s for s in args.ops.split(",") if s and s in info["row_dims"]]
    if not ops:
        print(json.dumps({"error": f"none of the requested ops "
                                   f"({args.ops}) is served by this tier; "
                                   f"it serves {sorted(info['row_dims'])}"}),
              file=sys.stderr, flush=True)
        cli.close()
        return 2
    sizes = [int(s) for s in args.sizes.split(",") if s]
    rng = np.random.RandomState(args.seed)
    dims = info["row_dims"]
    ids = []
    t0 = time.perf_counter()
    for i in range(args.requests):
        op = ops[i % len(ops)]
        n = sizes[i % len(sizes)]
        batch = (rng.rand(n, dims[op]) > 0.5).astype(np.float32) \
            if op != "decode" else rng.randn(n, dims[op]).astype(np.float32)
        ids.append((cli.submit(op, batch.tolist(), model=args.model), n))
        if args.rate > 0:
            time.sleep(rng.exponential(1.0 / args.rate))
    responses = cli.drain([rid for rid, _ in ids])
    wall = time.perf_counter() - t0
    cli.close()
    ok_rows = sum(n for rid, n in ids if responses[rid].get("ok"))
    errors: dict = {}
    for rid, _ in ids:
        r = responses[rid]
        if not r.get("ok"):
            errors[r.get("error", "internal")] = \
                errors.get(r.get("error", "internal"), 0) + 1
    out = {"mode": "client", "target": args.client,
           "requests": args.requests, "ok_rows": ok_rows,
           "errors": errors, "wall_seconds": round(wall, 3),
           "rows_per_sec": round(ok_rows / wall, 2) if wall else None,
           "info": info}
    print(json.dumps(out), flush=True)
    return 0


def _synthetic_load(eng, ops, args) -> dict:
    """Open-loop ragged request stream; returns the final snapshot."""
    import numpy as np

    from iwae_replication_project_tpu.serving.batcher import EngineOverloaded

    sizes = [int(s) for s in args.sizes.split(",") if s]
    rng = np.random.RandomState(args.seed)
    dims = eng.row_dims
    eng.start()
    futures = []
    t0 = time.perf_counter()
    for i in range(args.requests):
        op = ops[i % len(ops)]
        n = sizes[i % len(sizes)]
        batch = (rng.rand(n, dims[op]) > 0.5).astype(np.float32) \
            if op != "decode" else rng.randn(n, dims[op]).astype(np.float32)
        for row in batch:
            try:
                futures.append(eng.submit(op, row))
            except EngineOverloaded:
                pass  # counted by the engine as shed
        if args.rate > 0:
            time.sleep(rng.exponential(1.0 / args.rate))
    for f in futures:
        try:
            f.result()
        except Exception:
            pass  # timeouts/errors are counted in the snapshot
    wall = time.perf_counter() - t0
    eng.stop()
    snap = eng.metrics.snapshot()
    snap["wall_seconds"] = round(wall, 3)
    snap["throughput_rows_per_sec"] = round(
        snap["counters"]["completed"] / wall, 2) if wall else None
    return snap


def _interactive(eng, args) -> None:
    eng.start()
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            op = req.get("op", "score")
            x = req["x"]
            fn = {"score": eng.score, "encode": eng.encode,
                  "decode": eng.decode}[op]
            kw = {"k": req["k"]} if "k" in req and op != "decode" else {}
            out = fn(x, **kw)
            print(json.dumps({"op": op, "result": out.tolist()}), flush=True)
        except Exception as e:  # a bad request must not kill the loop
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}),
                  flush=True)
    eng.stop()


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)

    if args.client:
        # pure socket client: no model, no device, no cache to set up
        return _client_mode(args)

    if args.pin_core is not None:
        # before ANY jax import: XLA:CPU sizes its intra-op thread pool
        # from the schedulable-CPU count at backend init, so pinning here
        # gives this replica process a disjoint single-core compute slice
        # (the replica_scaling bench runs one pinned process per "device")
        os.sched_setaffinity(0, {args.pin_core})

    from iwae_replication_project_tpu.utils.compile_cache import (
        set_store_budget, setup_persistent_cache)

    # warm path: compiled serving programs persist across server restarts —
    # keyed under the checkpoint dir when serving one, else the cwd
    setup_persistent_cache(base_dir=args.checkpoint or os.getcwd())
    if args.store_budget_mb is not None:
        if args.store_budget_mb < 0:
            raise SystemExit(f"--store-budget-mb {args.store_budget_mb} "
                             f"must be >= 0 (omit the flag for unbounded)")
        # the multi-tenant device-memory bound: LRU executables past it
        # demote to the persistent cache above and readmit on demand
        set_store_budget(int(args.store_budget_mb * 2 ** 20))

    if args.models and args.replicas <= 0:
        args.replicas = 1       # --models IS the tier: one replica per model

    if args.replicas > 0:
        return _tier_mode(args,
                          tuple(s for s in args.ops.split(",") if s))

    eng = _build_engine(args)
    ops = tuple(s for s in args.ops.split(",") if s)
    warm = eng.warmup(ops=ops)
    metrics_srv = None
    if args.metrics_port is not None:
        from iwae_replication_project_tpu.telemetry import (
            get_registry, start_metrics_server)
        # engine registry (counters, per-bucket latency, serve/* spans) plus
        # the process-default registry (aot/* dispatch spans); the engine's
        # dispatch profiler backs /prof and /healthz reports bare liveness
        metrics_srv = start_metrics_server(
            (get_registry(), eng.metrics.registry), args.metrics_port,
            profilers=(eng.profiler,) if eng.profiler is not None else (),
            health=lambda: {"ok": True, "mode": "engine", "ops": list(ops)})
    print(json.dumps({"warmup": warm,
                      "buckets": list(eng.ladder.buckets),
                      "k": eng.k,
                      "metrics_port": (metrics_srv.server_address[1]
                                       if metrics_srv else None)}),
          flush=True)

    if args.interactive:
        _interactive(eng, args)
        if metrics_srv is not None:
            metrics_srv.shutdown()
        return 0
    snap = _synthetic_load(eng, ops, args)
    print(json.dumps(snap), flush=True)
    if args.log_dir:
        from iwae_replication_project_tpu.utils.logging import MetricsLogger
        logger = MetricsLogger(args.log_dir, run_name="serving")
        logger.log(eng.metrics.flat(),
                   step=int(snap["counters"]["dispatches"]))
        logger.close()
    if metrics_srv is not None:
        metrics_srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
