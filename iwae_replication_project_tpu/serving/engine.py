"""ServingEngine: pipelined online inference over the AOT warm paths.

Request lifecycle (ARCHITECTURE.md "Serving") — a two-stage pipeline behind
a bounded in-flight window:

    submit -> bounded queue -> [dispatcher thread]
                 coalesce (max_batch / max_wait_us) -> pad to shape bucket
                 -> async AOT enqueue (device arrays, no host sync)
                 -> bounded in-flight window (max_inflight)
           -> [completion thread]
                 block on device->host fetch -> slice real rows
                 -> complete futures, record latency split

The dispatcher does policy work only: it never blocks on the device, so the
micro-batcher keeps coalescing the NEXT batch while the device computes the
current one(s). The completion thread owns the single blocking fetch. With
``max_inflight=0`` the pipeline collapses to the serial mode (dispatcher
fetches inline) — the baseline ``bench.py --serving`` compares against.

The engine is in-process: callers get ``concurrent.futures.Future``s (or use
the blocking ``score``/``encode``/``decode`` helpers). The background
threads spawn on :meth:`start`; without it, the blocking helpers drain the
queue inline (serial, fully deterministic — what most tests use).

Invariants the design leans on:

* **row independence** — the serving programs (serving/programs.py) key RNG
  per request, so padded-bucket dispatch is bitwise equal to unpadded
  execution, padding rows are sliced off, and results are bitwise
  independent of HOW work was pipelined (serial-vs-pipelined parity is
  pinned by tests/test_serving.py);
* **closed shape menu** — every dispatch lands on a
  :class:`~.buckets.BucketLadder` rung, pre-compiled by :meth:`warmup`
  through the AOT registry (utils/compile_cache.py): a warm engine serves
  any ragged request stream with zero compiles;
* **bounded everything** — queue bound (:class:`EngineOverloaded` shed),
  in-flight window (a saturated device stalls the dispatcher, which fills
  the queue, which sheds), per-request timeout (:class:`RequestTimeout`
  error result), dispatch/fetch errors land in exactly the affected
  in-flight batch's futures, never in a dead engine thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from iwae_replication_project_tpu.serving.batcher import (
    EngineOverloaded,
    InflightWindow,
    MicroBatcher,
    Request,
    RequestTimeout,
    complete_future,
)
from iwae_replication_project_tpu.serving.buckets import (
    BucketLadder,
    as_row,
    as_rows,
    validate_adaptive_target,
    validate_k,
    validate_model,
    validate_precision,
)
from iwae_replication_project_tpu.serving.faults import (
    SITE_ENGINE_FETCH,
    SITE_ENGINE_LAUNCH,
    fault_point,
)
from iwae_replication_project_tpu.serving.metrics import ServingMetrics
from iwae_replication_project_tpu.serving.programs import PROGRAMS

__all__ = ["ServingEngine", "EngineOverloaded", "RequestTimeout"]

#: default per-request k admission bound for single-device engines. A k
#: above it is a typed ``bad_request`` (ValueError) at submit — NOT a
#: silent compile of an arbitrarily large program: the single-device
#: score/encode programs bake k in statically, so an unbounded client k
#: is an unbounded compile + device occupation. Paper-grade k (5000)
#: belongs to the mesh-backed sharded path (serving/sharded.py), whose
#: menu carries its own k_max.
DEFAULT_K_MAX = 1024


@dataclasses.dataclass
class _InFlight:
    """One dispatched-but-uncompleted batch riding the in-flight window."""

    batch: List[Request]
    op: str
    k: int
    bucket: int
    out: Any                       # device array(s), still computing
    #: executable-store pin held for the dispatch lifetime: the store's LRU
    #: eviction must never pull this batch's program while it is in flight
    pin: Any = None
    #: launch-stage timestamps (engine clock) for the per-request trace
    #: spans assembled at completion: _launch entry (coalesce boundary) and
    #: end of pad+device_put (the AOT enqueue start)
    t_launch0: float = 0.0
    t_args: float = 0.0


class ServingEngine:
    """Typed online-inference API over one model's weights.

    `source` is a compiled jax-backend :class:`~..api.FlexibleModel` or a
    checkpoint run directory (the ``<checkpoint_dir>/<run_name>`` Orbax tree
    the experiment driver writes); alternatively pass ``params=`` +
    ``model_config=`` directly (what the facade's ``serving_engine()`` does).

    Knobs: ``k`` (default importance samples per score/encode request;
    ``None`` = the checkpoint's stored training k, else 50), ``k_max``
    (per-request k admission bound — past it ``submit`` raises the typed
    ValueError/``bad_request``, never a silent compile of an arbitrarily
    large static-k program; default ``max(DEFAULT_K_MAX, k)``),
    ``max_batch``/``max_wait_us`` (coalescing policy), ``queue_limit``
    (backpressure bound), ``max_inflight`` (dispatched-but-uncompleted batch
    window for the two-stage pipeline; ``0`` = serial dispatch, the
    pre-pipeline behavior), ``timeout_s`` (per-request queue deadline; None
    disables), ``ladder`` (shape buckets; default powers-of-two up to
    max_batch), ``kernel_path`` (force the hot-loop implementation of every
    gated program: None = the probe-gated per-(op, bucket, k) selection,
    ``"reference"`` = the historical serving pin — see :meth:`_kernel_for`),
    ``model`` (the tenant label of the weights this engine serves — a zoo
    preset name or checkpoint tag. It keys this engine's executables in the
    process-wide capacity-bounded store (utils/compile_cache.py), labels
    its latency histograms, and is the replica capability snapshot the
    router's model-affinity classification reads; a submit naming a
    DIFFERENT model is the typed ``bad_request``. ``None`` = the historical
    single-model engine, schema-identical to pre-multi-tenant builds),
    ``precision`` (the per-model serving precision policy, ISSUE 16:
    ``None`` = the historical fp32 engine, key- and schema-identical to
    pre-precision builds; ``"fp32"`` pins the exact program explicitly;
    ``"bf16"`` runs decoder scoring with bf16 operands / fp32 accumulation;
    ``"int8"`` serves the weight-only-quantized decoder output block —
    int8 weights + per-channel fp32 scales, quantized once at load — but
    ONLY where the measured admission gate
    (ops/hot_loop.serving_int8_admit) says the quantized program wins;
    every rejected shape serves the exact fp32 program. The policy rides
    the AOT build key, the executable-store tenant label
    (:attr:`store_label`), and the metrics labels, so fp32 and
    low-precision tenants of one model coexist in one store budget without
    colliding; an unknown precision string raises the typed ValueError —
    never a silent fp32 fallback).
    """

    def __init__(self, source=None, *, params=None, model_config=None,
                 k: Optional[int] = None, k_max: Optional[int] = None,
                 max_batch: int = 64,
                 max_wait_us: float = 2000.0,
                 queue_limit: int = 1024, max_inflight: int = 2,
                 timeout_s: Optional[float] = 2.0,
                 ladder: Optional[BucketLadder] = None, seed: int = 0,
                 metrics: Optional[ServingMetrics] = None,
                 kernel_path: Optional[str] = None,
                 model: Optional[str] = None,
                 precision: Optional[str] = None,
                 profiling=None):
        import jax

        if isinstance(source, str):
            params, model_config, stored_k = _load_checkpoint(source)
            if k is None:
                k = stored_k  # serve at the budget the model trained under
        elif source is not None:
            if getattr(source, "state", None) is None or \
                    not hasattr(source, "cfg"):
                raise ValueError(
                    "source must be a compiled jax-backend FlexibleModel "
                    "(call .compile() first) or a checkpoint directory path")
            params, model_config = source.params, source.cfg
        if params is None or model_config is None:
            raise ValueError("pass a model, a checkpoint directory, or "
                             "params= + model_config=")
        # the serving pin is LIFTED (ROADMAP item 3; PRs 3-11 pinned the
        # unfused path pending hardware validation of the row-vmapped
        # kernel): per (op, bucket, k), :meth:`_kernel_for` resolves the
        # probe-gated hot-loop selection OUTSIDE the trace — one probe
        # compile of the actual row-vmapped kernel per shape, cached
        # (ops/hot_loop.serving_select_path), consulting any persisted
        # autotune winners (ops/autotune.py) — and bakes the outcome into
        # that dispatch's config (ModelConfig.hot_loop_path/hot_loop_tile).
        # Any shape the probe rejects — and every ineligible model
        # (likelihood != "logits") — automatically falls back to `self.cfg`
        # below: the unfused reference program, byte-identical to the
        # previously pinned path. `kernel_path` forces one outcome for the
        # whole engine ("reference" restores the historical pin — the bench
        # baseline and the parity tests' oracle).
        #: the serving precision policy (validated at the construction
        #: boundary: a typo'd policy dies HERE, not as a silent fp32 engine)
        self.precision = validate_precision(precision) \
            if precision is not None else None
        self.cfg = dataclasses.replace(model_config, fused_likelihood=False,
                                       hot_loop_path=None,
                                       hot_loop_tile=None)
        if self.precision == "bf16":
            # bf16 operands / fp32 accumulation on every dense apply — the
            # compute_dtype the hot loop already has parity coverage for
            self.cfg = dataclasses.replace(self.cfg,
                                           compute_dtype="bfloat16")
        elif self.precision in ("fp32", "int8"):
            # the exact-oracle base program: an explicit fp32 policy pins
            # it; int8 needs it too — every shape the admission gate
            # rejects serves this exact program
            self.cfg = dataclasses.replace(self.cfg, compute_dtype=None)
        if kernel_path is not None and kernel_path not in (
                "pallas", "blocked_scan", "reference"):
            raise ValueError(f"kernel_path={kernel_path!r}: expected None "
                             f"(probe-gated auto) | pallas | blocked_scan "
                             f"| reference")
        self.kernel_path_force = kernel_path
        #: tenant label (None = single-model legacy): the executable-store
        #: key component, the metrics label, and the router's capability bit
        self.model = str(model) if model is not None else None
        #: the capability set a router snapshot reads (RemoteEngine proxies
        #: expose several; an in-process engine serves exactly one)
        self.models = frozenset({self.model}) if self.model else None
        #: (op, k, bucket) -> (dispatch cfg, path name, tile) — the gate's
        #: per-shape memo; resolution is deterministic, so the memo only
        #: saves repeated probe-cache lookups on the dispatch hot path
        self._kernel_cache: Dict[tuple, tuple] = {}
        self.k = int(k) if k is not None else 50
        # the engine's k admission bound (typed bad_request past it); the
        # default never rejects the engine's own configured k, and an
        # explicit bound below it is a construction error — otherwise every
        # default-k submit would fail at runtime instead
        if k_max is not None and int(k_max) < self.k:
            raise ValueError(f"k_max={int(k_max)} is below this engine's "
                             f"default k={self.k}")
        self.k_max = int(k_max) if k_max is not None \
            else max(DEFAULT_K_MAX, self.k)
        #: whether this replica runs the mesh-sharded large-k path — the
        #: replica router's classification bit (serving/frontend/router.py)
        self.sharded = False
        #: capability bit the replica router reads before forwarding a
        #: trace context: this engine accepts ``submit(trace=)`` and emits
        #: pipeline-stage spans (fakes without the attribute read as
        #: untraceable and never see the kwarg)
        self.traces = True
        #: op -> (jitted program, takes k?) — instance-level so mesh-backed
        #: subclasses swap programs without touching the dispatch machinery
        self._programs: Dict[str, tuple] = dict(PROGRAMS)
        self.timeout_s = timeout_s
        self.ladder = ladder or BucketLadder.powers_of_two(max_batch)
        if self.ladder.max_batch != max_batch:
            max_batch = self.ladder.max_batch
        self.metrics = metrics or ServingMetrics(model=self.model,
                                                 precision=self.precision)
        # the continuous profiling plane (telemetry/profiling.py): on by
        # default — per-dispatch device-time attribution + drift detection
        # on the completion thread, host-side metadata only (programs and
        # results are identical with profiling off; bench.py --profiling
        # stamps the measured overhead). ``profiling`` accepts a
        # ProfilingConfig, True/None (defaults), or False (off).
        self.profiler = None
        if profiling is not False:
            from iwae_replication_project_tpu.telemetry.profiling import (
                DispatchProfiler, ProfilingConfig)
            prof_cfg = profiling if isinstance(profiling, ProfilingConfig) \
                else ProfilingConfig()
            if prof_cfg.enabled:
                self.profiler = DispatchProfiler(
                    registry=self.metrics.registry, config=prof_cfg,
                    label=self.store_label)
        #: (op, k, bucket) -> static cost record | None — the profiler's
        #: per-shape memo over the executable store's cost stamps (one
        #: store scan per shape, not per dispatch)
        self._prof_cost_cache: Dict[tuple, Optional[dict]] = {}
        self._clock = time.monotonic
        self._batcher = MicroBatcher(max_batch=max_batch,
                                     max_wait_us=max_wait_us,
                                     queue_limit=queue_limit,
                                     clock=self._clock)
        # commit everything device-side ONCE, here: the dispatch path then
        # only ever device_puts the per-batch payload explicitly, and runs
        # clean under jax.transfer_guard("disallow") (tests/test_sanitize.py)
        self._params = jax.device_put(params)
        #: the int8 policy's quantized parameter tree (None otherwise):
        #: shares the encoder/decoder chain buffers with ``_params`` by
        #: reference and swaps the fp32 output block for its weight-only
        #: int8 twin — the "out" leaves are ABSENT, so the quantized
        #: program's signature (and its executable-store billing,
        #: utils/dtypes byte widths) carries the genuinely smaller bytes
        self._params_q = None
        #: (op, k, bucket) -> the admission gate's verdict reason (int8
        #: policy only) — why a shape serves quantized or exact, surfaced
        #: through ServingTier.info/bench so the fallback is observable
        self.int8_admission: Dict[tuple, str] = {}
        if self.precision == "int8":
            from iwae_replication_project_tpu.ops.hot_loop import (
                quantize_out_block)
            self._params_q = {key: val for key, val in self._params.items()
                              if key != "out"}
            self._params_q["out_q"] = quantize_out_block(self._params["out"])
        self._base_key = jax.device_put(jax.random.PRNGKey(seed))
        self._seed_counter = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        if max_inflight < 0:
            raise ValueError(f"max_inflight must be >= 0, got {max_inflight}")
        self.max_inflight = int(max_inflight)
        self._window: Optional[InflightWindow] = None
        self._completion_thread: Optional[threading.Thread] = None
        self._completion_stop = threading.Event()
        #: op -> required payload feature count (public: callers building
        #: requests — e.g. the CLI's load generator — read it from here)
        self.row_dims = {
            "score": self.cfg.x_dim,
            "encode": self.cfg.x_dim,
            "decode": self.cfg.n_latent_enc[-1],
        }

    # ------------------------------------------------------------------
    # request API
    # ------------------------------------------------------------------

    #: ops whose program takes an accuracy target (``score_adaptive``) —
    #: their submits are validated through the shared adaptive-target
    #: validator and their ``k`` is the cap, not the sample count. Empty on
    #: the base engine (the mesh-backed subclass registers the adaptive op).
    _ADAPTIVE_OPS: Tuple[str, ...] = ()

    def submit(self, op: str, row, k: Optional[int] = None, *,
               seed: Optional[int] = None,
               model: Optional[str] = None,
               trace=None,
               target_se: Optional[float] = None,
               ess_floor: Optional[float] = None) -> Future:
        """Enqueue ONE example; returns its Future. Raises
        :class:`EngineOverloaded` when the queue bound is hit.

        ``trace`` is an optional
        :class:`~..telemetry.tracing.TraceContext`: the engine's pipeline
        stages (queue → pad → AOT dispatch → device → fetch) are then
        recorded as child spans of it at completion time.  Tracing is pure
        host-side metadata: it never touches seeds, payloads, or program
        shapes, so results are bitwise identical with or without it.

        ``model`` asserts WHICH tenant's weights must serve the request: a
        name other than this engine's own is the typed ``bad_request``
        (ValueError) — a mis-routed model request must fail loudly at the
        replica boundary, never be silently served by the wrong weights.
        ``None`` accepts the engine's model (the single-model legacy path).

        ``seed`` overrides the engine's own per-request seed counter: a
        request's result is a pure function of (weights, payload, seed, k)
        (serving/programs.py), so a caller that mints its own seeds — the
        replica router (serving/frontend/router.py) mints them in tier
        admission order — gets results that are bitwise independent of
        WHICH engine replica serves the request, and a retried request
        re-submitted with its original seed returns the identical result.
        The counter does not advance on an explicit-seed submit.

        The queue only drains when something pumps it: call :meth:`start`
        first for background dispatch (the serving deployment shape), or
        follow up with a blocking helper / :meth:`flush` (the inline shape).
        A bare ``submit(...).result()`` with neither will wait forever —
        timeouts too are evaluated at pump time, by design (no timer
        thread)."""
        if op not in self._programs:
            raise ValueError(f"unknown op {op!r}; choose "
                             f"{sorted(self._programs)}")
        if model is not None:
            # the typed bad_request of the multi-tenant contract (via the
            # ONE shared validator): the one wrong answer is serving the
            # request with the wrong weights
            validate_model(model, self.models or ())
        _, takes_k = self._programs[op]
        if op in self._ADAPTIVE_OPS:
            # the typed bad_request of the adaptive contract, via the ONE
            # shared validator (serving/buckets.py): k is the cap here, and
            # a target-less / malformed-target request must die at this
            # boundary, never inside a replica program
            target_se, ess_floor, k = validate_adaptive_target(
                target_se, ess_floor, self.k if k is None else k, self.k_max)
        elif target_se is not None or ess_floor is not None:
            raise ValueError(
                f"target_se/ess_floor only apply to adaptive ops "
                f"({sorted(self._ADAPTIVE_OPS)}); {op!r} is fixed-k — use "
                f"score_adaptive for accuracy-targeted scoring")
        else:
            target_se = ess_floor = 0.0
            # typed bad_request for out-of-range k at the engine boundary:
            # a k past k_max must never reach program build (for the
            # single-device static-k programs that would be a silent giant
            # compile)
            k = validate_k(self.k if k is None else k, self.k_max) \
                if takes_k else 0
        row = as_row(row, self.row_dims[op], op)
        now = self._clock()
        if seed is not None and not 0 <= int(seed) < 2 ** 31:
            # the seed rides a row of the int32 seeds tensor: an
            # out-of-range value would OverflowError at batch assembly and
            # take the whole coalesced batch down with it — reject THIS
            # request synchronously instead
            raise ValueError(f"seed must be in [0, 2**31), got {seed}")
        with self._cv:
            if seed is None:
                seed = self._seed_counter
                self._seed_counter = (self._seed_counter + 1) % (2 ** 31)
            req = Request(op=op, payload=row, k=k, seed=seed, t_enqueue=now,
                          deadline=(now + self.timeout_s
                                    if self.timeout_s is not None else None),
                          trace=trace,
                          target_se=target_se, ess_floor=ess_floor)
            try:
                self._batcher.submit(req)
            except EngineOverloaded:
                self.metrics.count("shed")
                raise
            self.metrics.count("submitted")
            self.metrics.set_queue_depth(self._batcher.pending)
            self._cv.notify()
        return req.future

    def _blocking(self, op: str, x, k: Optional[int]) -> np.ndarray:
        rows, single = as_rows(x)
        futures = [self.submit(op, r, k=k) for r in rows]
        if self._thread is None:
            self.flush()
        # completion (threaded or inline) already fetched to host ndarrays
        out = np.stack([f.result() for f in futures])
        return out[0] if single else out

    def score(self, x, k: Optional[int] = None) -> np.ndarray:
        """k-sample IWAE log p̂(x) per example (``[n]``, or a scalar for a
        single row). Blocks until served."""
        return self._blocking("score", x, k)

    def encode(self, x, k: Optional[int] = None) -> np.ndarray:
        """Posterior deepest-latent mean embedding per example."""
        return self._blocking("encode", x, k)

    def decode(self, h) -> np.ndarray:
        """Pixel probabilities decoded from deepest-latent rows."""
        return self._blocking("decode", h, None)

    def score_adaptive(self, x, k_cap: Optional[int] = None, *,
                       target_se: Optional[float] = None,
                       ess_floor: Optional[float] = None) -> np.ndarray:
        """Accuracy-targeted scoring: ``[n, 3]`` rows of
        ``(log p_hat, achieved_se, k_used)`` (or ``[3]`` for a single row) —
        each row stops at the first sample-stream prefix meeting
        ``target_se`` (delta-method SE on ``log p_hat``) and/or
        ``ess_floor``, capped at ``k_cap``. Blocks until served; only
        engines registering the adaptive op (the mesh-sharded scorer)
        accept it."""
        rows, single = as_rows(x)
        futures = [self.submit("score_adaptive", r, k=k_cap,
                               target_se=target_se, ess_floor=ess_floor)
                   for r in rows]
        if self._thread is None:
            self.flush()
        out = np.stack([f.result() for f in futures])
        return out[0] if single else out

    # ------------------------------------------------------------------
    # dispatch machinery
    # ------------------------------------------------------------------

    def flush(self) -> int:
        """Drain the queue inline (force-flush every group); returns the
        number of dispatches. The no-thread mode's engine pump."""
        n = 0
        while True:
            with self._cv:
                expired, batches = self._batcher.poll(force=True)
                self.metrics.set_queue_depth(self._batcher.pending)
            self._complete_expired(expired)
            if not batches:
                return n
            for batch in batches:
                self._dispatch(batch)
                n += 1

    def start(self) -> "ServingEngine":
        """Spawn the background pipeline (idempotent): the dispatcher thread
        always; the completion thread when ``max_inflight >= 1`` (pipelined
        mode). In serial mode (``max_inflight=0``) the dispatcher alone
        runs the pre-pipeline dispatch-then-fetch loop."""
        if self._thread is None:
            self._stop_evt.clear()
            self._completion_stop.clear()
            if self.max_inflight >= 1:
                # the window updates the inflight gauge itself, under its
                # own lock: dispatcher and completion thread both mutate the
                # slot count, and unsynchronized read-then-set from either
                # side could publish a stale occupancy
                self._window = InflightWindow(
                    self.max_inflight, on_change=self.metrics.set_inflight)
                self._completion_thread = threading.Thread(
                    target=self._completion_loop,
                    name="iwae-serve-complete", daemon=True)
                self._completion_thread.start()
            self._thread = threading.Thread(target=self._loop,
                                            name="iwae-serve-dispatch",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the pipeline, draining everything: queued requests are still
        dispatched (inline) and every in-flight batch is fetched and
        completed before the threads are joined — no future accepted before
        this call is ever lost to a shutdown. (A ``submit`` that races
        ``stop`` from another thread may land after the final drain; like
        any submit with no pump running, it waits in the queue for the next
        ``start``/``flush``/blocking helper — the general pump contract in
        :meth:`submit`.)"""
        if self._thread is not None:
            self._stop_evt.set()
            with self._cv:
                self._cv.notify_all()
            if self._window is not None:
                self._window.wake()     # unblock a push stalled on the window
            self._thread.join()
            self._thread = None
        if self._completion_thread is not None:
            # the dispatcher is gone: nothing pushes anymore. Signal drain —
            # pop() returns every remaining in-flight batch, then None.
            self._completion_stop.set()
            self._window.wake()
            self._completion_thread.join()
            self._completion_thread = None
            self._window = None
        self.flush()

    def _loop(self) -> None:
        pipelined = self._window is not None
        while not self._stop_evt.is_set():
            with self._cv:
                expired, batches = self._batcher.poll()
                self.metrics.set_queue_depth(self._batcher.pending)
                if not batches and not expired:
                    nxt = self._batcher.next_event()
                    wait = None if nxt is None \
                        else max(nxt - self._clock(), 1e-4)
                    self._cv.wait(timeout=wait)
                    continue
            self._complete_expired(expired)
            for batch in batches:
                if pipelined:
                    # backpressure BEFORE the device enqueue: block while
                    # max_inflight batches are outstanding (stall -> queue
                    # fills -> submit sheds), so device-side memory is
                    # bounded by the window, exactly. On shutdown the
                    # acquire is forced so the batch is never lost.
                    self._window.acquire(abort=self._stop_evt.is_set)
                    inf = self._launch_routed(batch)
                    if inf is None:
                        self._window.release()
                    else:
                        self._window.commit(inf)
                else:
                    self._dispatch(batch)

    def _completion_loop(self) -> None:
        """The pipeline's second stage: block on each in-flight batch's
        device->host fetch in dispatch order, slice padding, complete
        futures. Exits only once stopped AND the window has drained."""
        while True:
            inf = self._window.pop(stop=self._completion_stop.is_set)  # iwaelint: disable=unlocked-shared-state -- _window is an InflightWindow monitor (internally locked); .pop is its blocking dequeue, not a bare container mutation
            if inf is None:
                return
            self._finish(inf)
            self._window.done()

    # tolerant completion (shared with the router and RemoteEngine): a
    # cancelled or already-completed Future must never kill the thread
    _complete = staticmethod(complete_future)

    def _complete_expired(self, expired: List[Request]) -> None:
        for r in expired:
            self.metrics.count("timeouts")
            self._complete(r.future, exc=RequestTimeout(
                f"{r.op} request expired after {self.timeout_s}s in queue "
                f"(engine saturated — shed load or raise timeout_s)"))

    #: ops whose program routes ``log p(x|h)`` through the hot-loop
    #: dispatcher and are therefore kernel-gated; ``encode``/``decode``
    #: never touch the decoder score block, so they stay on the reference
    #: config unconditionally (their programs are byte-identical either way)
    _GATED_OPS = ("score",)

    def _kernel_for(self, op: str, k: int, bucket: int) -> tuple:
        """``(dispatch cfg, path name, tile)`` of one (op, k, bucket) —
        the lifted serving gate. Resolution runs OUTSIDE any trace, is a
        pure function of (engine config, shape, env, VMEM budget, autotune
        winners), and is memoized per engine; the chosen path/tile ride the
        dispatch config, so program identity, the AOT build key, and the
        metrics stamp all agree by construction."""
        key = (op, k, bucket)
        hit = self._kernel_cache.get(key)
        if hit is None:
            hit = self._resolve_kernel(op, k, bucket)
            self._kernel_cache[key] = hit  # iwaelint: disable=unlocked-shared-state -- idempotent memo publish: the value is a pure function of the key, dict setitem is atomic under the GIL, and a double resolution is benign (both writers store the identical tuple)
        return hit

    def _resolve_kernel(self, op: str, k: int, bucket: int) -> tuple:
        """One gate resolution (see :meth:`_kernel_for`): the probe-gated
        row-vmapped selection for gated ops, the reference config — the
        previously pinned unfused program, bitwise-identical by the PR 6
        parity pins — for everything else (including every probe
        rejection: automatic fallback, never a crash)."""
        from iwae_replication_project_tpu.models.iwae import _on_tpu
        from iwae_replication_project_tpu.ops.hot_loop import (
            serving_dispatch_config,
            serving_int8_admit,
        )

        if op not in self._GATED_OPS:
            return self.cfg, "reference", None
        if self.precision == "int8":
            # the measured-win contract: the quantized program serves this
            # shape only where the serving_int8 autotune kind ranked it
            # faster than the exact fp32 reference (or the env forces it);
            # any rejection falls through to the standard gate below — the
            # exact fp32 program, with the reason kept for telemetry
            from iwae_replication_project_tpu.ops.autotune import (
                dims_for_model)
            h1_dim, hid, n_pixels = dims_for_model(self.cfg)
            admitted, reason = serving_int8_admit(k, bucket, h1_dim, hid,
                                                  n_pixels, on_tpu=_on_tpu())
            self.int8_admission[(op, k, bucket)] = reason  # iwaelint: disable=unlocked-shared-state -- idempotent telemetry memo: the admission reason is a pure function of the key; racing writers store the identical string
            if admitted:
                return self.cfg, "int8", None
        return serving_dispatch_config(self.cfg, k, bucket,
                                       on_tpu=_on_tpu(),
                                       force=self.kernel_path_force)

    def _program_for(self, op: str, k: int, bucket: int):
        """The jitted program of one dispatch (subclasses whose programs
        close over the config — the mesh-sharded scorer — resolve their
        per-bucket variant here)."""
        return self._programs[op][0]

    def _stamp_k(self, op: str, k: int):
        """The k component of the metrics kernel-stamp key: the PROGRAM
        identity's k. Static-k engines stamp the request k; the dynamic-k
        sharded scorer stamps one "dyn" slot per bucket (its selection is
        k-independent by construction — a ragged k stream must not mint a
        gauge per k)."""
        return k

    def _dispatch_args(self, op: str, k: int, payload: np.ndarray,
                       seeds: np.ndarray,
                       targets: Optional[Tuple[float, float]] = None
                       ) -> Tuple[tuple, dict, dict]:
        """The (args, kwargs, static_kwargs) of one AOT dispatch — shared by
        the live path and :meth:`warmup` so both hit the same registry key.
        ``targets`` is the adaptive op's ``(target_se, ess_floor)`` pair
        (dynamic scalars, never static); None for fixed-k ops — the base
        engine registers no adaptive op and ignores it."""
        import jax

        _, takes_k = self._programs[op]
        # ONE explicit transfer per dispatch (transfer_guard-clean), not
        # two: device_put dispatch overhead is dispatcher-thread GIL time
        # that competes with the completion stage in the pipelined mode
        payload_dev, seeds_dev = jax.device_put((payload, seeds))
        kwargs = dict(base_key=self._base_key, seeds=seeds_dev)
        kwargs["h_top" if op == "decode" else "x"] = payload_dev
        cfg, path, _ = self._kernel_for(op, k, len(payload))
        static = dict(cfg=cfg)
        if takes_k:
            static["k"] = k
        # an int8-admitted dispatch serves the quantized tree (its "out_q"
        # leaves route log p(x|h) through the quantized scorer); every
        # other path — including int8-policy shapes the gate rejected —
        # serves the exact fp32 parameters
        params = self._params_q if path == "int8" else self._params
        return (params,), kwargs, static

    def _build_key(self, op: str, k: int, bucket: int) -> tuple:
        key = (op, self._kernel_for(op, k, bucket)[0], k, bucket)
        # the precision policy rides the build key (ISSUE 16): an fp32 and
        # a bf16/int8 engine over the SAME weights/config must never share
        # an executable. None keeps the historical 4-tuple exactly.
        return key if self.precision is None else key + (self.precision,)

    @property
    def store_label(self) -> Optional[str]:
        """The executable-store tenant label this engine's programs key
        under: the model name, ``@precision``-suffixed when a precision
        policy is set, so (model, precision) variants hold DISTINCT store
        entries — evicted, billed, and reported per variant — under one
        process-wide budget. ``None`` (no model, no policy) keeps the
        historical unlabeled store schema."""
        if self.precision is None:
            return self.model
        return f"{self.model or 'default'}@{self.precision}"

    def _aot_name(self, op: str) -> str:
        """Registry/span name of the op's program (subclasses that swap in
        a different program for the same op name rename it here so the AOT
        accounting and the audit suite agree on program identity)."""
        return f"serve_{op}"

    def _launch(self, batch: List[Request]) -> _InFlight:
        """Stage one: pad, device_put, enqueue the async AOT dispatch.
        Returns the in-flight handle WITHOUT synchronizing — the device
        computes while the dispatcher returns to coalescing."""
        from iwae_replication_project_tpu.telemetry.spans import span
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_call_async, cache_stats, executable_store, stats_delta)

        # op/k come from the request fields, NOT a group unpack: the
        # adaptive coalescing key is a 4-tuple (op, k, target_se,
        # ess_floor), and every request in a batch shares all four by the
        # grouping contract (batcher.Request.group)
        op, k = batch[0].op, batch[0].k
        targets = (batch[0].target_se, batch[0].ess_floor) \
            if op in self._ADAPTIVE_OPS else None
        n = len(batch)
        # chaos hook (utils/faults.py; off = one None check): a raise here
        # is the replica-crash signal — it propagates into _launch_routed
        # and lands in exactly this batch's futures
        fault_point(SITE_ENGINE_LAUNCH, engine=self, op=op, k=k, batch=n)
        # trace-stage timestamps: stamped unconditionally (two clock reads)
        # so the hot path does no per-request tracing work — the spans are
        # assembled at completion, and only for traced requests
        t_launch0 = self._clock()
        bucket = self.ladder.bucket_for(n)
        payload = self.ladder.pad_rows(
            np.stack([r.payload for r in batch]), bucket)
        seeds = np.zeros((bucket,), np.int32)
        seeds[:n] = [r.seed for r in batch]
        program = self._program_for(op, k, bucket)
        args, kwargs, static = self._dispatch_args(op, k, payload, seeds,
                                                   targets)
        t_args = self._clock()
        # stamp the gate's selection for THIS dispatch's (op, k, bucket) —
        # recomputed from the row's own config via the deterministic gate
        # memo, never read from trace-order state (the PR 6 contract)
        from iwae_replication_project_tpu.ops.hot_loop import PATH_CODES
        _, path, tile = self._kernel_for(op, k, bucket)
        self.metrics.set_kernel(op, self._stamp_k(op, k), bucket,
                                PATH_CODES[path], path, tile)
        s0 = cache_stats()
        build_key = self._build_key(op, k, bucket)
        # pin the dispatch's store entry until completion: a multi-tenant
        # budget squeeze (another model's admission) must never evict an
        # executable while this batch is between enqueue and fetch
        pin = executable_store().pin_prefix(self.store_label,
                                            self._aot_name(op), build_key)
        try:
            # spans nest: serve/dispatch/aot/serve_<op> — the outer one (in
            # the engine's own registry) covers pad+device_put+enqueue, NOT
            # device completion (that is the completion stage's
            # serve/complete span)
            with span(f"serve/dispatch/{op}",
                      registry=self.metrics.registry):
                out = aot_call_async(
                    self._aot_name(op), program, args,
                    kwargs=kwargs, static_kwargs=static,
                    build_key=build_key, model=self.store_label)
        except BaseException:
            pin.release()
            raise
        d = stats_delta(s0)
        t_disp = self._clock()
        for r in batch:
            r.t_dispatch = t_disp
        self.metrics.count("dispatches")
        self.metrics.count("real_rows", n)
        self.metrics.count("padded_rows", bucket - n)
        self.metrics.count("aot_hits", d["aot_hits"])
        self.metrics.count("aot_misses", d["aot_misses"])
        self.metrics.count("recompiles", d["persistent_cache_misses"])
        return _InFlight(batch=batch, op=op, k=k, bucket=bucket, out=out,
                         pin=pin, t_launch0=t_launch0, t_args=t_args)

    def _launch_routed(self, batch: List[Request]) -> Optional[_InFlight]:
        """:meth:`_launch` with enqueue-failure routing: an exception lands
        in exactly this batch's futures, never in the dispatcher thread."""
        try:
            return self._launch(batch)
        except Exception as e:
            for r in batch:
                self.metrics.count("errors")
                self._complete(r.future, exc=e)
            return None

    def _fetch(self, out) -> np.ndarray:
        """The pipeline's ONE blocking device->host transfer (completion
        stage). Async dispatch errors (including deferred device-side
        failures) surface here."""
        return np.asarray(out)  # iwaelint: disable=host-sync -- the completion stage's designated fetch: blocking D2H is this thread's entire job; the dispatch hot path stays sync-free

    def _prof_flops(self, op: str, k: int, rows: int) -> Optional[float]:
        """Analytic matmul FLOPs of one dispatch's real rows (the measured-
        MFU numerator, utils/flops.py — the same honest lower-bound
        accounting every bench phase uses). Only ``score`` runs the
        decoder stack the accounting models; other ops profile device time
        without an MFU gauge."""
        if op != "score":
            return None
        from iwae_replication_project_tpu.utils.flops import (
            serving_score_flops_per_row)
        return serving_score_flops_per_row(self.cfg, k) * rows

    def _static_cost_for(self, op: str, k: int, bucket: int):
        """This dispatch shape's static cost record from the executable
        store (the compile-time ``iwae-cost`` stamp — the measured-vs-
        static ceiling's denominator), memoized per shape. None when the
        stamp was skipped/failed (the gauges then stay unpublished)."""
        key = (op, k, bucket)
        if key not in self._prof_cost_cache:
            from iwae_replication_project_tpu.utils.compile_cache import (
                executable_store)
            cost = executable_store().cost_for(
                self.store_label, self._aot_name(op),
                self._build_key(op, k, bucket))
            self._prof_cost_cache[key] = cost  # iwaelint: disable=unlocked-shared-state -- idempotent memo publish: the record is a pure function of the key; racing writers store the identical dict
        return self._prof_cost_cache[key]

    def _prof_adaptive(self, inf: _InFlight, out: np.ndarray):
        """``(flops, total_k_used)`` of an adaptive dispatch, read from the
        fetched result's k_used column — or None for fixed-k ops. The
        profiling plane attributes adaptive work at the samples actually
        drawn, not the cap: a burn rate charged at k_cap could be gamed by
        easy rows that stopped after one block. Base engine: no adaptive
        ops, always None."""
        return None

    def _profile_dispatch(self, inf: _InFlight, now: float,
                          out: Optional[np.ndarray] = None) -> None:
        """Completion-stage profiling hook: attribute this batch's measured
        device interval (enqueue -> fetched — the completion thread's own
        clock reads, no extra sync) to its (model, program, bucket,
        k-class) key. One profiler call per DISPATCH, not per request."""
        t_disp = inf.batch[0].t_dispatch if inf.batch else None
        if t_disp is None:
            return
        flops = self._prof_flops(inf.op, inf.k, len(inf.batch))
        samples = None
        adaptive = self._prof_adaptive(inf, out)
        if adaptive is not None:
            flops, samples = adaptive
        self.profiler.observe(
            program=self._aot_name(inf.op), bucket=inf.bucket,
            k_class=self._stamp_k(inf.op, inf.k), rows=len(inf.batch),
            device_s=now - t_disp,
            flops=flops,
            cost=self._static_cost_for(inf.op, inf.k, inf.bucket),
            samples=samples)

    def _trace_attrs(self, op: str, k: int, bucket: int, n: int) -> dict:
        """Attrs stamped on a traced dispatch's ``engine/dispatch`` span
        (the mesh-sharded subclass adds its chunk/mesh shape here)."""
        return {"op": op, "k": k, "bucket": bucket, "batch": n,
                "program": self._aot_name(op)}

    def _emit_trace_spans(self, inf: _InFlight, t_fetch0: float,
                          now: float, error: Optional[str] = None) -> None:
        """Per-request pipeline-stage spans, assembled from the timestamps
        the hot path stamped (queue → pad → dispatch → device → fetch) —
        recorded only for traced requests, at completion, off the dispatch
        hot path."""
        traced = [r for r in inf.batch if r.trace is not None]
        if not traced:
            return
        from iwae_replication_project_tpu.telemetry.tracing import emit_span

        attrs = self._trace_attrs(inf.op, inf.k, inf.bucket, len(inf.batch))
        for r in traced:
            ctx = r.trace
            emit_span(ctx, "engine/queue", r.t_enqueue, inf.t_launch0)
            emit_span(ctx, "engine/pad", inf.t_launch0, inf.t_args)
            emit_span(ctx, "engine/dispatch", inf.t_args,
                      r.t_dispatch if r.t_dispatch is not None
                      else inf.t_args, attrs=attrs)
            if r.t_dispatch is not None:
                emit_span(ctx, "engine/device", r.t_dispatch, t_fetch0)
            emit_span(ctx, "engine/fetch", t_fetch0, now, error=error)

    def _finish(self, inf: _InFlight) -> None:
        """Stage two: fetch, slice padding, complete this batch's futures.
        A fetch failure (async device errors surface at the transfer) is
        routed to exactly this in-flight batch's futures."""
        from iwae_replication_project_tpu.telemetry.spans import span

        t_fetch0 = self._clock()
        try:
            with span(f"serve/complete/{inf.op}",
                      registry=self.metrics.registry):
                # chaos hook: a raise here models a deferred device failure
                # — routed to exactly this batch's futures below (ctx
                # carries op, matching serving/faults.py's site table)
                fault_point(SITE_ENGINE_FETCH, engine=self, op=inf.op)
                out = self._fetch(inf.out)
        except Exception as e:
            if inf.pin is not None:
                inf.pin.release()
            self._emit_trace_spans(inf, t_fetch0, self._clock(),
                                   error="internal")
            for r in inf.batch:
                self.metrics.count("errors")
                self._complete(r.future, exc=e)
            return
        if inf.pin is not None:
            # the fetch landed: the dispatch is complete and the store may
            # evict this program again under budget pressure
            inf.pin.release()
        now = self._clock()
        if self.profiler is not None:
            self._profile_dispatch(inf, now, out)
        self._emit_trace_spans(inf, t_fetch0, now)
        for i, r in enumerate(inf.batch):
            self.metrics.record_latency(
                inf.op, inf.bucket, now - r.t_enqueue,
                trace_id=(r.trace.trace_id if r.trace is not None else None))
            if r.t_dispatch is not None:
                self.metrics.record_queue_wait(inf.op, inf.bucket,
                                               r.t_dispatch - r.t_enqueue)
                self.metrics.record_device_wait(inf.op, inf.bucket,
                                                now - r.t_dispatch)
            if self._complete(r.future, result=out[i]):
                self.metrics.count("completed")

    def _dispatch(self, batch: List[Request]) -> None:
        """Serial dispatch: launch then immediately fetch-and-complete on
        the calling thread — the inline :meth:`flush` path and the
        ``max_inflight=0`` baseline mode."""
        inf = self._launch_routed(batch)
        if inf is not None:
            self._finish(inf)

    # ------------------------------------------------------------------
    # warmup
    # ------------------------------------------------------------------

    def warmup(self, ops: Sequence[str] = ("score", "encode", "decode"),
               ks: Optional[Iterable[int]] = None) -> Dict[str, float]:
        """Pre-compile every (op, k, bucket) executable on the ladder via the
        AOT registry — after this, a ragged request stream over those ops
        runs with zero compiles (the bench's ``cache_stats`` delta proves
        it). Returns ``{"programs": N, "compiles": M, "seconds": S}``
        (programs > compiles when some rungs were already registered)."""
        from iwae_replication_project_tpu.telemetry.spans import span
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_warm, cache_stats, stats_delta)

        ks = list(ks) if ks is not None else [self.k]
        s0 = cache_stats()
        t0 = time.perf_counter()
        n_programs = 0
        from iwae_replication_project_tpu.ops.hot_loop import PATH_CODES
        with span("serve/warmup", registry=self.metrics.registry):
            for op in ops:
                if op not in self._programs:
                    raise ValueError(f"unknown op {op!r}")
                _, takes_k = self._programs[op]
                for k in (ks if takes_k else [0]):
                    for bucket in self.ladder.buckets:
                        payload = np.zeros((bucket, self.row_dims[op]),
                                           np.float32)
                        seeds = np.zeros((bucket,), np.int32)
                        # adaptive targets are DYNAMIC scalars: any value
                        # warms the bucket's one executable for every
                        # (k_cap, target_se, ess_floor)
                        targets = (0.0, 0.0) \
                            if op in self._ADAPTIVE_OPS else None
                        args, kwargs, static = self._dispatch_args(
                            op, k, payload, seeds, targets)
                        aot_warm(self._aot_name(op),
                                 self._program_for(op, k, bucket), args,
                                 kwargs=kwargs, static_kwargs=static,
                                 build_key=self._build_key(op, k, bucket),
                                 model=self.store_label)
                        _, path, tile = self._kernel_for(op, k, bucket)
                        self.metrics.set_kernel(op, self._stamp_k(op, k),
                                                bucket, PATH_CODES[path],
                                                path, tile)
                        n_programs += 1
        d = stats_delta(s0)
        # record which hot-loop path this engine's score programs run on
        # THIS engine's registry (ops/hot_loop.PATH_CODES) — recomputed
        # through the deterministic gate memo for the engine's own
        # (config, k, bucket), never read from trace-order state (a
        # cache-warm warmup traces nothing). With the pin lifted this is
        # the lifted gate's outcome, not a hard-coded reference stamp.
        _, path, _ = self._kernel_for("score", self.k,
                                      self.ladder.bucket_for(1))
        self.metrics.registry.gauge("kernel_path").set(
            float(PATH_CODES[path]))
        return {"programs": float(n_programs),
                "compiles": float(d["aot_misses"]),
                "recompiles": float(d["persistent_cache_misses"]),
                "seconds": round(time.perf_counter() - t0, 3)}


def _load_checkpoint(run_dir: str):
    """(params, ModelConfig, trained k) from an experiment checkpoint run
    directory, using the stored config JSON for the architecture/template."""
    import jax

    from iwae_replication_project_tpu.training import (
        create_train_state, make_adam)
    from iwae_replication_project_tpu.utils.checkpoint import (
        restore_latest, stored_config_json)
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    cfg_json = stored_config_json(run_dir)
    if cfg_json is None:
        raise FileNotFoundError(
            f"no checkpoint (or no stored config) under {run_dir!r} — pass "
            f"the run directory the experiment driver writes, "
            f"<checkpoint_dir>/<run_name>")
    ecfg = ExperimentConfig.from_json(cfg_json)
    model_cfg = ecfg.model_config()
    template = create_train_state(jax.random.PRNGKey(ecfg.seed), model_cfg,
                                  optimizer=make_adam(eps=ecfg.adam_eps))
    restored = restore_latest(run_dir, template)
    if restored is None:
        raise FileNotFoundError(f"no restorable checkpoint under {run_dir!r}")
    _, state, _, _ = restored
    return state.params, model_cfg, ecfg.k
