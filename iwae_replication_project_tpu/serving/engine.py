"""ServingEngine: online inference over the AOT warm paths.

Request lifecycle (ARCHITECTURE.md "Serving"):

    submit -> bounded queue -> coalesce (max_batch / max_wait_us)
           -> pad to shape bucket -> AOT executable dispatch
           -> slice real rows -> complete futures

The engine is in-process: callers get ``concurrent.futures.Future``s (or use
the blocking ``score``/``encode``/``decode`` helpers). A background
dispatcher thread drives the micro-batcher when :meth:`start` is called;
without it, the blocking helpers drain the queue inline — fully
deterministic, which is what the tests use.

Three invariants the design leans on:

* **row independence** — the serving programs (serving/programs.py) key RNG
  per request, so padded-bucket dispatch is bitwise equal to unpadded
  execution and padding rows are sliced off, never returned;
* **closed shape menu** — every dispatch lands on a
  :class:`~.buckets.BucketLadder` rung, pre-compiled by :meth:`warmup`
  through the AOT registry (utils/compile_cache.py): a warm engine serves
  any ragged request stream with zero compiles;
* **bounded everything** — queue bound (:class:`EngineOverloaded` shed),
  per-request timeout (:class:`RequestTimeout` error result), dispatch
  errors land in the affected futures, not in the dispatcher thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from iwae_replication_project_tpu.serving.batcher import (
    EngineOverloaded,
    MicroBatcher,
    Request,
    RequestTimeout,
)
from iwae_replication_project_tpu.serving.buckets import BucketLadder
from iwae_replication_project_tpu.serving.metrics import ServingMetrics
from iwae_replication_project_tpu.serving.programs import PROGRAMS

__all__ = ["ServingEngine", "EngineOverloaded", "RequestTimeout"]


class ServingEngine:
    """Typed online-inference API over one model's weights.

    `source` is a compiled jax-backend :class:`~..api.FlexibleModel` or a
    checkpoint run directory (the ``<checkpoint_dir>/<run_name>`` Orbax tree
    the experiment driver writes); alternatively pass ``params=`` +
    ``model_config=`` directly (what the facade's ``serving_engine()`` does).

    Knobs: ``k`` (default importance samples per score/encode request;
    ``None`` = the checkpoint's stored training k, else 50),
    ``max_batch``/``max_wait_us`` (coalescing policy), ``queue_limit``
    (backpressure bound), ``timeout_s`` (per-request queue deadline; None
    disables), ``ladder`` (shape buckets; default powers-of-two up to
    max_batch).
    """

    def __init__(self, source=None, *, params=None, model_config=None,
                 k: Optional[int] = None, max_batch: int = 64,
                 max_wait_us: float = 2000.0,
                 queue_limit: int = 1024, timeout_s: Optional[float] = 2.0,
                 ladder: Optional[BucketLadder] = None, seed: int = 0,
                 metrics: Optional[ServingMetrics] = None):
        import jax

        if isinstance(source, str):
            params, model_config, stored_k = _load_checkpoint(source)
            if k is None:
                k = stored_k  # serve at the budget the model trained under
        elif source is not None:
            if getattr(source, "state", None) is None or \
                    not hasattr(source, "cfg"):
                raise ValueError(
                    "source must be a compiled jax-backend FlexibleModel "
                    "(call .compile() first) or a checkpoint directory path")
            params, model_config = source.params, source.cfg
        if params is None or model_config is None:
            raise ValueError("pass a model, a checkpoint directory, or "
                             "params= + model_config=")
        # serving batches are small and vmapped per-row; the Pallas fused
        # path is shaped for the big eval batches and does not compose with
        # the row-vmap, so serving programs always run the unfused kernels
        self.cfg = dataclasses.replace(model_config, fused_likelihood=False)
        self.k = int(k) if k is not None else 50
        self.timeout_s = timeout_s
        self.ladder = ladder or BucketLadder.powers_of_two(max_batch)
        if self.ladder.max_batch != max_batch:
            max_batch = self.ladder.max_batch
        self.metrics = metrics or ServingMetrics()
        self._clock = time.monotonic
        self._batcher = MicroBatcher(max_batch=max_batch,
                                     max_wait_us=max_wait_us,
                                     queue_limit=queue_limit,
                                     clock=self._clock)
        # commit everything device-side ONCE, here: the dispatch path then
        # only ever device_puts the per-batch payload explicitly, and runs
        # clean under jax.transfer_guard("disallow") (tests/test_sanitize.py)
        self._params = jax.device_put(params)
        self._base_key = jax.device_put(jax.random.PRNGKey(seed))
        self._seed_counter = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        #: op -> required payload feature count (public: callers building
        #: requests — e.g. the CLI's load generator — read it from here)
        self.row_dims = {
            "score": self.cfg.x_dim,
            "encode": self.cfg.x_dim,
            "decode": self.cfg.n_latent_enc[-1],
        }

    # ------------------------------------------------------------------
    # request API
    # ------------------------------------------------------------------

    def submit(self, op: str, row, k: Optional[int] = None) -> Future:
        """Enqueue ONE example; returns its Future. Raises
        :class:`EngineOverloaded` when the queue bound is hit.

        The queue only drains when something pumps it: call :meth:`start`
        first for background dispatch (the serving deployment shape), or
        follow up with a blocking helper / :meth:`flush` (the inline shape).
        A bare ``submit(...).result()`` with neither will wait forever —
        timeouts too are evaluated at pump time, by design (no timer
        thread)."""
        if op not in PROGRAMS:
            raise ValueError(f"unknown op {op!r}; choose {sorted(PROGRAMS)}")
        _, takes_k = PROGRAMS[op]
        k = (self.k if k is None else int(k)) if takes_k else 0
        row = np.asarray(row, np.float32).reshape(-1)
        want = self.row_dims[op]
        if row.shape[0] != want:
            raise ValueError(f"{op} payload must have {want} features, "
                             f"got {row.shape[0]}")
        now = self._clock()
        with self._cv:
            seed = self._seed_counter
            self._seed_counter = (self._seed_counter + 1) % (2 ** 31)
            req = Request(op=op, payload=row, k=k, seed=seed, t_enqueue=now,
                          deadline=(now + self.timeout_s
                                    if self.timeout_s is not None else None))
            try:
                self._batcher.submit(req)
            except EngineOverloaded:
                self.metrics.count("shed")
                raise
            self.metrics.count("submitted")
            self.metrics.set_queue_depth(self._batcher.pending)
            self._cv.notify()
        return req.future

    def _blocking(self, op: str, x, k: Optional[int]) -> np.ndarray:
        x = np.asarray(x, np.float32)
        single = x.ndim == 1
        rows = x[None] if single else x.reshape(x.shape[0], -1)
        futures = [self.submit(op, r, k=k) for r in rows]
        if self._thread is None:
            self.flush()
        out = np.stack([np.asarray(f.result()) for f in futures])
        return out[0] if single else out

    def score(self, x, k: Optional[int] = None) -> np.ndarray:
        """k-sample IWAE log p̂(x) per example (``[n]``, or a scalar for a
        single row). Blocks until served."""
        return self._blocking("score", x, k)

    def encode(self, x, k: Optional[int] = None) -> np.ndarray:
        """Posterior deepest-latent mean embedding per example."""
        return self._blocking("encode", x, k)

    def decode(self, h) -> np.ndarray:
        """Pixel probabilities decoded from deepest-latent rows."""
        return self._blocking("decode", h, None)

    # ------------------------------------------------------------------
    # dispatch machinery
    # ------------------------------------------------------------------

    def flush(self) -> int:
        """Drain the queue inline (force-flush every group); returns the
        number of dispatches. The no-thread mode's engine pump."""
        n = 0
        while True:
            with self._cv:
                expired, batches = self._batcher.poll(force=True)
                self.metrics.set_queue_depth(self._batcher.pending)
            self._complete_expired(expired)
            if not batches:
                return n
            for batch in batches:
                self._dispatch(batch)
                n += 1

    def start(self) -> "ServingEngine":
        """Spawn the background dispatcher thread (idempotent)."""
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="iwae-serve-dispatch",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the dispatcher and drain whatever is still queued."""
        if self._thread is not None:
            self._stop_evt.set()
            with self._cv:
                self._cv.notify_all()
            self._thread.join()
            self._thread = None
        self.flush()

    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            with self._cv:
                expired, batches = self._batcher.poll()
                self.metrics.set_queue_depth(self._batcher.pending)
                if not batches and not expired:
                    nxt = self._batcher.next_event()
                    wait = None if nxt is None \
                        else max(nxt - self._clock(), 1e-4)
                    self._cv.wait(timeout=wait)
                    continue
            self._complete_expired(expired)
            for batch in batches:
                self._dispatch(batch)

    @staticmethod
    def _complete(fut: Future, result=None, exc=None) -> bool:
        """Complete a future, tolerating caller-side cancellation: a client
        that cancelled its pending Future must not be able to kill the
        dispatcher thread with InvalidStateError (the thread outlives any
        one request by contract). Returns whether the result was delivered."""
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
            return True
        except Exception:  # cancelled (or already completed): drop quietly
            return False

    def _complete_expired(self, expired: List[Request]) -> None:
        for r in expired:
            self.metrics.count("timeouts")
            self._complete(r.future, exc=RequestTimeout(
                f"{r.op} request expired after {self.timeout_s}s in queue "
                f"(engine saturated — shed load or raise timeout_s)"))

    def _dispatch_args(self, op: str, k: int, payload: np.ndarray,
                       seeds: np.ndarray) -> Tuple[tuple, dict, dict]:
        """The (args, kwargs, static_kwargs) of one AOT dispatch — shared by
        the live path and :meth:`warmup` so both hit the same registry key."""
        import jax

        program, takes_k = PROGRAMS[op]
        kwargs = dict(base_key=self._base_key,
                      seeds=jax.device_put(seeds))
        if op == "decode":
            kwargs["h_top"] = jax.device_put(payload)
        else:
            kwargs["x"] = jax.device_put(payload)
        static = dict(cfg=self.cfg)
        if takes_k:
            static["k"] = k
        return (self._params,), kwargs, static

    def _build_key(self, op: str, k: int, bucket: int) -> tuple:
        return (op, self.cfg, k, bucket)

    def _dispatch(self, batch: List[Request]) -> None:
        from iwae_replication_project_tpu.telemetry.spans import span
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_call, cache_stats, stats_delta)

        op, k = batch[0].group
        n = len(batch)
        bucket = self.ladder.bucket_for(n)
        payload = self.ladder.pad_rows(
            np.stack([r.payload for r in batch]), bucket)
        seeds = np.zeros((bucket,), np.int32)
        seeds[:n] = [r.seed for r in batch]
        program, _ = PROGRAMS[op]
        args, kwargs, static = self._dispatch_args(op, k, payload, seeds)
        s0 = cache_stats()
        try:
            # spans nest: serve/dispatch/aot/serve_<op> — the outer one (in
            # the engine's own registry) covers pad+device_put+execute+fetch
            with span(f"serve/dispatch/{op}", registry=self.metrics.registry):
                out = np.asarray(aot_call(
                    f"serve_{op}", program, args,
                    kwargs=kwargs, static_kwargs=static,
                    build_key=self._build_key(op, k, bucket)))
        except Exception as e:  # dispatch failure -> per-request error,
            for r in batch:     # never a dead dispatcher thread
                self.metrics.count("errors")
                self._complete(r.future, exc=e)
            return
        d = stats_delta(s0)
        now = self._clock()
        self.metrics.count("dispatches")
        self.metrics.count("real_rows", n)
        self.metrics.count("padded_rows", bucket - n)
        self.metrics.count("aot_hits", d["aot_hits"])
        self.metrics.count("aot_misses", d["aot_misses"])
        self.metrics.count("recompiles", d["persistent_cache_misses"])
        for i, r in enumerate(batch):
            self.metrics.record_latency(op, bucket, now - r.t_enqueue)
            if self._complete(r.future, result=out[i]):
                self.metrics.count("completed")

    # ------------------------------------------------------------------
    # warmup
    # ------------------------------------------------------------------

    def warmup(self, ops: Sequence[str] = ("score", "encode", "decode"),
               ks: Optional[Iterable[int]] = None) -> Dict[str, float]:
        """Pre-compile every (op, k, bucket) executable on the ladder via the
        AOT registry — after this, a ragged request stream over those ops
        runs with zero compiles (the bench's ``cache_stats`` delta proves
        it). Returns ``{"programs": N, "compiles": M, "seconds": S}``
        (programs > compiles when some rungs were already registered)."""
        from iwae_replication_project_tpu.telemetry.spans import span
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_warm, cache_stats, stats_delta)

        ks = list(ks) if ks is not None else [self.k]
        s0 = cache_stats()
        t0 = time.perf_counter()
        n_programs = 0
        with span("serve/warmup", registry=self.metrics.registry):
            for op in ops:
                if op not in PROGRAMS:
                    raise ValueError(f"unknown op {op!r}")
                program, takes_k = PROGRAMS[op]
                for k in (ks if takes_k else [0]):
                    for bucket in self.ladder.buckets:
                        payload = np.zeros((bucket, self.row_dims[op]),
                                           np.float32)
                        seeds = np.zeros((bucket,), np.int32)
                        args, kwargs, static = self._dispatch_args(
                            op, k, payload, seeds)
                        aot_warm(f"serve_{op}", program, args, kwargs=kwargs,
                                 static_kwargs=static,
                                 build_key=self._build_key(op, k, bucket))
                        n_programs += 1
        d = stats_delta(s0)
        return {"programs": float(n_programs),
                "compiles": float(d["aot_misses"]),
                "recompiles": float(d["persistent_cache_misses"]),
                "seconds": round(time.perf_counter() - t0, 3)}


def _load_checkpoint(run_dir: str):
    """(params, ModelConfig, trained k) from an experiment checkpoint run
    directory, using the stored config JSON for the architecture/template."""
    import jax

    from iwae_replication_project_tpu.training import (
        create_train_state, make_adam)
    from iwae_replication_project_tpu.utils.checkpoint import (
        restore_latest, stored_config_json)
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    cfg_json = stored_config_json(run_dir)
    if cfg_json is None:
        raise FileNotFoundError(
            f"no checkpoint (or no stored config) under {run_dir!r} — pass "
            f"the run directory the experiment driver writes, "
            f"<checkpoint_dir>/<run_name>")
    ecfg = ExperimentConfig.from_json(cfg_json)
    model_cfg = ecfg.model_config()
    template = create_train_state(jax.random.PRNGKey(ecfg.seed), model_cfg,
                                  optimizer=make_adam(eps=ecfg.adam_eps))
    restored = restore_latest(run_dir, template)
    if restored is None:
        raise FileNotFoundError(f"no restorable checkpoint under {run_dir!r}")
    _, state, _, _ = restored
    return state.params, model_cfg, ecfg.k
