"""Serving-layer fault sites and chaos rule builders.

The serving stack's :func:`~..utils.faults.fault_point` hooks fire at
these sites (names are the schedule's addressing scheme — a typo'd site
matches nothing, so the constants below are the one spelling):

====================  =====================================================
site                  where / ctx kwargs
====================  =====================================================
SITE_ENGINE_LAUNCH    ServingEngine._launch, before the AOT enqueue
                      (``engine``, ``op``, ``k``, ``batch``) — a raise
                      lands in exactly that batch's futures, the signal
                      surface of a replica crash
SITE_ENGINE_FETCH     the completion stage's device->host fetch
                      (``engine``, ``op``) — deferred device failure
SITE_ROUTER_DISPATCH  ReplicaRouter._dispatch, inside the per-replica
                      submit try (``router``, ``replica``, ``attempt``)
SITE_TIER_WRITE       tier connection response write, under the
                      connection lock before ``sendall`` (``sock``,
                      ``conn``) — where dropped/garbled TCP lives
SITE_REMOTE_SEND      RemoteEngine.submit, inside the send try
                      (``addr``) — an OSError here poisons the proxy
====================  =====================================================

plus the generic sites defined in utils/faults.py (``aot.call_async``,
``train.pass``, ``train.checkpoint.save``). The builders below wrap the
common chaos cases as one-liner rules; anything they don't cover composes
from :class:`~..utils.faults.FaultRule` directly.
"""

from __future__ import annotations

import contextlib
import socket

from iwae_replication_project_tpu.utils.faults import (  # noqa: F401
    SITE_AOT_CALL_ASYNC,
    SITE_CKPT_SAVE,
    SITE_TRAIN_PASS,
    FaultContext,
    FaultInjected,
    FaultRule,
    FaultSchedule,
    clear,
    delay,
    fault_point,
    install,
    installed,
    raise_error,
    raise_fault,
    sigterm,
)

__all__ = [
    "SITE_ENGINE_LAUNCH", "SITE_ENGINE_FETCH", "SITE_ROUTER_DISPATCH",
    "SITE_TIER_WRITE", "SITE_REMOTE_SEND",
    "crash_replica", "slow_replica", "drop_tier_connection",
    "garble_tier_connection", "crash_aot_dispatch", "sever_remote",
]

SITE_ENGINE_LAUNCH = "serve.engine.launch"
SITE_ENGINE_FETCH = "serve.engine.fetch"
SITE_ROUTER_DISPATCH = "serve.router.dispatch"
SITE_TIER_WRITE = "serve.tier.write"
SITE_REMOTE_SEND = "serve.remote.send"


def _is_engine(engine) -> "callable":
    return lambda ctx: ctx.get("engine") is engine


def crash_replica(engine, after: int = 0, times=None,
                  name: str = "crash_replica") -> FaultRule:
    """Raise from `engine`'s dispatch path after `after` launches: the
    batch's futures error, the router marks the replica unhealthy and
    reroutes its outstanding work with the original seeds. ``times=None``
    keeps the replica down (re-admission probes keep failing) until the
    schedule is cleared; a finite ``times`` models a transient crash."""
    return FaultRule(site=SITE_ENGINE_LAUNCH, after=after, times=times,
                     match=_is_engine(engine), name=name,
                     action=raise_fault("replica crash (chaos)"))


def slow_replica(engine, delay_s: float, after: int = 0, times=1,
                 name: str = "slow_replica") -> FaultRule:
    """Stall `engine`'s dispatcher for `delay_s` on one (or `times`)
    launches — the tail-latency fault that client hedging exists for."""
    return FaultRule(site=SITE_ENGINE_LAUNCH, after=after, times=times,
                     match=_is_engine(engine), name=name,
                     action=delay(delay_s))


def _kill_sock(fc: FaultContext) -> None:
    sock_ = fc.ctx.get("sock")
    if sock_ is not None:
        with contextlib.suppress(OSError):
            sock_.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            sock_.close()


def drop_tier_connection(after: int = 0, times=1,
                         name: str = "drop_connection") -> FaultRule:
    """Close the client connection under the tier's response write: the
    response is produced but never delivered — the client sees a dead
    socket mid-request and must reconnect + retry. The action only touches
    the socket (never raises), so the server's own OSError handling runs
    exactly as it would for a real peer reset."""
    return FaultRule(site=SITE_TIER_WRITE, after=after, times=times,
                     name=name, action=_kill_sock)


def _garble_sock(fc: FaultContext) -> None:
    sock_ = fc.ctx.get("sock")
    if sock_ is not None:
        with contextlib.suppress(OSError):
            # not JSON, not even UTF-8: the client's framed reader must
            # surface a ProtocolError, not limp along
            sock_.sendall(b"\xff\xfe{garbled" + b"\n")


def garble_tier_connection(after: int = 0, times=1,
                           name: str = "garble_connection") -> FaultRule:
    """Interpose garbage bytes on the wire before a response line (fired
    under the connection's write lock, so the garbage is frame-aligned and
    the run is deterministic): the client reads a malformed frame and must
    treat the connection as poisoned."""
    return FaultRule(site=SITE_TIER_WRITE, after=after, times=times,
                     name=name, action=_garble_sock)


def crash_aot_dispatch(after: int = 0, times=1, program_prefix: str = "serve_",
                       name: str = "crash_aot") -> FaultRule:
    """Raise inside ``aot_call_async`` for matching programs — the
    enqueue-time failure class (OOM, poisoned runtime) that must land in
    exactly the affected batch's futures, never kill a dispatcher thread."""
    return FaultRule(
        site=SITE_AOT_CALL_ASYNC, after=after, times=times, name=name,
        match=lambda ctx: str(ctx.get("name", "")).startswith(program_prefix),
        action=raise_fault("AOT dispatch failure (chaos)"))


def sever_remote(after: int = 0, times=1,
                 name: str = "sever_remote") -> FaultRule:
    """Raise ``OSError`` from RemoteEngine's socket send: the proxy poisons
    itself, outstanding futures fail typed, and (under a RetryPolicy) the
    next submit attempts a fresh connection."""
    return FaultRule(site=SITE_REMOTE_SEND, after=after, times=times,
                     name=name,
                     action=raise_error(
                         lambda fc: OSError("connection severed (chaos)")))
