"""Elastic fleet: the SLO-driven control loop over the serving tier.

This package closes the loop the rest of the serving stack spent five PRs
instrumenting: the burn-rate gauges (telemetry/slo.py), the model-affinity
router with dynamic replica add/remove (frontend/router.py), the
``static_cost``-billed executable store with placement pins
(utils/compile_cache.py), the persistent XLA/autotune caches that make a
joining replica warm, and the lossless drain contract that makes a leaving
one invisible. The loop itself is four small modules, one concern each:

    signals.py ──► controller.py ──► lifecycle.py ──► planner.py
     (observe)       (decide)         (actuate)       (re-place)

* :mod:`.signals` — one :class:`SignalSnapshot` per control tick: the SLO
  burn-rate reductions (worst burn per window, trailing request counts),
  replica states, outstanding work, store residency — from a local tier or
  from the ``slo`` wire control op of a child tier (fleet-of-fleets);
* :mod:`.controller` — :class:`AutoscaleController`: the pure decision
  function (snapshot, config, seed) → :class:`Decision`, with hysteresis
  (up-threshold above down-threshold), per-direction cooldowns, bounds,
  dry-run, and a structured decision log;
* :mod:`.planner` — :func:`plan_placement`: deterministic first-fit-
  decreasing bin-packing of models onto replica store budgets using the
  per-model ``static_cost`` peak-bytes cost model — which executables live
  resident where;
* :mod:`.lifecycle` — :class:`FleetManager`: actuates decisions against a
  live tier (warm scale-up via a replica factory, drain-based scale-down
  via :meth:`~..frontend.router.ReplicaRouter.remove_replica`), applies
  each placement plan as store model-pins + router affinity hints, and
  runs the periodic control thread behind ``iwae-serve --autoscale``.

The invariant every piece preserves: seeds are minted at tier admission in
arrival order, before any replica is chosen — so a fleet that scaled up,
scaled down, or lost a replica mid-scale-event returns bitwise the same
results as one that never changed shape (pinned by tests/test_fleet.py and
``scripts/autoscale_smoke.py``).
"""

from iwae_replication_project_tpu.serving.fleet.controller import (
    AutoscaleConfig,
    AutoscaleController,
    Decision,
    choose_victim,
)
from iwae_replication_project_tpu.serving.fleet.lifecycle import FleetManager
from iwae_replication_project_tpu.serving.fleet.planner import (
    PlacementPlan,
    plan_placement,
)
from iwae_replication_project_tpu.serving.fleet.signals import (
    SignalSnapshot,
    local_signals,
    wire_signals,
)

__all__ = ["AutoscaleConfig", "AutoscaleController", "Decision",
           "choose_victim", "FleetManager", "PlacementPlan",
           "plan_placement", "SignalSnapshot", "local_signals",
           "wire_signals"]
